#!/usr/bin/env python
"""deepspeed_tpu headline benchmark.

Trains the flagship decoder (Llama-3 family) with the deepspeed_tpu engine
and reports tokens/sec/chip and MFU. Baseline context (BASELINE.md): the
reference's north star is ZeRO-3 Llama-3-70B at >=45% MFU on v5p; here we
report single-chip (or CPU-mesh smoke) MFU against that 45% bar, so
``vs_baseline`` = achieved_MFU / 0.45.

Default TPU config: the 1.2B-param preset (the VERDICT r1 bar: >=1B), bf16,
Pallas flash attention (512-element blocks), `save_attn_out` remat, 512 MB
chunked-CE logits budget (the biggest single MFU lever found tuning: 51.5%
-> 56.1% on v5e — small CE chunks starve the MXU on the [B*C, D]x[D, 128k]
logits matmul), and — on a single 16G chip, where fp32 Adam moments for
1.2B params cannot fit — bf16 optimizer states (`state_dtype` knob, the
analogue of the reference's fp16_master_weights_and_gradients,
stage_1_and_2.py:159). Multi-chip runs shard fp32 states ZeRO-3 style.

Prints exactly ONE JSON line to stdout.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    """bf16 peak FLOPs/s per chip (the table lives in telemetry.sampler;
    imported lazily so bench argparse stays jax-free)."""
    from deepspeed_tpu.telemetry.sampler import peak_flops
    return peak_flops(device)


def _apply_bench_slo(config) -> None:
    """DSTPU_BENCH_SLO=";"-separated objective strings (e.g.
    ``train/mfu >= 0.3;train/step_time_ms:p95 <= 250``) arms the SLO
    burn-rate engine for the bench run: objectives into the config's
    ``slo`` block, metric history every step so short runs still
    evaluate. No env → config untouched."""
    spec = os.environ.get("DSTPU_BENCH_SLO")
    if not spec:
        return
    config["slo"] = {"objectives":
                     [s.strip() for s in spec.split(";") if s.strip()]}
    config.setdefault("telemetry", {})["history_every"] = 1


def _slo_extra(engine_or_frontend):
    """SLO stamp for the BENCH JSON line — always present so trajectory
    files stay uniform; zeros when no objectives were armed."""
    slo = getattr(engine_or_frontend, "_slo", None)
    if slo is None:
        return {"objectives": 0, "evaluated": 0, "worst_burn": 0.0,
                "breached": []}
    return slo.summary()


def _run_sub(cmd, timeout):
    """Run a sub-benchmark; return its last JSON line or an error record."""
    import subprocess
    try:
        out = subprocess.run(
            [sys.executable] + cmd, capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "DSTPU_BENCH_SUITE": "0"})
        for line in reversed(out.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
        return {"error": (out.stderr or out.stdout)[-400:]}
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    except Exception as e:              # never break the headline line
        return {"error": str(e)[:400]}


def _suite(root):
    """The VERDICT r3 #2 'whole story' metrics: long-context 16K/32K MFU,
    MoE training MFU, int8/int4 serving tok/s — each in its own process
    (fresh HBM), folded into the headline line's extra.suite.

    Process model: the parent's TPU client stays alive while children run.
    That requires a runtime allowing concurrent clients (the axon/remote
    runtime this repo benches on does — verified end-to-end, BENCH r4);
    a locally-attached libtpu enforces single-process ownership, where
    each child would record an error entry instead of silently lying."""
    mfu = lambda r: {k: r.get("extra", {}).get(k) for k in
                     ("mfu", "achieved_tflops_per_chip")} \
        if "extra" in r else r
    bench = os.path.join(root, "bench.py")
    suite = {}
    suite["long_16k"] = mfu(_run_sub(
        [bench, "--seq", "16384", "--batch", "1", "--steps", "10"], 480))
    suite["long_32k"] = mfu(_run_sub(
        [bench, "--seq", "32768", "--batch", "1", "--steps", "8"], 540))
    # the FPDT regime (reference fpdt_layer.py:510): 128K tokens on ONE
    # chip via host-offloaded block inputs + flash-kernel residuals and
    # the sequence-chunked MLP
    suite["long_128k"] = mfu(_run_sub(
        [bench, "--seq", "131072", "--batch", "1", "--steps", "3"], 900))
    suite["moe_1b_8e_dropless"] = mfu(_run_sub(
        [bench, "--mode", "moe", "--steps", "24"], 480))
    for q in ("int8", "int4"):
        r = _run_sub([os.path.join(root, "bench_inference.py"),
                      "--quant", q], 560)
        suite[f"serving_{q}"] = (
            {"ragged_tok_s": r["extra"]["ragged_tok_s"],
             "vs_padded": r["extra"]["speedup"],
             "uniform_gen": r["extra"]["uniform_gen"]}
            if "extra" in r else r)
    return suite


def from_config_main(args) -> None:
    """``--from-config best.json``: replay a ``dstpu-tune`` winner and
    stamp predicted-vs-measured into ``extra.tune``. The emitted config
    carries everything needed — the mesh rebuilds from its
    parallel-topology knobs (``mesh_from_config``), the training knobs
    pass straight to ``initialize``, and the ``tune`` stamp supplies the
    model preset / sequence length / roofline prediction. When the tuned
    chip count exceeds the local devices, the run falls back to pure-DP
    over what exists (TP/SP/EP coerced away) — a scaled-down sanity run,
    flagged ``scaled_down`` in the stamp, not the tuned point."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.config.config import DeepSpeedTPUConfig
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.parallel.mesh import mesh_from_config

    with open(args.from_config) as fh:
        cfg = json.load(fh)
    parsed = DeepSpeedTPUConfig.from_any(dict(cfg))
    stamp = parsed.tune
    dev0 = jax.devices()[0]
    n_dev = len(jax.devices())
    on_tpu = dev0.platform == "tpu"

    size = args.size or str(stamp.model or "llama3-tiny").split(
        "llama3-")[-1]
    seq = args.seq or int(stamp.seq_len or (2048 if on_tpu else 128))
    steps = args.steps or (24 if on_tpu else 3)
    warmup = 3 if on_tpu else 1
    model = llama3_config(size, max_seq_len=seq, tie_embeddings=True)

    chips = 1
    for v in (stamp.mesh or {}).values():
        chips *= int(v)
    train_cfg = {k: v for k, v in cfg.items()
                 if k not in ("tune", "serving", "router", "autoscale")}
    _apply_bench_slo(train_cfg)
    scaled_down = chips > n_dev
    if scaled_down:
        for k in ("tensor_parallel", "sequence_parallel", "moe"):
            train_cfg.pop(k, None)
        ds.build_mesh(data=n_dev)
        run_chips = n_dev
    else:
        run_chips = max(1, chips)
        mesh_from_config(parsed, devices=jax.devices()[:run_chips])
    engine, *_ = ds.initialize(model=model, config=train_cfg,
                               rng=jax.random.PRNGKey(0))

    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    batches = [jax.device_put({"input_ids": rng.integers(
        0, model.vocab_size, size=(gb, seq), dtype=np.int32)})
        for _ in range(4)]
    for i in range(warmup):
        float(engine.train_batch(iter([batches[i % 4]])))
    t0 = time.perf_counter()
    loss = None
    for i in range(steps):
        loss = engine.train_batch(iter([batches[i % 4]]))
    loss_val = float(loss)
    dt = time.perf_counter() - t0
    measured_ms = dt / steps * 1e3

    tokens = gb * seq * steps
    tune_extra = {
        "config": os.path.basename(args.from_config),
        "search_key": stamp.search_key,
        "tuned_platform": stamp.platform,
        "tuned_chips": stamp.chips,
        "run_chips": run_chips,
        "scaled_down": scaled_down,
        "predicted_ms": stamp.predicted_step_ms,
        "measured_ms": round(measured_ms, 3),
        "pct_of_roofline": None,
    }
    try:
        from deepspeed_tpu.telemetry import explain as _explain
        rep = _explain.explain_engine(engine, measured_step_ms=measured_ms)
        rl = rep.roofline
        tune_extra["local_predicted_ms"] = round(rl.predicted_s * 1e3, 3)
        tune_extra["bound"] = rl.bound
        tune_extra["pct_of_roofline"] = round(
            rl.pct_of(dt / steps) or 0.0, 2)
    except Exception:
        pass
    result = {
        "metric": f"tokens/sec/chip tuned llama3-{size} seq{seq} "
                  f"[{stamp.search_key or 'untuned config'}]",
        "value": round(tokens / dt / run_chips, 2),
        "unit": "tokens/s/chip",
        "extra": {
            "loss": loss_val,
            "platform": dev0.platform,
            "n_devices": n_dev,
            "steps": steps,
            "global_batch": gb,
            "tune": tune_extra,
            "slo": _slo_extra(engine),
        },
    }
    print(json.dumps(result))
    if getattr(args, "trace", None):
        from deepspeed_tpu.telemetry import tracer
        tracer.dump(args.trace)


def moe_main(args) -> None:
    """MoE training bench: ~1B total params, 8 experts, top-2, dropless
    (lax.ragged_dot) dispatch — MFU on ACTIVE params (the standard MoE
    accounting; reference context: Mixtral-class EP configs)."""
    import jax
    dev0 = jax.devices()[0]
    on_tpu = dev0.platform == "tpu"
    n_dev = len(jax.devices())
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.mixtral import mixtral_config

    seq = args.seq or (2048 if on_tpu else 128)
    batch = args.batch or 8
    steps = args.steps or (24 if on_tpu else 3)
    warmup = 3 if on_tpu else 1
    ds.build_mesh(data=n_dev)
    if on_tpu:
        # head_dim 128 (8 heads), the TPU-native choice every production
        # family here uses (llama3/qwen2/mixtral all ship Dh=128): at
        # the old 16x64 config the flash kernels are VPU-bound (QK^T
        # contracts over 64 = half the MXU depth; traced at ~6.5
        # ms/layer vs ~3.5 at Dh=128) — same params, same active FLOPs,
        # same GQA ratio. Measured 36.4% -> 41.8% MFU on this bench
        # (the r5 kernel work lifted 26.3% -> 36.4% before this).
        model = mixtral_config(
            "tiny", hidden_size=1024, num_layers=12, num_heads=8,
            num_kv_heads=4, intermediate_size=2816, num_experts=8,
            num_experts_per_tok=2, vocab_size=32000, max_seq_len=seq,
            tie_embeddings=True)
    else:
        model = mixtral_config("tiny", max_seq_len=seq)
    config = {
        "train_micro_batch_size_per_gpu": max(1, batch // n_dev),
        "optimizer": {"type": "adamw", "params": {
            "lr": 1e-4, "weight_decay": 0.1,
            **({"state_dtype": "bfloat16", "master_weights": False}
               if on_tpu and n_dev < 8 else {})}},
        "zero_optimization": {"stage": 0},
        "bf16": {"enabled": bool(on_tpu)},
        "gradient_clipping": 1.0,
        "moe": {"impl": os.environ.get("DSTPU_BENCH_MOE_IMPL", "dropless")},
        # the fused MoE backward recomputes gate/up in-kernel, so no
        # policy choice affects the FFN re-run. save_attn_kernel_qkv
        # additionally keeps post-rope q/k/v: measured +0.4pt over
        # save_attn_kernel at THIS geometry (32-step pairs, r5) — the
        # 20pt qkv-residency loss documented for the 1.27B dense bench
        # does not reproduce at this smaller model's memory point.
        # (Saving moe_glu residual stacks instead measured ~1pt slower
        # than the in-kernel recompute.)
        "activation_checkpointing": {
            "policy": os.environ.get(
                "DSTPU_BENCH_MOE_POLICY",
                "save_attn_kernel_qkv") if on_tpu else "none"},
        "ce_logits_dtype": "bf16" if on_tpu else None,
        # DSTPU_BENCH_CE_MB=0 -> None (unchunked CE)
        "chunked_ce_budget_mb": (int(os.environ.get(
            "DSTPU_BENCH_CE_MB", 256)) or None) if on_tpu else None,
        "steps_per_print": 1000,
    }
    _apply_bench_slo(config)
    # DSTPU_BENCH_HEALTH=<every> arms the in-graph model-health taps at
    # that cadence for the benched engine (stamped into extra.health)
    hb_every = int(os.environ.get("DSTPU_BENCH_HEALTH", "0") or 0)
    if hb_every:
        config["telemetry"] = {"health": {"enabled": True,
                                          "every": hb_every}}
    engine, *_ = ds.initialize(model=model, config=config,
                               rng=jax.random.PRNGKey(0))
    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    batches = [jax.device_put({"input_ids": rng.integers(
        0, model.vocab_size, size=(gb, seq), dtype=np.int32)})
        for _ in range(4)]
    for i in range(warmup):
        float(engine.train_batch(iter([batches[i % 4]])))
    t0 = time.perf_counter()
    loss = None
    for i in range(steps):
        loss = engine.train_batch(iter([batches[i % 4]]))
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    tokens = gb * seq * steps
    active = model.num_active_params()
    attn = 12.0 * model.num_layers * model.hidden_size * seq * 0.5
    achieved = (6.0 * active + attn) * tokens / dt / n_dev
    peak = _peak_flops(dev0)
    mfu = achieved / peak if peak else 0.0
    result = {
        "metric": f"tokens/sec/chip moe-8e-top2 ~1B seq{seq} dropless",
        "value": round(tokens / dt / n_dev, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
        "extra": {"mfu": round(mfu, 4),
                  "achieved_tflops_per_chip": round(achieved / 1e12, 2),
                  "params_total_b": round(model.num_params() / 1e9, 3),
                  "params_active_b": round(active / 1e9, 3),
                  "loss": loss_val, "platform": dev0.platform,
                  "n_devices": n_dev, "steps": steps,
                  "global_batch": gb,
                  "slo": _slo_extra(engine)}}
    try:
        from deepspeed_tpu.telemetry import explain as _explain
        rep = _explain.explain_engine(
            engine, measured_step_ms=dt / steps * 1e3)
        rl = rep.roofline
        result["extra"]["roofline"] = {
            "flops_per_step": rl.flops, "bytes_per_step": rl.bytes,
            "comm_bytes_per_step": rl.comm_bytes,
            "predicted_step_ms": round(rl.predicted_s * 1e3, 3),
            "bound": rl.bound,
            "pct_of_roofline": round(rl.pct_of(dt / steps) or 0.0, 2),
        }
    except Exception:
        pass
    if hb_every:
        result["extra"]["health"] = _health_extra()
    print(json.dumps(result))
    if getattr(args, "trace", None):
        from deepspeed_tpu.telemetry import tracer
        tracer.dump(args.trace)


def _health_extra():
    """Final ``health/*`` gauge snapshot → the BENCH ``extra.health``
    stamp ({} on any failure — the stamp must never take the bench
    down)."""
    try:
        from deepspeed_tpu.telemetry.registry import registry
        snap = registry.snapshot(interval=False)
        return {k.split("/", 1)[1].replace("/", "_"): round(float(v), 4)
                for k, v in sorted(snap.items())
                if k.startswith("health/") and "layer/" not in k
                and "expert/" not in k
                and isinstance(v, (int, float))}
    except Exception:                                # noqa: BLE001
        return {}


def health_main(args) -> None:
    """--health-ab: A/B the in-graph model-health taps (health.every=1 —
    stats computed in-graph AND fetched every step) against the same
    engine with telemetry.health disabled: identical model, mesh, rng
    and data. The BENCH value is the step-time overhead in percent; the
    acceptance bar for the static-flag design is <5%, with zero extra
    retraces per engine (asserted against the compile counter)."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models.mixtral import mixtral_config

    dev0 = jax.devices()[0]
    n_dev = len(jax.devices())
    on_tpu = dev0.platform == "tpu"
    seq = args.seq or (2048 if on_tpu else 128)
    batch = args.batch or n_dev
    steps = args.steps or (24 if on_tpu else 6)
    warmup = 3 if on_tpu else 2
    ds.build_mesh(data=n_dev)
    model = mixtral_config("tiny", max_seq_len=seq)

    def run(health):
        config = {
            "train_micro_batch_size_per_gpu": max(1, batch // n_dev),
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 0},
            "moe": {"impl": "dropless"},
            "steps_per_print": 1000,
        }
        if health:
            config["telemetry"] = {"health": {"enabled": True,
                                              "every": 1}}
        traces0 = telemetry.compile_monitor.retrace_count(
            "engine/fused_step")
        engine, *_ = ds.initialize(model=model, config=config,
                                   rng=jax.random.PRNGKey(0))
        gb = int(engine.config.train_batch_size)
        rng = np.random.default_rng(0)
        batches = [{"input_ids": rng.integers(
            0, model.vocab_size, size=(gb, seq), dtype=np.int32)}
            for _ in range(4)]
        for i in range(warmup):
            float(engine.train_batch(iter([batches[i % 4]])))
        t0 = time.perf_counter()
        loss = None
        for i in range(steps):
            loss = engine.train_batch(iter([batches[i % 4]]))
        loss = float(loss)
        dt = time.perf_counter() - t0
        return {"step_ms": round(dt / steps * 1e3, 3),
                "loss": round(loss, 6),
                "retraces": telemetry.compile_monitor.retrace_count(
                    "engine/fused_step") - traces0}

    base = run(False)
    taps = run(True)
    overhead = (taps["step_ms"] / base["step_ms"] - 1.0) \
        if base["step_ms"] else 0.0
    result = {
        "metric": f"model-health taps A/B mixtral-tiny seq{seq} "
                  f"dp{n_dev} {dev0.platform} (every=1 vs off)",
        "value": round(overhead * 100.0, 2),
        "unit": "% step-time overhead",
        "extra": {"baseline": base, "health": taps,
                  "health_stamp": _health_extra(),
                  "platform": dev0.platform, "n_devices": n_dev,
                  "steps": steps, "seq": seq},
    }
    print(json.dumps(result))


def overlap_main(args) -> None:
    """A/B the chunked overlap-scheduled ZeRO-3 collectives against the
    monolithic stage-3 path: identical model, mesh, rng and data; one
    JSON line with per-mode step time, loss, roofline stamp and the
    ``overlap/*`` plan numbers (chunks, prefetch, transient HBM,
    achieved overlap fraction). On a CPU host the mesh is forced to 8
    virtual devices (the dp=8 smoke geometry the tier-1 tests use);
    wall-clock there validates ordering/numerics — the latency-hiding
    win itself only shows on TPU backends with the scheduler flags."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu" and \
            "host_platform_device_count" not in os.environ.get(
                "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()
    import jax
    dev0 = jax.devices()[0]
    on_tpu = dev0.platform == "tpu"
    n_dev = len(jax.devices())
    if n_dev < 2:
        print(json.dumps({"metric": "zero3 overlap A/B", "value": 0.0,
                          "error": f"needs a dp>=2 mesh, got {n_dev} "
                                   "device(s) (CPU: JAX_PLATFORMS=cpu)"}))
        return
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.runtime.zero.overlap import overlap_fraction

    size = args.size or ("1b" if on_tpu else "tiny")
    seq = args.seq or (2048 if on_tpu else 128)
    batch = args.batch or 8
    steps = args.steps or (24 if on_tpu else 4)
    warmup = 3 if on_tpu else 1
    model = llama3_config(size, max_seq_len=seq, tie_embeddings=True)
    chunk_knobs = {
        "overlap_comm": True,
        "overlap_bucket_bytes": int(os.environ.get(
            "DSTPU_BENCH_OVERLAP_BUCKET", 0)),
        "overlap_prefetch": int(os.environ.get(
            "DSTPU_BENCH_OVERLAP_PREFETCH", 1)),
        "overlap_regather": os.environ.get(
            "DSTPU_BENCH_OVERLAP_REGATHER", "1") != "0",
    }

    def run(zero_extra):
        ds.build_mesh(data=n_dev)
        config = {
            "train_micro_batch_size_per_gpu": max(1, batch // n_dev),
            "optimizer": {"type": "adamw",
                          "params": {"lr": 1e-4, "weight_decay": 0.1}},
            "zero_optimization": {"stage": 3, **zero_extra},
            "bf16": {"enabled": bool(on_tpu)},
            "gradient_clipping": 1.0,
            "steps_per_print": 1000,
        }
        engine, *_ = ds.initialize(model=model, config=config,
                                   rng=jax.random.PRNGKey(0))
        gb = int(engine.config.train_batch_size)
        rng = np.random.default_rng(0)
        batches = [jax.device_put({"input_ids": rng.integers(
            0, model.vocab_size, size=(gb, seq), dtype=np.int32)})
            for _ in range(4)]
        for i in range(warmup):
            float(engine.train_batch(iter([batches[i % 4]])))
        t0 = time.perf_counter()
        loss = None
        for i in range(steps):
            loss = engine.train_batch(iter([batches[i % 4]]))
        loss_val = float(loss)
        dt = time.perf_counter() - t0
        rec = {"step_ms": round(dt / steps * 1e3, 3),
               "loss": loss_val}
        try:
            from deepspeed_tpu.telemetry import explain as _explain
            rep = _explain.explain_engine(
                engine, measured_step_ms=dt / steps * 1e3)
            rl = rep.roofline
            rec["roofline"] = {
                "flops_per_step": rl.flops, "bytes_per_step": rl.bytes,
                "comm_bytes_per_step": rl.comm_bytes,
                "predicted_step_ms": round(rl.predicted_s * 1e3, 3),
                "bound": rl.bound,
                "pct_of_roofline": round(rl.pct_of(dt / steps) or 0.0, 2),
            }
            plan = getattr(engine, "_overlap_plan", None)
            if plan is not None:
                frac = overlap_fraction(rl.compute_s, rl.comm_s, dt / steps)
                rec["overlap"] = {
                    "chunks": plan.n_chunks,
                    "prefetch": plan.prefetch,
                    "regather": plan.regather,
                    "bucket_bytes": plan.bucket_bytes,
                    "transient_hbm_bytes": int(plan.transient_bytes()),
                    "fraction": (round(frac, 4)
                                 if frac is not None else None),
                }
        except Exception:
            pass
        return rec

    mono = run({"overlap_comm": False})
    chunked = run(chunk_knobs)
    speedup = (mono["step_ms"] / chunked["step_ms"]
               if chunked["step_ms"] else 0.0)
    result = {
        "metric": f"zero3 overlap A/B llama3-{size} seq{seq} dp{n_dev} "
                  f"{dev0.platform}",
        "value": round(speedup, 4),
        "unit": "x step-time vs monolithic",
        "extra": {
            "monolithic": mono, "chunked": chunked,
            "loss_abs_diff": abs(mono["loss"] - chunked["loss"]),
            "platform": dev0.platform, "n_devices": n_dev,
            "steps": steps, "seq": seq,
        },
    }
    print(json.dumps(result))
    if getattr(args, "trace", None):
        from deepspeed_tpu.telemetry import tracer
        tracer.dump(args.trace)


def chaos_main(args) -> None:
    """--chaos: short training run under a scripted fault plan (one
    poisoned step, one transient checkpoint IO error, one torn fragment)
    proving the recovery paths end-to-end. The BENCH line's value is the
    recovery ratio — 1.0 means every injected fault was answered by
    exactly one recovery (skipped step / IO retry / CRC fallback)."""
    import glob
    import tempfile

    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models.llama import llama3_config

    n_dev = len(jax.devices())
    seq = args.seq or 64
    batch = args.batch or n_dev
    steps = max(args.steps or 8, 7)
    ds.build_mesh(data=n_dev)
    model = llama3_config("tiny", max_seq_len=seq, tie_embeddings=True)
    config = {
        "train_micro_batch_size_per_gpu": max(1, batch // n_dev),
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 1000,
        "resilience": {"fault_plan":
                       "step:2:nonfinite_grad;step:5:io_error:checkpoint;"
                       "step:6:torn_fragment:checkpoint"},
        # goodput ledger: attribute the drill's wall clock (the
        # fault_recovery/ckpt categories are the drill's cost accounting)
        "telemetry": {"goodput": {"enabled": True}},
    }
    engine, *_ = ds.initialize(model=model, config=config,
                               rng=jax.random.PRNGKey(0))
    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    batches = [{"input_ids": rng.integers(
        0, model.vocab_size, size=(gb, seq), dtype=np.int32)}
        for _ in range(4)]
    recovered_steps = 0
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as ckpt:
        for i in range(steps):
            if engine.global_steps == 4:
                # clean tag committed BEFORE the checkpoint-site faults
                # become due — the fallback target
                engine.save_checkpoint(ckpt, tag="good")
            loss = float(engine.train_batch(iter([batches[i % 4]])))
            if loss != loss:                         # NaN → poisoned step
                recovered_steps += 1
        # final save: the io_error fires (absorbed by the bounded retry)
        # and the torn_fragment advisory truncates one fragment — the
        # load below must CRC-reject "final" and fall back to "good"
        engine.save_checkpoint(ckpt, tag="final")
        tag, _ = engine.load_checkpoint(ckpt)
        quarantined = glob.glob(os.path.join(ckpt, "*.quarantined*"))
    dt = time.perf_counter() - t0
    reg = telemetry.registry
    faults = int(reg.counter("resilience/faults_injected").value)
    recoveries = int(reg.counter("resilience/recoveries").value)
    fallbacks = int(reg.counter("resilience/ckpt_fallbacks").value)
    result = {
        "metric": f"chaos recovery ledger llama3-tiny seq{seq} "
                  f"dp{n_dev} ({steps} steps, 3 faults)",
        "value": round(recoveries / faults, 4) if faults else 0.0,
        "unit": "recoveries/faults",
        "extra": {
            "faults_injected": faults,
            "recoveries": recoveries,
            "recovered_steps": recovered_steps,
            "fallbacks": fallbacks,
            "ckpt_retries": int(
                reg.counter("resilience/ckpt_retries").value),
            "resumed_tag": tag,
            "quarantined": len(quarantined),
            "wall_s": round(dt, 3),
        },
    }
    gp = _goodput_extra()
    if gp:
        result["extra"]["goodput"] = gp
    print(json.dumps(result))


def _goodput_extra():
    """Final ledger sweep → the BENCH ``extra.goodput`` stamp ({} on any
    failure — the stamp must never take the bench down)."""
    try:
        from deepspeed_tpu.telemetry.goodput import goodput_ledger
        goodput_ledger.update()
        s = goodput_ledger.summary() or {}
        return {k: s.get(k) for k in
                ("uptime_s", "goodput_s", "fraction", "window_fraction",
                 "badput", "dominant_badput", "dominant_badput_s",
                 "captures")} if s else {}
    except Exception:                                # noqa: BLE001
        return {}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default=None,
                    help="llama3 preset (tiny/350m/1b/8b); default by platform")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--mode", default="dense", choices=("dense", "moe"))
    ap.add_argument("--overlap", action="store_true",
                    help="A/B the chunked overlap-scheduled ZeRO-3 "
                         "collectives vs the monolithic stage-3 path "
                         "(knobs: DSTPU_BENCH_OVERLAP_BUCKET/_PREFETCH/"
                         "_REGATHER)")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="record host-side spans and dump Chrome trace-event"
                         " JSON here (inspect with bin/dstpu-trace or "
                         "ui.perfetto.dev)")
    ap.add_argument("--health-ab", action="store_true",
                    help="A/B the in-graph model-health taps "
                         "(telemetry.health every=1 vs disabled) on the "
                         "tiny MoE bench and report % step-time overhead")
    ap.add_argument("--chaos", action="store_true",
                    help="run a short training loop under a scripted "
                         "fault plan (dstpu-chaos) and report the "
                         "recovery ledger instead of MFU")
    ap.add_argument("--from-config", default=None, metavar="JSON",
                    help="replay a dstpu-tune winner: build the mesh and "
                         "engine from the emitted config and stamp "
                         "predicted-vs-measured step time into "
                         "extra.tune")
    args = ap.parse_args()

    if args.trace:
        from deepspeed_tpu.telemetry import tracer
        tracer.configure(enabled=True)
    if args.from_config:
        from_config_main(args)
        return
    if args.chaos:
        chaos_main(args)
        return
    if args.health_ab:
        health_main(args)
        return
    if args.overlap:
        overlap_main(args)
        return
    if args.mode == "moe":
        moe_main(args)
        return
    # run the full suite only on the driver-style bare invocation — explicit
    # --seq/--batch/--steps/--trace runs are themselves sub-benchmarks or
    # tuning/profiling runs
    run_suite = (args.seq is None and args.batch is None
                 and args.steps is None and args.size is None
                 and args.trace is None
                 and os.environ.get("DSTPU_BENCH_SUITE", "1") != "0")

    import jax
    import jax.numpy as jnp
    dev0 = jax.devices()[0]
    platform = dev0.platform
    on_tpu = platform == "tpu"
    n_dev = len(jax.devices())

    size = args.size or ("1b" if on_tpu else "tiny")
    seq = args.seq or (2048 if on_tpu else 128)
    batch = args.batch or 8
    steps = args.steps or (48 if on_tpu else 3)
    warmup = 3 if on_tpu else 1

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.llama import llama3_config

    ds.build_mesh(data=n_dev)

    model = llama3_config(size, max_seq_len=seq, tie_embeddings=True)
    # single small-HBM chip: 1.2B params need bf16 moments + no separate
    # master (8 bytes/param); with >=8 chips ZeRO-3 shards fp32 states
    small_state = on_tpu and n_dev < 8
    opt_params = {"lr": 1e-4, "weight_decay": 0.1}
    if small_state:
        opt_params.update(state_dtype="bfloat16", master_weights=False)
    config = {
        "train_micro_batch_size_per_gpu": max(1, batch // n_dev),
        "optimizer": {"type": "adamw", "params": opt_params},
        "zero_optimization": {"stage": 3 if (on_tpu and n_dev > 1) else 0},
        "bf16": {"enabled": bool(on_tpu)},
        "gradient_clipping": 1.0,
        # save_attn_kernel keeps the Pallas kernel's (out, lse) residuals so
        # the backward never re-runs the flash FORWARD (measured v5e: 56.3
        # -> 57.0 MFU @2K, 46.6 -> 52.2 @16K); at 32K+ the block_in chain
        # no longer fits alongside them, so block inputs park on host
        "activation_checkpointing": {
            "policy": os.environ.get(
                "DSTPU_BENCH_REMAT",
                ("offload_save_attn_kernel_host" if seq >= 65536
                 else "offload_save_attn_kernel" if seq >= 32768
                 else "save_attn_kernel") if on_tpu else "none"),
            # FPDT regime: at 64K+ the [T, ffn] MLP activations alone
            # exceed HBM — run the MLP in sequence tiles
            "ffn_chunk": int(os.environ.get(
                "DSTPU_BENCH_FFN_CHUNK",
                8192 if (on_tpu and seq >= 65536) else 0))},
        # bf16 chunk logits (fp32 accumulation kept) at a 256 MB budget:
        # the optimum is ~128-token chunks — in bf16 that is half the
        # bytes, so the budget halves with the dtype (+0.7 MFU vs fp32)
        "ce_logits_dtype": "bf16" if on_tpu else None,
        "chunked_ce_budget_mb": 256 if on_tpu else None,
        # flash + host-offloaded residuals carries training to 256K;
        # attention_impl=fpdt stays opt-in (forward/serving oriented —
        # its reverse-mode AD stores per-chunk softmax intermediates)
        "attention_impl": os.environ.get("DSTPU_BENCH_ATTN", "auto"),
        "steps_per_print": 1000,
    }
    _apply_bench_slo(config)
    # DSTPU_BENCH_OFFLOAD=cpu|cpu_overlap|zenflow: measure the ZeRO-Offload
    # host-optimizer step (sync / overlapped / ZenFlow selective) against
    # the device step (the VERDICT r1 #6 'measure and report both' criterion)
    off = os.environ.get("DSTPU_BENCH_OFFLOAD")
    if off:
        config["optimizer"]["params"].pop("state_dtype", None)
        config["optimizer"]["params"].pop("master_weights", None)
        config["zero_optimization"]["stage"] = max(
            2, config["zero_optimization"]["stage"])
        config["zero_optimization"]["offload_optimizer"] = {
            "device": "cpu", "overlap": off == "cpu_overlap"}
        if off == "zenflow":
            config["zero_optimization"]["zenflow"] = {
                "topk_ratio": 0.05, "update_interval": 4,
                "select_interval": 32, "full_warm_up_rounds": 2}
    engine, *_ = ds.initialize(model=model, config=config,
                               rng=jax.random.PRNGKey(0))

    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    # distinct batches (cycled) so the reported loss reflects real training,
    # pre-staged on device so the timed loop measures compute, not input PCIe
    n_distinct = 8
    batches = [
        jax.device_put({"input_ids": rng.integers(
            0, model.vocab_size, size=(gb, seq), dtype=np.int32)})
        for _ in range(n_distinct)]

    for i in range(warmup):
        float(engine.train_batch(iter([batches[i % n_distinct]])))

    # async dispatch: no per-step host fetch (a scalar round-trip per step
    # stalls the pipeline under remote runtimes); block once at the end.
    # ONE long window beats best-of-short-windows here: the end-of-window
    # loss fetch is a full pipeline drain, so short windows amortize it
    # worse (measured 55.7% MFU best-of-3x8-step windows vs 56.2% as one
    # 24-step window; the shipped default is one 48-step window — 56.3%)
    t0 = time.perf_counter()
    loss = None
    for i in range(steps):
        loss = engine.train_batch(iter([batches[i % n_distinct]]))
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    tokens = gb * seq * steps
    tok_per_sec_chip = tokens / dt / n_dev
    flops_per_token = 6.0 * model.num_params()
    # +attention quadratic term: 12 * L * d * T per token (causal half)
    attn = 12.0 * model.num_layers * model.hidden_size * seq * 0.5
    achieved = (flops_per_token + attn) * tokens / dt / n_dev
    peak = _peak_flops(jax.devices()[0])
    mfu = achieved / peak if peak else 0.0

    stage = config["zero_optimization"]["stage"]
    prec = "bf16" if on_tpu else "fp32"
    result = {
        "metric": f"tokens/sec/chip llama3-{size} seq{seq} zero{stage} {prec}",
        "value": round(tok_per_sec_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
        "extra": {
            "mfu": round(mfu, 4),
            "achieved_tflops_per_chip": round(achieved / 1e12, 2),
            "params_b": round(model.num_params() / 1e9, 3),
            "loss": loss_val,
            "platform": platform,
            "n_devices": n_dev,
            "steps": steps,
            "global_batch": gb,
            "slo": _slo_extra(engine),
        },
    }
    # compile-time roofline stamp (telemetry/explain): predicted FLOPs /
    # bytes and % of roofline, so BENCH trajectories can distinguish
    # "kernel got faster" from "model got smaller". Never breaks the
    # headline line — any failure just drops the stamp.
    try:
        from deepspeed_tpu.telemetry import explain as _explain
        rep = _explain.explain_engine(
            engine, measured_step_ms=dt / steps * 1e3)
        rl = rep.roofline
        result["extra"]["roofline"] = {
            "flops_per_step": rl.flops, "bytes_per_step": rl.bytes,
            "comm_bytes_per_step": rl.comm_bytes,
            "predicted_step_ms": round(rl.predicted_s * 1e3, 3),
            "bound": rl.bound,
            "pct_of_roofline": round(
                rl.pct_of(dt / steps) or 0.0, 2),
        }
    except Exception:
        pass
    if run_suite and on_tpu:
        result["extra"]["suite"] = _suite(
            os.path.dirname(os.path.abspath(__file__)))
        # durable-record notes the prose used to carry (VERDICT r4 #10):
        # measured claims + the environment limits that shape them
        result["extra"]["notes"] = {
            "serving_8b_int4": (
                "llama3-8b int4 serves on one 16G v5e chip (r5 offline "
                "run of bench_inference.py --size 8b --quant int4 "
                "--n-requests 24 --n-prompts 8; full-precision weights "
                "never touch HBM — host-side init + quantize): uniform "
                "closed-batch decode 182 tok/s ragged / 215 padded; the "
                "24-req long-tail stream lands at 80 tok/s (0.79x "
                "padded) — at 8B the decode is weight-fetch-bound, so "
                "slot retirement buys little at concurrency 8 and the "
                "stream advantage needs the 1B-class concurrency-16 "
                "shape the suite measures"),
            "environment_limits": (
                "this runtime tunnels host<->device over the network "
                "(axon): DSTPU_BENCH_OFFLOAD=* offload step benches "
                "measure the tunnel (~2GB/step of gradient/master "
                "traffic), not the design — ZenFlow/offload validation "
                "lives in the CPU-mesh tests; host dispatch costs "
                "~20ms/call, so serving loops are measured with "
                "device-resident fused chunks; seq 192K+ single-chip "
                "crashes the remote TPU-VM worker (host pinned-memory "
                "pressure) regardless of remat policy or model size — "
                "128K is the driver-visible FPDT point and this "
                "runtime's single-chip ceiling (with SP=8 that local "
                "length is 1M tokens of global context)"),
        }
    print(json.dumps(result))
    if args.trace:
        from deepspeed_tpu.telemetry import tracer
        tracer.dump(args.trace)


if __name__ == "__main__":
    main()
