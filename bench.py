#!/usr/bin/env python
"""deepspeed_tpu headline benchmark.

Trains the flagship decoder (Llama-3 family) with the deepspeed_tpu engine
and reports tokens/sec/chip and MFU. Baseline context (BASELINE.md): the
reference's north star is ZeRO-3 Llama-3-70B at >=45% MFU on v5p; here we
report single-chip (or CPU-mesh smoke) MFU against that 45% bar, so
``vs_baseline`` = achieved_MFU / 0.45.

Prints exactly ONE JSON line to stdout.
"""

import argparse
import json
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    """bf16 peak FLOPs/s per chip by device kind (public TPU specs)."""
    kind = getattr(device, "device_kind", "cpu").lower()
    table = {
        "v6e": 918e12, "trillium": 918e12,
        "v5p": 459e12,
        "v5e": 197e12, "v5 lite": 197e12, "v5litepod": 197e12,
        "v4": 275e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 0.0   # CPU / unknown: MFU not meaningful


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default=None,
                    help="llama3 preset (tiny/1b/8b); default by platform")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    args = ap.parse_args()

    import jax
    dev0 = jax.devices()[0]
    platform = dev0.platform
    on_tpu = platform == "tpu"
    n_dev = len(jax.devices())

    # size to the chip: fp32 Adam states need ~14 bytes/param on the
    # ZeRO shard — one v5e (16G) fits ~350M params unsharded
    kind = dev0.device_kind.lower() if on_tpu else ""
    small_hbm = any(k in kind for k in ("v5 lite", "v5e", "v2", "v3"))
    default_size = "350m" if (on_tpu and small_hbm and n_dev == 1) else \
        ("1b" if on_tpu else "tiny")
    size = args.size or default_size
    seq = args.seq or (2048 if on_tpu else 128)
    batch = args.batch or (8 if on_tpu else 8)
    steps = args.steps or (20 if on_tpu else 3)
    warmup = 3 if on_tpu else 1

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.llama import llama3_config

    ds.build_mesh(data=n_dev)

    model = llama3_config(size, max_seq_len=seq)
    config = {
        "train_micro_batch_size_per_gpu": max(1, batch // n_dev),
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 3 if on_tpu else 2},
        "bf16": {"enabled": bool(on_tpu)},
        "gradient_clipping": 1.0,
        # 'full' recomputes within each block, saving only the residual
        # stream — dots_saveable would materialize every [B,H,T,T] score
        # matrix for backward (OOM at seq 2048 without a flash kernel)
        "activation_checkpointing": {"policy": "full" if on_tpu else "none"},
    }
    engine, *_ = ds.initialize(model=model, config=config,
                               rng=jax.random.PRNGKey(0))

    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    batch_data = {"input_ids": rng.integers(
        0, model.vocab_size, size=(gb, seq), dtype=np.int32)}

    for _ in range(warmup):
        float(engine.train_batch(iter([batch_data])))

    # force materialization with a host fetch each step — under the axon
    # tunnel block_until_ready alone does not guarantee remote execution
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(iter([batch_data]))
        loss_val = float(loss)
    dt = time.perf_counter() - t0

    tokens = gb * seq * steps
    tok_per_sec_chip = tokens / dt / n_dev
    flops_per_token = 6.0 * model.num_params()
    # +2x attention quadratic term: 12 * L * d * T per token (causal half)
    attn = 12.0 * model.num_layers * model.hidden_size * seq * 0.5
    achieved = (flops_per_token + attn) * tokens / dt / n_dev
    peak = _peak_flops(jax.devices()[0])
    mfu = achieved / peak if peak else 0.0

    stage = config["zero_optimization"]["stage"]
    prec = "bf16" if on_tpu else "fp32"
    result = {
        "metric": f"tokens/sec/chip llama3-{size} seq{seq} zero{stage} {prec}",
        "value": round(tok_per_sec_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
        "extra": {
            "mfu": round(mfu, 4),
            "achieved_tflops_per_chip": round(achieved / 1e12, 2),
            "loss": loss_val,
            "platform": platform,
            "n_devices": n_dev,
            "steps": steps,
            "global_batch": gb,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
