// Async file I/O engine — the DeepNVMe analogue for TPU-VM local NVMe.
//
// Reference: csrc/aio/py_lib/deepspeed_aio_thread.cpp + deepspeed_py_aio.cpp
// (libaio O_DIRECT thread pool behind ops/aio). This implementation uses a
// std::thread worker pool issuing pread/pwrite (O_DIRECT optional) — the
// same architecture (submit queue -> N workers -> completion count), with
// a C ABI for ctypes. io_uring is intentionally avoided for portability
// across TPU-VM kernels; the worker model saturates NVMe queue depth the
// same way the reference's aio_thread pool does.

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Request {
  int64_t id;
  bool write;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

class AsyncIOEngine {
 public:
  AsyncIOEngine(int num_threads, bool o_direct)
      : o_direct_(o_direct), stop_(false), next_id_(1), completed_(0),
        errors_(0) {
    for (int i = 0; i < num_threads; ++i) {
      workers_.emplace_back([this] { worker(); });
    }
  }

  ~AsyncIOEngine() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) {
    Request r;
    r.write = write;
    r.path = path;
    r.buf = buf;
    r.nbytes = nbytes;
    r.offset = offset;
    {
      std::unique_lock<std::mutex> lk(mu_);
      r.id = next_id_++;
      queue_.push_back(r);
    }
    cv_.notify_one();
    return r.id;
  }

  // Block until all submitted requests completed; returns error count.
  int64_t drain() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] {
      return queue_.empty() && inflight_ == 0;
    });
    return errors_.load();
  }

  int64_t completed() const { return completed_.load(); }

 private:
  void worker() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        r = queue_.front();
        queue_.pop_front();
        ++inflight_;
      }
      process(r);
      {
        std::unique_lock<std::mutex> lk(mu_);
        --inflight_;
        ++completed_;
        if (queue_.empty() && inflight_ == 0) done_cv_.notify_all();
      }
    }
  }

  void process(const Request& r) {
    int flags = r.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
    if (o_direct_) flags |= O_DIRECT;
#endif
    int fd = ::open(r.path.c_str(), flags, 0644);
    if (fd < 0 && o_direct_) {
      // filesystem may not support O_DIRECT (tmpfs): retry buffered
      fd = ::open(r.path.c_str(),
                  r.write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
    }
    if (fd < 0) {
      ++errors_;
      return;
    }
    int64_t off = r.offset;
    char* p = static_cast<char*>(r.buf);
    int64_t left = r.nbytes;
    while (left > 0) {
      ssize_t n = r.write ? ::pwrite(fd, p, left, off)
                          : ::pread(fd, p, left, off);
      if (n <= 0) {
        ++errors_;
        break;
      }
      p += n;
      off += n;
      left -= n;
    }
    ::close(fd);
  }

  bool o_direct_;
  bool stop_;
  int64_t next_id_;
  int64_t inflight_ = 0;
  std::atomic<int64_t> completed_, errors_;
  std::deque<Request> queue_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
};

}  // namespace

extern "C" {

void* ds_aio_create(int32_t num_threads, int32_t o_direct) {
  return new AsyncIOEngine(num_threads > 0 ? num_threads : 4, o_direct != 0);
}

void ds_aio_destroy(void* h) { delete static_cast<AsyncIOEngine*>(h); }

int64_t ds_aio_pwrite(void* h, const char* path, void* buf, int64_t nbytes,
                      int64_t offset) {
  return static_cast<AsyncIOEngine*>(h)->submit(true, path, buf, nbytes,
                                                offset);
}

int64_t ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                     int64_t offset) {
  return static_cast<AsyncIOEngine*>(h)->submit(false, path, buf, nbytes,
                                                offset);
}

int64_t ds_aio_drain(void* h) {
  return static_cast<AsyncIOEngine*>(h)->drain();
}

int64_t ds_aio_completed(void* h) {
  return static_cast<AsyncIOEngine*>(h)->completed();
}

}  // extern "C"
