// Host-side vectorized Adam/AdamW for ZeRO-Offload.
//
// TPU-native equivalent of the reference's CPU optimizer
// (csrc/adam/cpu_adam_impl.cpp with AVX512/AVX256 intrinsics via
// csrc/includes/simd.h). Differences: instead of hand-written AVX
// intrinsics we give the compiler contiguous fp32 loops (-O3 -ffast-math
// auto-vectorizes to the host ISA — portable across the x86/ARM TPU-VM
// fleet) and parallelize across a persistent std::thread pool, matching
// the reference's per-tensor-group threading.
//
// C ABI (ctypes-friendly): all state is caller-owned flat fp32 buffers.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

class ThreadPool {
 public:
  explicit ThreadPool(int n) : stop_(false), pending_(0) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> job;
          {
            std::unique_lock<std::mutex> lk(mu_);
            cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
            if (stop_ && jobs_.empty()) return;
            job = std::move(jobs_.back());
            jobs_.pop_back();
          }
          job();
          if (--pending_ == 0) {
            std::unique_lock<std::mutex> lk(mu_);
            done_cv_.notify_all();
          }
        }
      });
    }
  }

  ~ThreadPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  void run(std::function<void()> job) {
    ++pending_;
    {
      std::unique_lock<std::mutex> lk(mu_);
      jobs_.push_back(std::move(job));
    }
    cv_.notify_one();
  }

  void wait() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return pending_.load() == 0; });
  }

  int size() const { return static_cast<int>(workers_.size()); }

 private:
  std::vector<std::thread> workers_;
  std::vector<std::function<void()>> jobs_;
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  bool stop_;
  std::atomic<int> pending_;
};

ThreadPool& pool() {
  static ThreadPool p(std::max(1u, std::thread::hardware_concurrency() / 2));
  return p;
}

inline void adam_span(float* __restrict p, const float* __restrict g,
                      float* __restrict m, float* __restrict v, int64_t n,
                      float lr, float beta1, float beta2, float eps,
                      float weight_decay, bool adamw, float bc1, float bc2) {
  const float one_m_b1 = 1.0f - beta1;
  const float one_m_b2 = 1.0f - beta2;
  // single contiguous loop: clang/gcc vectorize this to the native ISA
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (!adamw && weight_decay != 0.0f) grad += weight_decay * p[i];
    float mi = beta1 * m[i] + one_m_b1 * grad;
    float vi = beta2 * v[i] + one_m_b2 * grad * grad;
    m[i] = mi;
    v[i] = vi;
    float update = (mi / bc1) / (std::sqrt(vi / bc2) + eps);
    if (adamw && weight_decay != 0.0f) update += weight_decay * p[i];
    p[i] -= lr * update;
  }
}

}  // namespace

extern "C" {

// One fused Adam sweep over a flat fp32 buffer, parallelized across the
// host thread pool (reference ds_adam_step, csrc/adam/cpu_adam_impl.cpp).
void ds_host_adam_step(float* params, const float* grads, float* exp_avg,
                       float* exp_avg_sq, int64_t n, int32_t step, float lr,
                       float beta1, float beta2, float eps,
                       float weight_decay, int32_t adamw_mode) {
  const float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  const float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  const int nthreads = pool().size();
  const int64_t chunk = std::max<int64_t>((n + nthreads - 1) / nthreads,
                                          1 << 16);
  for (int64_t off = 0; off < n; off += chunk) {
    const int64_t len = std::min(chunk, n - off);
    pool().run([=] {
      adam_span(params + off, grads + off, exp_avg + off, exp_avg_sq + off,
                len, lr, beta1, beta2, eps, weight_decay, adamw_mode != 0,
                bc1, bc2);
    });
  }
  pool().wait();
}

// Host Adagrad sweep (reference csrc/adagrad/cpu_adagrad.cpp).
void ds_host_adagrad_step(float* params, const float* grads,
                          float* exp_avg_sq, int64_t n, float lr, float eps,
                          float weight_decay) {
  const int nthreads = pool().size();
  const int64_t chunk = std::max<int64_t>((n + nthreads - 1) / nthreads,
                                          1 << 16);
  for (int64_t off = 0; off < n; off += chunk) {
    const int64_t len = std::min(chunk, n - off);
    float* p = params + off;
    const float* g = grads + off;
    float* s = exp_avg_sq + off;
    pool().run([=] {
      for (int64_t i = 0; i < len; ++i) {
        float grad = g[i];
        if (weight_decay != 0.0f) grad += weight_decay * p[i];
        s[i] += grad * grad;
        p[i] -= lr * grad / (std::sqrt(s[i]) + eps);
      }
    });
  }
  pool().wait();
}

// Host Lion sweep (reference csrc/lion/cpu_lion_impl.cpp): sign of the
// interpolated momentum, decoupled weight decay.
void ds_host_lion_step(float* params, const float* grads, float* exp_avg,
                       int64_t n, float lr, float beta1, float beta2,
                       float weight_decay) {
  const float one_m_b1 = 1.0f - beta1;
  const float one_m_b2 = 1.0f - beta2;
  const int nthreads = pool().size();
  const int64_t chunk = std::max<int64_t>((n + nthreads - 1) / nthreads,
                                          1 << 16);
  for (int64_t off = 0; off < n; off += chunk) {
    const int64_t len = std::min(chunk, n - off);
    float* p = params + off;
    const float* g = grads + off;
    float* m = exp_avg + off;
    pool().run([=] {
      for (int64_t i = 0; i < len; ++i) {
        const float c = beta1 * m[i] + one_m_b1 * g[i];
        const float u = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
        p[i] -= lr * (u + weight_decay * p[i]);
        m[i] = beta2 * m[i] + one_m_b2 * g[i];
      }
    });
  }
  pool().wait();
}

// bf16 (stored as uint16) -> fp32 widening copy, vectorizable; used when
// grads arrive from device in bf16 (reference: cpu_adam half paths).
void ds_bf16_to_f32(const uint16_t* src, float* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
    std::memcpy(&dst[i], &bits, sizeof(float));
  }
}

// fp32 -> bf16 round-to-nearest-even (matches XLA's convert).
void ds_f32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    std::memcpy(&bits, &src[i], sizeof(float));
    uint32_t lsb = (bits >> 16) & 1u;
    uint32_t rounded = bits + 0x7FFFu + lsb;
    dst[i] = static_cast<uint16_t>(rounded >> 16);
  }
}

// L2 norm over a flat buffer (overflow/clip support on host).
double ds_l2_norm_sq(const float* x, int64_t n) {
  double acc = 0.0;
  for (int64_t i = 0; i < n; ++i) acc += static_cast<double>(x[i]) * x[i];
  return acc;
}

}  // extern "C"
