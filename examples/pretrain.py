"""Pretrain a Llama-family model from scratch with ZeRO-3 + bf16.

Usage (single host; the mesh spans all visible devices):
    python examples/pretrain.py --size tiny --steps 20
On CPU for a dry run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pretrain.py --size tiny --steps 5

The config dict is the same JSON schema the reference accepts
(train_micro_batch_size_per_gpu / zero_optimization / bf16 / ...).
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="tiny")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--micro-batch", type=int, default=1)
    ap.add_argument("--zero-stage", type=int, default=3)
    args = ap.parse_args()

    from _common import setup_jax
    jax = setup_jax()
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.llama import llama3_config

    ds.build_mesh(data=len(jax.devices()))
    model = llama3_config(args.size, max_seq_len=args.seq)
    on_tpu = jax.default_backend() == "tpu"
    engine, _, _, _ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": args.micro_batch,
            "optimizer": {"type": "adamw",
                          "params": {"lr": 3e-4, "weight_decay": 0.1}},
            "zero_optimization": {"stage": args.zero_stage},
            "bf16": {"enabled": on_tpu},
            "gradient_clipping": 1.0,
            "activation_checkpointing": {
                "policy": "save_attn_out" if on_tpu else "none"},
            "steps_per_print": 10,
        },
        rng=jax.random.PRNGKey(0))

    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(
            0, model.vocab_size, size=(gb, args.seq), dtype=np.int32)}
        loss = engine.train_batch(iter([batch]))
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss):.4f}")
    engine.save_checkpoint("/tmp/dstpu_pretrain_ckpt")
    print("checkpoint saved to /tmp/dstpu_pretrain_ckpt")


if __name__ == "__main__":
    main()
