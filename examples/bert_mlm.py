"""Masked-LM fine-tuning for BERT-class encoders (the reference's
encoder path: module_inject/containers/bert.py served encoders; the
1-bit Adam benchmarks were BERT pretraining).

    # random-init BERT-base, synthetic data, 2-way data parallel
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/bert_mlm.py --steps 20

    # or fine-tune a real HF checkpoint
    python examples/bert_mlm.py --model-dir /path/to/hf_bert --steps 20

The batch contract for encoders: ``input_ids`` (with [MASK]
corruptions), ``labels`` (-100 everywhere except masked positions),
optional ``attention_mask`` (1 = real, 0 = pad — correctness-critical
for bidirectional attention) and ``token_type_ids``.
"""

import argparse

import numpy as np

from _common import setup_jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-dir", default=None,
                    help="HF BERT/DistilBERT dir; default random init")
    ap.add_argument("--size", default="base",
                    help="preset when no --model-dir (tiny|base|large)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--mask-prob", type=float, default=0.15)
    ap.add_argument("--zero-stage", type=int, default=2)
    args = ap.parse_args()

    jax = setup_jax()
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel.mesh import build_mesh

    params = None
    if args.model_dir:
        import jax.numpy as jnp
        from deepspeed_tpu.models.hf_loader import load_hf_checkpoint
        cfg, params = load_hf_checkpoint(args.model_dir)
        params = jax.tree.map(jnp.asarray, params)
    else:
        from deepspeed_tpu.models import bert_config
        cfg = bert_config(args.size, max_seq_len=args.seq)

    n = min(2, len(jax.devices()))
    build_mesh(data=n, devices=jax.devices()[:n])
    engine, _, _, _ = ds.initialize(
        model=cfg, params=params,
        config={"train_micro_batch_size_per_gpu": args.batch // n,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": args.zero_stage}},
        rng=jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    mask_id = 103 if cfg.vocab_size > 103 else 0   # BERT [MASK]
    for step in range(args.steps):
        tokens = rng.integers(1000 if cfg.vocab_size > 2000 else 1,
                              cfg.vocab_size,
                              size=(args.batch, args.seq), dtype=np.int32)
        labels = np.full_like(tokens, -100)
        m = rng.random(tokens.shape) < args.mask_prob
        labels[m] = tokens[m]
        corrupted = tokens.copy()
        corrupted[m] = mask_id
        loss = engine.train_batch(iter([{"input_ids": corrupted,
                                         "labels": labels}]))
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: mlm_loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
