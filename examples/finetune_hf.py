"""Fine-tune a HuggingFace safetensors checkpoint (LoRA optional), then
export back to HF format.

    python examples/finetune_hf.py --model-dir /path/to/hf_llama \
        --steps 10 --export-dir /tmp/finetuned_hf

Load + --export-dir re-export work for all 14 in-tree families (Llama/
Mistral/Mixtral/Qwen2/Qwen2-MoE/GPT-NeoX/Gemma/GPT-2/OPT/BLOOM/
Falcon/Phi/Phi-3/GPT-BigCode)
(models/hf_loader.py maps names both directions; logits parity is tested
in tests/test_hf_interop.py).
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--zero-stage", type=int, default=3)
    ap.add_argument("--export-dir", default=None)
    args = ap.parse_args()

    from _common import setup_jax
    jax = setup_jax()
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.hf_loader import (export_hf_checkpoint,
                                                load_hf_checkpoint)

    cfg, params = load_hf_checkpoint(args.model_dir)
    ds.build_mesh(data=len(jax.devices()))
    engine, _, _, _ = ds.initialize(
        model=cfg, params=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-5}},
            "zero_optimization": {"stage": args.zero_stage},
            "bf16": {"enabled": jax.default_backend() == "tpu"},
        },
        rng=jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    gb = int(engine.config.train_batch_size)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(
            0, cfg.vocab_size, size=(gb, args.seq), dtype=np.int32)}
        loss = engine.train_batch(iter([batch]))
        print(f"step {step}: loss {float(loss):.4f}")

    if args.export_dir:
        # export_hf_checkpoint gathers + casts to fp32 internally
        export_hf_checkpoint(cfg, engine.params, args.export_dir)
        print(f"exported HF checkpoint to {args.export_dir}")


if __name__ == "__main__":
    main()
