"""Serve a model with continuous batching (ragged/paged engine — the
FastGen analogue) or the simpler padded v1 engine.

    python examples/serve.py --engine ragged --prompts "hello" "the sky"

``--stream`` routes the ragged engine through the serving frontend
(deepspeed_tpu/serving/): prefix-cached admission, SplitFuse token-budget
scheduling and per-token streaming; ``--concurrency`` caps how many
requests are in flight at once (the rest wait in the admission queue).
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", choices=("ragged", "v1"), default="ragged")
    ap.add_argument("--model-dir", default=None,
                    help="HF checkpoint dir; random tiny llama if unset")
    ap.add_argument("--prompts", nargs="+", default=["1 2 3 4"])
    ap.add_argument("--max-new-tokens", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--weight-quant", choices=("int8", "fp8", "int4"),
                    default=None,
                    help="weight-only quantized serving (half or quarter "
                         "the weight HBM; ops/quantized_linear.py)")
    ap.add_argument("--stream", action="store_true",
                    help="drive the ServingFrontend and print tokens as "
                         "they are produced (ragged engine only)")
    ap.add_argument("--concurrency", type=int, default=0,
                    help="with --stream: max requests in flight at once "
                         "(0 = engine max_sequences)")
    ap.add_argument("--megastep", type=int, default=0, metavar="K",
                    help="with --stream: fuse up to K decode iterations "
                         "into one device program when the batch is "
                         "decode-only (docs/serving.md; 0 = stepwise)")
    args = ap.parse_args()

    from _common import setup_jax
    jax = setup_jax()
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.llama import llama3_config

    ds.build_mesh(data=1, devices=jax.devices()[:1])
    params = None
    if args.model_dir:
        from deepspeed_tpu.models.hf_loader import load_hf_checkpoint
        cfg, params = load_hf_checkpoint(args.model_dir)
        try:
            from transformers import AutoTokenizer
            tok = AutoTokenizer.from_pretrained(args.model_dir)
        except Exception:
            tok = None
    else:
        cfg, tok = llama3_config("tiny", max_seq_len=512), None

    def encode(p):
        if tok is not None:
            return tok(p)["input_ids"]
        return [int(x) % cfg.vocab_size for x in p.split()]

    prompts = [encode(p) for p in args.prompts]
    eng_cfg = {}
    if args.weight_quant:
        eng_cfg["weight_quant"] = args.weight_quant
    if args.stream:
        from deepspeed_tpu.inference.engine_v2 import RaggedInferenceEngineTPU
        from deepspeed_tpu.serving import ServingFrontend
        eng = RaggedInferenceEngineTPU(cfg, eng_cfg or None, params=params)
        if args.concurrency:
            eng.config.max_sequences = min(eng.config.max_sequences,
                                           args.concurrency)
        fe = ServingFrontend(eng, megastep_tokens=args.megastep)

        def cb_for(i):
            def cb(t):
                piece = tok.decode([t]) if tok is not None else str(t)
                print(f"[{i}] {piece}", flush=True)
            return cb

        reqs = [fe.submit(p, max_new_tokens=args.max_new_tokens,
                          stream_cb=cb_for(i))
                for i, p in enumerate(prompts)]
        fe.run_until_idle()
        outs = [r.tokens_out for r in reqs]
        stats = fe.stats()
        print(f"# engine_steps={stats['engine_steps']} "
              f"prefix_hit_rate={stats.get('prefix_hit_rate', 0.0):.2f} "
              f"ttft_mean={stats['ttft']['mean']:.4f}s")
    elif args.engine == "ragged":
        from deepspeed_tpu.inference.engine_v2 import RaggedInferenceEngineTPU
        eng = RaggedInferenceEngineTPU(cfg, eng_cfg or None, params=params)
        outs = eng.generate(prompts, max_new_tokens=args.max_new_tokens,
                            temperature=args.temperature)
    else:
        from deepspeed_tpu.inference.engine import InferenceEngineTPU
        eng = InferenceEngineTPU(cfg, eng_cfg or None, params=params)
        outs = eng.generate(prompts, max_new_tokens=args.max_new_tokens,
                            temperature=args.temperature)
    for p, o in zip(args.prompts, outs):
        text = tok.decode(o) if tok is not None else " ".join(map(str, o))
        print(f"> {p}\n{text}\n")


if __name__ == "__main__":
    main()
