"""Shared example bootstrap."""

import os


def setup_jax():
    """Import jax honoring the JAX_PLATFORMS env var even when a site
    hook (e.g. a remote-TPU tunnel plugin) overrides it programmatically —
    the config knob set after import wins."""
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    return jax
