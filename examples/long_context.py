"""Long-context training: Ulysses or ring sequence parallelism.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context.py --sp 4 --mode ulysses --seq 2048
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--mode", choices=("ulysses", "ring"), default="ulysses")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    from _common import setup_jax
    jax = setup_jax()
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.llama import llama3_config

    n = len(jax.devices())
    ds.build_mesh(data=n // args.sp, seq=args.sp)
    model = llama3_config("tiny", max_seq_len=args.seq)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
            "zero_optimization": {"stage": 1},
            "sequence_parallel": {"size": args.sp, "mode": args.mode},
        },
        rng=jax.random.PRNGKey(0))

    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(
            0, model.vocab_size, size=(gb, args.seq), dtype=np.int32)}
        loss = engine.train_batch(iter([batch]))
        print(f"step {step}: loss {float(loss):.4f} "
              f"(seq {args.seq} over sp={args.sp} {args.mode})")


if __name__ == "__main__":
    main()
