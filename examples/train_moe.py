"""Train a Mixtral-family MoE with expert parallelism or dropless routing.

Usage (single host; the mesh spans all visible devices):
    python examples/train_moe.py --ep 4 --steps 20          # capacity + EP
    python examples/train_moe.py --impl dropless --steps 20 # dropless, EP=1
On CPU for a dry run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_moe.py --ep 4 --steps 3

Two routing modes (docs/parallelism.md "EP"):
- capacity (reference GShard semantics, deepspeed/moe/sharded_moe.py):
  static per-expert capacity, over-capacity tokens dropped, shards over
  the 'expert' mesh axis.
- dropless (TPU-native extra): sort + lax.ragged_dot grouped matmul —
  no token drops, no capacity padding; requires ep_size=1.
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--impl", default="capacity",
                    choices=["capacity", "dropless"])
    ap.add_argument("--capacity-factor", type=float, default=2.0)
    args = ap.parse_args()

    from _common import setup_jax
    jax = setup_jax()
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.mixtral import mixtral_config

    n = len(jax.devices())
    ds.build_mesh(data=n // args.ep, expert=args.ep)
    model = mixtral_config("tiny", max_seq_len=args.seq)
    on_tpu = jax.default_backend() == "tpu"
    engine, _, _, _ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adamw",
                          "params": {"lr": 3e-4, "weight_decay": 0.1}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": on_tpu},
            "gradient_clipping": 1.0,
            "moe": {"enabled": True, "ep_size": args.ep,
                    "num_experts": model.num_experts,
                    "impl": args.impl,
                    "capacity_factor": args.capacity_factor},
            "steps_per_print": 5,
        },
        rng=jax.random.PRNGKey(0))

    gb = int(engine.config.train_batch_size)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, model.vocab_size,
                                       size=(gb, args.seq),
                                       dtype=np.int32)}
    for step in range(args.steps):
        loss = float(engine.train_batch(iter([batch])))
    print(f"moe {args.impl} ep={args.ep} final loss={loss:.4f}")


if __name__ == "__main__":
    main()
