#!/usr/bin/env python
"""Inference throughput: ragged continuous batching vs padded v1.

The VERDICT r1 'done' criterion for the paged-attention work: a
single-chip throughput number for mixed-length decode, ragged vs the
padded path (reference claim context: FastGen's up-to-2.3x effective
throughput vs padded serving, blogs/deepspeed-fastgen).

Workload: a batch of prompts with a long tail of lengths (the serving
case padding punishes); both engines decode the same number of new
tokens; metric = generated tokens / wall second (best-of-3 per engine).
NOTE: on remote/tunneled runtimes every host call costs ~20 ms, so the
end-to-end ratio measures per-step HOST work; the compiled decode-step
latencies (0.86 ms ragged vs 1.5 ms padded on v5e) are the device-side
comparison. Prints ONE JSON line.
"""

import argparse
import json
import sys
import time

import numpy as np


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default=None)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--n-prompts", type=int, default=16)
    ap.add_argument("--no-pallas", action="store_true")
    ap.add_argument("--quant", nargs="?", const="int8", default=None,
                    choices=("int8", "fp8", "int4", "fp6"),
                    help="weight-only quantized serving (bare flag = "
                         "int8; int4 quarters the decode weight fetch)")
    args = ap.parse_args()

    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    size = args.size or ("1b" if on_tpu else "tiny")

    import deepspeed_tpu as ds
    from deepspeed_tpu.inference import (RaggedInferenceEngineTPU,
                                         init_inference)
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.models.transformer import init_params

    ds.build_mesh(data=1, devices=jax.devices()[:1])
    seq_cap = 1024
    model = llama3_config(size, max_seq_len=seq_cap, tie_embeddings=True)
    dtype = "bfloat16" if on_tpu else "float32"
    params = None   # random weights; throughput doesn't depend on values

    if args.quant:
        # ONE host-side init shared by both engines, pre-quantized on the
        # host (numpy init: single-core threefry for 8B params costs ~25
        # min; values don't matter for throughput). Both engines accept
        # pre-quantized trees (the bin/dstpu_quantize serving path), so
        # full-precision weights never touch HBM — int4 llama-8B serves
        # on one 16G chip.
        from deepspeed_tpu.ops.quantized_linear import quantize_param_tree
        shapes = jax.eval_shape(
            lambda r: init_params(model, r), jax.random.PRNGKey(0))
        host_rng = np.random.default_rng(0)

        def np_leaf(s):
            flat = host_rng.standard_normal(int(np.prod(s.shape)),
                                            dtype=np.float32) * 0.02
            return flat.reshape(s.shape).astype(s.dtype)

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = quantize_param_tree(jax.tree.map(np_leaf, shapes),
                                         mode=args.quant)

    rng = np.random.default_rng(0)
    # long-tail prompt lengths: few long, many short (padding's worst case)
    lens = rng.integers(16, 512, size=args.n_prompts)
    lens[: max(1, args.n_prompts // 8)] = 512
    prompts = [rng.integers(0, model.vocab_size, size=(int(n),),
                            dtype=np.int32) for n in lens]
    new = args.new_tokens

    # ---- padded v1: one batch padded to the longest prompt
    # (pre-quantized trees carry their own scales — weight_quant stays
    # unset; the engines detect quantized leaves)
    v1 = init_inference(model, {"dtype": dtype},
                        params=params, rng=jax.random.PRNGKey(0))
    width = int(max(lens))
    padded = np.zeros((args.n_prompts, width), np.int32)
    for i, p in enumerate(prompts):
        padded[i, width - len(p):] = p      # left-pad
    v1.generate(padded, max_new_tokens=new)              # compile real shapes
    # best-of-3: the generation loop is host-dispatch-bound on remote
    # runtimes, so single runs carry ±15% scheduler noise
    t_padded = min(_timed(lambda: v1.generate(padded, max_new_tokens=new))
                   for _ in range(3))

    # ---- ragged v2: continuous batching over the true lengths
    # arena sized to the workload: the flat 512-block default costs
    # nb*block*L*kvh*dh*4 bytes (17 GB at llama-8B dims — more than HBM);
    # the measured workload needs ceil((prompt+new)/block) blocks/seq
    block = 64
    blocks_per_seq = -(-(seq_cap + new) // block)
    num_blocks = max(128, args.n_prompts * blocks_per_seq + 16)
    v2 = RaggedInferenceEngineTPU(
        model, {"dtype": dtype, "num_blocks": num_blocks,
                "block_size": block,
                "max_seq_len": seq_cap, "prefill_chunk": 512,
                "max_batch_tokens": 8192,
                "use_pallas": (False if args.no_pallas else None)},
        params=params if args.quant else v1.params,
        rng=jax.random.PRNGKey(0))
    v2.generate(prompts, max_new_tokens=new)             # compile real buckets
    t_ragged = min(_timed(lambda: v2.generate(prompts, max_new_tokens=new))
                   for _ in range(3))

    gen_tokens = args.n_prompts * new
    result = {
        "metric": f"ragged vs padded decode llama3-{size} "
                  f"{args.n_prompts} mixed-length prompts"
                  + (f" {args.quant}" if args.quant else ""),
        "value": round(gen_tokens / t_ragged, 2),
        "unit": "gen tokens/s (ragged)",
        "vs_baseline": round(t_padded / t_ragged, 4),
        "extra": {
            "padded_tok_s": round(gen_tokens / t_padded, 2),
            "ragged_tok_s": round(gen_tokens / t_ragged, 2),
            "speedup": round(t_padded / t_ragged, 3),
            "prompt_lens": [int(x) for x in lens],
            "new_tokens": new,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
