#!/usr/bin/env python
"""Serving throughput: ragged continuous batching vs padded batches.

Reference claim context: FastGen's up-to-2.3x effective throughput vs
padded serving (blogs/deepspeed-fastgen/README.md:28). The workload is a
REQUEST STREAM with long-tail prompt AND generation lengths, served at a
fixed concurrency: the ragged engine (v2.serve) backfills freed slots
from the queue between device-resident fused-decode chunks, while the
padded v1 engine processes arrival-order static batches, each run to its
longest request. Metric = total generated tokens / wall second
(best-of-3 per engine); extra.uniform_gen carries a closed-batch
uniform-length comparison that strips the retirement/backfill advantage.
Prints ONE JSON line.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _roofline_extra(eng) -> dict:
    """Compile-time prefill/decode roofline stamp (telemetry/explain)
    for the result line's extra; {} on any failure — the stamp must
    never break the headline JSON."""
    try:
        recs = eng.cost_records()
        return {lbl: {
            "flops": recs[lbl]["flops"],
            "bytes_accessed": recs[lbl]["bytes_accessed"],
            "predicted_step_ms": round(
                recs[lbl]["predicted_s"] * 1e3, 4),
            "bound": recs[lbl]["bound"],
        } for lbl in ("prefill", "decode") if not recs[lbl].get("error")}
    except Exception:
        return {}


def _slo_extra() -> dict:
    """SLO stamp for the BENCH JSON line. DSTPU_BENCH_SLO=";"-separated
    objective strings (e.g. ``serving/ttft_seconds:p95 <= 0.5``) arms a
    one-shot evaluation: the final registry state is flushed through an
    in-memory metric history and judged by the burn-rate engine. Always
    returns a stamp (zeros when unarmed) so trajectory files stay
    uniform; never breaks the headline JSON."""
    spec = os.environ.get("DSTPU_BENCH_SLO")
    if not spec:
        return {"objectives": 0, "evaluated": 0, "worst_burn": 0.0,
                "breached": []}
    try:
        from deepspeed_tpu.telemetry.registry import registry
        from deepspeed_tpu.telemetry.slo import engine_from_config
        from deepspeed_tpu.telemetry.timeseries import MetricHistory
        hist = MetricHistory()                       # memory-only
        slo = engine_from_config({"objectives": [
            s.strip() for s in spec.split(";") if s.strip()]})
        slo.publish = False
        hist.subscribe(slo.observe)
        registry.flush_to_monitor(None, 0, history=hist)
        return slo.summary()
    except Exception as e:               # noqa: BLE001
        return {"error": str(e)[:200]}


def _trace_exemplars_extra() -> dict:
    """Worst-TTFT / worst-TPOT exemplar trace_ids for the BENCH JSON
    line (request tracing's latency exemplars — telemetry/reqtrace):
    the exact traces to open with ``dstpu-trace --request`` when this
    run's tail regresses. {} when tracing is off or no exemplar was
    recorded; never breaks the headline JSON."""
    try:
        from deepspeed_tpu.telemetry.registry import registry
        out = {}
        for short, name in (("worst_ttft", "serving/ttft_seconds"),
                            ("worst_tpot", "serving/tpot_seconds"),
                            ("worst_router_ttft", "router/ttft_seconds")):
            m = registry.get(name)
            ex = (m.worst_exemplar()
                  if hasattr(m, "worst_exemplar") else None)
            if ex is not None:
                out[short] = {"trace_id": ex[0],
                              "value_s": round(ex[1], 6)}
        return out
    except Exception:                                # noqa: BLE001
        return {}


def _goodput_extra() -> dict:
    """Final goodput-ledger sweep → the BENCH ``extra.goodput`` stamp
    (uptime attribution + dominant badput). {} when the ledger is off or
    on any failure; never breaks the headline JSON."""
    try:
        from deepspeed_tpu.telemetry.goodput import goodput_ledger
        if not goodput_ledger.enabled:
            return {}
        goodput_ledger.update()
        s = goodput_ledger.summary() or {}
        return {k: s.get(k) for k in
                ("uptime_s", "goodput_s", "fraction", "window_fraction",
                 "badput", "dominant_badput", "dominant_badput_s",
                 "captures")} if s else {}
    except Exception:                                # noqa: BLE001
        return {}


def bench_shared_prefix(args) -> None:
    """serving-frontend scenario: a stream of prompts sharing a 50%
    prefix (system prompt / few-shot preamble), served through
    deepspeed_tpu/serving with the radix prefix cache ON vs OFF. Cache
    hits alias the shared pages and skip their prefill entirely, so with
    prefill-dominated requests (short generations) requests/sec should
    approach 2x; the CI floor is 1.5x. Prints ONE JSON line."""
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    size = args.size or ("1b" if on_tpu else "tiny")

    import deepspeed_tpu as ds
    from deepspeed_tpu.inference import RaggedInferenceEngineTPU
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.serving import ServingFrontend

    ds.build_mesh(data=1, devices=jax.devices()[:1])
    seq_cap = 512
    model = llama3_config(size, max_seq_len=seq_cap, tie_embeddings=True)
    dtype = "bfloat16" if on_tpu else "float32"

    rng = np.random.default_rng(0)
    n_req = args.n_requests
    conc = args.n_prompts
    plen, share, new = 384, 192, 4          # 50%-shared, prefill-heavy
    prefix = rng.integers(0, model.vocab_size, size=share)
    prompts = [
        np.concatenate([prefix, rng.integers(0, model.vocab_size,
                                             size=plen - share)])
        for _ in range(n_req)]

    # prefill_chunk 32: a sequence advances ONE chunk per engine step, so
    # the cold run pays plen/32 prefill rounds and the cached run only
    # (plen-share)/32 — on CPU each step costs near-flat wall time
    # (dispatch-bound at tiny sizes), so the request-rate ratio tracks
    # the step-count ratio the cache actually removes
    block = 32
    blocks_per_seq = -(-(plen + new) // block)
    eng = RaggedInferenceEngineTPU(
        model, {"dtype": dtype,
                "num_blocks": conc * blocks_per_seq + blocks_per_seq + 32,
                "block_size": block, "max_seq_len": seq_cap,
                "prefill_chunk": 32, "max_batch_tokens": 2048,
                "max_sequences": conc,
                "use_pallas": (False if args.no_pallas else None)},
        rng=jax.random.PRNGKey(0))

    def run(fe):
        reqs = [fe.submit([int(t) for t in p], max_new_tokens=new)
                for p in prompts]
        fe.run_until_idle()
        assert all(len(r.tokens_out) == new for r in reqs)

    fe_cold = ServingFrontend(eng, max_queue=n_req,
                              enable_prefix_cache=False)
    run(fe_cold)                                     # compile real buckets
    t_cold = min(_timed(lambda: run(fe_cold)) for _ in range(2))
    fe_hot = ServingFrontend(eng, max_queue=n_req)
    run(fe_hot)                        # warm: populates the radix cache
    t_hot = min(_timed(lambda: run(fe_hot)) for _ in range(2))

    result = {
        "metric": f"serving frontend prefix cache llama3-{size}, "
                  f"{n_req} req stream @ conc {conc}, "
                  f"{share}/{plen} shared prefix",
        "value": round(n_req / t_hot, 2),
        "unit": "requests/s (prefix cache on)",
        "vs_baseline": round(t_cold / t_hot, 4),
        "extra": {
            "nocache_req_s": round(n_req / t_cold, 2),
            "cache_req_s": round(n_req / t_hot, 2),
            "speedup": round(t_cold / t_hot, 3),
            "prefix_hit_rate": round(fe_hot.cache.hit_rate, 3),
            "prefix_tokens_reused":
                fe_hot.metrics.counters["prefix_tokens_reused"],
            "engine_steps_cache":
                fe_hot.metrics.counters["engine_steps"],
            "engine_steps_nocache":
                fe_cold.metrics.counters["engine_steps"],
            "ttft_mean_s": round(fe_hot.metrics.ttft.mean, 4),
            "roofline": _roofline_extra(eng),
            "slo": _slo_extra(),
            "trace_exemplars": _trace_exemplars_extra(),
        },
    }
    print(json.dumps(result))


def bench_router(args) -> None:
    """multi-replica scenario: the SAME shared-prefix request stream
    served through the fault-tolerant router over ``--replicas N``
    in-process replicas, optionally under a ``--chaos`` fault plan
    (e.g. ``serving_step:8:replica_kill:router``). Stamps per-replica
    tok/s, failover count and recovery time into the BENCH JSON; when
    the plan degrades a replica (``replica_slow``), runs a hedging A/B
    (same stream, hedge off vs on) and stamps the p99 TTFT improvement
    hedged dispatch buys back. Prints ONE JSON line."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference import RaggedInferenceEngineTPU
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.models.transformer import init_params
    from deepspeed_tpu.resilience.faults import fault_injector
    from deepspeed_tpu.serving import LocalReplica, Router, ServingFrontend

    on_tpu = jax.devices()[0].platform == "tpu"
    size = args.size or ("1b" if on_tpu else "tiny")
    ds.build_mesh(data=1, devices=jax.devices()[:1])
    seq_cap = 256
    model = llama3_config(size, max_seq_len=seq_cap, tie_embeddings=True)
    dtype = "bfloat16" if on_tpu else "float32"
    params = init_params(model, jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    n_req = args.n_requests
    conc = min(args.n_prompts, 16)
    new = max(2, min(args.new_tokens, 16))
    plen, share = 48, 24                      # 50%-shared → affinity work
    prefix = rng.integers(0, model.vocab_size, size=share)
    prompts = [np.concatenate(
        [prefix, rng.integers(0, model.vocab_size, size=plen - share)])
        for _ in range(n_req)]
    block = 16
    blocks_per_seq = -(-(plen + new) // block)
    eng_cfg = {"dtype": dtype,
               "num_blocks": conc * blocks_per_seq + blocks_per_seq + 16,
               "block_size": block, "max_seq_len": seq_cap,
               "prefill_chunk": 32, "max_batch_tokens": 1024,
               "max_sequences": conc,
               "use_pallas": (False if args.no_pallas else None)}

    c = telemetry.registry.counter

    def run_pool(hedge: bool) -> dict:
        """One fresh pool + router over the stream; per-mode counter
        deltas so A/B modes don't bleed into each other."""
        replicas = [
            LocalReplica(f"r{i}", ServingFrontend(
                RaggedInferenceEngineTPU(model, dict(eng_cfg),
                                         params=params),
                max_queue=n_req, enable_prefix_cache=False))
            for i in range(args.replicas)]
        router = Router(replicas, hedge=hedge,
                        hedge_delay_s=args.hedge_delay)
        # warm every replica's compile buckets before arming chaos so
        # the drill times recovery, not XLA
        warm = [router.submit([int(t) for t in p], max_new_tokens=new)
                for p in prompts[:args.replicas * 2]]
        router.run_until_idle(wall_timeout_s=600.0)
        assert all(w.finish_reason == "length" for w in warm)
        base = {k: c(k).value for k in (
            "router/failovers", "router/hedges", "router/hedges_won",
            "resilience/faults_injected", "resilience/recoveries")}
        if args.chaos:
            fault_injector.arm(args.chaos, _env=False)
        tok0 = dict(router.replica_tokens)
        t0 = time.perf_counter()
        reqs = [router.submit([int(t) for t in p], max_new_tokens=new)
                for p in prompts]
        router.run_until_idle(wall_timeout_s=600.0)
        wall = time.perf_counter() - t0
        fault_injector.disarm()
        toks = sum(len(r.tokens_out) for r in reqs)
        stats = router.stats()
        out = {
            "tok_s": round(toks / wall, 2), "wall_s": round(wall, 3),
            "completed": sum(r.finish_reason == "length" for r in reqs),
            "requests": n_req,
            "replica_tok_s": {
                name: round((stats["replica_tokens"].get(name, 0) -
                             tok0.get(name, 0)) / wall, 2)
                for name in tok0},
            "replica_states": stats["replicas"],
            "failovers": int(c("router/failovers").value -
                             base["router/failovers"]),
            "hedges": int(c("router/hedges").value -
                          base["router/hedges"]),
            "hedges_won": int(c("router/hedges_won").value -
                              base["router/hedges_won"]),
            "recovery_s": stats["last_recovery_s"],
            "ttft_p99_s": round(router.ttft.percentile(99), 4),
            "ledger": {
                "faults": int(c("resilience/faults_injected").value -
                              base["resilience/faults_injected"]),
                "recoveries": int(c("resilience/recoveries").value -
                                  base["resilience/recoveries"])},
        }
        router.close()
        return out

    hedge_ab = None
    if args.chaos and "replica_slow" in args.chaos:
        off = run_pool(hedge=False)
        on = run_pool(hedge=True)
        hedge_ab = {
            "hedge_off": off, "hedge_on": on,
            "p99_ttft_improvement": round(
                off["ttft_p99_s"] / max(1e-9, on["ttft_p99_s"]), 3)}
        headline = on
    else:
        headline = run_pool(hedge=not args.no_hedge)

    result = {
        "metric": f"multi-replica router llama3-{size}, {n_req} req "
                  f"stream @ {args.replicas} replicas"
                  + (f", chaos [{args.chaos}]" if args.chaos else ""),
        "value": headline["tok_s"],
        "unit": "gen tokens/s (router)",
        "vs_baseline": (hedge_ab["p99_ttft_improvement"]
                        if hedge_ab else 1.0),
        "extra": {
            "replicas": args.replicas,
            "chaos": args.chaos,
            **headline,
            "slo": _slo_extra(),
            "trace_exemplars": _trace_exemplars_extra(),
        },
    }
    if hedge_ab is not None:
        result["extra"]["hedge_ab"] = hedge_ab
    print(json.dumps(result))


def bench_returning_sessions(args) -> None:
    """tiered-KV-cache scenario: N conversation sessions are served,
    go idle (their cached prefixes evicted from HBM), then RETURN with
    a follow-up — with the HBM arena sized for ~N/10 resident sessions.
    With the tier ON the evicted pages land in a bounded host-DRAM
    arena and spill onward to NVMe; the returning request's pages are
    prefetched at submit and re-adopted at admission, so warm resume
    pays only the follow-up prefill. With the tier OFF the pages are
    simply freed and every return re-prefills the full folded prompt.
    Headline = re-prefill TTFT / warm-resume TTFT (mean over all
    returns). Prints ONE JSON line."""
    import shutil
    import tempfile

    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.inference import RaggedInferenceEngineTPU
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.serving import ServingFrontend

    on_tpu = jax.devices()[0].platform == "tpu"
    size = args.size or ("1b" if on_tpu else "tiny")
    ds.build_mesh(data=1, devices=jax.devices()[:1])
    seq_cap = 256
    model = llama3_config(size, max_seq_len=seq_cap, tie_embeddings=True)
    dtype = "bfloat16" if on_tpu else "float32"

    rng = np.random.default_rng(0)
    n_sessions = min(args.n_requests, 40)
    conc = 2                    # low concurrency: sessions are IDLE, not
    block, chunk = 16, 16       # in flight — HBM holds the working set
    plen, new, follow, new2 = 192, 8, 16, 8
    blocks_per_seq = -(-(plen + new) // block)        # phase-1 footprint
    num_blocks = conc * (blocks_per_seq + 2) + 2      # ~2 cached sessions
    hbm_sessions = num_blocks // blocks_per_seq
    prompts = [[int(t) for t in rng.integers(0, model.vocab_size,
                                             size=plen)]
               for _ in range(n_sessions)]
    follows = [[int(t) for t in rng.integers(0, model.vocab_size,
                                             size=follow)]
               for _ in range(n_sessions)]

    eng = RaggedInferenceEngineTPU(
        model, {"dtype": dtype, "num_blocks": num_blocks,
                "block_size": block, "max_seq_len": seq_cap,
                "prefill_chunk": chunk, "max_batch_tokens": 256,
                "max_sequences": conc,
                "use_pallas": (False if args.no_pallas else None)},
        rng=jax.random.PRNGKey(0))
    page_nbytes = eng.kv_page_nbytes()
    nvme_dir = tempfile.mkdtemp(prefix="dstpu-kvtier-bench-")

    def run_mode(tier_on: bool) -> dict:
        cfg = {"kvtier": {"enabled": True, "nvme_dir": nvme_dir,
                          "dram_bytes": 60 * page_nbytes,
                          "high_watermark": 0.75, "low_watermark": 0.5,
                          }} if tier_on else None
        fe = ServingFrontend(eng, max_queue=n_sessions + conc, config=cfg)
        steps0 = fe.metrics.counters["engine_steps"]
        # phase 1: serve every session in small waves, then idle them out
        # of HBM entirely (eviction captures to the tier when it's on)
        gens = [None] * n_sessions
        for lo in range(0, n_sessions, conc):
            reqs = [(i, fe.submit(prompts[i], max_new_tokens=new))
                    for i in range(lo, min(lo + conc, n_sessions))]
            fe.run_until_idle()
            for i, r in reqs:
                gens[i] = list(r.tokens_out)
        fe.cache.evict(1 << 30)
        steps_serve = fe.metrics.counters["engine_steps"] - steps0
        # phase 2: every session returns with a follow-up; TTFT per return
        ttfts = []
        for i in range(n_sessions):
            folded = prompts[i] + gens[i] + follows[i]
            t0 = time.perf_counter()
            r = fe.submit(folded, max_new_tokens=new2)
            while not r.tokens_out:
                fe.step()
            ttfts.append(time.perf_counter() - t0)
            fe.run_until_idle()
            assert len(r.tokens_out) == new2
            # the session idles again: evict at IDLE time (captures to
            # the tier when it's on) so the next return's latency window
            # never pays another conversation's demotion
            fe.cache.evict(1 << 30)
        out = {
            "ttft_mean_s": round(sum(ttfts) / len(ttfts), 5),
            "ttft_p50_s": round(sorted(ttfts)[len(ttfts) // 2], 5),
            "ttft_p95_s": round(sorted(ttfts)[
                int(0.95 * (len(ttfts) - 1))], 5),
            "engine_steps_serve": steps_serve,
            "engine_steps_return":
                fe.metrics.counters["engine_steps"] - steps0 - steps_serve,
        }
        if tier_on:
            st = fe.kvtier.stats()
            out["kvtier"] = {k: st[k] for k in (
                "captures", "spills", "adopts", "hits", "misses",
                "prefetch_issued", "dram_pages", "nvme_pages",
                "bytes_spilled", "bytes_adopted")}
        fe.close()
        fe.cache.evict(1 << 30)            # free pages for the next mode
        return out

    warm_fe = ServingFrontend(eng, max_queue=4)      # compile real buckets
    w = warm_fe.submit(prompts[0] + [0] * (new + follow),
                       max_new_tokens=new2)
    warm_fe.run_until_idle()
    assert len(w.tokens_out) == new2
    warm_fe.close()
    warm_fe.cache.evict(1 << 30)

    off = run_mode(tier_on=False)
    on = run_mode(tier_on=True)
    shutil.rmtree(nvme_dir, ignore_errors=True)
    speedup = round(off["ttft_mean_s"] / max(1e-9, on["ttft_mean_s"]), 3)

    result = {
        "metric": f"tiered KV cache llama3-{size}, {n_sessions} returning "
                  f"sessions vs {hbm_sessions}-session HBM arena",
        "value": round(1.0 / max(1e-9, on["ttft_mean_s"]), 2),
        "unit": "warm resumes/s (mean 1/TTFT, tier on)",
        "vs_baseline": speedup,
        "extra": {
            "resident_sessions": n_sessions,
            "hbm_capacity_sessions": hbm_sessions,
            "residency_ratio": round(n_sessions / max(1, hbm_sessions), 1),
            "warm_resume_ttft_s": on["ttft_mean_s"],
            "reprefill_ttft_s": off["ttft_mean_s"],
            "ttft_speedup": speedup,
            "kv_page_bytes": page_nbytes,
            "tier_on": on, "tier_off": off,
            "slo": _slo_extra(),
            "trace_exemplars": _trace_exemplars_extra(),
        },
    }
    print(json.dumps(result))


def bench_diurnal(args) -> None:
    """elasticity scenario: a DISAGGREGATED prefill/decode fleet under a
    diurnal load swing (10x between trough and peak) with the SLO-driven
    autoscaler sizing each pool — replica counts must follow the curve
    while TTFT p95 holds — plus a chaos ``replica_kill`` landing on a
    replica MID-SCALE-DOWN (the drain window), which must still converge
    with the faults==recoveries ledger balanced. Prints ONE JSON line
    with per-phase pool sizes, TTFT p95, scale events, handoff counts
    and the ledger."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference import RaggedInferenceEngineTPU
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.models.transformer import init_params
    from deepspeed_tpu.resilience.faults import fault_injector
    from deepspeed_tpu.serving import (Autoscaler, LocalReplica, Router,
                                       ServingFrontend)

    on_tpu = jax.devices()[0].platform == "tpu"
    size = args.size or ("1b" if on_tpu else "tiny")
    ds.build_mesh(data=1, devices=jax.devices()[:1])
    # goodput ledger over the drill: serving/engine_step spans attribute
    # token work vs idle; the stamp lands in extra.goodput below
    telemetry.tracer.configure(enabled=True)
    telemetry.goodput_ledger.configure(enabled=True)
    seq_cap = 256
    model = llama3_config(size, max_seq_len=seq_cap, tie_embeddings=True)
    dtype = "bfloat16" if on_tpu else "float32"
    params = init_params(model, jax.random.PRNGKey(0))
    new = max(2, min(args.new_tokens, 8))
    eng_cfg = {"dtype": dtype, "num_blocks": 96, "block_size": 8,
               "max_seq_len": seq_cap, "prefill_chunk": 16,
               "max_batch_tokens": 256, "max_sequences": 16,
               "use_pallas": (False if args.no_pallas else None)}

    # --from-config: a dstpu-tune plan drives the fleet knobs — engine
    # SplitFuse budget / prefill chunk / resident sequences from the
    # tune stamp's serving_engine keys, hedge policy from router.*,
    # floors/ceilings/queue knee from autoscale.* (ceilings clamped to
    # this host's drill scale; the scenario's fast timing knobs stay so
    # the drill still converges in CI time)
    tuned = getattr(args, "_tuned_cfg", None)
    tuned_stamp = None
    scaler_kw = {"prefill_min": 1, "prefill_max": 3,
                 "decode_min": 1, "decode_max": 4, "queue_high": 2.0}
    hedge_kw = {"hedge": False}
    serving_kw = {}
    if tuned:
        tuned_stamp = dict(tuned.get("tune") or {})
        se = dict(tuned_stamp.get("serving_engine") or {})
        if se.get("prefill_chunk"):
            eng_cfg["prefill_chunk"] = max(8, min(64, int(
                se["prefill_chunk"])))
        if se.get("max_batch_tokens"):
            eng_cfg["max_batch_tokens"] = max(64, min(1024, int(
                se["max_batch_tokens"])))
        if se.get("max_sequences"):
            eng_cfg["max_sequences"] = max(4, min(16, int(
                se["max_sequences"])))
        rb = dict(tuned.get("router") or {})
        if rb:
            hedge_kw = {"hedge": bool(rb.get("hedge", False)),
                        "hedge_delay_s": rb.get("hedge_delay_s")}
        ab = dict(tuned.get("autoscale") or {})
        if ab:
            scaler_kw = {
                "prefill_min": max(1, min(int(ab.get("prefill_min", 1)),
                                          3)),
                "prefill_max": max(1, min(int(ab.get("prefill_max", 3)),
                                          3)),
                "decode_min": max(1, min(int(ab.get("decode_min", 1)), 4)),
                "decode_max": max(1, min(int(ab.get("decode_max", 4)), 4)),
                "queue_high": max(1.0, float(ab.get("queue_high", 2.0))),
            }
            scaler_kw["prefill_min"] = min(scaler_kw["prefill_min"],
                                           scaler_kw["prefill_max"])
            scaler_kw["decode_min"] = min(scaler_kw["decode_min"],
                                          scaler_kw["decode_max"])
        sb = dict(tuned.get("serving") or {})
        if sb.get("megastep_tokens"):
            serving_kw = {"megastep_tokens": int(sb["megastep_tokens"])}

    frontends = []

    def make_replica(pool: str, name: str) -> LocalReplica:
        eng = RaggedInferenceEngineTPU(model, dict(eng_cfg),
                                       params=params)
        fe = ServingFrontend(eng, max_queue=256, **serving_kw)
        frontends.append(fe)
        return LocalReplica(name, fe, pool=pool)

    spawned = {"prefill": 0, "decode": 0}

    def spawn(pool: str) -> LocalReplica:
        spawned[pool] += 1
        return router.add_replica(
            make_replica(pool, f"{pool[0]}{spawned[pool]}"))

    router = Router([make_replica("prefill", "p0"),
                     make_replica("decode", "d0")], **hedge_kw)
    scaler = Autoscaler(router, spawn_fn=spawn,
                        **scaler_kw, idle_s=0.3, cooldown_s=0.2,
                        evaluate_every_s=0.05, drain_deadline_s=15.0)

    rng = np.random.default_rng(0)
    prefix = rng.integers(0, model.vocab_size, size=8)

    def prompt():
        return [int(t) for t in np.concatenate(
            [prefix, rng.integers(0, model.vocab_size, size=4)])]

    # warm every compile bucket at floor size before measuring — the
    # drill times elasticity and recovery, not XLA
    warm = [router.submit(prompt(), max_new_tokens=new) for _ in range(4)]
    router.run_until_idle(wall_timeout_s=600.0)
    assert all(w.finish_reason in ("length", "eos") for w in warm)

    c = telemetry.registry.counter
    base = {k: c(k).value for k in (
        "resilience/faults_injected", "resilience/recoveries",
        "autoscale/scale_ups", "autoscale/scale_downs",
        "handoff/completed", "router/failovers")}

    def pool_sizes():
        return {p: len(router.pool_members(p))
                for p in ("prefill", "decode")}

    def drive(idle_spin_s: float, arm_kill: bool) -> bool:
        """Poll router + autoscaler until streams finish AND the fleet
        has idled ``idle_spin_s`` (the window where idle scale-down
        fires). ``arm_kill`` arms a replica_kill against the FIRST
        replica seen draining — the mid-scale-down chaos drill."""
        armed = False
        t_idle = None
        while True:
            busy = router.poll()
            scaler.maybe_evaluate()
            if arm_kill and not armed and router._draining:
                victim = sorted(router._draining)[0]
                os.environ["DSTPU_CHAOS_REPLICA"] = victim
                fault_injector.arm(
                    f"serving_step:{router._polls + 1}:"
                    f"replica_kill:router", _env=False)
                armed = True
            if busy:
                t_idle = None
            else:
                now = time.monotonic()
                if t_idle is None:
                    t_idle = now
                if now - t_idle >= idle_spin_s:
                    return armed
            time.sleep(0.001)

    # the diurnal curve: trough → ramp → 10x peak → trough again (the
    # final trough spins long enough for idle scale-down + the kill)
    phases = [("night", 2, 0.0), ("morning", 6, 0.0),
              ("peak", 20, 0.0), ("evening", 2, 1.2)]
    steps0 = sum(fe.metrics.counters["engine_steps"] for fe in frontends)
    t0 = time.perf_counter()
    all_reqs = []
    phase_rows = []
    killed = False
    for name, n_req, idle_spin in phases:
        reqs = [router.submit(prompt(), max_new_tokens=new)
                for _ in range(n_req)]
        all_reqs += reqs
        killed |= drive(idle_spin, arm_kill=(name == "evening"
                                             and not killed))
        phase_rows.append({
            "phase": name, "requests": n_req, "pools": pool_sizes(),
            "ttft_p95_s": (round(router.ttft.percentile(95), 4)
                           if router.ttft.count else None)})
    # convergence: the drain set empties (even with the kill landing
    # mid-drain) and the recovery ledger closes
    deadline = time.monotonic() + 60.0
    while (router._draining or router._pending_recovery or
           router._pending_handoff) and time.monotonic() < deadline:
        router.poll()
        time.sleep(0.002)
    wall = time.perf_counter() - t0
    fault_injector.disarm()
    os.environ.pop("DSTPU_CHAOS_REPLICA", None)
    converged = not router._draining and not router._pending_recovery
    toks = sum(len(r.tokens_out) for r in all_reqs)
    faults = int(c("resilience/faults_injected").value -
                 base["resilience/faults_injected"])
    recoveries = int(c("resilience/recoveries").value -
                     base["resilience/recoveries"])
    peak_pools = max(sum(row["pools"].values()) for row in phase_rows)
    tune_extra = None
    if tuned_stamp is not None:
        # predicted-vs-measured per engine step: the cost model's decode
        # prediction against the drill's mean wall time per engine step
        # (mixed prefill/decode; CPU hosts predict 0 → pct stays None)
        eng_steps = sum(fe.metrics.counters["engine_steps"]
                        for fe in frontends) - steps0
        measured_ms = wall / eng_steps * 1e3 if eng_steps else None
        predicted_ms = None
        try:
            recs = frontends[0].engine.cost_records()
            p = recs.get("decode", {}).get("predicted_s")
            predicted_ms = p * 1e3 if p else None
        except Exception:
            pass
        tune_extra = {
            "config": tuned_stamp.get("_path"),
            "search_key": tuned_stamp.get("search_key"),
            "tuned_platform": tuned_stamp.get("platform"),
            "predicted_ms": predicted_ms,
            "measured_ms": (round(measured_ms, 3)
                            if measured_ms else None),
            "pct_of_roofline": (round(100.0 * predicted_ms / measured_ms,
                                      2)
                                if predicted_ms and measured_ms
                                else None),
            "applied": {"engine": {k: eng_cfg[k] for k in
                                   ("prefill_chunk", "max_batch_tokens",
                                    "max_sequences")},
                        "router": hedge_kw,
                        "autoscale": scaler_kw,
                        "serving": serving_kw},
        }
    result = {
        "metric": f"diurnal elasticity llama3-{size}: disagg "
                  f"prefill/decode fleet, "
                  f"{sum(n for _, n, _ in phases)} req over "
                  f"{len(phases)} phases (10x swing), autoscaler + "
                  f"mid-scale-down replica_kill",
        "value": round(toks / wall, 2),
        "unit": "gen tokens/s (autoscaled fleet)",
        "vs_baseline": 1.0,
        "extra": {
            "phases": phase_rows,
            "final_pools": pool_sizes(),
            "peak_fleet": peak_pools,
            "scale_ups": int(c("autoscale/scale_ups").value -
                             base["autoscale/scale_ups"]),
            "scale_downs": int(c("autoscale/scale_downs").value -
                               base["autoscale/scale_downs"]),
            "handoffs": int(c("handoff/completed").value -
                            base["handoff/completed"]),
            "failovers": int(c("router/failovers").value -
                             base["router/failovers"]),
            "completed": sum(r.finish_reason in ("length", "eos")
                             for r in all_reqs),
            "requests": len(all_reqs),
            "kill_armed": killed,
            "converged": converged,
            "ttft_p95_s": (round(router.ttft.percentile(95), 4)
                           if router.ttft.count else None),
            "ledger": {"faults": faults, "recoveries": recoveries,
                       "balanced": faults == recoveries},
            "slo": _slo_extra(),
            "trace_exemplars": _trace_exemplars_extra(),
            "goodput": _goodput_extra(),
        },
    }
    if tune_extra is not None:
        result["extra"]["tune"] = tune_extra
    router.close()
    print(json.dumps(result))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default=None)
    ap.add_argument("--new-tokens", type=int, default=128,
                    help="max generation length (the long tail)")
    ap.add_argument("--n-prompts", type=int, default=16,
                    help="server concurrency (resident sequences)")
    ap.add_argument("--n-requests", type=int, default=64,
                    help="total requests in the stream")
    ap.add_argument("--no-pallas", action="store_true")
    ap.add_argument("--quant", nargs="?", const="int8", default=None,
                    choices=("int8", "fp8", "int4", "fp6"),
                    help="weight-only quantized serving (bare flag = "
                         "int8; int4 quarters the decode weight fetch)")
    ap.add_argument("--scenario", default="stream",
                    choices=("stream", "shared_prefix_stream", "router",
                             "diurnal", "returning_sessions"),
                    help="stream: ragged vs padded request stream; "
                         "shared_prefix_stream: serving frontend with "
                         "the radix prefix cache on vs off over "
                         "50%%-shared prompts; router: the stream over "
                         "--replicas N fault-tolerant replicas, "
                         "optionally under a --chaos plan; diurnal: "
                         "disaggregated prefill/decode fleet under a "
                         "10x load swing with the autoscaler sizing "
                         "each pool and a replica killed mid-scale-down; "
                         "returning_sessions: N idle sessions return "
                         "against an HBM arena sized for N/10 — warm "
                         "resume from the DRAM/NVMe KV tier vs full "
                         "re-prefill TTFT")
    ap.add_argument("--replicas", type=int, default=3,
                    help="router scenario: replica pool size")
    ap.add_argument("--chaos", default=None, metavar="PLAN",
                    help="router scenario: fault plan armed for the "
                         "measured stream (e.g. 'serving_step:8:"
                         "replica_kill:router'); a replica_slow plan "
                         "triggers the hedging A/B")
    ap.add_argument("--hedge-delay", type=float, default=0.05,
                    help="router scenario: fixed hedge delay seconds "
                         "(default 0.05 for deterministic A/Bs)")
    ap.add_argument("--no-hedge", action="store_true",
                    help="router scenario: disable hedged dispatch")
    ap.add_argument("--from-config", default=None, metavar="JSON",
                    help="drive the diurnal fleet scenario from a "
                         "dstpu-tune emitted config: serving/router/"
                         "autoscale blocks size the drill's knobs and "
                         "extra.tune stamps predicted-vs-measured "
                         "(forces --scenario diurnal)")
    ap.add_argument("--megastep", nargs="?", const=32, type=int,
                    default=None, metavar="K",
                    help="A/B the serving frontend stepwise vs decode "
                         "megasteps of up to K tokens (bare flag = 32) "
                         "on the stream workload, stamping per-mode "
                         "tok/s and host-dispatch calls per token "
                         "(dispatch/host_calls deltas) into the JSON")
    args = ap.parse_args()

    if args.from_config:
        with open(args.from_config) as fh:
            cfg = json.load(fh)
        cfg.setdefault("tune", {})["_path"] = os.path.basename(
            args.from_config)
        args._tuned_cfg = cfg
        args.scenario = "diurnal"

    if args.scenario == "shared_prefix_stream":
        return bench_shared_prefix(args)
    if args.scenario == "router":
        return bench_router(args)
    if args.scenario == "diurnal":
        return bench_diurnal(args)
    if args.scenario == "returning_sessions":
        return bench_returning_sessions(args)

    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    size = args.size or ("1b" if on_tpu else "tiny")

    import deepspeed_tpu as ds
    from deepspeed_tpu.inference import (RaggedInferenceEngineTPU,
                                         init_inference)
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.models.transformer import init_params

    ds.build_mesh(data=1, devices=jax.devices()[:1])
    seq_cap = 1024
    model = llama3_config(size, max_seq_len=seq_cap, tie_embeddings=True)
    dtype = "bfloat16" if on_tpu else "float32"
    params = None   # random weights; throughput doesn't depend on values

    if args.quant:
        # ONE host-side init shared by both engines, pre-quantized on the
        # host (numpy init: single-core threefry for 8B params costs ~25
        # min; values don't matter for throughput). Both engines accept
        # pre-quantized trees (the bin/dstpu_quantize serving path), so
        # full-precision weights never touch HBM — int4 llama-8B serves
        # on one 16G chip.
        from deepspeed_tpu.ops.quantized_linear import quantize_param_tree
        shapes = jax.eval_shape(
            lambda r: init_params(model, r), jax.random.PRNGKey(0))
        host_rng = np.random.default_rng(0)

        def np_leaf(s):
            flat = host_rng.standard_normal(int(np.prod(s.shape)),
                                            dtype=np.float32) * 0.02
            return flat.reshape(s.shape).astype(s.dtype)

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = quantize_param_tree(jax.tree.map(np_leaf, shapes),
                                         mode=args.quant)

    rng = np.random.default_rng(0)
    # A REQUEST STREAM, not one closed batch — the workload shape behind
    # the reference FastGen claim (2.3x effective throughput,
    # blogs/deepspeed-fastgen): n_requests arrive up front, the server
    # runs at most `concurrency` sequences resident. Long-tail prompt
    # lengths AND long-tail generation lengths: most requests finish
    # early, a few run long. The ragged engine backfills freed slots
    # from the queue between fused chunks; the padded engine processes
    # arrival-order batches of `concurrency`, each batch running to ITS
    # longest request.
    n_req = args.n_requests
    conc = args.n_prompts
    lens = rng.integers(16, 512, size=n_req)
    lens[rng.permutation(n_req)[: n_req // 8]] = 512
    prompts = [rng.integers(0, model.vocab_size, size=(int(n),),
                            dtype=np.int32) for n in lens]
    new_list = rng.integers(8, max(9, args.new_tokens // 4), size=n_req)
    new_list[rng.permutation(n_req)[: n_req // 8]] = args.new_tokens
    new = int(max(new_list))

    # ---- padded v1: arrival-order batches of `conc`, each padded to the
    # GLOBAL width bucket (one compile) and run to its own longest
    # request — the batch is static, so early-finished rows compute
    # until the batch's longest request completes. (pre-quantized trees
    # carry their own scales — weight_quant stays unset; the engines
    # detect quantized leaves)
    v1 = init_inference(model, {"dtype": dtype},
                        params=params, rng=jax.random.PRNGKey(0))
    width = int(max(lens))

    def padded_batches():
        for lo in range(0, n_req, conc):
            chunk = prompts[lo:lo + conc]
            padded = np.zeros((conc, width), np.int32)
            for i, p in enumerate(chunk):
                padded[i, width - len(p):] = p      # left-pad
            yield padded, int(max(new_list[lo:lo + conc]))

    def run_padded():
        for padded, batch_new in padded_batches():
            v1.generate(padded, max_new_tokens=batch_new)

    run_padded()                                      # compile real shapes
    # best-of-3: the generation loop is host-dispatch-bound on remote
    # runtimes, so single runs carry ±15% scheduler noise
    t_padded = min(_timed(run_padded) for _ in range(3))

    # ---- ragged v2: continuous batching over the true lengths
    # arena sized to the workload: the flat 512-block default costs
    # nb*block*L*kvh*dh*4 bytes (17 GB at llama-8B dims — more than HBM);
    # the measured workload needs ceil((prompt+new)/block) blocks/seq
    block = 64
    blocks_per_seq = -(-(seq_cap + new) // block)
    num_blocks = max(128, args.n_prompts * blocks_per_seq + 16)
    v2 = RaggedInferenceEngineTPU(
        model, {"dtype": dtype, "num_blocks": num_blocks,
                "block_size": block,
                "max_seq_len": seq_cap, "prefill_chunk": 512,
                "max_batch_tokens": 8192,
                "use_pallas": (False if args.no_pallas else None)},
        params=params if args.quant else v1.params,
        rng=jax.random.PRNGKey(0))
    budgets = [int(x) for x in new_list]
    v2.serve(prompts, max_new_tokens=budgets,
             max_concurrency=conc)                   # compile real buckets
    t_ragged = min(_timed(lambda: v2.serve(prompts,
                                           max_new_tokens=budgets,
                                           max_concurrency=conc))
                   for _ in range(3))

    # secondary: ONE closed batch, UNIFORM generation lengths (no
    # retirement/backfill advantage). NOTE the per-step numbers are
    # whole-call wall time (prefill included) divided by decode steps —
    # a like-for-like loop comparison, not a pure decode-step latency
    uni = min(32, new)
    first = prompts[:conc]
    pad_first = np.zeros((conc, width), np.int32)
    for i, p in enumerate(first):
        pad_first[i, width - len(p):] = p
    v2.generate(first, max_new_tokens=uni)
    t_ragged_uni = min(_timed(lambda: v2.generate(first,
                                                  max_new_tokens=uni))
                       for _ in range(2))
    v1.generate(pad_first, max_new_tokens=uni)
    t_padded_uni = min(_timed(lambda: v1.generate(pad_first,
                                                  max_new_tokens=uni))
                       for _ in range(2))

    # ---- optional --megastep A/B: the SAME long-tail stream through the
    # serving frontend, stepwise (K=1, 2+ host round-trips per token) vs
    # decode megasteps (up to K tokens per device program). The headline
    # is host-dispatch calls per generated token — the dispatch/
    # host_calls counter increments once per device launch, so the
    # megastep column should land near 1/K of stepwise on decode-heavy
    # stretches
    megastep_extra = None
    if args.megastep:
        from deepspeed_tpu.serving import ServingFrontend
        from deepspeed_tpu.telemetry.registry import registry

        def run_frontend(k):
            fe = ServingFrontend(v2, max_queue=n_req,
                                 enable_prefix_cache=False,
                                 megastep_tokens=k,
                                 megastep_adaptive=False)
            for p, m in zip(prompts, budgets):
                fe.submit([int(t) for t in p], max_new_tokens=int(m))
            fe.run_until_idle()
            return fe

        def measure(k):
            run_frontend(k)                       # compile this K's buckets
            hc0 = registry.counter("dispatch/host_calls").value
            t0 = time.perf_counter()
            fe = run_frontend(k)
            wall = time.perf_counter() - t0
            calls = registry.counter("dispatch/host_calls").value - hc0
            toks = fe.metrics.counters["tokens_out"]
            return {"tok_s": round(toks / wall, 2),
                    "host_calls": int(calls),
                    "host_calls_per_token": round(calls / max(1, toks), 4),
                    "tokens": int(toks), "wall_s": round(wall, 3)}

        stepwise = measure(0)
        mega = measure(int(args.megastep))
        megastep_extra = {
            "k": int(args.megastep),
            "stepwise": stepwise,
            "megastep": mega,
            "dispatch_reduction": round(
                stepwise["host_calls_per_token"] /
                max(1e-9, mega["host_calls_per_token"]), 2),
            "speedup": round(stepwise["wall_s"] / mega["wall_s"], 3),
        }

    gen_tokens = int(sum(new_list))
    uni_tokens = conc * uni
    result = {
        "metric": f"ragged-serve vs padded-batches llama3-{size} "
                  f"{n_req} req stream @ conc {conc}, long-tail gen"
                  + (f" {args.quant}" if args.quant else ""),
        "value": round(gen_tokens / t_ragged, 2),
        "unit": "gen tokens/s (ragged)",
        "vs_baseline": round(t_padded / t_ragged, 4),
        "extra": {
            "padded_tok_s": round(gen_tokens / t_padded, 2),
            "ragged_tok_s": round(gen_tokens / t_ragged, 2),
            "speedup": round(t_padded / t_ragged, 3),
            "n_requests": n_req, "concurrency": conc,
            "gen_lens_summary": {
                "total": gen_tokens, "max": new,
                "mean": round(float(np.mean(new_list)), 1)},
            "uniform_gen": {
                "new_tokens": uni,
                "ragged_tok_s": round(uni_tokens / t_ragged_uni, 2),
                "padded_tok_s": round(uni_tokens / t_padded_uni, 2),
                "ragged_wall_ms_per_step": round(
                    t_ragged_uni / uni * 1e3, 2),
                "padded_wall_ms_per_step": round(
                    t_padded_uni / uni * 1e3, 2),
            },
            "roofline": _roofline_extra(v2),
            "slo": _slo_extra(),
            "trace_exemplars": _trace_exemplars_extra(),
        },
    }
    if megastep_extra is not None:
        result["extra"]["megastep"] = megastep_extra
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
