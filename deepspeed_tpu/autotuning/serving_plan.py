"""Serving-side autotuning: size the PR 10/11 fleet knobs from roofline
cost records and a declared traffic mix.

The hand-picked knobs this replaces — router replica counts,
prefill/decode pool splits, autoscale floors/ceilings, megastep K,
SplitFuse token budgets, hedge delays — all derive from two numbers the
cost model already predicts: the prefill bucket-step time and the decode
step time (``engine_v2.cost_records()`` when an engine exists,
:func:`predict_serving_records` for offline ``--chips N`` sizing). The
emitted ``serving.*`` / ``router.*`` / ``autoscale.*`` blocks are
validated through the real config classes before they leave this module,
so ``dstpu-tune``'s JSON loads cleanly into ``DeepSpeedTPUConfig`` and
straight into ``Router(...)`` / ``Autoscaler(...)`` kwargs.

Zero predictions (CPU host, no ``--platform``) self-disable the sizing —
the plan comes back with the config-class defaults and
``"model": "none"`` — mirroring the frontend's SLO-admission
self-disable on the same records.
"""

import math
from dataclasses import asdict, dataclass
from typing import Any, Dict, Optional

from deepspeed_tpu.telemetry.explain import Peaks, Roofline


@dataclass
class TrafficMix:
    """The declared target traffic the plan sizes against."""
    rps_peak: float = 4.0           #: requests/s at the diurnal peak
    prompt_tokens: int = 512        #: mean prompt length
    gen_tokens: int = 128           #: mean generated tokens
    swing: float = 4.0              #: peak/trough demand ratio
    ttft_target_s: float = 0.5      #: TTFT objective (p95)
    utilization: float = 0.6        #: target busy fraction per replica
    headroom: float = 1.25          #: ceiling margin over peak demand


def predict_serving_records(dec_cfg, peaks: Peaks, n_bucket: int = 8,
                            prefill_chunk: int = 32,
                            context_tokens: Optional[int] = None,
                            p_bytes: int = 2) -> Dict[str, Any]:
    """Analytic stand-in for ``engine_v2.cost_records()`` when no engine
    exists (offline ``--chips N`` sizing): closed-form FLOPs/bytes for
    one prefill bucket step (``n_bucket × prefill_chunk`` tokens) and one
    decode step (``n_bucket`` tokens, weights + KV-cache reads), scored
    through the same :class:`Roofline`. Record shape matches
    ``explain_serving`` — ``predicted_s``/``bound``/``n_bucket``/
    ``chunk`` — so :func:`plan_serving` consumes either source."""
    N = float(dec_cfg.num_params())
    ctx = int(context_tokens or min(dec_cfg.max_seq_len, 1024))
    kv_per_tok = 2.0 * dec_cfg.num_layers * dec_cfg.kv_heads * \
        dec_cfg.head_dim * p_bytes
    records: Dict[str, Any] = {}
    for label, toks in (("prefill", n_bucket * prefill_chunk),
                        ("decode", n_bucket)):
        flops = 2.0 * N * toks
        hbm = N * p_bytes + toks * kv_per_tok * (ctx if label == "decode"
                                                 else 1)
        rl = Roofline(flops=flops, bytes=hbm,
                      peak_flops=peaks.peak_flops, hbm_bw=peaks.hbm_bw,
                      ici_bw=peaks.ici_bw)
        records[label] = {
            "name": f"serving_{label}", "available": bool(rl.predicted_s),
            "flops": flops, "bytes_accessed": hbm, "collective_bytes": 0.0,
            "n_bucket": n_bucket,
            "chunk": prefill_chunk if label == "prefill" else 1,
            "predicted_s": rl.predicted_s, "bound": rl.bound,
            "error": None, "source": "analytic",
        }
    records["platform"] = peaks.kind
    return records


def _default_plan(note: str) -> Dict[str, Any]:
    """Sizing self-disabled: emit the config-class defaults so the plan
    still loads cleanly, flagged so nobody mistakes it for a model."""
    from deepspeed_tpu.config.config import (AutoscaleConfig, RouterConfig,
                                             ServingConfig)
    return {"model": "none", "notes": [note],
            "serving": ServingConfig().model_dump(),
            "router": RouterConfig().model_dump(),
            "autoscale": AutoscaleConfig().model_dump(),
            "engine": {}, "predictions": {}}


def plan_serving(records: Dict[str, Any], mix: Optional[TrafficMix] = None,
                 validate: bool = True) -> Dict[str, Any]:
    """Size the fleet knobs from cost ``records`` (either
    ``engine_v2.cost_records()`` or :func:`predict_serving_records`)
    against ``mix``. Deterministic closed-form sizing:

    - decode replicas: demand ``rps·gen_tokens`` tokens/s over a
      replica's ``utilization · n_bucket / t_dec``;
    - prefill replicas: ``rps·prompt_tokens`` over
      ``utilization · n_bucket·chunk / t_pre``;
    - floors from the diurnal trough (peak/swing), ceilings at
      ``headroom`` over peak demand;
    - ``queue_high`` at the utilization knee of the decode bucket;
    - megastep K: the largest decode window that stays within ¼ of the
      TTFT budget (admission only happens on window boundaries);
    - SplitFuse budget: prefill tokens per mixed step capped so a mixed
      step costs ≲ 2 decode steps (decode-latency protection);
    - hedge delay: 2× the predicted no-queue TTFT (a hedge below the
      service floor would fire on every request).
    """
    mix = mix or TrafficMix()
    pre, dec = records.get("prefill", {}), records.get("decode", {})
    t_pre = float(pre.get("predicted_s") or 0.0)
    t_dec = float(dec.get("predicted_s") or 0.0)
    if t_pre <= 0.0 or t_dec <= 0.0:
        return _default_plan(
            "no step-time predictions (zero peaks / unavailable cost "
            "analysis) — serving plan self-disabled to defaults, like "
            "the frontend's SLO admission")
    nb = max(1, int(dec.get("n_bucket") or 8))
    chunk = max(1, int(pre.get("chunk") or 32))

    dec_cap = mix.utilization * nb / t_dec            # tokens/s/replica
    pre_cap = mix.utilization * nb * chunk / t_pre
    dec_demand = mix.rps_peak * mix.gen_tokens
    pre_demand = mix.rps_peak * mix.prompt_tokens
    dec_peak = max(1, math.ceil(dec_demand / dec_cap))
    pre_peak = max(1, math.ceil(pre_demand / pre_cap))
    swing = max(1.0, mix.swing)
    dec_min = max(1, math.ceil(dec_demand / swing / dec_cap))
    pre_min = max(1, math.ceil(pre_demand / swing / pre_cap))
    dec_max = max(dec_peak, math.ceil(dec_peak * mix.headroom), dec_min)
    pre_max = max(pre_peak, math.ceil(pre_peak * mix.headroom), pre_min)

    # megastep: admission/shed points land on window boundaries, so the
    # window must fit well inside the TTFT budget
    k = int(0.25 * mix.ttft_target_s / t_dec)
    megastep = min(32, k) if k >= 2 else 0

    # SplitFuse: prefill-token budget per mixed step — a mixed step may
    # cost at most ~2 decode steps extra
    tau = t_pre / (nb * chunk)                        # s per prefill token
    budget = int(min(nb * chunk, max(chunk, 2.0 * t_dec / tau)))

    ttft_best = math.ceil(mix.prompt_tokens / chunk) * t_pre + t_dec
    hedge_delay = max(0.05, round(2.0 * ttft_best, 3))

    serving_block = {"megastep_tokens": megastep, "megastep_adaptive": True}
    router_block = {
        "replicas": pre_peak + dec_peak,
        "affinity_tokens": max(8, min(64, mix.prompt_tokens // 2)),
        "hedge": True,
        "hedge_delay_s": hedge_delay,
    }
    autoscale_block = {
        "enabled": True,
        "prefill_min": pre_min, "prefill_max": pre_max,
        "decode_min": dec_min, "decode_max": dec_max,
        "queue_high": max(1.0, round(mix.utilization * nb, 1)),
    }
    if validate:
        from deepspeed_tpu.config.config import (AutoscaleConfig,
                                                 RouterConfig,
                                                 ServingConfig)
        ServingConfig(**serving_block)
        RouterConfig(**router_block)
        AutoscaleConfig(**autoscale_block)
    return {
        "model": "roofline",
        "notes": [],
        "serving": serving_block,
        "router": router_block,
        "autoscale": autoscale_block,
        #: engine-level recommendations (engine_v2 construction dict keys)
        "engine": {"max_batch_tokens": budget, "prefill_chunk": chunk,
                   "max_sequences": nb},
        "predictions": {
            "prefill_step_ms": t_pre * 1e3, "decode_step_ms": t_dec * 1e3,
            "prefill_bound": pre.get("bound"), "decode_bound": dec.get("bound"),
            "ttft_best_case_s": ttft_best,
            "decode_tokens_per_s_per_replica": dec_cap,
            "prefill_tokens_per_s_per_replica": pre_cap,
            "platform": records.get("platform"),
        },
        "traffic": asdict(mix),
    }
