"""Autotuning (reference: deepspeed/autotuning/ — 2,722 LoC Autotuner).

Two tiers:

- :class:`Autotuner` (seed) — *measured* sweep: builds engines on the
  local devices and ranks by throughput;
- :mod:`.search` / :mod:`.tune` / :mod:`.serving_plan` (``dstpu-tune``)
  — *offline* sweep: enumerates mesh/ZeRO/overlap/remat/micro-batch
  candidates, prunes by the HBM table, scores with the explain.py
  roofline, and emits ready-to-run config JSON plus a serving fleet
  plan. Nothing is allocated; 256-chip configs size from a laptop.
"""

from deepspeed_tpu.autotuning.autotuner import Autotuner, TuneResult
from deepspeed_tpu.autotuning.search import (Candidate, SearchSpace,
                                             candidate_hbm,
                                             enumerate_candidates,
                                             mesh_factorizations,
                                             predict_candidate,
                                             prune_infeasible)
from deepspeed_tpu.autotuning.serving_plan import (TrafficMix, plan_serving,
                                                   predict_serving_records)
from deepspeed_tpu.autotuning.tune import (ScoredCandidate, TuneReport,
                                           emit_config, run_tune)

__all__ = ["Autotuner", "TuneResult", "Candidate", "SearchSpace",
           "candidate_hbm", "enumerate_candidates", "mesh_factorizations",
           "predict_candidate", "prune_infeasible", "TrafficMix",
           "plan_serving", "predict_serving_records", "ScoredCandidate",
           "TuneReport", "emit_config", "run_tune"]
