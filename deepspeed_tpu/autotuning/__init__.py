"""Autotuning (reference: deepspeed/autotuning/ — 2,722 LoC Autotuner)."""

from deepspeed_tpu.autotuning.autotuner import Autotuner, TuneResult

__all__ = ["Autotuner", "TuneResult"]
