"""Offline search space for ``dstpu-tune``: candidate enumeration,
HBM-feasibility pruning, and an analytic roofline prediction.

The seed :class:`~deepspeed_tpu.autotuning.autotuner.Autotuner` measures
candidates by building engines and timing steps — right on the target
chips, useless for sizing a 256-chip job from a laptop. This module is
the offline half (ROADMAP item 1): every candidate is scored without
building anything, by feeding closed-form FLOPs / HBM-traffic /
collective-bytes counts into the same :class:`telemetry.explain.Roofline`
model (predicted step = max(compute, memory, comm)) that ``explain.py``
derives from real lowered programs — so the analytic score and the
lowered score share units, peaks tables, and the bound taxonomy.

Candidates that fit on the local host (e.g. the 8-virtual-device CPU
mesh) can additionally be *lowered* for exact XLA numbers
(``tune.py --lower``); the analytic tier is what makes
``--chips 256 --platform v5e`` work from anywhere.

Mesh-shape constraints (``mesh_factorizations``):
- ``model`` (tensor parallel) must divide both ``num_heads`` and
  ``kv_heads`` (row/col sharding of attention projections);
- ``seq`` (Ulysses) must divide ``num_heads`` (the all-to-all
  repartitions heads ↔ sequence) and the sequence length;
- ``expert`` must divide ``num_experts`` (absent for dense models);
- the remaining factor is ``data`` (the ZeRO axis) and must be ≥ 1.
"""

import itertools
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.autotuning.autotuner import estimate_candidate_hbm
from deepspeed_tpu.telemetry.explain import Peaks, Roofline
from deepspeed_tpu.utils.logging import logger

#: fraction of the forward pass recomputed in backward, by remat policy
#: (the compute side of the remat ↔ activation-memory trade the tuner
#: searches; the memory side lives in estimate_candidate_hbm's
#: per_layer_d table)
REMAT_RECOMPUTE: Dict[str, float] = {
    "none": 0.0,
    "save_attn_out": 0.55,
    "save_attn_kernel": 0.55,
    "dots_saveable": 0.35,
    "full": 1.0,
    "offload_full": 0.15,          # D2H/H2D traffic, little recompute
    "nothing_saveable": 1.0,
}


class _MeshShim:
    """Duck-typed stand-in for ``jax.sharding.Mesh`` exposing only
    ``.shape`` — enough for :func:`estimate_candidate_hbm`, with no jax
    devices required (the whole point: prune a 256-chip candidate from a
    laptop before anything exists)."""

    def __init__(self, shape: Dict[str, int]):
        self.shape = dict(shape)


@dataclass(frozen=True)
class Candidate:
    """One point of the search space. Frozen + fully ordered through
    :meth:`key` so enumeration and ranking are deterministic."""
    data: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1
    zero_stage: int = 3
    micro_batch: int = 1
    grad_accum: int = 1
    remat: str = "none"
    #: PR 6 chunked-overlap knobs (stage 3 only; ignored below)
    overlap: bool = True
    overlap_prefetch: int = 1
    overlap_regather: bool = True
    overlap_bucket_bytes: int = 0
    #: compute dtype: bf16 (the TPU default) vs fp32
    bf16: bool = True
    #: chunked-CE logits budget (None → engine default)
    ce_budget_mb: Optional[int] = None

    @property
    def chips(self) -> int:
        return self.data * self.model * self.seq * self.expert

    def mesh_dict(self) -> Dict[str, int]:
        return {"pipe": 1, "data": self.data, "data_inner": 1,
                "expert": self.expert, "seq": self.seq,
                "model": self.model}

    def key(self) -> str:
        """Deterministic identity — the ranking tie-break, the cost-cache
        key, and the ``tune.search_key`` stamp in emitted configs."""
        ov = (f"ov{int(self.overlap)}p{self.overlap_prefetch}"
              f"rg{int(self.overlap_regather)}b{self.overlap_bucket_bytes}"
              if self.zero_stage >= 3 else "ov-")
        ce = f".ce{self.ce_budget_mb}" if self.ce_budget_mb else ""
        return (f"d{self.data}.m{self.model}.s{self.seq}.e{self.expert}"
                f".z{self.zero_stage}.mb{self.micro_batch}"
                f".ga{self.grad_accum}.r-{self.remat}.{ov}"
                f".{'bf16' if self.bf16 else 'fp32'}{ce}")

    def to_config(self, base: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
        """Ready-to-run DeepSpeedTPUConfig dict: the mesh shape is
        encoded through the parallel-topology blocks (so
        ``mesh_from_config`` rebuilds it) and every searched knob lands
        on its real config key — the emitted JSON reproduces the scored
        candidate when fed straight back to ``initialize()``."""
        import copy
        cfg: Dict[str, Any] = copy.deepcopy(base) if base else {}
        cfg["train_micro_batch_size_per_gpu"] = self.micro_batch
        cfg["gradient_accumulation_steps"] = self.grad_accum
        cfg.pop("train_batch_size", None)
        zo = cfg.setdefault("zero_optimization", {})
        zo["stage"] = self.zero_stage
        if self.zero_stage >= 3:
            zo["overlap_comm"] = self.overlap
            if self.overlap:
                zo["overlap_prefetch"] = self.overlap_prefetch
                zo["overlap_regather"] = self.overlap_regather
                if self.overlap_bucket_bytes:
                    zo["overlap_bucket_bytes"] = self.overlap_bucket_bytes
        cfg.setdefault("activation_checkpointing", {})["policy"] = \
            self.remat
        cfg.setdefault("bf16", {})["enabled"] = self.bf16
        if self.ce_budget_mb:
            cfg["chunked_ce_budget_mb"] = self.ce_budget_mb
        if self.model > 1:
            cfg.setdefault("tensor_parallel", {})["tp_size"] = self.model
        if self.seq > 1:
            cfg.setdefault("sequence_parallel", {})["size"] = self.seq
        if self.expert > 1:
            moe = cfg.setdefault("moe", {})
            moe["enabled"] = True
            moe["ep_size"] = self.expert
        return cfg


@dataclass
class SearchSpace:
    """Which axes ``enumerate_candidates`` sweeps. Defaults cover the
    knobs that proved decisive on the v5e bench (ZeRO stage, micro-batch,
    remat, overlap) without blowing the candidate count up."""
    zero_stages: Sequence[int] = (1, 2, 3)
    micro_batches: Sequence[int] = (1, 2, 4, 8)
    remat_policies: Sequence[str] = ("none", "save_attn_out", "full")
    #: (overlap, prefetch, regather) triples swept at stage 3; stage < 3
    #: candidates always carry the monolithic default
    overlap_variants: Sequence[Tuple[bool, int, bool]] = (
        (False, 1, True), (True, 1, True), (True, 2, False))
    grad_accums: Sequence[int] = (1,)
    dtypes: Sequence[bool] = (True,)           # bf16 only by default
    ce_budgets_mb: Sequence[Optional[int]] = (None,)
    max_model: int = 16
    max_seq_parallel: int = 8
    #: enumeration guard — a sweep this size is a config error, not a run
    max_candidates: int = 200_000


def _divisors(n: int) -> List[int]:
    out = [d for d in range(1, n + 1) if n % d == 0]
    return out


def mesh_factorizations(chips: int, dec_cfg,
                        space: Optional[SearchSpace] = None
                        ) -> List[Tuple[int, int, int, int]]:
    """All (data, model, seq, expert) factorizations of ``chips`` that
    the model's shape admits, sorted deterministically (dp-major first)."""
    space = space or SearchSpace()
    heads = dec_cfg.num_heads
    kv = dec_cfg.kv_heads
    seq_len = dec_cfg.max_seq_len
    n_exp = getattr(dec_cfg, "num_experts", 0) or 0
    models = [m for m in _divisors(chips)
              if m <= space.max_model and heads % m == 0 and kv % m == 0]
    seqs = [s for s in _divisors(chips)
            if s <= space.max_seq_parallel and heads % s == 0
            and seq_len % s == 0]
    experts = [e for e in _divisors(chips) if n_exp and n_exp % e == 0] \
        or [1]
    shapes = set()
    for m, s, e in itertools.product(models, seqs, experts):
        denom = m * s * e
        if chips % denom:
            continue
        d = chips // denom
        if d >= 1:
            shapes.add((d, m, s, e))
    return sorted(shapes, key=lambda t: (-t[0], t[1], t[2], t[3]))


def enumerate_candidates(dec_cfg, chips: int,
                         space: Optional[SearchSpace] = None
                         ) -> List[Candidate]:
    """The full candidate list, deterministic order (sorted by key)."""
    space = space or SearchSpace()
    cands: List[Candidate] = []
    for (d, m, s, e) in mesh_factorizations(chips, dec_cfg, space):
        for stage in space.zero_stages:
            variants = space.overlap_variants if stage >= 3 \
                else [(False, 1, True)]
            for mb, ga, remat, (ov, pf, rg), bf16, ce in \
                    itertools.product(space.micro_batches,
                                      space.grad_accums,
                                      space.remat_policies,
                                      variants, space.dtypes,
                                      space.ce_budgets_mb):
                cands.append(Candidate(
                    data=d, model=m, seq=s, expert=e, zero_stage=stage,
                    micro_batch=mb, grad_accum=ga, remat=remat,
                    overlap=ov, overlap_prefetch=pf, overlap_regather=rg,
                    bf16=bf16, ce_budget_mb=ce))
                if len(cands) > space.max_candidates:
                    raise ValueError(
                        f"search space exceeds max_candidates="
                        f"{space.max_candidates} — narrow the sweep axes")
    cands.sort(key=lambda c: c.key())
    return cands


# ---------------------------------------------------------------------------
# HBM feasibility (pruning)
# ---------------------------------------------------------------------------

def candidate_hbm(dec_cfg, cand: Candidate,
                  seq_len: Optional[int] = None) -> Dict[str, float]:
    """Per-device HBM prediction for one candidate — the seed
    :func:`estimate_candidate_hbm` model (which understands ZeRO/MiCS
    sharding over the data axes), extended with the axes the offline
    search adds on top:

    - tensor parallel shards params/grads/opt over ``model``;
    - sequence parallel shards activations over ``seq``;
    - the chunked-overlap path adds its transient gathered-chunk
      footprint (prefetch+1 chunks; the whole gathered stack when
      ``overlap_regather=False`` keeps forward chunks for backward).
    """
    cfg = cand.to_config()
    est = estimate_candidate_hbm(dec_cfg, cfg, _MeshShim(cand.mesh_dict()),
                                 seq_len=seq_len)
    tp, sp = cand.model, cand.seq
    out = {"params": est["params"] / tp, "grads": est["grads"] / tp,
           "opt": est["opt"] / tp, "activations": est["activations"] / sp,
           "ce": est["ce"] / max(tp, 1)}
    p_bytes = 2 if cand.bf16 else 4
    n_local = dec_cfg.num_params() * p_bytes / tp
    if cand.zero_stage >= 3 and cand.overlap and cand.data > 1:
        chunk = max(cand.overlap_bucket_bytes / max(tp, 1),
                    n_local / max(dec_cfg.num_layers, 1))
        if cand.overlap_regather:
            out["overlap_transient"] = (cand.overlap_prefetch + 1) * chunk
        else:
            # forward-gathered chunks live through backward
            out["overlap_transient"] = n_local
    out["total"] = sum(out.values()) * 1.15     # same fudge as the seed
    return out


def prune_infeasible(dec_cfg, cands: Sequence[Candidate],
                     capacity_bytes: float,
                     seq_len: Optional[int] = None
                     ) -> Tuple[List[Candidate],
                                List[Tuple[Candidate, str]]]:
    """Split candidates into (feasible, [(candidate, reason), ...]) by
    the compile-free HBM table. ``capacity_bytes <= 0`` (unknown chip)
    disables pruning — everything passes, with a one-time note."""
    if capacity_bytes <= 0:
        logger.warning("autotune: no HBM capacity for the target platform"
                       " — feasibility pruning disabled")
        return list(cands), []
    keep: List[Candidate] = []
    pruned: List[Tuple[Candidate, str]] = []
    for c in cands:
        est = candidate_hbm(dec_cfg, c, seq_len=seq_len)
        if est["total"] <= capacity_bytes:
            keep.append(c)
        else:
            pruned.append((c, f"predicted HBM "
                              f"{est['total'] / 2**30:.2f} GiB > "
                              f"{capacity_bytes / 2**30:.2f} GiB"))
    return keep, pruned


# ---------------------------------------------------------------------------
# analytic roofline
# ---------------------------------------------------------------------------

def _active_params(dec_cfg) -> float:
    """Params touched per token: full N for dense; for MoE, the expert
    MLPs scale by top_k/num_experts (the rest is shared)."""
    N = float(dec_cfg.num_params())
    n_exp = getattr(dec_cfg, "num_experts", 0) or 0
    if n_exp <= 1:
        return N
    d, h, L = dec_cfg.hidden_size, dec_cfg.ffn_size, dec_cfg.num_layers
    mlp = (3 if dec_cfg.is_glu else 2) * d * h * L
    expert_mlp = mlp * n_exp
    shared = N - expert_mlp
    top_k = getattr(dec_cfg, "num_experts_per_tok", 1) or 1
    return shared + mlp * top_k


def predict_candidate(dec_cfg, cand: Candidate, peaks: Peaks,
                      seq_len: Optional[int] = None
                      ) -> Tuple[Roofline, float]:
    """Closed-form per-device roofline for one optimizer step of one
    candidate, plus a serial-exposure penalty (seconds) the max() model
    can't see. Returns ``(roofline, penalty_s)``; zero peaks yield an
    unknown-bound roofline with ``predicted_s == 0`` — callers rank such
    candidates behind every known-bound one and keep searching.

    Counts (all per device, per optimizer step; B = micro-batch,
    T = tokens, ga = grad-accum, dp/tp/sp/ep = mesh axes):

    - FLOPs: ``(6·N_active + 6·L·q_dim·T)·B·ga·T / (tp·sp)``, scaled by
      ``1 + recompute/3`` for the remat policy (forward ≈ ⅓ of fwd+bwd).
    - HBM bytes: weight reads per pass (stage-3 gathers still *read*
      full N/tp per pass), gradient accumulate traffic, optimizer-state
      read+write over its shard, activation save/restore traffic, and
      the CE logits round-trip.
    - Collective bytes: ZeRO param all-gathers ((dp-1)/dp · N/tp per
      gather; backward re-gathers double it under ``overlap_regather``),
      grad reduce-scatter or all-reduce, Megatron-style TP all-reduces
      (4/layer fwd+bwd), Ulysses all-to-alls (8/layer), and MoE dispatch
      all-to-alls.
    - Penalty: a monolithic (non-overlapped) stage-3 gather exposes
      ~half its wire time outside the compute window (XLA's scheduler
      hides some, not all); the chunked-overlap path with prefetch ≥ 1
      hides it, which is exactly the trade PR 6 measured.
    """
    T = int(seq_len or dec_cfg.max_seq_len)
    B, ga = cand.micro_batch, cand.grad_accum
    dp, tp, sp, ep = cand.data, cand.model, cand.seq, cand.expert
    L, d2 = dec_cfg.num_layers, dec_cfg.hidden_size
    p_bytes = 2 if cand.bf16 else 4
    N = float(dec_cfg.num_params())
    n_act = _active_params(dec_cfg)
    tokens = float(B * ga * T)                 # per data-parallel replica
    recompute = REMAT_RECOMPUTE.get(cand.remat, 0.5)

    flops = (6.0 * n_act + 6.0 * L * dec_cfg.q_dim * T) * tokens
    flops *= (1.0 + recompute / 3.0)
    flops /= (tp * sp)

    # HBM traffic: weights re-read per microbatch pass (fwd + bwd +
    # recompute), one grad accumulate write per pass, optimizer sweep
    passes = ga * (2.0 + recompute)
    weight_traffic = passes * N * p_bytes / tp
    grad_traffic = ga * N * p_bytes / tp
    opt_shard = dp if cand.zero_stage >= 1 else 1
    opt_traffic = 2.0 * 12.0 * N / (opt_shard * tp)   # fp32 master+moments
    act_traffic = 12.0 * L * d2 * p_bytes * tokens / sp
    ce_traffic = 2.0 * tokens * dec_cfg.vocab_size * p_bytes / tp
    hbm_bytes = (weight_traffic + grad_traffic + opt_traffic +
                 act_traffic + ce_traffic)

    # collectives (per-device wire bytes)
    comm = 0.0
    gather_bytes = 0.0
    n_tp = N * p_bytes / tp
    if cand.zero_stage >= 3 and dp > 1:
        gathers = ga * (2.0 if (not cand.overlap or cand.overlap_regather)
                        else 1.0)
        gather_bytes = gathers * (dp - 1) / dp * n_tp
        comm += gather_bytes
    if dp > 1:
        if cand.zero_stage >= 2:
            comm += (dp - 1) / dp * n_tp               # grad reduce-scatter
        else:
            comm += 2.0 * (dp - 1) / dp * n_tp         # grad all-reduce
    act_msg = tokens * d2 * p_bytes / sp
    if tp > 1:
        comm += 4.0 * L * 2.0 * (tp - 1) / tp * act_msg
    if sp > 1:
        comm += 8.0 * L * (sp - 1) / sp * act_msg
    n_exp = getattr(dec_cfg, "num_experts", 0) or 0
    if ep > 1 and n_exp:
        top_k = getattr(dec_cfg, "num_experts_per_tok", 1) or 1
        comm += 4.0 * L * top_k * (ep - 1) / ep * act_msg

    rl = Roofline(flops=flops, bytes=hbm_bytes, comm_bytes=comm,
                  peak_flops=peaks.peak_flops, hbm_bw=peaks.hbm_bw,
                  ici_bw=peaks.ici_bw)
    penalty_s = 0.0
    if gather_bytes and peaks.ici_bw and not cand.overlap:
        penalty_s = 0.5 * gather_bytes / peaks.ici_bw
    return rl, penalty_s


def work_proxy(rl: Roofline) -> float:
    """Rank stand-in for unknown-bound candidates (no peaks): raw
    work — FLOPs weighted at a nominal 100 TFLOP/s plus bytes at
    1 TB/s — so even a CPU host with no ``--platform`` produces a
    deterministic, monotone-in-work ordering."""
    return rl.flops / 100e12 + (rl.bytes + rl.comm_bytes) / 1e12
