"""Autotuner — micro-batch / ZeRO-config search with an HBM memory model.

Reference: ``autotuning/autotuner.py:42`` (``Autotuner``: builds a space of
micro-batch sizes × ZeRO stages (+offload), launches short experiment runs,
ranks by throughput, reports the best config; ``tune()``, model-info
profiling, FAST mode). The reference orchestrates subprocess experiment
launches through the DeepSpeed launcher; on TPU a candidate is just an
engine construction + a few jitted steps in-process — the measurement is
identical (steps/sec after compile warmup) without the process plumbing.

Memory model (reference FAST mode: ``_get_model_info``/mem estimates prune
the space BEFORE launching): per candidate, predict device HBM from
abstract shapes — params/grads/optimizer state divided by their ZeRO
sharding factors, plus a remat-policy-dependent activation estimate and
the CE-chunk workspace — and skip predicted-infeasible configs without
building them. On a real chip each skipped candidate saves an engine
build + compile + RESOURCE_EXHAUSTED unwind (minutes on a v5e).

Candidates that pass the model but still fail at run time are recorded as
infeasible and the sweep continues — the reference does the same via
experiment exit codes.
"""

import copy
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

import jax

from deepspeed_tpu.utils.logging import log_dist, logger


@dataclass
class TuneResult:
    config: Dict[str, Any]
    throughput: float           #: samples/sec (0 → infeasible)
    step_time: float
    error: Optional[str] = None
    #: True when the memory model rejected the candidate WITHOUT building
    predicted_oom: bool = False
    #: memory-model breakdown in bytes (also set for measured candidates)
    predicted_hbm: Optional[Dict[str, float]] = None
    #: backend-reported peak HBM bytes for candidates that actually ran
    #: (None when the backend exposes no memory stats)
    measured_hbm: Optional[int] = None

    @property
    def feasible(self) -> bool:
        return self.error is None


def device_peak_bytes() -> Optional[int]:
    """Backend-reported peak HBM in use (None when unavailable — e.g.
    the CPU backend). Reset is not exposed by all runtimes, so callers
    compare peaks measured after their own workload ran."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return int(peak) if peak else None
    except Exception:
        return None


def calibration_report(results, tolerance: float = 0.20) -> Dict[str, Any]:
    """Predicted-vs-measured HBM calibration over the candidates that
    actually ran (VERDICT r4 #7: an uncalibrated model re-introduces the
    OOM-by-building failure mode it exists to prevent). ``ok`` is False
    when any candidate's |predicted - measured| / measured exceeds
    ``tolerance`` — the sweep report carries the failure loudly."""
    rows = []
    for r in results:
        if r.measured_hbm and r.predicted_hbm and r.error is None:
            pred = float(r.predicted_hbm["total"])
            meas = float(r.measured_hbm)
            rows.append({
                "micro_batch": r.config.get(
                    "train_micro_batch_size_per_gpu"),
                "zero_stage": (r.config.get("zero_optimization", {})
                               or {}).get("stage"),
                "predicted_gib": round(pred / 2**30, 3),
                "measured_gib": round(meas / 2**30, 3),
                "pct_error": round((pred - meas) / meas * 100.0, 1),
            })
    worst = max((abs(c["pct_error"]) for c in rows), default=0.0)
    return {"tolerance_pct": tolerance * 100.0, "candidates": rows,
            "max_abs_pct_error": worst,
            # None (not True) when nothing was measurable: an empty
            # calibration must not read as a passing one
            "ok": (worst <= tolerance * 100.0) if rows else None,
            "caveat": ("peak_bytes_in_use is process-cumulative: a "
                       "candidate's measurement can include residual "
                       "live buffers from earlier candidates, and "
                       "candidates that never exceed the prior peak "
                       "record no measurement — run single-candidate "
                       "sweeps for a clean calibration")}


def estimate_candidate_hbm(dec_cfg, config: Dict[str, Any], mesh,
                           seq_len: Optional[int] = None) -> Dict[str, float]:
    """Predict per-device HBM for one candidate from abstract shapes only
    (nothing is allocated). Returns a component breakdown plus 'total'.

    Model (coarse by design, mirrored on the reference's FAST-mode
    activation/model-state estimates):
      params   — compute-dtype leaves; stage 3 shards them over the data
                 axes, MiCS over 'data_inner'.
      grads    — one transient compute-dtype copy; reduce-scattered (so
                 sharded) at stage ≥ 2.
      opt      — Adam family: fp32 master (unless master_weights=False or
                 params already fp32) + two moments in state_dtype; sharded
                 at stage ≥ 1; 0 on device when offloaded to cpu/nvme.
      acts     — scan-carry residuals per layer per token by remat policy
                 + one block's recompute working set + CE chunk workspace.
    """
    zo = config.get("zero_optimization", {}) or {}
    stage = int(zo.get("stage", 0))
    off_dev = (zo.get("offload_optimizer", {}) or {}).get("device", "none")
    bf16 = bool((config.get("bf16", {}) or {}).get("enabled"))
    p_bytes = 2 if bf16 else 4
    opt_p = (config.get("optimizer", {}) or {}).get("params", {}) or {}
    state_bytes = 2 if str(opt_p.get("state_dtype", "")).startswith("bf") \
        else 4
    master = opt_p.get("master_weights", True) and bf16

    d = dec_cfg.hidden_size
    ffn = dec_cfg.ffn_size
    L = dec_cfg.num_layers
    V = dec_cfg.vocab_size
    T = seq_len or dec_cfg.max_seq_len
    B = int(config.get("train_micro_batch_size_per_gpu", 1))
    N = dec_cfg.num_params()

    dp = mesh.shape.get("data", 1) * mesh.shape.get("data_inner", 1)
    mics = int(zo.get("mics_shard_size", 0) or 0)
    param_shard = (mics if mics > 1 else dp) if stage >= 3 else 1
    grad_shard = dp if stage >= 2 else 1
    opt_shard = dp if stage >= 1 else 1

    params = N * p_bytes / param_shard
    grads = N * p_bytes / grad_shard
    if off_dev in ("cpu", "nvme"):
        opt = 0.0
    else:
        opt = N * ((4 if master else 0) + 2 * state_bytes) / opt_shard

    # residuals saved per layer per token (bytes / d), by policy
    policy = (config.get("activation_checkpointing", {}) or {}) \
        .get("policy") or "none"
    act = 2 if dec_cfg.is_glu else 1   # silu_glu keeps 3·ffn recompute live
    per_layer_d = {
        "full": 1.0, "offload_full": 0.0,
        # block_in AND the flash residuals parked on host: no per-layer
        # device residency at all (the 128K+ policy)
        "offload_save_attn_kernel_host": 0.0,
        "offload_attn_out": 1.0, "offload_attn_qkv": 1.0,
        "save_attn_out": 2.0, "save_attn_kernel": 2.0,
        "offload_save_attn_out": 1.0, "offload_save_attn_kernel": 1.0,
        "save_attn_qkv": 2.0 + (dec_cfg.q_dim
                                + 2 * dec_cfg.kv_heads * dec_cfg.head_dim) / d,
        "save_attn_kernel_qkv": 2.0 + (
            dec_cfg.q_dim + 2 * dec_cfg.kv_heads * dec_cfg.head_dim) / d,
        # no remat: everything lives until backward
        "none": 6.0 + act * 3.0 * ffn / d,
        "dots_saveable": 4.0 + act * 1.5 * ffn / d,
        "nothing_saveable": 1.0,
        "dots_with_no_batch_dims_saveable": 1.0,
    }.get(policy, 2.0)
    carry = L * B * T * d * p_bytes * per_layer_d
    # one block recompute; the sequence-chunked MLP (ffn_chunk) caps the
    # live [*, ffn] tiles at chunk tokens instead of the full T
    ffn_chunk = int((config.get("activation_checkpointing", {}) or {})
                    .get("ffn_chunk") or 0)
    t_ffn = min(T, ffn_chunk) if ffn_chunk else T
    working = B * (T * 4 * d + t_ffn * 3 * ffn) * p_bytes
    ce_mb = config.get("chunked_ce_budget_mb")
    ce = (int(ce_mb) * 2 ** 20 * 2 if ce_mb
          else B * T * V * (2 if config.get("ce_logits_dtype") else 4))
    total = (params + grads + opt + carry + working + ce) * 1.15  # fudge
    return {"params": params, "grads": grads, "opt": opt,
            "activations": carry + working, "ce": ce, "total": total}


def device_hbm_bytes(default: Optional[int] = None) -> Optional[int]:
    """Per-chip HBM capacity, from the backend when it reports one."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit)
    except Exception:
        pass
    return default


class Autotuner:
    """Sweep engine configs, rank by measured throughput (reference
    Autotuner.tune).

    ``batch_fn(micro_batch_size) -> batch dict`` supplies one microbatch
    of the right shape per candidate.
    """

    def __init__(self, model, base_config: Dict[str, Any],
                 batch_fn: Callable[[int], Dict[str, Any]],
                 micro_batch_sizes: Optional[List[int]] = None,
                 zero_stages: Optional[List[int]] = None,
                 remat_policies: Optional[List[str]] = None,
                 ce_budgets_mb: Optional[List[int]] = None,
                 steps: int = 5, warmup: int = 2,
                 rng: Optional[jax.Array] = None,
                 hbm_bytes: Optional[int] = None,
                 memory_model: bool = True):
        self.model = model
        self.base_config = base_config
        self.batch_fn = batch_fn
        self.micro_batch_sizes = micro_batch_sizes or [1, 2, 4, 8]
        self.zero_stages = zero_stages or [2, 3]
        #: optional extra sweep axes (both proved decisive on the v5e
        #: bench: remat policy and the chunked-CE logits budget)
        self.remat_policies = remat_policies or [None]
        self.ce_budgets_mb = ce_budgets_mb or [None]
        self.steps = steps
        self.warmup = warmup
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        #: per-chip HBM budget for the memory model; auto-detected from the
        #: backend when it reports a limit (CPU virtual meshes don't — pass
        #: explicitly to exercise pruning there)
        self.hbm_bytes = hbm_bytes if hbm_bytes is not None \
            else device_hbm_bytes()
        self.memory_model = memory_model and self.hbm_bytes is not None
        self.results: List[TuneResult] = []

    def _decoder_config(self):
        dc = getattr(self.model, "decoder_config", None)
        if dc is not None:
            return dc
        return self.model if hasattr(self.model, "num_params") else None

    def _candidates(self) -> Iterator[Dict[str, Any]]:
        for stage in self.zero_stages:
            for mbs in self.micro_batch_sizes:
                for remat in self.remat_policies:
                    for ce_mb in self.ce_budgets_mb:
                        cfg = copy.deepcopy(self.base_config)
                        cfg["train_micro_batch_size_per_gpu"] = mbs
                        cfg.pop("train_batch_size", None)
                        cfg.setdefault("zero_optimization",
                                       {})["stage"] = stage
                        if remat is not None:
                            cfg.setdefault("activation_checkpointing",
                                           {})["policy"] = remat
                        if ce_mb is not None:
                            cfg["chunked_ce_budget_mb"] = ce_mb
                        yield cfg

    def _measure(self, cfg: Dict[str, Any],
                 pred: Optional[Dict[str, float]] = None) -> TuneResult:
        from deepspeed_tpu.parallel.mesh import get_mesh
        from deepspeed_tpu.runtime.engine import initialize
        mbs = cfg["train_micro_batch_size_per_gpu"]
        # the cumulative peak BEFORE this candidate: peak_bytes_in_use is
        # monotone (no reset API), so a candidate's own peak is only
        # observable when it sets a new high-water mark
        peak_before = device_peak_bytes()
        try:
            # chunked_ce_budget_mb is a REAL config key, so the winning
            # config in autotune_best.json reproduces the measured run
            # when fed straight back to initialize()
            engine, *_ = initialize(model=self.model, config=cfg,
                                    mesh=get_mesh(), rng=self.rng)
            batch = self.batch_fn(mbs)
            gas = int(engine.config.gradient_accumulation_steps)
            it = lambda: iter([batch] * gas)
            for _ in range(self.warmup):
                # host fetch, not block_until_ready: remote runtimes
                # (axon tunnel) only execute on fetch — blocking on the
                # handle times dispatch, not the step
                float(engine.train_batch(it()))
            t0 = time.perf_counter()
            loss = None
            for _ in range(self.steps):
                loss = engine.train_batch(it())
            float(loss)
            dt = (time.perf_counter() - t0) / self.steps
            tput = int(engine.config.train_batch_size) / dt
            peak_after = device_peak_bytes()
            measured = (peak_after if peak_after and
                        (peak_before is None or peak_after > peak_before)
                        else None)       # stale high-water mark: unknown
            return TuneResult(config=cfg, throughput=tput, step_time=dt,
                              predicted_hbm=pred, measured_hbm=measured)
        except Exception as e:          # OOM / invalid combo → infeasible
            logger.warning(f"autotune candidate failed: {e}")
            return TuneResult(config=cfg, throughput=0.0, step_time=0.0,
                              error=str(e)[:500])

    def _predict(self, cfg: Dict[str, Any]):
        """Memory-model gate → (gate_result, estimate): gate_result is a
        predicted-OOM TuneResult (skip the build entirely) or None when
        the candidate fits; the estimate threads into _measure so the
        calibration record reuses it instead of recomputing."""
        dec = self._decoder_config()
        if not self.memory_model or dec is None:
            return None, None
        from deepspeed_tpu.parallel.mesh import get_mesh
        try:
            est = estimate_candidate_hbm(dec, cfg, get_mesh())
        except Exception as e:      # a model the estimator can't shape
            logger.warning(f"autotune memory model failed ({e}); "
                           f"building the candidate unguarded")
            return None, None
        if est["total"] <= self.hbm_bytes:
            return None, est
        return TuneResult(
            config=cfg, throughput=0.0, step_time=0.0,
            error=(f"predicted OOM: {est['total'] / 2**30:.2f} GiB > "
                   f"{self.hbm_bytes / 2**30:.2f} GiB HBM "
                   f"(params {est['params'] / 2**30:.2f}, opt "
                   f"{est['opt'] / 2**30:.2f}, acts "
                   f"{est['activations'] / 2**30:.2f})"),
            predicted_oom=True, predicted_hbm=est), est

    def tune(self, results_dir: Optional[str] = None) -> TuneResult:
        """Run the sweep; returns the best feasible candidate (reference
        autotuner 'tune' + results json output)."""
        for cfg in self._candidates():
            gate, est = self._predict(cfg)
            res = gate or self._measure(cfg, pred=est)
            self.results.append(res)
            extras = ""
            ac = cfg.get("activation_checkpointing", {}).get("policy")
            if ac:
                extras += f" remat={ac}"
            if "chunked_ce_budget_mb" in cfg:
                extras += f" ce={cfg['chunked_ce_budget_mb']}MB"
            log_dist(
                f"autotune: mbs={cfg['train_micro_batch_size_per_gpu']} "
                f"zero={cfg['zero_optimization']['stage']}{extras} → "
                f"{res.throughput:.1f} samples/s"
                + (f" (FAILED: {res.error[:60]})" if res.error else ""))
        feasible = [r for r in self.results if r.feasible]
        if not feasible:
            raise RuntimeError("autotuning found no feasible config")
        best = max(feasible, key=lambda r: r.throughput)
        cal = calibration_report(self.results)
        if cal["candidates"] and not cal["ok"]:
            logger.error(
                f"autotune memory-model calibration FAILED: worst "
                f"|predicted-measured| = {cal['max_abs_pct_error']:.1f}% "
                f"> {cal['tolerance_pct']:.0f}% tolerance — the predicted-"
                f"OOM gate may prune configs that fit (or admit ones "
                f"that don't); details in autotune_results.json")
        if results_dir:
            os.makedirs(results_dir, exist_ok=True)
            with open(os.path.join(results_dir, "autotune_results.json"),
                      "w") as fh:
                json.dump({"candidates": [
                    {"config": r.config,
                     "throughput": r.throughput,
                     "step_time": r.step_time,
                     "error": r.error,
                     "predicted_oom": r.predicted_oom,
                     "predicted_hbm_gib": (
                         round(r.predicted_hbm["total"] / 2**30, 3)
                         if r.predicted_hbm else None),
                     "measured_hbm_gib": (
                         round(r.measured_hbm / 2**30, 3)
                         if r.measured_hbm else None)}
                    for r in self.results],
                    "calibration": cal},
                    fh, indent=1)
            with open(os.path.join(results_dir, "autotune_best.json"),
                      "w") as fh:
                json.dump(best.config, fh, indent=1)
        return best
