"""Autotuner — micro-batch / ZeRO-config search.

Reference: ``autotuning/autotuner.py:42`` (``Autotuner``: builds a space of
micro-batch sizes × ZeRO stages (+offload), launches short experiment runs,
ranks by throughput, reports the best config; ``tune()``, model-info
profiling, FAST mode). The reference orchestrates subprocess experiment
launches through the DeepSpeed launcher; on TPU a candidate is just an
engine construction + a few jitted steps in-process — the measurement is
identical (steps/sec after compile warmup) without the process plumbing.

OOM-safe: a candidate that fails to build or step (RESOURCE_EXHAUSTED) is
recorded as infeasible and the sweep continues — the reference does the
same via experiment exit codes.
"""

import copy
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

import jax

from deepspeed_tpu.utils.logging import log_dist, logger


@dataclass
class TuneResult:
    config: Dict[str, Any]
    throughput: float           #: samples/sec (0 → infeasible)
    step_time: float
    error: Optional[str] = None

    @property
    def feasible(self) -> bool:
        return self.error is None


class Autotuner:
    """Sweep engine configs, rank by measured throughput (reference
    Autotuner.tune).

    ``batch_fn(micro_batch_size) -> batch dict`` supplies one microbatch
    of the right shape per candidate.
    """

    def __init__(self, model, base_config: Dict[str, Any],
                 batch_fn: Callable[[int], Dict[str, Any]],
                 micro_batch_sizes: Optional[List[int]] = None,
                 zero_stages: Optional[List[int]] = None,
                 remat_policies: Optional[List[str]] = None,
                 ce_budgets_mb: Optional[List[int]] = None,
                 steps: int = 5, warmup: int = 2,
                 rng: Optional[jax.Array] = None):
        self.model = model
        self.base_config = base_config
        self.batch_fn = batch_fn
        self.micro_batch_sizes = micro_batch_sizes or [1, 2, 4, 8]
        self.zero_stages = zero_stages or [2, 3]
        #: optional extra sweep axes (both proved decisive on the v5e
        #: bench: remat policy and the chunked-CE logits budget)
        self.remat_policies = remat_policies or [None]
        self.ce_budgets_mb = ce_budgets_mb or [None]
        self.steps = steps
        self.warmup = warmup
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.results: List[TuneResult] = []

    def _candidates(self) -> Iterator[Dict[str, Any]]:
        for stage in self.zero_stages:
            for mbs in self.micro_batch_sizes:
                for remat in self.remat_policies:
                    for ce_mb in self.ce_budgets_mb:
                        cfg = copy.deepcopy(self.base_config)
                        cfg["train_micro_batch_size_per_gpu"] = mbs
                        cfg.pop("train_batch_size", None)
                        cfg.setdefault("zero_optimization",
                                       {})["stage"] = stage
                        if remat is not None:
                            cfg.setdefault("activation_checkpointing",
                                           {})["policy"] = remat
                        if ce_mb is not None:
                            cfg["chunked_ce_budget_mb"] = ce_mb
                        yield cfg

    def _measure(self, cfg: Dict[str, Any]) -> TuneResult:
        from deepspeed_tpu.parallel.mesh import get_mesh
        from deepspeed_tpu.runtime.engine import initialize
        mbs = cfg["train_micro_batch_size_per_gpu"]
        try:
            # chunked_ce_budget_mb is a REAL config key, so the winning
            # config in autotune_best.json reproduces the measured run
            # when fed straight back to initialize()
            engine, *_ = initialize(model=self.model, config=cfg,
                                    mesh=get_mesh(), rng=self.rng)
            batch = self.batch_fn(mbs)
            gas = int(engine.config.gradient_accumulation_steps)
            it = lambda: iter([batch] * gas)
            for _ in range(self.warmup):
                # host fetch, not block_until_ready: remote runtimes
                # (axon tunnel) only execute on fetch — blocking on the
                # handle times dispatch, not the step
                float(engine.train_batch(it()))
            t0 = time.perf_counter()
            loss = None
            for _ in range(self.steps):
                loss = engine.train_batch(it())
            float(loss)
            dt = (time.perf_counter() - t0) / self.steps
            tput = int(engine.config.train_batch_size) / dt
            return TuneResult(config=cfg, throughput=tput, step_time=dt)
        except Exception as e:          # OOM / invalid combo → infeasible
            logger.warning(f"autotune candidate failed: {e}")
            return TuneResult(config=cfg, throughput=0.0, step_time=0.0,
                              error=str(e)[:500])

    def tune(self, results_dir: Optional[str] = None) -> TuneResult:
        """Run the sweep; returns the best feasible candidate (reference
        autotuner 'tune' + results json output)."""
        for cfg in self._candidates():
            res = self._measure(cfg)
            self.results.append(res)
            extras = ""
            ac = cfg.get("activation_checkpointing", {}).get("policy")
            if ac:
                extras += f" remat={ac}"
            if "chunked_ce_budget_mb" in cfg:
                extras += f" ce={cfg['chunked_ce_budget_mb']}MB"
            log_dist(
                f"autotune: mbs={cfg['train_micro_batch_size_per_gpu']} "
                f"zero={cfg['zero_optimization']['stage']}{extras} → "
                f"{res.throughput:.1f} samples/s"
                + (f" (FAILED: {res.error[:60]})" if res.error else ""))
        feasible = [r for r in self.results if r.feasible]
        if not feasible:
            raise RuntimeError("autotuning found no feasible config")
        best = max(feasible, key=lambda r: r.throughput)
        if results_dir:
            os.makedirs(results_dir, exist_ok=True)
            with open(os.path.join(results_dir, "autotune_results.json"),
                      "w") as fh:
                json.dump([{"config": r.config,
                            "throughput": r.throughput,
                            "step_time": r.step_time,
                            "error": r.error} for r in self.results],
                          fh, indent=1)
            with open(os.path.join(results_dir, "autotune_best.json"),
                      "w") as fh:
                json.dump(best.config, fh, indent=1)
        return best
