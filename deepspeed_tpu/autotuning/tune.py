"""``dstpu-tune`` — roofline-driven offline config search.

Pipeline (all compile-free by default):

1. enumerate — :func:`search.enumerate_candidates` over mesh shape ×
   ZeRO stage × micro-batch × remat × overlap knobs;
2. prune — :func:`search.prune_infeasible` against the target chip's
   HBM capacity (the seed autotuner's memory model, extended with
   TP/SP sharding and overlap transients);
3. score — :func:`search.predict_candidate`'s analytic roofline against
   the platform peak tables; optionally re-score the top N candidates
   by really lowering them through ``explain_engine`` when the mesh
   fits the local devices (``--lower N``);
4. rank — feasible first, known-bound before unknown-bound, ascending
   predicted step time, deterministic tie-break on the candidate key;
5. emit — the winner as a ready-to-run DeepSpeedTPUConfig JSON with a
   ``tune`` stamp, plus ``serving``/``router``/``autoscale`` blocks
   sized by :mod:`.serving_plan` when a traffic mix is declared.

``tune/*`` gauges publish the sweep's shape for dashboards:
candidates enumerated/pruned/unknown-bound and the winner's predicted
step time.
"""

import argparse
import json
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.autotuning.search import (Candidate, SearchSpace,
                                             candidate_hbm,
                                             enumerate_candidates,
                                             predict_candidate,
                                             prune_infeasible, work_proxy)
from deepspeed_tpu.autotuning.serving_plan import (TrafficMix, plan_serving,
                                                   predict_serving_records)
from deepspeed_tpu.telemetry.explain import (Peaks, Roofline, resolve_peaks,
                                             roofline_from_cost)
from deepspeed_tpu.telemetry.registry import registry as _registry
from deepspeed_tpu.utils.logging import logger

#: the pure max(compute, memory, comm) roofline assumes PERFECT overlap
#: of the two non-binding terms — under it every compute-bound candidate
#: at the same per-token FLOPs ties exactly, no matter how much comm it
#: drags along. Scoring charges this fraction of the hidden (non-max)
#: terms as imperfect-overlap residual, so less traffic wins ties.
OVERLAP_RESIDUAL = 0.10


@dataclass
class ScoredCandidate:
    candidate: Candidate
    roofline: Roofline
    penalty_s: float = 0.0
    hbm: Dict[str, float] = field(default_factory=dict)
    source: str = "analytic"          #: "analytic" | "lowered"
    #: global tokens per optimizer step (micro × ga × T × dp) — the
    #: ranking normalizer: the objective is time per token (throughput),
    #: not raw step time, or the sweep would always pick micro_batch=1
    tokens_per_step: float = 1.0

    @property
    def score_s(self) -> float:
        rl = self.roofline
        residual = (rl.compute_s + rl.memory_s + rl.comm_s -
                    rl.predicted_s)
        return rl.predicted_s + self.penalty_s + \
            OVERLAP_RESIDUAL * residual

    @property
    def s_per_token(self) -> float:
        return self.score_s / max(self.tokens_per_step, 1.0)

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_per_step / self.score_s if self.score_s else 0.0

    @property
    def bound(self) -> str:
        return self.roofline.bound

    def to_dict(self) -> Dict[str, Any]:
        return {"key": self.candidate.key(),
                "mesh": self.candidate.mesh_dict(),
                "zero_stage": self.candidate.zero_stage,
                "micro_batch": self.candidate.micro_batch,
                "remat": self.candidate.remat,
                "overlap": self.candidate.overlap,
                "predicted_ms": self.roofline.predicted_s * 1e3,
                "penalty_ms": self.penalty_s * 1e3,
                "score_ms": self.score_s * 1e3,
                "tokens_per_step": self.tokens_per_step,
                "tokens_per_s": self.tokens_per_s,
                "bound": self.bound,
                "hbm_gib": round(self.hbm.get("total", 0.0) / 2**30, 3),
                "source": self.source}


@dataclass
class TuneReport:
    platform: str
    chips: int
    seq_len: int
    model_desc: str
    peaks: Peaks
    ranked: List[ScoredCandidate] = field(default_factory=list)
    pruned: List[Tuple[str, str]] = field(default_factory=list)
    serving_plan: Optional[Dict[str, Any]] = None

    def best(self) -> Optional[ScoredCandidate]:
        return self.ranked[0] if self.ranked else None

    def to_dict(self, top: int = 10) -> Dict[str, Any]:
        return {"platform": self.platform, "chips": self.chips,
                "seq_len": self.seq_len, "model": self.model_desc,
                "candidates_ranked": len(self.ranked),
                "candidates_pruned": len(self.pruned),
                "ranked": [s.to_dict() for s in self.ranked[:top]],
                "pruned": [{"key": k, "reason": r}
                           for k, r in self.pruned[:top]],
                "serving_plan": self.serving_plan}

    def render(self, top: int = 10) -> str:
        out = [f"== dstpu-tune ({self.model_desc}, {self.chips} chips, "
               f"platform {self.platform}, seq {self.seq_len}) ==",
               f"candidates: {len(self.ranked)} ranked, "
               f"{len(self.pruned)} pruned (HBM)",
               "",
               f"  {'#':<3}{'candidate':<46}{'bound':<9}"
               f"{'pred ms':>9}{'Mtok/s':>9}{'hbm GiB':>9}  src"]
        for i, s in enumerate(self.ranked[:top]):
            out.append(
                f"  {i + 1:<3}{s.candidate.key()[:45]:<46}{s.bound:<9}"
                f"{s.roofline.predicted_s * 1e3:>9.2f}"
                f"{s.tokens_per_s / 1e6:>9.3f}"
                f"{s.hbm.get('total', 0.0) / 2**30:>9.2f}  {s.source}")
        if not self.ranked:
            out.append("  (no feasible candidates)")
        if self.serving_plan:
            p = self.serving_plan
            if p.get("model") == "none":
                out.append("")
                out.append(f"serving plan: self-disabled — "
                           f"{p['notes'][0] if p.get('notes') else ''}")
            else:
                pred = p["predictions"]
                a = p["autoscale"]
                out.append("")
                out.append(
                    f"serving plan ({p['model']}): prefill "
                    f"{pred['prefill_step_ms']:.2f} ms/step, decode "
                    f"{pred['decode_step_ms']:.2f} ms/step → replicas "
                    f"prefill {a['prefill_min']}..{a['prefill_max']}, "
                    f"decode {a['decode_min']}..{a['decode_max']}, "
                    f"megastep {p['serving']['megastep_tokens']}, "
                    f"splitfuse {p['engine']['max_batch_tokens']} tok, "
                    f"hedge {p['router']['hedge_delay_s']}s")
        return "\n".join(out)


def _rank_key(s: ScoredCandidate) -> Tuple:
    unknown = s.bound == "unknown"
    norm = max(s.tokens_per_step, 1.0)
    primary = work_proxy(s.roofline) / norm if unknown \
        else s.s_per_token
    return (unknown, primary, s.candidate.key())


def lower_candidate(dec_cfg, cand: Candidate, peaks: Peaks,
                    seq_len: int, platform: Optional[str] = None,
                    base_config: Optional[Dict[str, Any]] = None
                    ) -> Optional[Roofline]:
    """Exact re-score: build the candidate's mesh + engine on the local
    devices and lower the real fused step through ``explain_engine``.
    Only possible when the candidate's chip count fits the host (the
    8-virtual-device CPU mesh covers every ``--chips 8`` smoke). Any
    failure — including a backend whose cost_analysis comes back empty —
    degrades to None / unknown-bound; the sweep continues on the
    analytic score."""
    import jax
    if cand.chips > len(jax.devices()):
        return None
    try:
        from deepspeed_tpu.parallel.mesh import build_mesh
        from deepspeed_tpu.runtime.engine import initialize
        from deepspeed_tpu.telemetry.explain import explain_engine
        mesh = build_mesh(data=cand.data, model=cand.model, seq=cand.seq,
                          expert=cand.expert,
                          devices=jax.devices()[:cand.chips])
        cfg = cand.to_config(base_config)
        import dataclasses as _dc
        model = _dc.replace(dec_cfg, max_seq_len=seq_len) \
            if seq_len != dec_cfg.max_seq_len else dec_cfg
        engine, *_ = initialize(model=model, config=cfg, mesh=mesh,
                                rng=jax.random.PRNGKey(0))
        rep = explain_engine(engine, platform=platform)
        step = next((f for f in rep.functions
                     if f.name == "train_step"), None)
        return roofline_from_cost(step, peaks)
    except Exception as e:                               # noqa: BLE001
        logger.warning("autotune: lowering %s failed (%s: %s) — keeping "
                       "the analytic score", cand.key(),
                       type(e).__name__, e)
        return None


def run_tune(dec_cfg, chips: int, platform: Optional[str] = None,
             seq_len: Optional[int] = None,
             space: Optional[SearchSpace] = None,
             hbm_capacity: Optional[float] = None,
             traffic: Optional[TrafficMix] = None,
             serving_records: Optional[Dict[str, Any]] = None,
             include_serving: bool = True,
             lower: int = 0,
             base_config: Optional[Dict[str, Any]] = None,
             model_desc: str = "model") -> TuneReport:
    """The offline sweep. Deterministic: same inputs → same ranking."""
    seq_len = int(seq_len or dec_cfg.max_seq_len)
    peaks = resolve_peaks(platform=platform)
    cap = hbm_capacity if hbm_capacity is not None else peaks.capacity
    cands = enumerate_candidates(dec_cfg, chips, space)
    keep, pruned = prune_infeasible(dec_cfg, cands, cap, seq_len=seq_len)

    scored: List[ScoredCandidate] = []
    for c in keep:
        rl, penalty = predict_candidate(dec_cfg, c, peaks, seq_len=seq_len)
        scored.append(ScoredCandidate(
            candidate=c, roofline=rl, penalty_s=penalty,
            hbm=candidate_hbm(dec_cfg, c, seq_len=seq_len),
            tokens_per_step=float(c.micro_batch * c.grad_accum *
                                  seq_len * c.data)))
    scored.sort(key=_rank_key)

    if lower > 0:
        for s in scored[:lower]:
            rl = lower_candidate(dec_cfg, s.candidate, peaks, seq_len,
                                 platform=platform,
                                 base_config=base_config)
            if rl is not None and rl.bound != "unknown":
                s.roofline, s.source = rl, "lowered"
        scored.sort(key=_rank_key)

    report = TuneReport(platform=peaks.kind,
                        chips=chips, seq_len=seq_len,
                        model_desc=model_desc, peaks=peaks, ranked=scored,
                        pruned=[(c.key(), r) for c, r in pruned])

    if include_serving:
        records = serving_records or predict_serving_records(
            dec_cfg, peaks)
        report.serving_plan = plan_serving(records, traffic)

    unknown = sum(1 for s in scored if s.bound == "unknown")
    _registry.gauge("tune/candidates_total",
                    help="candidates enumerated by the last sweep").set(
        len(cands))
    _registry.gauge("tune/candidates_pruned",
                    help="candidates rejected by the HBM table").set(
        len(pruned))
    _registry.gauge("tune/candidates_unknown_bound",
                    help="candidates scored with no peak numbers").set(
        unknown)
    best = report.best()
    _registry.gauge("tune/best_predicted_ms",
                    help="winner's roofline-predicted step (0 = no "
                         "model)").set(
        best.roofline.predicted_s * 1e3 if best else 0.0)
    return report


def emit_config(report: TuneReport,
                base: Optional[Dict[str, Any]] = None,
                path: Optional[str] = None) -> Dict[str, Any]:
    """The winner as a ready-to-run config dict (optionally written to
    ``path``): the candidate's real config keys, the serving-plan
    blocks, and the ``tune`` stamp that records where the numbers came
    from (``config.TuneConfig`` — informational; the engine ignores
    it). Round-trips through ``DeepSpeedTPUConfig.from_any``."""
    best = report.best()
    if best is None:
        raise RuntimeError("tune found no feasible candidate to emit")
    cfg = best.candidate.to_config(base)
    plan = report.serving_plan
    if plan and plan.get("model") != "none":
        cfg["serving"] = plan["serving"]
        cfg["router"] = plan["router"]
        cfg["autoscale"] = plan["autoscale"]
    cfg["tune"] = {
        "tuned": True,
        "model": report.model_desc,
        "platform": report.platform,
        "chips": report.chips,
        "seq_len": report.seq_len,
        "mesh": best.candidate.mesh_dict(),
        "predicted_step_ms": best.roofline.predicted_s * 1e3,
        "bound": best.bound,
        "source": best.source,
        "candidates_scored": len(report.ranked),
        "candidates_pruned": len(report.pruned),
        "search_key": best.candidate.key(),
    }
    if plan and plan.get("model") != "none":
        cfg["tune"]["serving_engine"] = dict(plan.get("engine") or {})
    if path:
        with open(path, "w") as fh:
            json.dump(cfg, fh, indent=1)
    return cfg


# ---------------------------------------------------------------------------
# CLI — bin/dstpu-tune
# ---------------------------------------------------------------------------

def _smoke(args) -> int:
    """Tier-1-runnable end-to-end check: tiny model, 8-chip search,
    v5e-modeled peaks — asserts a non-empty ranked table and that the
    emitted JSON round-trips through DeepSpeedTPUConfig (and rebuilds
    its mesh when 8 local devices exist)."""
    import os
    import tempfile
    from deepspeed_tpu.config.config import DeepSpeedTPUConfig
    from deepspeed_tpu.models.llama import llama3_config
    model = llama3_config("tiny", max_seq_len=128)
    space = SearchSpace(zero_stages=(2, 3), micro_batches=(1, 2, 4),
                        remat_policies=("none", "full"),
                        overlap_variants=((False, 1, True),
                                          (True, 1, True)))
    report = run_tune(model, chips=8, platform=args.platform or "v5e",
                      seq_len=128, space=space,
                      traffic=TrafficMix(rps_peak=2.0, prompt_tokens=64,
                                         gen_tokens=32),
                      model_desc="llama3-tiny")
    print(report.render(top=5))
    assert report.ranked, "smoke: empty ranked candidate table"
    assert report.best().bound != "unknown", \
        "smoke: winner has no roofline model (peak tables broken?)"
    path = args.output or os.path.join(tempfile.mkdtemp(), "best.json")
    cfg_dict = emit_config(report, path=path)
    loaded = DeepSpeedTPUConfig.from_any(path)
    assert loaded.tune.tuned, "smoke: tune stamp lost in round-trip"
    assert loaded.zero_optimization.stage == cfg_dict[
        "zero_optimization"]["stage"], "smoke: config round-trip mismatch"
    try:
        import jax
        if len(jax.devices()) >= 8:
            from deepspeed_tpu.parallel.mesh import mesh_from_config
            mesh = mesh_from_config(loaded,
                                    devices=jax.devices()[:8])
            assert sum(1 for _ in mesh.devices.flat) == 8
            print(f"mesh rebuilt from emitted config: "
                  f"{dict(mesh.shape)}")
    except ImportError:
        pass
    print(f"emitted: {path}")
    print("SMOKE OK")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-tune",
        description="Roofline-driven offline autotuner: search mesh "
                    "shape / ZeRO stage / overlap / remat / micro-batch "
                    "against the explain.py cost model and emit the "
                    "best config as ready-to-run JSON. Works from any "
                    "host — nothing is allocated unless --lower asks "
                    "for exact re-scoring of local-sized candidates.")
    ap.add_argument("--model", "--size", dest="size", default="tiny",
                    help="llama3 preset (tiny/350m/1b/8b/70b)")
    ap.add_argument("--chips", type=int, default=8,
                    help="target chip count to factorize")
    ap.add_argument("--platform", default=None,
                    help="target chip for the peak tables "
                         "(v2/v3/v4/v5e/v5p/v6e/v7); unknown names warn "
                         "once and score unknown-bound")
    ap.add_argument("--seq", type=int, default=None,
                    help="sequence length (default: model preset)")
    ap.add_argument("--top", type=int, default=10,
                    help="ranked candidates to print")
    ap.add_argument("--lower", type=int, default=0, metavar="N",
                    help="re-score the top N candidates by lowering a "
                         "real engine (needs the candidate's chips <= "
                         "local devices)")
    ap.add_argument("-o", "--output", default=None,
                    help="write the winning config JSON here")
    ap.add_argument("--base-config", default=None,
                    help="JSON config the winner's knobs are merged into")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured report as JSON to stdout")
    ap.add_argument("--no-serving", action="store_true",
                    help="skip the serving-plan sizing")
    ap.add_argument("--rps", type=float, default=4.0,
                    help="serving traffic: peak requests/s")
    ap.add_argument("--prompt-tokens", type=int, default=512)
    ap.add_argument("--gen-tokens", type=int, default=128)
    ap.add_argument("--swing", type=float, default=4.0,
                    help="diurnal peak/trough demand ratio")
    ap.add_argument("--ttft", type=float, default=0.5,
                    help="TTFT p95 target, seconds")
    ap.add_argument("--zero-stages", default=None,
                    help="comma list overriding the ZeRO stages swept")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny end-to-end self-check (tier-1 CI)")
    args = ap.parse_args(argv)

    if args.smoke:
        return _smoke(args)

    from deepspeed_tpu.models.llama import llama3_config
    overrides = {"max_seq_len": args.seq} if args.seq else {}
    model = llama3_config(args.size, **overrides)
    space = SearchSpace()
    if args.zero_stages:
        space = SearchSpace(zero_stages=tuple(
            int(s) for s in args.zero_stages.split(",")))
    base = None
    if args.base_config:
        with open(args.base_config) as fh:
            base = json.load(fh)
    traffic = TrafficMix(rps_peak=args.rps,
                         prompt_tokens=args.prompt_tokens,
                         gen_tokens=args.gen_tokens, swing=args.swing,
                         ttft_target_s=args.ttft)
    report = run_tune(model, chips=args.chips, platform=args.platform,
                      seq_len=args.seq, space=space, traffic=traffic,
                      include_serving=not args.no_serving,
                      lower=args.lower, base_config=base,
                      model_desc=f"llama3-{args.size}")
    if args.json:
        print(json.dumps(report.to_dict(top=args.top), indent=1,
                         default=repr))
    else:
        print(report.render(top=args.top))
    if args.output:
        if report.ranked:
            emit_config(report, base=base, path=args.output)
            print(f"emitted: {args.output}")
        else:
            print("no feasible candidate — nothing emitted",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
