"""Environment / op report — the ``ds_report`` analogue.

Reference: ``deepspeed/env_report.py`` (op_report:30, debug_report:84) and
``bin/ds_report``. The reference enumerates CUDA extension builders and
torch/nvcc compatibility; the TPU-native report covers what actually
matters here: the JAX stack (jax/jaxlib/libtpu), the device inventory with
HBM stats, the host C++ toolchain, and the build/load status of each
native op in ``csrc/`` (cached .so signature, trial build on request).
"""

import os
import platform
import shutil
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[FAIL]{END}"

def _native_ops():
    """Enumerate csrc/*.cpp — one op per source, matching NativeOpBuilder's
    default `name → name.cpp` convention, so new ops appear automatically."""
    from deepspeed_tpu.ops.op_builder import _CSRC
    return sorted(p.stem for p in _CSRC.glob("*.cpp"))


def _version(mod_name):
    try:
        mod = __import__(mod_name)
        return getattr(mod, "__version__", "unknown")
    except ImportError:
        return None


def op_report(build: bool = False, file=None) -> bool:
    """Native (C++) op status table. Returns True if all ops are healthy.

    ``build=True`` trial-compiles any op whose cached .so is missing
    (reference op_report only checks compatibility; here a build IS the
    compatibility check — there is no separate arch matrix on a host CPU).
    """
    from deepspeed_tpu.ops.op_builder import NativeOpBuilder, is_native_available

    print("-" * 58, file=file)
    print("deepspeed_tpu native (C++) op report", file=file)
    print("-" * 58, file=file)
    cxx = os.environ.get("CXX", "g++")
    have_cxx = is_native_available()
    print(f"host toolchain ({cxx}) ".ljust(34, ".") +
          f" {OKAY if have_cxx else FAIL}", file=file)
    ok = have_cxx
    for name in _native_ops():
        builder = NativeOpBuilder(name)
        try:
            cached = builder.so_path().exists()
        except OSError:
            cached = False
        status = f"{GREEN}[CACHED]{END}" if cached else f"{YELLOW}[JIT]{END}"
        if build and not cached and have_cxx:
            try:
                builder.build()
                status = f"{GREEN}[BUILT]{END}"
            except Exception as exc:  # report, don't raise: this is a report
                status = FAIL
                ok = False
                print(f"  build error: {exc}", file=file)
        print(f"op {name} ".ljust(34, ".") + f" {status}", file=file)
    print("NOTE: [JIT] ops compile on first use into "
          f"{os.environ.get('DSTPU_CACHE_DIR', '~/.cache/deepspeed_tpu')}",
          file=file)
    return ok


def device_report(file=None) -> None:
    import jax
    from deepspeed_tpu.accelerator import get_accelerator
    from deepspeed_tpu.utils.platform import sync_jax_platform_env

    sync_jax_platform_env()

    accel = get_accelerator()
    print("-" * 58, file=file)
    print("device inventory", file=file)
    print("-" * 58, file=file)
    print(f"backend ".ljust(24, ".") + f" {jax.default_backend()}", file=file)
    devs = jax.devices()
    print(f"devices ".ljust(24, ".") + f" {len(devs)}", file=file)
    for d in devs[:8]:
        print(f"  [{d.id}] {d.device_kind} (process {d.process_index})",
              file=file)
    if len(devs) > 8:
        print(f"  ... and {len(devs) - 8} more", file=file)
    print(f"process count ".ljust(24, ".") + f" {jax.process_count()}",
          file=file)
    try:
        stats = accel.memory_stats()
        if stats:
            tot = stats.get("bytes_limit", 0)
            used = stats.get("bytes_in_use", 0)
            print(f"HBM in use / limit ".ljust(24, ".") +
                  f" {used / 2**30:.2f} / {tot / 2**30:.2f} GiB", file=file)
    except Exception:
        pass
    print(f"comm backend ".ljust(24, ".") +
          f" {accel.communication_backend_name()}", file=file)


def version_report(file=None) -> None:
    import deepspeed_tpu

    print("-" * 58, file=file)
    print("version information", file=file)
    print("-" * 58, file=file)
    rows = [("deepspeed_tpu", deepspeed_tpu.__version__),
            ("python", platform.python_version()),
            ("platform", platform.platform())]
    for mod in ("jax", "jaxlib", "numpy", "flax", "optax", "orbax",
                "transformers"):
        v = _version(mod)
        if v is not None:
            rows.append((mod, v))
    libtpu = _version("libtpu")
    if libtpu is not None:
        rows.append(("libtpu", libtpu))
    for k, v in rows:
        print(f"{k} ".ljust(24, ".") + f" {v}", file=file)
    flags = os.environ.get("XLA_FLAGS")
    if flags:
        print(f"XLA_FLAGS ".ljust(24, ".") + f" {flags}", file=file)


def storage_report(file=None) -> None:
    """NVMe/disk line for the offload/Infinity configs."""
    print("-" * 58, file=file)
    print("storage (ZeRO-Infinity swap target)", file=file)
    print("-" * 58, file=file)
    paths = dict.fromkeys(
        p for p in ("/tmp", os.environ.get("DSTPU_NVME_PATH", ""))
        if p and os.path.isdir(p))
    for path in paths:
        usage = shutil.disk_usage(path)
        print(f"{path} ".ljust(24, ".") +
              f" {usage.free / 2**30:.1f} GiB free of "
              f"{usage.total / 2**30:.1f} GiB", file=file)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="dstpu_report",
        description="deepspeed_tpu environment and native-op report")
    parser.add_argument("--build", action="store_true",
                        help="trial-build any native op not yet cached")
    parser.add_argument("--no-device", action="store_true",
                        help="skip device probing (no jax backend init)")
    parser.add_argument("--compare", nargs=2, metavar=("A", "B"),
                        help="regression gate: compare two runs' BENCH "
                             "JSONL or metric-history files (baseline A "
                             "vs candidate B); exit 1 on a regression "
                             "beyond the noise band")
    parser.add_argument("--noise", type=float, default=0.05,
                        help="relative noise band for --compare "
                             "(default %(default)s)")
    parser.add_argument("--json", action="store_true",
                        help="emit the --compare report as JSON")
    args = parser.parse_args(argv)

    if args.compare:
        from deepspeed_tpu.telemetry.compare import main_compare
        return main_compare(args.compare[0], args.compare[1],
                            noise=args.noise, as_json=args.json)

    version_report()
    ok = op_report(build=args.build)
    if not args.no_device:
        device_report()
    storage_report()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
