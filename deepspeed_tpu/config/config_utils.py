"""Typed-config base for deepspeed_tpu.

Plays the role of the reference's pydantic config base
(``deepspeed/runtime/config_utils.py`` — ``DeepSpeedConfigModel``): every
feature of the framework gets a typed sub-config parsed from one JSON/dict
tree, with support for the ``"auto"`` sentinel, deprecated-field migration,
and unknown-key warnings.

Design differences from the reference (TPU-first, not a port):
- values that the reference leaves to CUDA-era knobs (loss scaling windows,
  cuda-graph toggles) default to bf16-native behavior;
- sub-configs carry mesh-axis metadata so the engine can translate a config
  straight into a ``jax.sharding`` layout.
"""

from typing import Any, ClassVar, Dict

from pydantic import BaseModel, ConfigDict, model_validator

from deepspeed_tpu.utils.logging import logger

#: Sentinel used by HuggingFace integration: values set to "auto" are filled
#: in by the engine at initialize() time (reference: runtime/config.py "auto"
#: resolution for HF Trainer).
AUTO = "auto"


def is_auto(value: Any) -> bool:
    return isinstance(value, str) and value.lower() == AUTO


class TPUConfigModel(BaseModel):
    """Base class for all deepspeed_tpu config models.

    Mirrors ``DeepSpeedConfigModel`` (reference runtime/config_utils.py):
    - extra keys are collected and warned about, not fatal;
    - ``deprecated_aliases`` maps old key -> new key and migrates values;
    - ``"auto"`` string values are preserved untouched so the engine can
      resolve them later (``resolve_auto``).
    """

    model_config = ConfigDict(extra="allow", validate_assignment=True,
                              arbitrary_types_allowed=True, populate_by_name=True)

    #: subclasses may override: {old_field_name: new_field_name}
    deprecated_aliases: ClassVar[Dict[str, str]] = {}

    @model_validator(mode="before")
    @classmethod
    def _migrate_deprecated(cls, values: Any) -> Any:
        if not isinstance(values, dict):
            return values
        for old, new in cls.deprecated_aliases.items():
            if old in values:
                logger.warning("Config field '%s' is deprecated; use '%s'", old, new)
                if new not in values:
                    values[new] = values.pop(old)
                else:
                    values.pop(old)
        return values

    @model_validator(mode="after")
    def _warn_extra(self) -> "TPUConfigModel":
        extra = getattr(self, "model_extra", None) or {}
        for key in extra:
            logger.warning("Unknown config key '%s' in %s (ignored)", key,
                           type(self).__name__)
        return self

    def resolve_auto(self, field: str, value: Any) -> None:
        """Fill in a field that was left as "auto" in user config."""
        if is_auto(getattr(self, field, None)):
            setattr(self, field, value)

    def dict_without_auto(self) -> Dict[str, Any]:
        return {k: v for k, v in self.model_dump().items() if not is_auto(v)}


def get_scalar_param(config_dict: Dict[str, Any], name: str, default: Any) -> Any:
    """Reference-compatible helper (runtime/config_utils.py:get_scalar_param)."""
    return config_dict.get(name, default)
