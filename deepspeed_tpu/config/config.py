"""deepspeed_tpu master config.

TPU-native equivalent of the reference's ``DeepSpeedConfig``
(reference: deepspeed/runtime/config.py:651) — one JSON/dict tree parsed
into typed sub-configs, with the batch-size triple solver
(train_batch = micro_batch × grad_accum × dp_world, reference
runtime/config.py batch resolution) and ``"auto"`` resolution.

Key design translation for TPU:
- ``zero_optimization.stage`` selects a *sharding layout* over the mesh's
  ``data`` axis (stage1: optimizer state sharded; stage2: +grads via
  reduce-scatter output shardings; stage3: +params, allgather-on-use done
  by XLA), not a hook engine.
- ``fp16`` exists for API compatibility but TPU-native training is bf16
  (no loss scaling needed); enabling fp16 turns on a DynamicLossScaler for
  parity testing.
- parallel-topology knobs (tensor/pipeline/sequence/expert) become mesh
  axis sizes (see deepspeed_tpu/parallel/mesh.py).
"""

import json
from enum import Enum
from typing import Literal, Any, Dict, List, Optional, Union

from pydantic import Field, model_validator

from deepspeed_tpu.config.config_utils import AUTO, TPUConfigModel, is_auto
from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# Optimizer / scheduler
# ---------------------------------------------------------------------------

class OptimizerConfig(TPUConfigModel):
    """Reference: ``"optimizer": {"type": ..., "params": {...}}``
    (runtime/config.py get_optimizer_name/params)."""
    type: str = "adamw"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(TPUConfigModel):
    """Reference: ``"scheduler"`` block (runtime/config.py:get_scheduler_name)."""
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Precision
# ---------------------------------------------------------------------------

class FP16Config(TPUConfigModel):
    """Reference: runtime/fp16 configs (config.py fp16 block). On TPU fp16 is
    discouraged; bf16 is native. Kept for API parity + loss-scaler tests."""
    enabled: Union[bool, str] = False
    loss_scale: float = 0.0          # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    auto_cast: bool = False


class BF16Config(TPUConfigModel):
    """Reference: ``"bf16": {"enabled": ...}`` (runtime/config.py bf16 block).
    TPU default-on when neither fp16 nor bf16 specified explicitly is handled
    at engine level."""
    enabled: Union[bool, str] = False
    #: dtype used for gradient accumulation buffers across microbatches
    #: (reference knob: gradient_accumulation_dtype)
    accumulate_grads_in_fp32: bool = True


class ActivationCheckpointingConfig(TPUConfigModel):
    """Reference: activation_checkpointing block (runtime/activation_checkpointing).
    On TPU this maps to ``jax.checkpoint`` policies applied per transformer
    block (remat). ``cpu_checkpointing: true`` (the reference's host-memory
    checkpointing knob) selects the ``offload_full`` policy: each layer's
    residual-stream input is parked in pinned host DRAM via XLA's async
    device→host copies and streamed back for backward."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    #: jax-native remat policy: 'none'|'full'|'save_attn_out'|'dots_saveable'|
    #: 'nothing_saveable'|'dots_with_no_batch_dims_saveable', or host-offload
    #: variants (see models/transformer.resolve_remat_policy) incl.
    #: 'offload_save_attn_out'
    policy: str = "none"
    #: sequence-chunked FFN (FPDT's chunked MLP, reference
    #: fpdt_layer.py:1056): the dense MLP runs ``ffn_chunk``-token tiles
    #: under remat, so its [T, ffn] activations never materialize — the
    #: knob that holds 128K+ single-chip training under HBM. 0 = off.
    ffn_chunk: int = Field(default=0, ge=0)


# ---------------------------------------------------------------------------
# ZeRO
# ---------------------------------------------------------------------------

class OffloadDeviceEnum(str, Enum):
    """Reference: runtime/zero/offload_config.py OffloadDeviceEnum."""
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class OffloadOptimizerConfig(TPUConfigModel):
    """Reference: runtime/zero/offload_config.py:DeepSpeedZeroOffloadOptimizerConfig.
    On TPU 'cpu' = host DRAM via jax.device_put to CPU backend / pinned
    host memory; 'nvme' = the C++ async-io path (deepspeed_tpu/io)."""
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    #: NVMe window size in ELEMENTS per swap buffer (0 → 16M default);
    #: reference analogue: swap_tensor aligned buffer sizing
    buffer_size: int = 0
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0
    #: ZenFlow-style stall-free step (reference runtime/zenflow/engine.py:14):
    #: the host Adam for step t runs concurrently with the device fwd/bwd of
    #: step t+1 (gradients one step stale). bf16/fp32 only — fp16 dynamic
    #: loss scaling needs the synchronous overflow signal.
    overlap: bool = False
    #: SuperOffload (reference runtime/superoffload/superoffload_stage3.py):
    #: bucketed D2H gradient fetch pipelined against the SIMD Adam sweep,
    #: with a speculative step + rollback instead of a norm pre-pass.
    superoffload: bool = False

    @model_validator(mode="after")
    def _validate_superoffload(self) -> "OffloadOptimizerConfig":
        if self.superoffload and self.device.value != "cpu":
            raise ValueError(
                "offload_optimizer.superoffload requires device='cpu' "
                "(the NVMe tier has its own windowed pipeline)")
        return self


class ZenFlowTPUConfig(TPUConfigModel):
    """Reference: runtime/zenflow/zenflow_config.py (ZenFlowConfig).

    Stall-free offload with selective on-device updates: the top
    ``topk_ratio`` important gradient blocks get a synchronous device
    AdamW every step; the tail accumulates on host and applies every
    ``update_interval`` steps, overlapped (runtime/zero/zenflow.py)."""
    topk_ratio: float = 0.1
    select_strategy: str = "auto"            # parity; TPU selects by step
    select_interval: Union[str, int] = "auto"
    update_interval: Union[str, int] = "auto"
    overlap_step: bool = True
    full_warm_up_rounds: int = 2
    #: TPU knob: importance granularity in flat elements — the reference
    #: selects per-column (zenflow_stage_1_and_2.py); static-shape SPMD
    #: wants fixed-size blocks of the flat parameter space instead
    block_size: int = 4096
    #: tail learning-rate compensation: the reference applies ONE Adam step
    #: per update_interval on the accumulated tail gradient, so tail weights
    #: move ~1/interval as fast as synchronous training. 'auto' scales the
    #: tail lr by the number of accumulated steps (total movement matches
    #: the synchronous path); 1.0 reproduces the reference exactly
    tail_lr_scale: Union[str, float] = "auto"
    #: dp>1: rank selection per-shard over dp contiguous block ranges
    #: (the reference stage-3 per-rank selection,
    #: runtime/zenflow/engine_stage3.py). Off by default: on the
    #: single-controller runtime global top-K costs the same and selects
    #: strictly better; the total K budget is preserved either way.
    shard_selection: bool = False

    @model_validator(mode="after")
    def _validate(self) -> "ZenFlowTPUConfig":
        if not 0.0 < self.topk_ratio <= 1.0:
            raise ValueError("zenflow.topk_ratio must be in (0, 1]")
        for f in ("select_interval", "update_interval"):
            val = getattr(self, f)
            if isinstance(val, str) and val != "auto":
                raise ValueError(f"zenflow.{f} must be an int or 'auto'")
        return self


class OffloadParamConfig(TPUConfigModel):
    """Reference: runtime/zero/offload_config.py:DeepSpeedZeroOffloadParamConfig."""
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


class ZeroConfig(TPUConfigModel):
    """Reference: runtime/zero/config.py:DeepSpeedZeroConfig.

    TPU semantics of ``stage``:
      0 — pure data parallel: params/grads/opt replicated over 'data' axis.
      1 — optimizer states sharded over 'data' (flat fp32 master partitions).
      2 — + gradients reduce-scattered to shards (XLA emits reduce-scatter
          from the output sharding annotation on the grad pytree).
      3 — + parameters stored sharded (FSDP); allgather-on-use is emitted
          and overlapped by XLA's latency-hiding scheduler, replacing the
          reference's fetch/release hook engine
          (runtime/zero/partitioned_param_coordinator.py).
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: Union[int, str] = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: Union[int, str] = 500_000_000
    #: stage 3 only: chunk the per-layer param all-gathers / grad
    #: reduce-scatters and pipeline them against compute
    #: (runtime/zero/overlap.py). None/False keeps the monolithic
    #: whole-tree collectives (XLA still overlaps what it can).
    overlap_comm: Optional[bool] = None
    #: layer-bucket size (global param bytes) for the chunked overlap
    #: path; 0 = one chunk per layer (finest pipelining)
    overlap_bucket_bytes: int = 0
    #: chunks gathered ahead of the one computing (>=0); higher hides
    #: more latency at the cost of transient HBM (prefetch+1 gathered
    #: chunks live at once — see overlap/transient_hbm_bytes)
    overlap_prefetch: int = 1
    #: true (default): the backward re-gathers each chunk, so gathered
    #: weights never persist from forward to backward (transient HBM =
    #: prefetch+1 chunks; comm doubles for param gathers). false: keep
    #: gathered chunks as backward residuals — the reference's
    #: stage3_max_reuse_distance reuse — saving the re-gather traffic at
    #: the cost of the whole gathered stack living through the step (the
    #: HBM budget accounts whichever is selected).
    overlap_regather: bool = True
    offload_optimizer: OffloadOptimizerConfig = Field(default_factory=OffloadOptimizerConfig)
    offload_param: OffloadParamConfig = Field(default_factory=OffloadParamConfig)
    #: ZenFlow (reference zero/config.py:171): presence enables it; needs
    #: offload_optimizer.device='cpu'
    zenflow: Optional[ZenFlowTPUConfig] = None
    sub_group_size: Union[int, str] = 1_000_000_000
    stage3_max_live_parameters: Union[int, str] = 1_000_000_000
    stage3_max_reuse_distance: Union[int, str] = 1_000_000_000
    stage3_prefetch_bucket_size: Union[int, str] = 50_000_000
    stage3_param_persistence_threshold: Union[int, str] = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    round_robin_gradients: bool = False
    #: ZeRO++-style knobs — on TPU these select quantized-collective paths
    #: (int8 block quant allgather / hierarchical quantized grad reduce)
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    zero_hpz_partition_size: int = 1   # hpZ secondary shard group size (MiCS-like)
    #: MiCS (reference runtime/zero/mics.py): stage-3 param shards live
    #: within a sub-group of this size ('data_inner' mesh axis) and
    #: replicate across the outer data axis — group-local allgathers.
    #: 0/1 = off.
    mics_shard_size: int = 0
    #: log a warning then ignore knobs that XLA subsumes
    model_config = TPUConfigModel.model_config

    @model_validator(mode="after")
    def _validate_stage(self) -> "ZeroConfig":
        if self.stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        if self.overlap_bucket_bytes < 0:
            raise ValueError("zero_optimization.overlap_bucket_bytes must be >= 0")
        if self.overlap_prefetch < 0:
            raise ValueError("zero_optimization.overlap_prefetch must be >= 0")
        if self.overlap_comm and self.stage != 3:
            # ported DeepSpeed configs routinely carry overlap_comm at
            # stage 1/2, where the reference overlaps on a side stream;
            # here there is no param gather to chunk below stage 3
            logger.warning(
                "zero_optimization.overlap_comm is a stage-3 knob here "
                f"(chunked param gathers); ignored at stage {self.stage}")
            self.overlap_comm = False
        return self


# ---------------------------------------------------------------------------
# Parallel topology
# ---------------------------------------------------------------------------

class TensorParallelConfig(TPUConfigModel):
    """Reference: runtime/tensor_parallel/tp_manager.py + 'autotp_size'
    (engine.py:1020). On TPU: size of the 'model' mesh axis; parameters get
    row/column PartitionSpecs from the AutoTP sharding planner
    (deepspeed_tpu/parallel/tensor.py)."""
    enabled: bool = False
    autotp_size: int = 1
    tp_size: int = 1
    tp_grain_size: int = 1

    @model_validator(mode="after")
    def _merge(self) -> "TensorParallelConfig":
        # object.__setattr__ avoids re-triggering validate_assignment
        if self.autotp_size > 1 and self.tp_size == 1:
            object.__setattr__(self, "tp_size", self.autotp_size)
        if self.tp_size > 1:
            object.__setattr__(self, "enabled", True)
        return self


class PipelineParallelConfig(TPUConfigModel):
    """Reference: runtime/pipe/ (PipelineModule partitioning + 1F1B schedule).
    On TPU: size of the 'pipe' mesh axis; stages execute under shard_map with
    ppermute-rotated activations (deepspeed_tpu/runtime/pipe)."""
    stages: int = 1
    partition_method: str = "parameters"   # 'uniform' | 'parameters' | 'type:regex'
    micro_batches: Union[int, str] = AUTO
    activation_checkpoint_interval: int = 0
    schedule: str = "1f1b"                 # '1f1b' | 'gpipe'


class SequenceParallelConfig(TPUConfigModel):
    """Reference: deepspeed/sequence (Ulysses). On TPU: 'seq' mesh axis;
    attention uses ICI all-to-all head/sequence repartition
    (deepspeed_tpu/parallel/ulysses.py) or ring attention
    (deepspeed_tpu/parallel/ring.py)."""
    size: int = 1
    mode: str = "ulysses"  # 'ulysses' | 'ring'


class MoEConfig(TPUConfigModel):
    """Reference: deepspeed/moe (expert parallelism). On TPU: 'expert' mesh
    axis; token dispatch via jax all_to_all (deepspeed_tpu/parallel/moe.py)."""
    enabled: bool = False
    ep_size: int = 1
    num_experts: Union[int, List[int]] = 1
    top_k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    #: Residual-MoE (PR-MoE's residual half, reference moe/layer.py
    #: use_residual): each MoE layer also runs a dense MLP, mixed with
    #: the routed output by a learned per-token 2-way softmax
    use_residual: bool = False
    aux_loss_coef: float = 0.01
    # "capacity": GShard einsum dispatch with static capacity (the
    # reference's only mode; required for ep_size > 1). "dropless":
    # sort + lax.ragged_dot grouped matmul, no token ever dropped
    # (MegaBlocks-style; TPU-native extra, EP=1 only).
    impl: Literal["capacity", "dropless"] = "capacity"


# ---------------------------------------------------------------------------
# Aux subsystems
# ---------------------------------------------------------------------------

class CommsLoggerConfig(TPUConfigModel):
    """Reference: comms_logger block (utils/comms_logging.py)."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class FlopsProfilerConfig(TPUConfigModel):
    """Reference: profiling/config.py. TPU impl uses jax AOT cost analysis
    (compiled.cost_analysis()) instead of monkey-patching tensor ops."""
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class WatchdogConfig(TPUConfigModel):
    """``"telemetry": {"watchdog": {...}}`` → telemetry/watchdog.py. The
    engine arms the watchdog around each train_batch / serving decode
    step; a missed deadline dumps all-thread stacks + the flight-recorder
    black box, then warns or kills per ``action``."""
    enabled: bool = False
    #: a step taking longer than this (compile excluded only by making it
    #: generous) trips the watchdog
    step_timeout_s: float = Field(default=300.0, gt=0)
    #: "warn": log + dump and keep going; "kill": dump then hard-exit 124
    #: so the launcher's restart policy takes over
    action: Literal["warn", "kill"] = "warn"
    #: where stack/black-box/metric dumps land (default: cwd)
    dump_dir: Optional[str] = None
    #: per-host heartbeat JSON for dstpu-doctor straggler naming (default:
    #: env DSTPU_HEARTBEAT_FILE, exported by launcher/agent.py)
    heartbeat_file: Optional[str] = None


class ReqTraceConfig(TPUConfigModel):
    """``"telemetry": {"reqtrace": {...}}`` → telemetry/reqtrace.py:
    request-scoped distributed tracing with tail-based sampling. Spans a
    request's legs emit (router dispatch, hedge races, failover replays,
    prefill→decode handoff, kvtier prefetch/adopt) are buffered per
    trace_id and retained only when the request ended *interesting* —
    SLO-slow, errored/drained, or flagged (failover/hedge/reprefill/
    kvtier-fallback) — plus a configurable head-sample rate."""
    enabled: bool = False
    #: fraction of traces retained regardless of outcome (deterministic
    #: by trace_id, so every host keeps the same traces)
    head_sample: float = Field(default=0.0, ge=0.0, le=1.0)
    #: a TTFT or TPOT at/over this retains the trace (0 disables the
    #: latency trigger; flags and error reasons still retain)
    retain_slow_ms: float = Field(default=500.0, ge=0.0)
    #: in-flight traces buffered per host; oldest evicted beyond this
    buffer_traces: int = Field(default=256, ge=1)


class GoodputConfig(TPUConfigModel):
    """``"telemetry": {"goodput": {...}}`` → telemetry/goodput.py: the
    per-host wall-clock attribution ledger (goodput vs named badput
    categories, summing to 100% of process lifetime) plus the
    profile-on-regression capture trigger. Enabling it also enables the
    span tracer — the ledger attributes off the tracer ring."""
    enabled: bool = False
    #: trailing window for ``goodput/window_fraction`` (the capture
    #: trigger's signal; lifetime fraction is published separately)
    window_s: float = Field(default=60.0, gt=0)
    #: windowed goodput fraction below this arms a one-shot bounded
    #: jax.profiler capture (0 disables capture entirely; an SLO breach
    #: latch also triggers while captures are armed)
    capture_threshold: float = Field(default=0.0, ge=0.0, le=1.0)
    #: minimum seconds between capture starts
    capture_cooldown_s: float = Field(default=600.0, ge=0)
    #: capture length; the profiler is stopped on the next ledger update
    #: at/after this bound
    capture_duration_ms: float = Field(default=2000.0, gt=0)
    #: where profiler dumps land (default: ``dstpu_goodput_captures/``
    #: in the cwd); each capture gets a timestamped subdirectory
    capture_dir: Optional[str] = None


class HealthConfig(TPUConfigModel):
    """``"telemetry": {"health": {...}}`` → telemetry/health.py: in-graph
    model-health statistics (per-layer grad/param/update norms, activation
    RMS/absmax, MoE expert load + routing entropy) computed as extra
    outputs of the already-jitted fused train step. The stat branch is
    baked in at trace time — the flag never flips mid-run, so on- and
    off-cadence steps execute the *identical* program (zero retraces);
    ``every`` only gates the host-side fetch/publish."""
    enabled: bool = False
    #: fetch + publish ``health/*`` gauges every N steps (stats are
    #: computed on-device every step; off-cadence steps skip the host
    #: transfer entirely)
    every: int = Field(default=50, ge=1)
    #: tap per-layer activation RMS/absmax (and MoE router stats) from
    #: the forward pass; off → only optimizer-side per-layer norms
    activations: bool = True
    #: publish per-layer gauges for at most this many layers (0 = all);
    #: aggregates + the localizer always see every layer
    max_layers: int = Field(default=0, ge=0)
    #: |z| of a layer's grad-norm against its own rolling window past
    #: this flags ``anomaly/layer_divergence`` naming the layer
    z_threshold: float = Field(default=6.0, gt=0)
    #: an expert whose windowed mean load fraction sits below
    #: ``dead_fraction / num_experts`` counts dead; persistent deadness
    #: flags ``anomaly/expert_collapse`` naming the expert
    dead_fraction: float = Field(default=0.1, gt=0, le=1.0)


class TelemetryConfig(TPUConfigModel):
    """``"telemetry"`` block → deepspeed_tpu/telemetry (tracer + registry +
    samplers + diagnostics). Metrics recording and the flight recorder are
    always on (cheap, process-wide); this block controls span *tracing*,
    its export, and the diagnostics layer's knobs."""
    enabled: bool = False
    #: ring-buffer capacity; oldest spans evicted beyond this
    trace_buffer_events: int = Field(default=100_000, ge=1)
    #: dump Chrome trace-event JSON here at engine destruction / bench exit
    trace_file: Optional[str] = None
    #: enter jax.profiler TraceAnnotation/StepTraceAnnotation per span so
    #: names line up inside a real profiler capture
    jax_annotations: bool = False
    #: sample device/host memory gauges on monitor flushes
    sample_memory: bool = True
    #: override the per-chip peak FLOPs/s used for MFU (0/None → auto
    #: from the device kind; CPU has no peak, so MFU reads 0 there)
    peak_flops_override: Optional[float] = Field(default=None, gt=0)
    #: flight-recorder ring size (per-step records kept for the black box)
    flight_recorder_steps: int = Field(default=512, ge=1)
    #: where crash/preemption black boxes land (default:
    #: ``dstpu_blackbox_<pid>.json`` in the cwd)
    blackbox_path: Optional[str] = None
    #: warn once a single function has been retraced this many times
    compile_storm_threshold: int = Field(default=8, ge=1)
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)
    #: request-scoped distributed tracing (its own ``enabled`` gate,
    #: independent of span tracing) — telemetry/reqtrace.py
    reqtrace: ReqTraceConfig = Field(default_factory=ReqTraceConfig)
    #: goodput/badput wall-clock attribution ledger (its own ``enabled``
    #: gate; enabling it also enables span tracing) — telemetry/goodput.py
    goodput: GoodputConfig = Field(default_factory=GoodputConfig)
    #: in-graph per-layer / per-expert model-health stats (its own
    #: ``enabled`` gate) — telemetry/health.py
    health: HealthConfig = Field(default_factory=HealthConfig)
    #: serve ``GET /metrics`` + ``GET /healthz`` on this port (0 =
    #: ephemeral; None = no server) — telemetry/endpoint.py
    http_port: Optional[int] = Field(default=None, ge=0)
    #: run the full compile-time explain (telemetry/explain.py) at engine
    #: init: lowers the jitted step once more to log the roofline + HBM
    #: budget and publish roofline/* gauges. Off by default — it costs an
    #: extra XLA compile of the step program.
    explain_startup: bool = False
    #: override the per-chip peak HBM bytes/s used for the roofline
    #: memory bound (0/None → auto from the device kind)
    peak_hbm_bw_override: Optional[float] = Field(default=None, gt=0)
    #: append every registry flush to this per-host metric-history JSONL
    #: (telemetry/timeseries.py; None → no history file, though an
    #: in-memory history still backs any declared SLO objectives)
    history_file: Optional[str] = None
    #: rotate (downsample the oldest half) when the history file would
    #: exceed this many bytes
    history_max_bytes: int = Field(default=8_388_608, ge=4096)
    #: keep every Nth record of the oldest half on rotation
    history_downsample: int = Field(default=2, ge=2)
    #: flush history every N steps (0 → follow ``steps_per_print`` in the
    #: engine; the serving frontend defaults to every 10 engine steps)
    history_every: int = Field(default=0, ge=0)


class SLOConfig(TPUConfigModel):
    """``"slo"`` block → telemetry/slo.py (burn-rate objectives).

    Objectives are ``"<metric>[:field] <op> <target>"`` strings (or
    dicts with per-objective overrides), e.g.
    ``"serving/ttft_seconds:p95 <= 0.5"`` or ``"train/mfu >= 0.3"``.
    Declaring any objective turns continuous evaluation on wherever the
    metric history flows (engine + serving frontend): burn gauges under
    ``slo/*``, /healthz 503 naming the objective, flight-recorder
    events, doctor verdicts. See docs/observability.md "Metric history
    & SLOs"."""
    objectives: List[Union[str, Dict[str, Any]]] = Field(
        default_factory=list)
    #: error budget: tolerated bad fraction of evaluations (0.01 = 1%)
    budget: float = Field(default=0.01, gt=0, le=1)
    #: fast alert window (catches the cliff)
    fast_window_s: float = Field(default=60.0, gt=0)
    #: slow alert window (suppresses blips); must exceed fast_window_s
    slow_window_s: float = Field(default=600.0, gt=0)
    #: breach when BOTH windows burn budget at ≥ this multiple of the
    #: sustainable rate
    burn_threshold: float = Field(default=2.0, gt=0)

    @model_validator(mode="after")
    def _windows_ordered(self):
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError(
                f"slo.fast_window_s ({self.fast_window_s}) must be "
                f"shorter than slo.slow_window_s ({self.slow_window_s})")
        return self


class ServingConfig(TPUConfigModel):
    """``"serving"`` block → deepspeed_tpu/serving (ServingFrontend).

    Decode megasteps: when the SplitFuse selection is decode-only, the
    frontend may run up to ``megastep_tokens`` single-token iterations in
    ONE jitted device program (engine_v2 ``_try_megastep``) — the host
    syncs once per window instead of 2+ round-trips per token. Megastep
    boundaries are the admission/shed/cancel points, so bigger windows
    trade TTFT responsiveness for dispatch amortization (docs/serving.md
    "Decode megasteps")."""
    #: max decode tokens per device-resident window (0/1 = stepwise;
    #: ServingFrontend(megastep_tokens=...) overrides)
    megastep_tokens: int = Field(default=0, ge=0)
    #: shrink the window dynamically: pending admissions cap it at the
    #: shallowest remaining budget, a shallow decode backlog and tight
    #: deadlines (roofline-predicted decode step time) pull it toward 1
    megastep_adaptive: bool = True


class RouterConfig(TPUConfigModel):
    """``"router"`` block → serving/router.py (the multi-replica tier;
    docs/serving.md "Router, failover & draining"). Every knob has a
    same-named ``Router(...)`` kwarg override."""
    #: replicas a local pool spins up (dstpu-router / launcher --pool)
    replicas: int = Field(default=2, ge=1)
    #: leading prompt tokens hashed for prefix-affinity placement —
    #: shared-prefix traffic lands where the radix cache is warm
    affinity_tokens: int = Field(default=64, ge=1)
    #: override affinity when the target is this many times busier than
    #: the least-loaded replica (warm cache never justifies a hot queue)
    spill_factor: float = Field(default=2.0, ge=1.0)
    #: race a second replica for requests with no first token past the
    #: hedge delay
    hedge: bool = True
    #: fixed hedge delay; None derives it from the router's observed
    #: TTFT p95 (0.25s until 20 samples exist)
    hedge_delay_s: Optional[float] = Field(default=None, gt=0)
    #: mid-stream re-dispatches one request survives before it is
    #: finished with reason ``"error"`` (fleet tier above
    #: resilience.serving_retry_budget, which is per-replica)
    retry_budget: int = Field(default=2, ge=0)
    #: consecutive failure observations that open a replica's breaker
    breaker_failures: int = Field(default=3, ge=1)
    #: half-open probe backoff: initial, doubling per failed probe
    breaker_backoff_s: float = Field(default=1.0, gt=0)
    #: backoff cap
    breaker_backoff_max_s: float = Field(default=30.0, gt=0)
    #: an assigned stream making no progress for this long counts as a
    #: breaker failure and fails over
    stall_timeout_s: float = Field(default=30.0, gt=0)
    #: poll replica /healthz+/metrics endpoints every N router polls
    #: (0 disables the out-of-band sweep)
    health_every: int = Field(default=50, ge=0)
    #: per-pump latency a ``replica_slow`` chaos fault injects
    chaos_slow_s: float = Field(default=0.25, ge=0)


class AutoscaleConfig(TPUConfigModel):
    """``"autoscale"`` block → serving/autoscaler.py (SLO-driven fleet
    elasticity; docs/serving.md "Disaggregated pools & autoscaling").
    Every knob has a same-named ``Autoscaler(...)`` kwarg."""
    #: master switch — off, the fleet keeps its launch size
    enabled: bool = False
    #: per-pool replica floor/ceiling (the ``any`` pool of a monolithic
    #: fleet uses min(floors)..max(ceilings))
    prefill_min: int = Field(default=1, ge=0)
    prefill_max: int = Field(default=4, ge=1)
    decode_min: int = Field(default=1, ge=0)
    decode_max: int = Field(default=8, ge=1)
    #: mean in-flight requests per replica past which the pool grows
    #: (the queueing knee: beyond it TTFT grows super-linearly)
    queue_high: float = Field(default=4.0, gt=0)
    #: a pool at zero load this long shrinks toward its floor
    idle_s: float = Field(default=5.0, gt=0)
    #: per-pool freeze after any scale action (flapping guard)
    cooldown_s: float = Field(default=10.0, ge=0)
    #: decision cadence for ``maybe_evaluate``
    evaluate_every_s: float = Field(default=1.0, gt=0)
    #: ``slo/worst_burn`` at or above this adds capacity even before
    #: queue depth shows the pressure
    burn_threshold: float = Field(default=1.0, gt=0)
    #: scale-down drain deadline — stragglers past it fail over with
    #: the token fold instead of pinning the replica open
    drain_deadline_s: float = Field(default=30.0, gt=0)

    @model_validator(mode="after")
    def _floors_below_ceilings(self) -> "AutoscaleConfig":
        if self.prefill_min > self.prefill_max:
            raise ValueError(
                f"autoscale.prefill_min ({self.prefill_min}) > "
                f"autoscale.prefill_max ({self.prefill_max})")
        if self.decode_min > self.decode_max:
            raise ValueError(
                f"autoscale.decode_min ({self.decode_min}) > "
                f"autoscale.decode_max ({self.decode_max})")
        return self


class TuneConfig(TPUConfigModel):
    """``"tune"`` block — the stamp ``dstpu-tune`` writes into emitted
    configs (autotuning/tune.py:emit_config). Purely informational: it
    records where the knobs came from (target platform/chips, the
    winning candidate's search key, the roofline prediction) so
    ``bench.py --from-config`` can compare predicted vs measured and
    ``dstpu_report --compare`` can gate the drift. The engine never
    reads it."""
    #: True on configs emitted by dstpu-tune
    tuned: bool = False
    #: model preset the sweep was scored for (e.g. "llama3-8b") — lets
    #: ``bench.py --from-config`` rebuild the same model
    model: Optional[str] = None
    #: target chip the peaks were modeled for (v5e/v5p/...)
    platform: Optional[str] = None
    #: target chip count the mesh factorizes
    chips: Optional[int] = None
    #: sequence length the candidate was scored at
    seq_len: Optional[int] = None
    #: the winning mesh shape ({axis: size})
    mesh: Dict[str, int] = Field(default_factory=dict)
    #: roofline-predicted step time for the winner (0/None = no model)
    predicted_step_ms: Optional[float] = None
    #: roofline bound of the winner (compute/memory/comm/unknown)
    bound: Optional[str] = None
    #: "analytic" (closed-form) or "lowered" (real XLA cost analysis)
    source: Optional[str] = None
    candidates_scored: Optional[int] = None
    candidates_pruned: Optional[int] = None
    #: deterministic candidate identity (search.Candidate.key())
    search_key: Optional[str] = None
    #: serving-plan engine recommendations (engine_v2 construction keys:
    #: max_batch_tokens / prefill_chunk / max_sequences) — carried here
    #: because they are constructor kwargs, not a config block
    serving_engine: Dict[str, Any] = Field(default_factory=dict)


class ResilienceConfig(TPUConfigModel):
    """``"resilience"`` block → deepspeed_tpu/resilience (fault injection
    + recovery policy; docs/resilience.md). The fault plan makes chaos
    testing a config key: the same plan replays the same faults at the
    same steps, so recovery paths run in CI instead of for the first
    time in production."""
    #: deterministic fault schedule (';'-separated
    #: ``<trigger>:<at>:<kind>[:<site>]`` entries — see
    #: resilience/faults.py); env ``DSTPU_FAULT_PLAN`` adds to it.
    #: None → injector disarmed (production default).
    fault_plan: Optional[str] = None
    #: bounded exponential-backoff retries for transient checkpoint
    #: fragment-write IO errors (checkpoint/store.py)
    ckpt_io_retries: int = Field(default=3, ge=0)
    #: initial retry backoff, doubling per attempt
    ckpt_io_backoff_s: float = Field(default=0.05, ge=0)
    #: engine faults a running serving request survives before it is
    #: finished with reason ``"error"`` (serving/frontend.py)
    serving_retry_budget: int = Field(default=2, ge=0)


class KVTierConfig(TPUConfigModel):
    """``"kvtier"`` block → serving/kvtier.py (vertical HBM → host DRAM
    → NVMe page tier under the radix prefix cache; docs/serving.md
    "Tiered KV cache"). Off by default: serving behavior is unchanged
    until a deployment opts in to holding idle conversations' KV below
    HBM for warm resume."""
    #: build a KVTier under the frontend's prefix cache
    enabled: bool = False
    #: host-DRAM arena budget for captured page bundles (bytes)
    dram_bytes: int = Field(default=256 << 20, ge=0)
    #: NVMe spill directory; None → DRAM-only (watermark overflow drops
    #: the coldest entries instead of spilling)
    nvme_dir: Optional[str] = None
    #: NVMe level budget (bytes); None → unbounded
    nvme_max_bytes: Optional[int] = Field(default=None, ge=0)
    #: DRAM usage fraction that triggers spilling …
    high_watermark: float = Field(default=0.9, gt=0, le=1)
    #: … and the fraction spilling drains back down to (hysteresis)
    low_watermark: float = Field(default=0.7, gt=0, le=1)
    #: cold-page encoding: "none" (byte-exact), "fp16" or "int8"
    #: (EQuARX-style low-precision, halves/quarters tier footprint)
    compress: Literal["none", "fp16", "int8"] = "none"

    @model_validator(mode="after")
    def _watermarks_ordered(self) -> "KVTierConfig":
        if self.low_watermark > self.high_watermark:
            raise ValueError(
                f"kvtier.low_watermark ({self.low_watermark}) > "
                f"kvtier.high_watermark ({self.high_watermark})")
        return self


class TensorBoardConfig(TPUConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class WandbConfig(TPUConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(TPUConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class CometConfig(TPUConfigModel):
    """Reference: monitor/config.py CometConfig (comet_ml writer)."""
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


class MonitorConfig(TPUConfigModel):
    """Reference: monitor/config.py → MonitorMaster fan-out."""
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    comet: CometConfig = Field(default_factory=CometConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)


class CheckpointConfig(TPUConfigModel):
    """Reference: checkpoint block (runtime/config.py checkpoint_config) +
    checkpoint_engine selection. TPU default engine is orbax-backed with a
    universal (mesh-agnostic) per-parameter fragment layout."""
    tag_validation: str = "Warn"   # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    async_save: bool = False


class DataEfficiencyConfig(TPUConfigModel):
    """Reference: runtime/data_pipeline/config.py (curriculum etc.)."""
    enabled: bool = False
    seed: int = 1234
    curriculum_learning: Dict[str, Any] = Field(default_factory=dict)
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)


class ElasticityConfig(TPUConfigModel):
    """Reference: deepspeed/elasticity/config.py."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.2


class CompressionConfig(TPUConfigModel):
    """Reference: deepspeed/compression/config.py (subset round 1)."""
    weight_quantization: Dict[str, Any] = Field(default_factory=dict)
    activation_quantization: Dict[str, Any] = Field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = Field(default_factory=dict)
    row_pruning: Dict[str, Any] = Field(default_factory=dict)
    head_pruning: Dict[str, Any] = Field(default_factory=dict)
    channel_pruning: Dict[str, Any] = Field(default_factory=dict)
    layer_reduction: Dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Master config
# ---------------------------------------------------------------------------

class DeepSpeedTPUConfig(TPUConfigModel):
    """The master config (reference: runtime/config.py:DeepSpeedConfig:651).

    Batch triple resolution implemented in :meth:`resolve_batch_sizes`
    (reference batch-size solver semantics: train_batch_size =
    micro_batch_per_replica × gradient_accumulation_steps × dp_world_size).
    """

    train_batch_size: Union[int, str, None] = None
    train_micro_batch_size_per_gpu: Union[int, str, None] = None
    gradient_accumulation_steps: Union[int, str, None] = None

    optimizer: OptimizerConfig = Field(default_factory=OptimizerConfig)
    scheduler: SchedulerConfig = Field(default_factory=SchedulerConfig)

    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    #: dtype of cross-replica gradient reduction (reference knob
    #: communication_data_type, stage_1_and_2.py:159)
    communication_data_type: Optional[str] = None

    zero_optimization: ZeroConfig = Field(default_factory=ZeroConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)

    tensor_parallel: TensorParallelConfig = Field(default_factory=TensorParallelConfig)
    pipeline: PipelineParallelConfig = Field(default_factory=PipelineParallelConfig)
    sequence_parallel: SequenceParallelConfig = Field(default_factory=SequenceParallelConfig)
    moe: MoEConfig = Field(default_factory=MoEConfig)

    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    slo: SLOConfig = Field(default_factory=SLOConfig)
    serving: ServingConfig = Field(default_factory=ServingConfig)
    kvtier: KVTierConfig = Field(default_factory=KVTierConfig)
    router: RouterConfig = Field(default_factory=RouterConfig)
    autoscale: AutoscaleConfig = Field(default_factory=AutoscaleConfig)
    resilience: ResilienceConfig = Field(default_factory=ResilienceConfig)
    tune: TuneConfig = Field(default_factory=TuneConfig)
    monitor_config: MonitorConfig = Field(default_factory=MonitorConfig)
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    data_efficiency: DataEfficiencyConfig = Field(default_factory=DataEfficiencyConfig)
    elasticity: ElasticityConfig = Field(default_factory=ElasticityConfig)
    compression_training: CompressionConfig = Field(default_factory=CompressionConfig)

    #: attention implementation (the reference's replace_with_kernel_inject
    #: seam, inference/config.py): 'auto' picks the chunked-XLA path —
    #: robust on every TPU runtime; 'pallas_flash' opts into the Pallas
    #: kernel (fastest where Mosaic runs at full MXU rate); 'naive'
    #: materializes [T,T] scores (tests/short seqs only)
    attention_impl: str = "auto"

    #: chunked cross-entropy logits budget in MB (None → env
    #: DSTPU_CE_BUDGET_MB or 512). Bigger chunks feed the MXU better on
    #: large-vocab logits matmuls; this is the autotuner's ce axis.
    chunked_ce_budget_mb: Optional[int] = Field(default=None, ge=1)
    #: 'bf16' emits chunked-CE logits in bf16 (fp32 MXU accumulation is
    #: kept; only the [B,C,V] HBM roundtrip halves). Default fp32.
    ce_logits_dtype: Optional[Literal["fp32", "float32", "bf16",
                                      "bfloat16"]] = None

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    dump_state: bool = False
    memory_breakdown: bool = False
    seed: int = 1234
    #: NaN/Inf sanity checks (reference is_sanity_checks_enabled). True or
    #: "debug" flips global jax_debug_nans (raises at the offending op but
    #: de-optimizes EVERY jitted fn); "scoped" keeps full-speed jit and
    #: instead runs a per-leaf finite check on the grads each step,
    #: reporting the first bad leaf path through telemetry/anomaly.py
    check_nan_inf: Union[bool, Literal["debug", "scoped"]] = False

    deprecated_aliases = {
        "tensorboard": "monitor_config",
    }

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_any(cls, config: Union[str, Dict[str, Any], "DeepSpeedTPUConfig", None]
                 ) -> "DeepSpeedTPUConfig":
        if config is None:
            return cls()
        if isinstance(config, DeepSpeedTPUConfig):
            return config
        if isinstance(config, str):
            with open(config) as fh:
                config = json.load(fh)
        if not isinstance(config, dict):
            raise TypeError(f"config must be a dict, json path, or "
                            f"DeepSpeedTPUConfig, got {type(config)}")
        config = dict(config)
        # accept the reference's nested "monitor" keys at top level
        monitor_keys = {}
        for key in ("tensorboard", "wandb", "comet", "csv_monitor"):
            if key in config:
                monitor_keys[key] = config.pop(key)
        if monitor_keys:
            config.setdefault("monitor_config", {}).update(monitor_keys)
        return cls(**config)

    # -- batch triple solver -------------------------------------------------

    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        """Solve train_batch = micro × gas × dp (reference
        runtime/config.py:_batch_assertion / _set_batch_related_parameters)."""
        tb = None if is_auto(self.train_batch_size) else self.train_batch_size
        mb = None if is_auto(self.train_micro_batch_size_per_gpu) else \
            self.train_micro_batch_size_per_gpu
        gas = None if is_auto(self.gradient_accumulation_steps) else \
            self.gradient_accumulation_steps

        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"train_batch_size ({tb}) != micro_batch ({mb}) × "
                    f"grad_accum ({gas}) × dp_world ({dp_world_size})")
        elif tb is not None and mb is not None:
            gas, rem = divmod(tb, mb * dp_world_size)
            if rem:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by micro_batch×dp "
                    f"{mb * dp_world_size}")
        elif tb is not None and gas is not None:
            mb, rem = divmod(tb, gas * dp_world_size)
            if rem:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by gas×dp "
                    f"{gas * dp_world_size}")
        elif mb is not None:
            gas = gas or 1
            tb = mb * gas * dp_world_size
        elif tb is not None:
            mb, rem = divmod(tb, dp_world_size)
            gas = 1
            if rem:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by dp world "
                    f"{dp_world_size}")
        else:
            # reference defaults to train_batch_size=32; we default micro=1
            mb, gas = 1, 1
            tb = mb * gas * dp_world_size
        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas

    # -- precision helpers ---------------------------------------------------

    @property
    def compute_dtype(self) -> str:
        if self.fp16.enabled is True:
            return "float16"
        if self.bf16.enabled is True:
            return "bfloat16"
        # TPU-native default: bf16 unless user explicitly disabled both
        if self.bf16.enabled is False and self.fp16.enabled is False:
            return "float32"
        return "bfloat16"

    @property
    def zero_enabled(self) -> bool:
        return self.zero_optimization.stage > 0
