from deepspeed_tpu.config.config import (
    ActivationCheckpointingConfig,
    BF16Config,
    CheckpointConfig,
    CommsLoggerConfig,
    DeepSpeedTPUConfig,
    ElasticityConfig,
    FlopsProfilerConfig,
    FP16Config,
    MoEConfig,
    MonitorConfig,
    OffloadDeviceEnum,
    OffloadOptimizerConfig,
    OffloadParamConfig,
    OptimizerConfig,
    PipelineParallelConfig,
    RouterConfig,
    SchedulerConfig,
    SequenceParallelConfig,
    TensorParallelConfig,
    ZeroConfig,
)
from deepspeed_tpu.config.config_utils import AUTO, TPUConfigModel, is_auto

__all__ = [
    "AUTO", "is_auto", "TPUConfigModel", "DeepSpeedTPUConfig",
    "OptimizerConfig", "SchedulerConfig", "FP16Config", "BF16Config",
    "ZeroConfig", "OffloadDeviceEnum", "OffloadOptimizerConfig",
    "OffloadParamConfig", "TensorParallelConfig", "PipelineParallelConfig",
    "SequenceParallelConfig", "MoEConfig", "CommsLoggerConfig",
    "FlopsProfilerConfig", "MonitorConfig", "CheckpointConfig",
    "ElasticityConfig", "ActivationCheckpointingConfig", "RouterConfig",
]
