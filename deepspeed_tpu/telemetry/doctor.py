"""``dstpu-doctor``: post-mortem health reports from flight-recorder
black boxes.

Feed it one or many per-host dumps (plus optional watchdog heartbeat
files) and it prints what an on-call engineer wants first:

- where the run stopped (last completed step per host) and why
  (exception / watchdog / preemption / nothing recorded);
- per-step timing and the slowest host per step (straggler skew);
- achieved vs **algorithmic** collective bandwidth — byte counts come
  from trace-time recording, converted per op with
  :func:`~deepspeed_tpu.comm.comms_logger.get_msg_size` (ring all-reduce
  moves ``2(w-1)/w`` of the payload per rank, all-gather ``(w-1)/w``);
- recompile storms and the anomaly timeline;
- a plain-language verdict, ranked crash > hang > non-finite > straggler
  > recompile storm > healthy.

Usage::

    dstpu-doctor host0_blackbox.json host1_blackbox.json
    python -m deepspeed_tpu.telemetry.doctor --json dump.json
"""

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from deepspeed_tpu.comm.comms_logger import convert_size, get_msg_size
from deepspeed_tpu.telemetry.flight_recorder import load_dump

#: slowest-host mean step time must exceed the fastest by this factor
#: before the verdict calls out a straggler
STRAGGLER_SKEW_FACTOR = 1.5


def _host_name(doc: Dict[str, Any], idx: int) -> str:
    meta = doc.get("meta", {})
    host = meta.get("hostname") or f"host{idx}"
    pi = meta.get("process_index")
    return f"{host}[p{pi}]" if pi is not None else host


def _mean(vals: List[float]) -> Optional[float]:
    return sum(vals) / len(vals) if vals else None


def analyze(dumps: List[Dict[str, Any]],
            heartbeats: Optional[List[Dict[str, Any]]] = None
            ) -> Dict[str, Any]:
    """Pure analysis: per-host dumps → structured report dict."""
    hosts = []
    for i, doc in enumerate(dumps):
        steps = doc.get("steps", [])
        durs = [s["dur_ms"] for s in steps
                if isinstance(s.get("dur_ms"), (int, float))]
        watchdog_events = [e for e in doc.get("events", [])
                           if e.get("kind") == "watchdog"]
        preempt_events = [e for e in doc.get("events", [])
                          if e.get("kind") == "preemption"]
        fault_events = [e for e in doc.get("events", [])
                        if e.get("kind") == "fault_injected"]
        recovery_events = [e for e in doc.get("events", [])
                           if e.get("kind") == "recovery"]
        slo_events = [e for e in doc.get("events", [])
                      if e.get("kind") in ("slo_breach", "slo_recovered")]
        # goodput ledger state: the black box's own summary section when
        # present, else reconstructed from the metrics_text exposition
        gp = doc.get("goodput") if isinstance(doc.get("goodput"), dict) \
            else None
        if gp is None and doc.get("metrics_text"):
            try:
                from deepspeed_tpu.telemetry.fleet import (
                    goodput_state, parse_prometheus_text)
                gp = goodput_state(
                    parse_prometheus_text(doc["metrics_text"]))
            except Exception:                        # noqa: BLE001
                gp = None
        hosts.append({
            "name": _host_name(doc, i),
            "reason": doc.get("reason"),
            "last_step": steps[-1]["step"] if steps else None,
            "n_steps": len(steps),
            "mean_step_ms": _mean(durs),
            "max_step_ms": max(durs) if durs else None,
            "exception": doc.get("exception"),
            "watchdog": watchdog_events,
            "preemption": preempt_events,
            "faults_injected": fault_events,
            "recoveries": recovery_events,
            "storms": (doc.get("compile") or {}).get("storms", []),
            "compile_functions": (doc.get("compile") or {}).get(
                "functions", {}),
            "slo_events": slo_events,
            "goodput": gp,
        })
        # predicted vs achieved: when the black box carries an explain
        # snapshot (telemetry/explain.py), compare its roofline
        # prediction against this host's measured mean step time
        exp = (doc.get("explain") or {}).get("train") or {}
        pred_ms = ((exp.get("roofline") or {}).get("predicted_ms")
                   or 0.0)
        if pred_ms > 0:
            row = {"predicted_ms": pred_ms,
                   "bound": (exp.get("roofline") or {}).get("bound")}
            mean = hosts[-1]["mean_step_ms"]
            if mean:
                row["pct_of_roofline"] = 100.0 * pred_ms / mean
            hosts[-1]["roofline"] = row

    # -- straggler skew: per-step slowest host over steps seen everywhere
    per_step: Dict[int, Dict[str, float]] = {}
    for i, doc in enumerate(dumps):
        name = _host_name(doc, i)
        for s in doc.get("steps", []):
            if isinstance(s.get("dur_ms"), (int, float)):
                per_step.setdefault(s["step"], {})[name] = s["dur_ms"]
    slowest_counts: Dict[str, int] = {}
    shared_steps = {k: v for k, v in per_step.items() if len(v) > 1}
    for step, by_host in shared_steps.items():
        slowest_counts[max(by_host, key=by_host.get)] = \
            slowest_counts.get(max(by_host, key=by_host.get), 0) + 1
    straggler = None
    means = {h["name"]: h["mean_step_ms"] for h in hosts
             if h["mean_step_ms"]}
    if len(means) > 1:
        slow = max(means, key=means.get)
        fast = min(means, key=means.get)
        skew = means[slow] / means[fast] if means[fast] > 0 else 1.0
        straggler = {"host": slow, "skew": skew,
                     "slow_mean_ms": means[slow],
                     "fast_mean_ms": means[fast],
                     "slowest_step_counts": slowest_counts,
                     "significant": skew >= STRAGGLER_SKEW_FACTOR}

    # -- stalled heartbeat naming (multi-host hang: the host whose step
    # counter stopped advancing, or whose phase says "stalled")
    stalled = []
    for hb in heartbeats or []:
        if hb.get("phase") == "stalled":
            stalled.append({"host": hb.get("hostname"),
                            "step": hb.get("step"),
                            "label": hb.get("label")})

    # -- collective bandwidth: algorithmic bytes via get_msg_size over
    # recorded per-op time; zero recorded time (trace-time logging under
    # jit) falls back to total stepped wall time as an UPPER BOUND
    world = max([d.get("meta", {}).get("process_count") or 1
                 for d in dumps] + [len(dumps)])
    bandwidth = []
    for i, doc in enumerate(dumps):
        total_step_s = sum(s["dur_ms"] for s in doc.get("steps", [])
                           if isinstance(s.get("dur_ms"), (int, float))
                           ) / 1e3
        for op, sizes in (doc.get("comm") or {}).items():
            alg_bytes = 0
            raw_bytes = 0
            t = 0.0
            calls = 0
            for size, (count, total_t) in sizes.items():
                alg_bytes += get_msg_size(op, int(size), world) * count
                raw_bytes += int(size) * count
                t += total_t
                calls += count
            row = {"host": _host_name(doc, i), "op": op, "calls": calls,
                   "raw_bytes": raw_bytes, "algorithmic_bytes": alg_bytes}
            if t > 0:
                row["achieved_gbps"] = alg_bytes / t / 1e9
            elif total_step_s > 0:
                row["achieved_gbps_upper_bound"] = \
                    alg_bytes / total_step_s / 1e9
            bandwidth.append(row)

    # -- recovery timeline: every fault/recovery-shaped event across
    # hosts in time order — the chaos-run audit trail (which faults
    # fired, which recovery answered each, what is still open)
    recovery_timeline = []
    for i, doc in enumerate(dumps):
        for e in doc.get("events", []):
            if e.get("kind") in ("fault_injected", "recovery",
                                 "ckpt_fallback", "serving_engine_fault",
                                 "preemption", "router_replica_kill",
                                 "router_replica_slow", "router_failover",
                                 "router_breaker", "router_drain_start",
                                 "router_drained", "router_handoff",
                                 "router_handoff_fallback",
                                 "router_replica_added", "autoscale_up",
                                 "autoscale_down", "kvtier_spill",
                                 "kvtier_adopt", "kvtier_fallback"):
                recovery_timeline.append({**e, "host": _host_name(doc, i)})
    recovery_timeline.sort(key=lambda e: (e.get("ts", 0.0),
                                          e.get("step") or 0))
    n_faults = sum(len(h["faults_injected"]) for h in hosts)
    n_recoveries = sum(len(h["recoveries"]) for h in hosts)

    # -- crash-loop naming from agent heartbeats: a host whose launch
    # agent is burning its rolling restart budget. A "draining" phase
    # is the OPPOSITE of a crash loop — an intentional scale-down in
    # flight — and is reported separately so operators don't page on it
    crash_looping = []
    draining = []
    for hb in heartbeats or []:
        if hb.get("phase") in ("restart_backoff", "crash_loop"):
            crash_looping.append(
                {"host": hb.get("hostname"),
                 "phase": hb.get("phase"),
                 "restarts_in_window": hb.get("restarts_in_window"),
                 "backoff_s": hb.get("backoff_s"),
                 "rc": hb.get("rc")})
        elif hb.get("phase") == "draining":
            draining.append({"host": hb.get("hostname"),
                             "replica": hb.get("replica")})

    # -- SLO breach timeline: breach/recovery transitions recorded by
    # the burn-rate engine (telemetry/slo.py); an objective whose latest
    # transition on some host is a breach is still OPEN there
    slo_timeline = []
    for i, doc in enumerate(dumps):
        for e in doc.get("events", []):
            if e.get("kind") in ("slo_breach", "slo_recovered"):
                slo_timeline.append({**e, "host": _host_name(doc, i)})
    slo_timeline.sort(key=lambda e: (e.get("ts", 0.0), e.get("step") or 0))
    latest_slo: Dict[Any, Dict[str, Any]] = {}
    for e in slo_timeline:
        latest_slo[(e["host"], e.get("objective"))] = e
    slo_open = [e for e in latest_slo.values()
                if e.get("kind") == "slo_breach"]

    # -- slow requests: tail-retained request traces from each host's
    # reqtrace black-box section, worst total first, each with its
    # critical-path dominant segment (the acceptance question "where did
    # this slow request's time go" answered without opening Perfetto)
    slow_requests = []
    trace_drops = {"dropped_ok": 0.0, "ring_dropped": 0.0, "pending": 0}
    for i, doc in enumerate(dumps):
        rq = doc.get("reqtrace") or {}
        trace_drops["dropped_ok"] += float(rq.get("dropped_ok") or 0)
        trace_drops["ring_dropped"] += float(rq.get("ring_dropped") or 0)
        trace_drops["pending"] += int(rq.get("pending") or 0)
        for s in rq.get("retained", []):
            row = {**s, "host": _host_name(doc, i)}
            bd = dict(s.get("breakdown_ms") or {})
            if bd:
                dom = max(bd, key=bd.get)
                total = s.get("total_ms") or sum(bd.values()) or 1.0
                row["dominant"] = dom
                row["dominant_pct"] = \
                    100.0 * bd[dom] / max(total, 1e-9)
            slow_requests.append(row)
    slow_requests.sort(key=lambda r: -(r.get("total_ms") or 0.0))

    # -- anomaly timeline across hosts
    timeline = []
    for i, doc in enumerate(dumps):
        for e in doc.get("events", []):
            if e.get("kind") == "anomaly":
                timeline.append({**e, "host": _host_name(doc, i)})
    timeline.sort(key=lambda e: (e.get("ts", 0.0), e.get("step") or 0))
    nonfinite = [e for e in timeline
                 if str(e.get("anomaly", "")).startswith("nonfinite")]
    # model-health localizer flags (telemetry/health.py → anomaly.py):
    # carry the layer/expert coordinates so the verdict can NAME the
    # diverged component, not just count anomalies
    layer_div = [e for e in timeline
                 if e.get("anomaly") == "layer_divergence"]
    expert_col = [e for e in timeline
                  if e.get("anomaly") == "expert_collapse"]

    # -- goodput: worst ledger fraction across reporting hosts; below
    # LOW_GOODPUT_FRACTION the verdict names the dominant badput
    from deepspeed_tpu.telemetry.goodput import LOW_GOODPUT_FRACTION
    low_goodput = sorted(
        (h for h in hosts
         if isinstance((h.get("goodput") or {}).get("fraction"),
                       (int, float))
         and h["goodput"]["fraction"] < LOW_GOODPUT_FRACTION),
        key=lambda h: h["goodput"]["fraction"])

    # -- verdict, most fatal condition first
    crashed = [h for h in hosts if h["exception"]]
    hung = [h for h in hosts if h["watchdog"]]
    preempted = [h for h in hosts if h["preemption"]]
    storms = sorted({s for h in hosts for s in h["storms"]})
    if crashed:
        h = crashed[0]
        verdict = (f"CRASH on {h['name']} after step {h['last_step']}: "
                   f"{h['exception']['type']}: "
                   f"{h['exception']['message'][:200]}")
    elif hung or stalled:
        if stalled:
            s = stalled[0]
            verdict = (f"HANG: host {s['host']} stalled at step "
                       f"{s['step']} ({s['label']}) — see its watchdog "
                       f"stack dump")
        else:
            h = hung[0]
            ev = h["watchdog"][0]
            verdict = (f"HANG on {h['name']}: step {ev.get('step')} "
                       f"({ev.get('label')}) missed the "
                       f"{ev.get('timeout_s')}s watchdog deadline")
    elif crash_looping:
        c = crash_looping[0]
        verdict = (f"CRASH LOOP: host {c['host']} has burned "
                   f"{c['restarts_in_window']} restarts of its rolling "
                   f"budget (agent phase {c['phase']}, last rc "
                   f"{c.get('rc')})")
    elif preempted:
        h = preempted[0]
        verdict = (f"PREEMPTED on {h['name']} at step {h['last_step']} "
                   f"(checkpoint tag "
                   f"{h['preemption'][0].get('checkpoint_tag')!r})")
    elif nonfinite:
        e = nonfinite[0]
        verdict = (f"NON-FINITE values from step {e.get('step')} on "
                   f"{e['host']}: {e.get('detail') or e.get('anomaly')}")
    elif layer_div:
        e = layer_div[0]
        z = e.get("z")
        verdict = (f"LAYER DIVERGENCE on {e['host']}: layer "
                   f"{e.get('layer')} {e.get('stat', 'grad_norm')} "
                   f"z={z:+.1f} from step {e.get('step')} "
                   f"({len(layer_div)} flag(s))"
                   if isinstance(z, (int, float)) else
                   f"LAYER DIVERGENCE on {e['host']}: layer "
                   f"{e.get('layer')} from step {e.get('step')}")
    elif expert_col:
        e = expert_col[0]
        ld = e.get("load")
        verdict = (f"EXPERT COLLAPSE on {e['host']}: expert "
                   f"{e.get('expert')} windowed load "
                   f"{ld if ld is not None else '?'} from step "
                   f"{e.get('step')} ({len(expert_col)} flag(s))")
    elif slo_open:
        e = slo_open[0]
        verdict = (f"SLO BREACH on {e['host']}: objective "
                   f"{e.get('objective')} ({e.get('metric')} "
                   f"{e.get('op')} {e.get('target')}) still burning at "
                   f"{e.get('burn_fast')}x budget "
                   f"(last value {e.get('value')})")
    elif low_goodput:
        h = low_goodput[0]
        gp = h["goodput"]
        dom = gp.get("dominant_badput") or "other"
        dom_s = gp.get("dominant_badput_s") or \
            (gp.get("badput") or {}).get(dom, 0.0)
        verdict = (f"LOW GOODPUT on {h['name']}: "
                   f"{100.0 * gp['fraction']:.0f}% of wall clock was "
                   f"productive; dominant badput {dom} ({dom_s:.1f}s)")
    elif straggler and straggler["significant"]:
        verdict = (f"STRAGGLER: {straggler['host']} runs "
                   f"{straggler['skew']:.2f}x slower than the fastest "
                   f"host ({straggler['slow_mean_ms']:.1f}ms vs "
                   f"{straggler['fast_mean_ms']:.1f}ms mean step)")
    elif storms:
        verdict = (f"RECOMPILATION STORM: {', '.join(storms)} — check "
                   f"for drifting shapes or out-of-bucket requests")
    elif slo_timeline:
        n_br = len([e for e in slo_timeline if e["kind"] == "slo_breach"])
        verdict = (f"SLO BREACHED AND RECOVERED: {n_br} breach(es) over "
                   f"the run, all recovered (first: "
                   f"{slo_timeline[0].get('objective')} at step "
                   f"{slo_timeline[0].get('step')})")
    elif timeline:
        verdict = (f"COMPLETED WITH ANOMALIES: {len(timeline)} flagged "
                   f"(first: {timeline[0].get('anomaly')} at step "
                   f"{timeline[0].get('step')})")
    else:
        verdict = "HEALTHY: no crash, hang, anomaly, or storm recorded"

    return {"hosts": hosts, "straggler": straggler, "stalled": stalled,
            "bandwidth": bandwidth, "anomalies": timeline,
            "model_health": {"layer_divergence": layer_div,
                             "expert_collapse": expert_col},
            "storms": storms, "world": world, "verdict": verdict,
            "slo": {"timeline": slo_timeline, "open": slo_open},
            "recovery_timeline": recovery_timeline,
            "reqtrace": {"slow_requests": slow_requests, **trace_drops},
            "crash_looping": crash_looping, "draining": draining,
            "goodput": {"low": [{"host": h["name"], **h["goodput"]}
                                for h in low_goodput]},
            "resilience": {"faults_injected": n_faults,
                           "recoveries": n_recoveries,
                           "unrecovered": max(0, n_faults - n_recoveries)}}


def render(report: Dict[str, Any]) -> str:
    """Structured report → plain-text health report."""
    out: List[str] = []
    out.append("== dstpu-doctor report ==")
    out.append(f"VERDICT: {report['verdict']}")
    out.append("")
    out.append(f"{'host':<24}{'last step':>10}{'steps':>7}"
               f"{'mean ms':>10}{'max ms':>10}  status")
    for h in report["hosts"]:
        if h["exception"]:
            status = f"crashed ({h['exception']['type']})"
        elif h["watchdog"]:
            status = "hung (watchdog fired)"
        elif h["preemption"]:
            status = "preempted"
        else:
            status = "ok"
        mean = f"{h['mean_step_ms']:.1f}" if h["mean_step_ms"] else "-"
        mx = f"{h['max_step_ms']:.1f}" if h["max_step_ms"] else "-"
        last = h["last_step"] if h["last_step"] is not None else "-"
        out.append(f"{h['name']:<24}{last!s:>10}{h['n_steps']:>7}"
                   f"{mean:>10}{mx:>10}  {status}")
    st = report["straggler"]
    if st:
        out.append("")
        out.append(f"straggler skew: {st['host']} is {st['skew']:.2f}x "
                   f"the fastest host"
                   + (" (SIGNIFICANT)" if st["significant"] else ""))
        for host, n in sorted(st["slowest_step_counts"].items(),
                              key=lambda kv: -kv[1]):
            out.append(f"  slowest on {n} shared steps: {host}")
    if report["bandwidth"]:
        out.append("")
        out.append(f"collective bandwidth (world={report['world']}, "
                   f"algorithmic bytes via get_msg_size):")
        out.append(f"  {'host':<24}{'op':<16}{'calls':>7}"
                   f"{'alg bytes':>12}{'GB/s':>10}")
        for b in report["bandwidth"]:
            if "achieved_gbps" in b:
                bw = f"{b['achieved_gbps']:.2f}"
            elif "achieved_gbps_upper_bound" in b:
                bw = f"<={b['achieved_gbps_upper_bound']:.2f}"
            else:
                bw = "-"
            out.append(f"  {b['host']:<24}{b['op']:<16}{b['calls']:>7}"
                       f"{convert_size(b['algorithmic_bytes']):>12}"
                       f"{bw:>10}")
    rl_hosts = [h for h in report["hosts"] if h.get("roofline")]
    if rl_hosts:
        out.append("")
        out.append("roofline (predicted vs achieved, from the explain "
                   "snapshot):")
        for h in rl_hosts:
            r = h["roofline"]
            pct = (f"{r['pct_of_roofline']:.1f}% of roofline"
                   if r.get("pct_of_roofline") else "no measured steps")
            out.append(f"  {h['name']:<24}predicted "
                       f"{r['predicted_ms']:.2f} ms "
                       f"({r.get('bound')}-bound) — {pct}")
    gp_hosts = [h for h in report["hosts"] if h.get("goodput")]
    if gp_hosts:
        out.append("")
        out.append("goodput ledger (share of wall clock that was "
                   "productive; dominant badput named):")
        for h in gp_hosts:
            gp = h["goodput"]
            frac = gp.get("fraction")
            frac_s = (f"{100.0 * frac:.0f}%"
                      if isinstance(frac, (int, float)) else "-")
            dom = gp.get("dominant_badput")
            dom_s = (f"  dominant badput: {dom} "
                     f"({gp.get('dominant_badput_s', 0.0):.1f}s)"
                     if dom else "")
            caps = (f"  captures: {gp['captures']}"
                    if gp.get("captures") else "")
            out.append(f"  {h['name']:<24}goodput {frac_s}{dom_s}{caps}")
    slo = report.get("slo") or {}
    if slo.get("timeline"):
        out.append("")
        n_open = len(slo.get("open") or [])
        out.append(f"SLO transitions ({n_open} still open):")
        for e in slo["timeline"][:50]:
            state = "BREACH" if e["kind"] == "slo_breach" else "recovered"
            out.append(f"  {e['host']:<24}{state:<10}"
                       f"{e.get('objective', '?'):<32}"
                       f"value={e.get('value')} "
                       f"burn={e.get('burn_fast')}x")
        if len(slo["timeline"]) > 50:
            out.append(f"  ... {len(slo['timeline']) - 50} more")
    mh = report.get("model_health") or {}
    if mh.get("layer_divergence") or mh.get("expert_collapse"):
        out.append("")
        out.append("model health (per-layer z-score localizer):")
        for e in (mh.get("layer_divergence") or [])[:20]:
            z = e.get("z")
            zs = f"z={z:+.1f}" if isinstance(z, (int, float)) else ""
            out.append(f"  step {e.get('step')!s:>8} {e['host']:<24}"
                       f"layer {e.get('layer')!s:<6}"
                       f"{e.get('stat', 'grad_norm'):<12}{zs}")
        for e in (mh.get("expert_collapse") or [])[:20]:
            out.append(f"  step {e.get('step')!s:>8} {e['host']:<24}"
                       f"expert {e.get('expert')!s:<5}"
                       f"windowed load {e.get('load')}")
    if report["storms"]:
        out.append("")
        out.append(f"recompile storms: {', '.join(report['storms'])}")
    if report["anomalies"]:
        out.append("")
        out.append("anomaly timeline:")
        for e in report["anomalies"][:50]:
            out.append(f"  step {e.get('step')!s:>8} {e['host']:<24}"
                       f"{e.get('anomaly', '?'):<22}"
                       f"{e.get('detail') or e.get('value') or ''}")
        if len(report["anomalies"]) > 50:
            out.append(f"  ... {len(report['anomalies']) - 50} more")
    rt = report.get("recovery_timeline") or []
    res = report.get("resilience") or {}
    if rt or report.get("crash_looping") or report.get("draining"):
        out.append("")
        out.append(f"recovery timeline ({res.get('faults_injected', 0)} "
                   f"faults injected, {res.get('recoveries', 0)} "
                   f"recoveries, {res.get('unrecovered', 0)} unrecovered):")
        for e in rt[:50]:
            kind = e.get("kind", "?")
            what = (e.get("spec") or e.get("recovery")
                    or e.get("checkpoint_tag") or e.get("bad_tag")
                    or e.get("error") or "")
            if e.get("replica"):
                # fleet events name their replica — "which replica died
                # and who answered" reads straight off the timeline
                dst = f" -> {e['to']}" if e.get("to") else ""
                what = f"replica={e['replica']}{dst} {what}".rstrip()
            out.append(f"  step {e.get('step')!s:>8} {e['host']:<24}"
                       f"{kind:<22}{what}")
        if len(rt) > 50:
            out.append(f"  ... {len(rt) - 50} more")
        for c in report.get("crash_looping") or []:
            out.append(f"  CRASH-LOOPING: {c['host']} "
                       f"({c['restarts_in_window']} restarts in window, "
                       f"backoff {c.get('backoff_s')}s, phase "
                       f"{c['phase']})")
        for d in report.get("draining") or []:
            who = (f"{d['host']} replica={d['replica']}"
                   if d.get("replica") else f"{d['host']}")
            out.append(f"  draining: {who} (intentional scale-down in "
                       f"flight — not a crash loop)")
    rq = report.get("reqtrace") or {}
    if rq.get("slow_requests") or rq.get("dropped_ok") \
            or rq.get("ring_dropped"):
        out.append("")
        out.append(f"slow requests ({len(rq.get('slow_requests') or [])} "
                   f"tail-retained, {int(rq.get('dropped_ok') or 0)} "
                   f"dropped ok, {int(rq.get('ring_dropped') or 0)} "
                   f"ring-dropped spans, {int(rq.get('pending') or 0)} "
                   f"undecided):")
        for r in (rq.get("slow_requests") or [])[:20]:
            ttft = r.get("ttft_ms")
            dom = (f"{r['dominant']} "
                   f"{r.get('dominant_pct', 0.0):.0f}%"
                   if r.get("dominant") else "?")
            out.append(
                f"  {r.get('trace_id', '?'):<18}{r['host']:<20}"
                f"reason={r.get('reason')!s:<10}"
                f"ttft={'-' if ttft is None else f'{ttft:.0f}ms':<9}"
                f"total={r.get('total_ms') or 0.0:.0f}ms  "
                f"dominant: {dom}  "
                f"[{','.join(r.get('causes') or [])}]")
            bd = r.get("breakdown_ms") or {}
            total = r.get("total_ms") or sum(bd.values()) or 1.0
            if bd:
                out.append("      " + " | ".join(
                    f"{seg} {ms:.0f}ms ({100.0 * ms / total:.0f}%)"
                    for seg, ms in sorted(bd.items(),
                                          key=lambda kv: -kv[1])))
            out.append(f"      replay with: dstpu-trace --request "
                       f"{r.get('trace_id', '?')} <dump dir>")
        if len(rq.get("slow_requests") or []) > 20:
            out.append(f"  ... {len(rq['slow_requests']) - 20} more")
    out.append("")
    return "\n".join(out)


def _load_any(path: str):
    """Flight-recorder dump or watchdog heartbeat file (small JSON with a
    ``phase`` key) — the doctor takes both on one command line."""
    try:
        return "dump", load_dump(path)
    except ValueError:
        with open(path) as fh:
            doc = json.load(fh)
        if isinstance(doc, dict) and "phase" in doc:
            return "heartbeat", doc
        raise


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-doctor",
        description="Post-mortem health report from flight-recorder "
                    "black boxes (and optional heartbeat files).")
    ap.add_argument("paths", nargs="+",
                    help="per-host black-box JSONs / heartbeat files")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured report as JSON")
    args = ap.parse_args(argv)
    dumps, heartbeats = [], []
    for p in args.paths:
        try:
            kind, doc = _load_any(p)
        except Exception as e:
            print(f"dstpu-doctor: cannot read {p}: {e}", file=sys.stderr)
            return 2
        (dumps if kind == "dump" else heartbeats).append(doc)
    if not dumps:
        print("dstpu-doctor: no flight-recorder dumps among the inputs",
              file=sys.stderr)
        return 2
    report = analyze(dumps, heartbeats)
    if args.json:
        print(json.dumps(report, indent=1, default=repr))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
