"""Resource samplers: device-memory watermarks and MFU accounting.

Memory: jax device ``memory_stats()`` where the backend reports it (TPU,
GPU), falling back to summing live device buffers, falling back to
nothing — plus host RSS from /proc (psutil when available). Every path
degrades to a clean no-op; sampling must never take a training loop down.

MFU: achieved FLOPs/s/chip over peak, with the bf16 peak-FLOPs table
keyed by TPU platform generation (public chip specs — the same numbers
``bench.py`` has always used; this module is now their home).
"""

import os
from typing import Any, Dict, Optional

from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.registry import registry as _global_registry

#: bf16 peak FLOPs/s per chip by device kind substring (public TPU specs)
PEAK_FLOPS_BF16: Dict[str, float] = {
    "v7": 2307e12, "ironwood": 2307e12,
    "v6e": 918e12, "trillium": 918e12,
    "v5p": 459e12,
    "v5e": 197e12, "v5 lite": 197e12, "v5litepod": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

#: peak HBM bandwidth, bytes/s per chip (public TPU specs; the memory
#: side of the roofline — see telemetry/explain.py)
PEAK_HBM_BW: Dict[str, float] = {
    "v7": 7370e9, "ironwood": 7370e9,
    "v6e": 1640e9, "trillium": 1640e9,
    "v5p": 2765e9,
    "v5e": 819e9, "v5 lite": 819e9, "v5litepod": 819e9,
    "v4": 1228e9,
    "v3": 900e9,
    "v2": 700e9,
}

#: HBM capacity, bytes per chip (public TPU specs; v2/v3 listed per core
#: — jax exposes cores as devices there). Used as the budget ceiling when
#: the backend doesn't report ``memory_stats()['bytes_limit']``.
HBM_CAPACITY: Dict[str, float] = {
    "v7": 192 * 2**30, "ironwood": 192 * 2**30,
    "v6e": 32 * 2**30, "trillium": 32 * 2**30,
    "v5p": 95 * 2**30,
    "v5e": 16 * 2**30, "v5 lite": 16 * 2**30, "v5litepod": 16 * 2**30,
    "v4": 32 * 2**30,
    "v3": 16 * 2**30,
    "v2": 8 * 2**30,
}

#: platforms the user has already been warned about (once per process);
#: see :func:`warn_unknown_platform`
_warned_platforms: set = set()


def known_platforms() -> list:
    """Sorted spec-table keys — the ``--platform`` values that resolve to
    non-zero peaks (every table is keyed identically)."""
    return sorted(PEAK_FLOPS_BF16)


def warn_unknown_platform(name: str, context: str = "roofline") -> bool:
    """One-time (per process, per name) warning for a ``--platform``
    string that matches no spec-table entry. Returns True when the
    platform IS unknown — callers degrade to zero peaks / unknown-bound
    scoring instead of raising (an autotune sweep must not abort on a
    typo'd or future chip name). 'cpu' is silently unknown by design."""
    key = str(name).lower()
    if key in ("", "cpu", "none"):
        return key != ""
    if any(k in key for k in PEAK_FLOPS_BF16):
        return False
    if key not in _warned_platforms:
        _warned_platforms.add(key)
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "unknown platform %r for %s — no peak numbers in the spec "
            "tables (known: %s); peaks read 0 and predictions degrade "
            "to unknown-bound", name, context,
            ", ".join(known_platforms()))
    return True


def _lookup(table: Dict[str, float], device: Any) -> float:
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            return 0.0
    kind = str(getattr(device, "device_kind", "cpu")).lower()
    for key, val in table.items():
        if key in kind:
            return val
    return 0.0


def peak_flops(device: Any = None) -> float:
    """Peak bf16 FLOPs/s for ``device`` (default: first jax device).
    0.0 for CPU/unknown platforms — MFU is not meaningful there."""
    return _lookup(PEAK_FLOPS_BF16, device)


def peak_hbm_bw(device: Any = None) -> float:
    """Peak HBM bytes/s for ``device`` (default: first jax device).
    0.0 for CPU/unknown platforms."""
    return _lookup(PEAK_HBM_BW, device)


def hbm_capacity(device: Any = None) -> float:
    """Per-device HBM bytes: the backend's ``bytes_limit`` when reported
    (the allocator's real ceiling), else the spec-sheet table, else 0.0
    (CPU/unknown — no budget ceiling to check against)."""
    stats = device_memory_stats(device)
    if stats and stats.get("bytes_limit"):
        return float(stats["bytes_limit"])
    return _lookup(HBM_CAPACITY, device)


def mfu(flops: float, seconds: float, n_devices: int = 1,
        peak: Optional[float] = None) -> float:
    """Model FLOPs utilization: ``flops`` (total model FLOPs for the
    measured interval, all chips) executed in ``seconds`` over
    ``n_devices`` chips of ``peak`` FLOPs/s each. Returns 0.0 whenever
    the ratio is undefined (no peak known, zero interval)."""
    if seconds <= 0.0 or flops <= 0.0:
        return 0.0
    peak = peak_flops() if peak is None else peak
    if not peak:
        return 0.0
    return flops / seconds / (max(1, n_devices) * peak)


def device_memory_stats(device: Any = None) -> Optional[Dict[str, float]]:
    """``device.memory_stats()`` as floats, or None when the backend does
    not implement it (CPU) or jax is unavailable."""
    try:
        import jax
        device = device if device is not None else jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: float(v) for k, v in stats.items()
            if isinstance(v, (int, float))}


def live_buffer_bytes() -> Optional[float]:
    """Total bytes of live jax arrays (the ``live_buffers`` fallback when
    ``memory_stats`` is unavailable). Counts global logical bytes."""
    try:
        import jax
        return float(sum(getattr(x, "nbytes", 0)
                         for x in jax.live_arrays()))
    except Exception:
        return None


def host_rss_bytes() -> Optional[float]:
    """Host resident-set size in bytes (psutil, else /proc/self/statm)."""
    try:
        import psutil
        return float(psutil.Process().memory_info().rss)
    except Exception:
        pass
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        return None


class MemorySampler:
    """Samples device + host memory into ``mem/*`` gauges.

    ``mem/device_bytes_in_use`` — current device allocation (from
    ``memory_stats`` or the live-buffer sum); ``mem/device_peak_bytes`` —
    high-watermark (backend-reported peak when available, else the max
    sample seen); ``mem/host_rss_bytes`` — process RSS. Missing sources
    are skipped, never raised.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._reg = registry if registry is not None else _global_registry
        self._peak = 0.0

    def sample(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        stats = device_memory_stats()
        in_use = stats.get("bytes_in_use") if stats else None
        if in_use is None:
            in_use = live_buffer_bytes()
        if in_use is not None:
            backend_peak = (stats or {}).get("peak_bytes_in_use", 0.0)
            self._peak = max(self._peak, backend_peak, in_use)
            out["mem/device_bytes_in_use"] = in_use
            out["mem/device_peak_bytes"] = self._peak
        rss = host_rss_bytes()
        if rss is not None:
            out["mem/host_rss_bytes"] = rss
        for name, val in out.items():
            self._reg.gauge(name).set(val)
        return out
