"""Span tracer: nestable context-manager spans over a thread-safe ring buffer.

The host-side companion to ``jax.profiler``: XLA's profiler sees device
programs, but "where did step time go" on the *host* — admission, batch
placement, host optimizer sweeps, monitor flushes — is invisible to it.
Spans recorded here export as Chrome/Perfetto trace-event JSON
(``chrome://tracing`` / https://ui.perfetto.dev) and, when
``jax_annotations`` is on, additionally enter
``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` so the same
names line up inside a real profiler capture.

Design constraints:
- disabled tracing must be near-free (one attribute check per span);
- recording must never allocate unboundedly (fixed-size ring buffer,
  oldest events evicted, eviction counted);
- spans may be emitted retroactively (:meth:`Tracer.complete`) for
  lifecycles that cross call boundaries, e.g. serving requests.
"""

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

DEFAULT_BUFFER_EVENTS = 100_000


class Tracer:
    """Thread-safe trace-event recorder (Chrome trace-event format).

    Events are stored as plain dicts in the on-disk schema, so
    :meth:`dump` is a serialization, not a conversion. Complete spans use
    ``ph="X"`` (ts/dur in microseconds), instants use ``ph="i"``.
    """

    def __init__(self, buffer_events: int = DEFAULT_BUFFER_EVENTS):
        self.enabled = False
        self.jax_annotations = False
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=buffer_events)
        self._dropped = 0

    # -- configuration ------------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  buffer_events: Optional[int] = None,
                  jax_annotations: Optional[bool] = None) -> None:
        with self._lock:
            if buffer_events is not None and \
                    buffer_events != self._buf.maxlen:
                self._buf = deque(self._buf, maxlen=max(1, buffer_events))
            if enabled is not None:
                self.enabled = bool(enabled)
            if jax_annotations is not None:
                self.jax_annotations = bool(jax_annotations)

    def now(self) -> float:
        """Seconds on the tracer's clock (``time.perf_counter``)."""
        return time.perf_counter()

    # -- recording ----------------------------------------------------------

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
                dropped = True
            else:
                dropped = False
            self._buf.append(ev)
        if dropped:
            # ring wrap is data loss for the post-mortem — announce it
            # (dstpu-doctor reads trace/ring_dropped from the black box)
            try:
                from deepspeed_tpu.telemetry.registry import registry
                registry.counter(
                    "trace/ring_dropped",
                    help="span events evicted by tracer ring wrap").inc()
            except Exception:                            # noqa: BLE001
                pass

    def ingest(self, events: List[Dict[str, Any]]) -> None:
        """Append pre-formed trace-event dicts (the tail-sampler's flush
        path: a retained request's buffered spans enter the ring here).
        Ring bounds and drop accounting apply as for live spans."""
        for ev in events:
            self._append(ev)

    def _event(self, name: str, ph: str, ts_us: float,
               tid: Optional[int], args: Dict[str, Any]) -> Dict[str, Any]:
        ev: Dict[str, Any] = {
            "name": name, "ph": ph, "cat": "dstpu",
            "ts": ts_us, "pid": self._pid,
            "tid": threading.get_ident() if tid is None else tid,
        }
        if args:
            ev["args"] = args
        return ev

    def _annotation(self, name: str, step: Optional[int]):
        """jax.profiler annotation object, or None when passthrough is off
        or jax is unavailable. Annotations are inert outside an active
        profiler capture, so entering them unconditionally is safe."""
        if not self.jax_annotations:
            return None
        try:
            from jax import profiler as jprof
            if step is not None:
                return jprof.StepTraceAnnotation(name, step_num=step)
            return jprof.TraceAnnotation(name)
        except Exception:
            return None

    @contextmanager
    def span(self, name: str, step: Optional[int] = None, ctx=None, **args):
        """Record the enclosed block as a complete span. Nestable; nesting
        is reconstructed from ts/dur containment (same pid/tid), which is
        how Chrome/Perfetto render the flame graph. ``ctx`` (a
        :class:`~deepspeed_tpu.telemetry.reqtrace.TraceContext`) stamps
        the span with trace_id/span_id/parent_span_id args so it joins a
        request-scoped distributed trace."""
        if not self.enabled:
            yield
            return
        ann = self._annotation(name, step)
        if ann is not None:
            ann.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            if ann is not None:
                ann.__exit__(None, None, None)
            if step is not None:
                args = {**args, "step": step}
            if ctx is not None:
                args = {**ctx.tags(), **args}
            ev = self._event(name, "X", (t0 - self._t0) * 1e6, None, args)
            ev["dur"] = (t1 - t0) * 1e6
            self._append(ev)

    def instant(self, name: str, tid: Optional[int] = None, ctx=None,
                **args) -> None:
        """Record a zero-duration marker (ph='i', thread-scoped)."""
        if not self.enabled:
            return
        if ctx is not None:
            args = {**ctx.tags(), **args}
        ev = self._event(name, "i",
                         (time.perf_counter() - self._t0) * 1e6, tid, args)
        ev["s"] = "t"
        self._append(ev)

    def complete(self, name: str, start: float, end: float,
                 tid: Optional[int] = None, ctx=None, **args) -> None:
        """Record a span retroactively from ``start``/``end`` timestamps in
        seconds on the tracer's clock (or any CLOCK_MONOTONIC-derived clock
        — ``time.monotonic`` stamps from the serving frontend align on
        Linux). Used for lifecycles that cross call boundaries."""
        if not self.enabled:
            return
        if ctx is not None:
            args = {**ctx.tags(), **args}
        ev = self._event(name, "X", (start - self._t0) * 1e6, tid, args)
        ev["dur"] = max(0.0, (end - start) * 1e6)
        self._append(ev)

    # -- export -------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    @property
    def dropped(self) -> int:
        return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0

    def to_chrome_trace(self) -> Dict[str, Any]:
        evs = sorted(self.events(), key=lambda e: e.get("ts", 0.0))
        return {"traceEvents": evs, "displayTimeUnit": "ms",
                "otherData": {"tracer": "deepspeed_tpu.telemetry",
                              "dropped_events": self._dropped}}

    def dump(self, path: str) -> str:
        """Write the Chrome trace-event JSON to ``path`` (parent dirs
        created). Load it in chrome://tracing or ui.perfetto.dev."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh)
        return path


#: process-wide tracer (the engine, comm layer, and serving frontend all
#: record here; ``deepspeed_tpu.telemetry.configure`` enables it)
tracer = Tracer()
