"""SLO objectives + multi-window burn-rate alerting over metric history.

Objectives are declared in config (``slo.objectives``), either as a
compact string::

    slo:
      objectives:
        - "serving/ttft_seconds:p95 <= 0.5"
        - "serving/tpot_seconds:p99 <= 0.05"
        - "train/step_time_ms:p95 <= 250"
        - "train/mfu >= 0.30"

or as a dict with per-objective overrides::

        - metric: serving/ttft_seconds:p95
          op: "<="
          target: 0.5
          budget: 0.01          # error budget: 1% of windows may be bad
          burn_threshold: 2.0

The metric grammar is :func:`~deepspeed_tpu.telemetry.timeseries
.resolve_metric`'s — ``area/name`` or ``area/name:field`` — and
histogram fields are judged on the INTERVAL summary (samples since the
previous flush) when one is present, so a latency storm that ends
actually shows recovery instead of being averaged into all-time
percentiles forever.

**Burn rate** is SRE arithmetic: over a trailing window, ``burn =
bad_fraction / error_budget``. Burn 1.0 spends the budget exactly at
sustainable pace; burn 10 exhausts a 30-day budget in 3 days. A breach
requires BOTH the fast window (default 60s — catches the cliff) and the
slow window (default 600s — suppresses blips) to exceed
``burn_threshold``; recovery is the fast window dropping back under.
This is the standard multi-window multi-burn-rate alert shape, sized
down to single-run horizons.

On every evaluation the engine publishes per-objective gauges
(``slo/<name>/burn_fast``, ``slo/<name>/burn_slow``,
``slo/<name>/breached``) plus aggregates (``slo/breached``,
``slo/worst_burn``, ``slo/objectives``). Breach/recovery transitions
are flight-recorded (``kind="slo_breach"`` / ``"slo_recovered"`` — the
doctor ranks these into its verdict) and flip ``/healthz`` to degraded
naming the objective (503 body: ``slo:<name> <metric> <op> <target>``).

The engine subscribes to a :class:`~deepspeed_tpu.telemetry.timeseries
.MetricHistory`, so SLOs are evaluated exactly as often as history is
written — one registry lock pass feeds both.
"""

import re
import time
from collections import deque
from typing import Any, Dict, List, Optional, Union

from deepspeed_tpu.telemetry.flight_recorder import flight_recorder
from deepspeed_tpu.telemetry.registry import registry
from deepspeed_tpu.telemetry.timeseries import Record, resolve_metric
from deepspeed_tpu.utils.logging import logger

DEFAULT_BUDGET = 0.01
DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 600.0
DEFAULT_BURN_THRESHOLD = 2.0

_OPS = {
    "<=": lambda v, t: v <= t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    ">": lambda v, t: v > t,
}
_SPEC = re.compile(r"^\s*(\S+)\s*(<=|>=|<|>)\s*([-+0-9.eE]+)\s*$")


def _sanitize(metric: str) -> str:
    """Lint-safe gauge-name segment for an objective: ``serving/
    ttft_seconds:p95`` → ``serving_ttft_seconds_p95``."""
    return re.sub(r"[^a-z0-9_]+", "_", metric.lower()).strip("_")


class Objective:
    """One declared SLO: ``<metric> <op> <target>`` plus alert tuning."""

    def __init__(self, metric: str, op: str, target: float,
                 name: Optional[str] = None,
                 budget: float = DEFAULT_BUDGET,
                 fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD):
        if op not in _OPS:
            raise ValueError(f"unknown SLO op {op!r} (want one of "
                             f"{sorted(_OPS)})")
        if not (0 < budget <= 1):
            raise ValueError(f"SLO budget must be in (0, 1], got {budget}")
        if fast_window_s >= slow_window_s:
            raise ValueError(
                f"SLO fast window ({fast_window_s}s) must be shorter than "
                f"the slow window ({slow_window_s}s)")
        self.metric = metric
        self.op = op
        self.target = float(target)
        self.name = name or _sanitize(metric)
        self.budget = float(budget)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        # (ts, bad) observations, pruned to the slow window
        self._obs: deque = deque()
        self.breached = False
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.last_value: Optional[float] = None

    @classmethod
    def parse(cls, spec: Union[str, Dict[str, Any]],
              defaults: Optional[Dict[str, Any]] = None) -> "Objective":
        """Build from the config grammar (string or dict form).
        ``defaults`` supplies engine-level budget/window/threshold that a
        dict spec may override per objective."""
        defaults = defaults or {}
        if isinstance(spec, str):
            m = _SPEC.match(spec)
            if not m:
                raise ValueError(
                    f"bad SLO objective {spec!r} (want "
                    f"'<metric>[:field] <op> <target>', e.g. "
                    f"'serving/ttft_seconds:p95 <= 0.5')")
            return cls(m.group(1), m.group(2), float(m.group(3)),
                       **defaults)
        if isinstance(spec, dict):
            kw = dict(defaults)
            kw.update({k: spec[k] for k in
                       ("name", "budget", "fast_window_s", "slow_window_s",
                        "burn_threshold") if k in spec})
            return cls(spec["metric"], spec.get("op", "<="),
                       float(spec["target"]), **kw)
        raise TypeError(f"SLO objective must be str or dict, got "
                        f"{type(spec).__name__}")

    def describe(self) -> str:
        return f"{self.metric} {self.op} {self.target:g}"

    def observe(self, record: Record, now: float) -> Optional[bool]:
        """Judge one history record; returns the bad/good verdict, or
        ``None`` when the record doesn't carry the metric (no samples
        this interval ≠ a violation)."""
        v = resolve_metric(record, self.metric, prefer_interval=True)
        if v is None:
            return None
        self.last_value = v
        bad = not _OPS[self.op](v, self.target)
        self._obs.append((now, bad))
        cutoff = now - self.slow_window_s
        while self._obs and self._obs[0][0] < cutoff:
            self._obs.popleft()
        return bad

    def burn(self, now: float) -> None:
        """Recompute fast/slow burn rates and the breach state."""
        fast_cut = now - self.fast_window_s
        nf = bf = ns = bs = 0
        for ts, bad in self._obs:
            ns += 1
            bs += bad
            if ts >= fast_cut:
                nf += 1
                bf += bad
        self.burn_fast = (bf / nf / self.budget) if nf else 0.0
        self.burn_slow = (bs / ns / self.budget) if ns else 0.0
        if not self.breached:
            self.breached = (self.burn_fast >= self.burn_threshold and
                             self.burn_slow >= self.burn_threshold)
        else:
            self.breached = self.burn_fast >= self.burn_threshold


class SLOEngine:
    """Evaluates objectives on each history record; publishes ``slo/*``
    gauges and drives healthz / flight-recorder / doctor on transitions.

    ``healthz`` is anything with ``set_degraded(flag, reason=...,
    source=...)`` — in practice the :class:`MetricsServer`; ``publish``
    =False runs side-effect-free (offline replay / tests).
    """

    def __init__(self, objectives: List[Union[str, Dict[str, Any]]],
                 budget: float = DEFAULT_BUDGET,
                 fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 healthz=None, publish: bool = True, clock=time.time):
        defaults = dict(budget=budget, fast_window_s=fast_window_s,
                        slow_window_s=slow_window_s,
                        burn_threshold=burn_threshold)
        self.objectives = [Objective.parse(s, defaults) for s in objectives]
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO objective names: {names} "
                             f"(set 'name:' on the dict form)")
        self.healthz = healthz
        self.publish = publish
        self._clock = clock
        self.evaluations = 0

    # -- evaluation ---------------------------------------------------------

    def observe(self, record: Record) -> None:
        """History-subscriber entry point: judge every objective against
        one record and emit all downstream effects."""
        now = float(record.get("ts") or self._clock())
        self.evaluations += 1
        for obj in self.objectives:
            was = obj.breached
            obj.observe(record, now)
            obj.burn(now)
            if self.publish:
                registry.gauge(f"slo/{obj.name}/burn_fast").set(
                    obj.burn_fast)
                registry.gauge(f"slo/{obj.name}/burn_slow").set(
                    obj.burn_slow)
                registry.gauge(f"slo/{obj.name}/breached").set(
                    float(obj.breached))
            if obj.breached != was:
                self._transition(obj, now)
        if self.publish:
            registry.gauge("slo/objectives").set(float(len(self.objectives)))
            registry.gauge("slo/breached").set(
                float(sum(o.breached for o in self.objectives)))
            registry.gauge("slo/worst_burn").set(self.worst_burn())
        self._sync_healthz()

    def _transition(self, obj: Objective, now: float) -> None:
        kind = "slo_breach" if obj.breached else "slo_recovered"
        detail = (f"objective {obj.name} ({obj.describe()}) "
                  f"value={obj.last_value} burn_fast={obj.burn_fast:.2f} "
                  f"burn_slow={obj.burn_slow:.2f}")
        (logger.warning if obj.breached else logger.info)(
            f"SLO {kind.split('_', 1)[1]}: {detail}")
        if not self.publish:
            return
        flight_recorder.record_event(
            kind, objective=obj.name, metric=obj.metric, op=obj.op,
            target=obj.target, value=obj.last_value,
            burn_fast=round(obj.burn_fast, 4),
            burn_slow=round(obj.burn_slow, 4))

    def _sync_healthz(self) -> None:
        if self.healthz is None or not self.publish:
            return
        breached = [o for o in self.objectives if o.breached]
        if breached:
            reason = "; ".join(
                f"slo:{o.name} {o.describe()} (burn {o.burn_fast:.1f}x)"
                for o in breached)
            self.healthz.set_degraded(True, reason=reason, source="slo")
        else:
            self.healthz.set_degraded(False, source="slo")

    # -- reporting ----------------------------------------------------------

    def worst_burn(self) -> float:
        return max((max(o.burn_fast, o.burn_slow)
                    for o in self.objectives), default=0.0)

    def summary(self) -> Dict[str, Any]:
        """Compact state for bench stamps / ``stats()`` blocks."""
        return {
            "objectives": len(self.objectives),
            "evaluated": self.evaluations,
            "worst_burn": round(self.worst_burn(), 4),
            "breached": [o.name for o in self.objectives if o.breached],
        }


def engine_from_config(slo_cfg, healthz=None,
                       clock=time.time) -> Optional[SLOEngine]:
    """Build an :class:`SLOEngine` from an ``slo:`` config block (pydantic
    model or plain dict); ``None`` when no objectives are declared."""
    if slo_cfg is None:
        return None
    get = (slo_cfg.get if isinstance(slo_cfg, dict)
           else lambda k, d=None: getattr(slo_cfg, k, d))
    objectives = get("objectives") or []
    if not objectives:
        return None
    return SLOEngine(
        objectives,
        budget=get("budget", DEFAULT_BUDGET),
        fast_window_s=get("fast_window_s", DEFAULT_FAST_WINDOW_S),
        slow_window_s=get("slow_window_s", DEFAULT_SLOW_WINDOW_S),
        burn_threshold=get("burn_threshold", DEFAULT_BURN_THRESHOLD),
        healthz=healthz, clock=clock)


def evaluate_history(records: List[Record], slo_cfg) -> Dict[str, Any]:
    """Offline replay: run the burn-rate engine over loaded history
    records with no side effects (no gauges, no healthz, no flight
    recorder). Returns the final :meth:`SLOEngine.summary` plus
    per-objective detail — what ``dstpu-report --compare`` consumes."""
    eng = engine_from_config(slo_cfg)
    if eng is None:
        return {"objectives": 0, "evaluated": 0, "worst_burn": 0.0,
                "breached": []}
    eng.publish = False
    for rec in records:
        eng.observe(rec)
    out = eng.summary()
    out["detail"] = [
        {"name": o.name, "objective": o.describe(),
         "burn_fast": round(o.burn_fast, 4),
         "burn_slow": round(o.burn_slow, 4),
         "breached": o.breached, "last_value": o.last_value}
        for o in eng.objectives]
    return out
