"""Flight recorder: an always-on bounded ring of per-step records that
serializes to a JSON "black box" when a run dies.

PR 3's tracer/registry answer "how fast was a healthy run"; this module
answers "why did the run die, hang, or slow down" — the dominant
operational cost of large pod jobs (preemptions, one-host stragglers,
recompilation storms, NaN blowups). Recording is cheap enough to leave on
unconditionally: one small dict append per optimizer step into a
fixed-size deque, never a device sync (device scalars are stored as-is
and resolved only at dump time, so the async dispatch pipeline is
untouched).

Dump triggers:
- **crash** — :meth:`install_excepthook` chains ``sys.excepthook`` and
  writes the black box before the traceback prints;
- **preemption** — ``elasticity/elastic_agent.py`` dumps next to the
  preemption checkpoint so the relaunch operator finds both in one log
  line;
- **hang** — :mod:`~deepspeed_tpu.telemetry.watchdog` dumps on a missed
  step deadline, alongside all-thread stacks;
- **on demand** — :meth:`dump`.

``bin/dstpu-doctor`` ingests one or many per-host dumps and prints the
post-mortem report (see :mod:`~deepspeed_tpu.telemetry.doctor`).
"""

import json
import os
import socket
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_MAX_STEPS = 512
DEFAULT_MAX_EVENTS = 512
SCHEMA_VERSION = 1


def _resolve(v: Any) -> Any:
    """JSON-safe view of a record field. Device scalars (jax arrays held
    lazily since record time) are fetched HERE, not at record time —
    fetching in the hot loop would sync the async dispatch pipeline."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_resolve(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _resolve(x) for k, x in v.items()}
    try:
        import numpy as np
        arr = np.asarray(v)
        if arr.ndim == 0:
            f = float(arr)
            return f if (f == f and abs(f) != float("inf")) else repr(f)
        return repr(arr)
    except Exception:
        return repr(v)[:200]


class FlightRecorder:
    """Thread-safe bounded ring of step records + out-of-band events."""

    def __init__(self, max_steps: int = DEFAULT_MAX_STEPS,
                 max_events: int = DEFAULT_MAX_EVENTS):
        self._lock = threading.Lock()
        self._steps: deque = deque(maxlen=max_steps)
        self._events: deque = deque(maxlen=max_events)
        self._meta: Dict[str, Any] = {}
        self._exception: Optional[Dict[str, Any]] = None
        self._default_path: Optional[str] = None
        self._prev_comm_bytes = 0.0
        self._hook_installed = False
        self._t0 = time.time()

    # -- configuration ------------------------------------------------------

    def configure(self, max_steps: Optional[int] = None,
                  path: Optional[str] = None) -> None:
        with self._lock:
            if max_steps is not None and max_steps != self._steps.maxlen:
                self._steps = deque(self._steps, maxlen=max(1, max_steps))
            if path is not None:
                self._default_path = path

    def set_meta(self, **kv: Any) -> None:
        with self._lock:
            self._meta.update(kv)

    # -- recording ----------------------------------------------------------

    def record_step(self, step: int, kind: str = "train",
                    dur_s: Optional[float] = None, **fields: Any) -> None:
        """Append one step record. ``fields`` may hold device scalars
        (loss, grad_norm, loss_scale, …) — they are kept lazy until dump.
        Collective traffic is charged per step as the delta of the
        ``comm/bytes`` registry counter."""
        from deepspeed_tpu.telemetry.registry import registry
        rec: Dict[str, Any] = {"step": int(step), "kind": kind,
                               "ts": time.time()}
        if dur_s is not None:
            rec["dur_ms"] = dur_s * 1e3
        comm = registry.get("comm/bytes")
        if comm is not None:
            with self._lock:
                rec["comm_bytes_delta"] = comm.value - self._prev_comm_bytes
                self._prev_comm_bytes = comm.value
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._steps.append(rec)

    def record_event(self, kind: str, **fields: Any) -> None:
        """Out-of-band marker (anomaly, compile, preemption, watchdog)."""
        ev: Dict[str, Any] = {"kind": kind, "ts": time.time()}
        ev.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self._events.append(ev)

    def note_exception(self, exc_type, exc, tb) -> None:
        self._exception = {
            "type": getattr(exc_type, "__name__", str(exc_type)),
            "message": str(exc)[:2000],
            "traceback": "".join(
                traceback.format_exception(exc_type, exc, tb))[-8000:],
            "ts": time.time(),
        }

    def last_step(self) -> Optional[int]:
        with self._lock:
            return self._steps[-1]["step"] if self._steps else None

    def clear(self) -> None:
        with self._lock:
            self._steps.clear()
            self._events.clear()
            self._exception = None
            self._prev_comm_bytes = 0.0

    # -- crash hook ---------------------------------------------------------

    def install_excepthook(self) -> None:
        """Chain ``sys.excepthook``: an uncaught exception writes the black
        box (best effort, never masks the original traceback) and then
        falls through to the previous hook. Idempotent."""
        if self._hook_installed:
            return
        self._hook_installed = True
        prev = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                self.note_exception(exc_type, exc, tb)
                path = self.dump(reason="crash")
                print(f"deepspeed_tpu: flight recorder black box written "
                      f"to {path}", file=sys.stderr)
            except Exception:
                pass
            prev(exc_type, exc, tb)

        sys.excepthook = hook

    # -- export -------------------------------------------------------------

    def snapshot(self, reason: str = "on_demand") -> Dict[str, Any]:
        """The full black-box document (JSON-serializable). Lazy device
        scalars are resolved here; every auxiliary source (registry,
        comms logger, compile monitor) is best-effort — a dump during a
        crash must never raise."""
        with self._lock:
            steps = [dict(r) for r in self._steps]
            events = [dict(e) for e in self._events]
            meta = dict(self._meta)
        meta.setdefault("hostname", socket.gethostname())
        meta.setdefault("pid", os.getpid())
        try:
            import jax
            meta.setdefault("process_index", jax.process_index())
            meta.setdefault("process_count", jax.process_count())
        except Exception:
            pass
        doc: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "reason": reason,
            "written_at": time.time(),
            "started_at": self._t0,
            "meta": meta,
            "steps": [_resolve(r) for r in steps],
            "events": [_resolve(e) for e in events],
            "exception": self._exception,
        }
        try:
            from deepspeed_tpu.telemetry.registry import registry
            doc["metrics_text"] = registry.prometheus_text()
        except Exception:
            pass
        try:
            from deepspeed_tpu.comm.comms_logger import comms_logger
            doc["comm"] = comms_logger._records_payload()
        except Exception:
            pass
        try:
            # tail-retained request-trace summaries + drop accounting
            # (dstpu-doctor's "slow requests" section reads this)
            from deepspeed_tpu.telemetry.reqtrace import reqtrace
            if reqtrace.enabled:
                doc["reqtrace"] = reqtrace.post_mortem()
        except Exception:
            pass
        try:
            from deepspeed_tpu.telemetry.compile_monitor import \
                compile_monitor
            doc["compile"] = compile_monitor.summary()
        except Exception:
            pass
        try:
            # goodput ledger summary (fraction, badput taxonomy,
            # profiler-capture paths) — dstpu-doctor's GOODPUT verdict
            # reads this section
            from deepspeed_tpu.telemetry.goodput import goodput_ledger
            if goodput_ledger.enabled:
                doc["goodput"] = goodput_ledger.summary()
        except Exception:
            pass
        try:
            from deepspeed_tpu.telemetry.sampler import host_rss_bytes
            rss = host_rss_bytes()
            if rss is not None:
                doc["host_rss_bytes"] = rss
        except Exception:
            pass
        try:
            # predicted-vs-achieved: the last compile-time explain
            # snapshot rides along so dstpu-doctor can name the roofline
            # gap post mortem
            from deepspeed_tpu.telemetry import explain
            if explain.last_report:
                doc["explain"] = dict(explain.last_report)
        except Exception:
            pass
        return doc

    def dump(self, path: Optional[str] = None,
             reason: str = "on_demand") -> str:
        """Write the black box to ``path`` (default: the configured path,
        else ``dstpu_blackbox_<pid>.json`` in the cwd). Parent dirs
        created; write is atomic (tmp + rename) so a dump racing a kill
        never leaves a half-written JSON."""
        path = path or self._default_path or \
            os.path.join(os.getcwd(), f"dstpu_blackbox_{os.getpid()}.json")
        path = os.path.abspath(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self.snapshot(reason), fh, indent=1, default=repr)
        os.replace(tmp, path)
        return path


#: process-wide flight recorder (counterpart of ``tracer``/``registry``)
flight_recorder = FlightRecorder()


def load_dump(path: str) -> Dict[str, Any]:
    """Load a black-box JSON (the doctor's ingestion helper)."""
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "steps" not in doc:
        raise ValueError(f"{path}: not a flight-recorder dump")
    return doc
