"""XLA compilation observability: compile counts/durations and a
recompilation-storm detector.

JAX recompiles silently — a drifting input shape, a weak-typed scalar, or
a serving request outside every bucket each cost seconds-to-minutes of
XLA time that show up only as mysterious step-time spikes. This module
makes each compile loud and attributable:

- ``install()`` subscribes to :mod:`jax.monitoring` duration events
  (``/jax/core/compile/backend_compile_duration`` et al.), mirroring them
  into ``compile/count`` + ``compile/time_ms`` registry metrics and
  tracer complete-spans.
- Per-function attribution: ``jax.monitoring`` events carry no function
  identity, so call sites mark cache misses explicitly via
  :meth:`count_trace` (e.g. ``inference/engine_v2`` on a jit-cache-key
  miss, attributing the compile to the request's bucket shape) or wrap a
  function with :meth:`instrument` — the wrapper body only executes while
  jax is *tracing*, i.e. exactly once per compilation cache miss.
- Storm detection: when one function/site retraces more than
  ``storm_threshold`` times, a single loud warning fires and the storm is
  recorded for the flight recorder / ``dstpu-doctor``.
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DEFAULT_STORM_THRESHOLD = 8

#: jax.monitoring duration events that mean "time spent compiling"
_COMPILE_EVENT_MARKERS = ("compile", "lowering", "jaxpr_to_mlir")


class CompileMonitor:
    """Process-wide compile tracker (counterpart of ``tracer``/``registry``)."""

    def __init__(self, storm_threshold: int = DEFAULT_STORM_THRESHOLD):
        self._lock = threading.Lock()
        self.storm_threshold = storm_threshold
        self._installed = False
        # jax.monitoring offers no per-listener unregister (only a global
        # clear), so the listener stays registered and checks this flag
        self._active = False
        self._events: Dict[str, Dict[str, float]] = {}
        self._functions: Dict[str, int] = {}
        self._details: Dict[str, List[Any]] = {}
        self._storms: List[str] = []

    # -- jax.monitoring bridge ----------------------------------------------

    def install(self, storm_threshold: Optional[int] = None) -> None:
        """Subscribe to jax compile-duration events. Idempotent."""
        if storm_threshold is not None:
            self.storm_threshold = storm_threshold
        self._active = True
        if self._installed:
            return
        self._installed = True
        try:
            from jax import monitoring as jax_monitoring
            jax_monitoring.register_event_duration_secs_listener(
                self._on_event_duration)
        except Exception as e:  # pragma: no cover - very old jax
            logger.warning(f"compile monitor: jax.monitoring unavailable "
                           f"({e}); only explicit count_trace/instrument "
                           f"call sites will be tracked")

    def uninstall(self) -> None:
        self._active = False

    def _on_event_duration(self, event: str, duration_secs: float,
                           **kwargs: Any) -> None:
        if not self._active:
            return
        if not any(m in event for m in _COMPILE_EVENT_MARKERS):
            return
        short = event.rsplit("/", 1)[-1]
        with self._lock:
            agg = self._events.setdefault(short, {"count": 0, "time_ms": 0.0})
            agg["count"] += 1
            agg["time_ms"] += duration_secs * 1e3
        try:
            from deepspeed_tpu.telemetry.registry import registry
            registry.counter("compile/count").inc()
            registry.histogram("compile/time_ms", lo=0.01,
                               hi=600_000.0).record(duration_secs * 1e3)
        except Exception:
            pass
        try:
            from deepspeed_tpu.telemetry.tracer import tracer
            now = tracer.now()
            tracer.complete(f"compile/{short}", now - duration_secs, now)
        except Exception:
            pass

    # -- per-function attribution -------------------------------------------

    def count_trace(self, name: str, detail: Any = None) -> int:
        """Record one (re)compilation of ``name``; returns the new count.
        ``detail`` (e.g. the serving bucket shape that missed the jit
        cache) is kept so ``dstpu-doctor`` can show *what* keeps changing."""
        with self._lock:
            n = self._functions.get(name, 0) + 1
            self._functions[name] = n
            if detail is not None:
                self._details.setdefault(name, []).append(detail)
                del self._details[name][:-16]
            storm = n == self.storm_threshold + 1 and name not in self._storms
            if storm:
                self._storms.append(name)
            details = list(self._details.get(name, ()))
        try:
            from deepspeed_tpu.telemetry.registry import registry
            registry.counter(f"compile/retrace/{name}").inc()
        except Exception:
            pass
        if storm:
            logger.warning(
                f"RECOMPILATION STORM: {name!r} has been traced {n} times "
                f"(threshold {self.storm_threshold}) — every retrace pays "
                f"full XLA compile time. Recent trigger details: "
                f"{details or 'n/a'}. Check for drifting shapes, weak-typed "
                f"scalars, or serving requests that fall outside every "
                f"bucket.")
            try:
                from deepspeed_tpu.telemetry.flight_recorder import \
                    flight_recorder
                flight_recorder.record_event("recompile_storm", name=name,
                                             count=n, details=details)
            except Exception:
                pass
            try:
                from deepspeed_tpu.telemetry.tracer import tracer
                tracer.instant(f"compile/storm/{name}")
            except Exception:
                pass
        return n

    def instrument(self, fn: Callable, name: Optional[str] = None) -> Callable:
        """Wrap ``fn`` so each jax *trace* of it is counted. The wrapper
        body runs only while jax traces (cache miss / retrace); cached
        executions never enter it, so steady state pays nothing."""
        label = name or getattr(fn, "__name__", repr(fn))

        def traced(*args, **kwargs):
            self.count_trace(label)
            return fn(*args, **kwargs)

        traced.__name__ = getattr(fn, "__name__", "traced")
        traced.__wrapped__ = fn
        return traced

    # -- export --------------------------------------------------------------

    def retrace_count(self, name: str) -> int:
        with self._lock:
            return self._functions.get(name, 0)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "events": {k: dict(v) for k, v in self._events.items()},
                "functions": dict(self._functions),
                "details": {k: list(v) for k, v in self._details.items()},
                "storms": list(self._storms),
                "storm_threshold": self.storm_threshold,
            }

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._functions.clear()
            self._details.clear()
            del self._storms[:]


#: process-wide compile monitor
compile_monitor = CompileMonitor()
