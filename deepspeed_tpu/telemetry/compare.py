"""``dstpu_report --compare a b`` — history-aware run regression gate.

Compares two runs' artifacts and flags metric regressions beyond a
noise band, exit-code-first so it drops straight into CI::

    dstpu_report --compare baseline.jsonl candidate.jsonl
    dstpu_report --compare runs/a/history.jsonl runs/b/history.jsonl \
                 --noise 0.08 --json

Each side may be:

- **BENCH JSONL** — lines of ``{"metric": ..., "value": ..., "unit":
  ...}`` as printed by ``bench.py`` / ``bench_inference.py`` (a driver
  wrapper object with the stdout under ``"tail"`` also works);
- **metric history** — a :mod:`~deepspeed_tpu.telemetry.timeseries`
  JSONL file (detected by the ``"m"`` record key). History compare
  summarizes each run over its whole span for a whitelist of
  regression-meaningful families (MFU, step time p95, TTFT p95,
  TPOT p99, token/step throughput, SLO worst burn) — per-flush noise is
  averaged out, tails are judged on interval percentiles.

Direction (higher- vs lower-is-better) is inferred from the metric name
and unit — latency/time/burn metrics regress upward, throughput/MFU
regress downward. A metric present on only one side is reported but
never fails the gate (benches grow metrics release to release).
"""

import json
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.timeseries import (Record, load_records,
                                                resolve_metric, windowed)

DEFAULT_NOISE = 0.05

#: name/unit fragments ⇒ lower is better (everything else: higher wins)
_LOWER_BETTER = re.compile(
    r"(time|latency|ttft|tpot|wall|ms\b|seconds|stall|burn|overhead|"
    r"bytes|hbm|breach|p9[059]|p50|retries|evictions|drops)", re.I)

#: history families worth gating on: (label, metric, agg, lower_better)
HISTORY_FAMILIES: List[Tuple[str, str, str, bool]] = [
    ("train/mfu (mean)", "train/mfu", "mean", False),
    ("train/step_time_ms p95 (mean)", "train/step_time_ms:p95",
     "mean", True),
    ("serving/ttft_seconds p95 (mean)", "serving/ttft_seconds:p95",
     "mean", True),
    ("serving/tpot_seconds p99 (mean)", "serving/tpot_seconds:p99",
     "mean", True),
    ("serving/tokens_out (rate/s)", "serving/tokens_out", "rate", False),
    ("train/steps (rate/s)", "train/steps", "rate", False),
    ("slo/worst_burn (max)", "slo/worst_burn", "max", True),
    ("slo/breached (max)", "slo/breached", "max", True),
]


def lower_is_better(metric: str, unit: str = "") -> bool:
    return bool(_LOWER_BETTER.search(f"{metric} {unit}"))


def load_bench_lines(path: str) -> List[Dict[str, Any]]:
    """BENCH result dicts from a bench-stdout JSONL file; also unwraps
    the driver's ``{"tail": "<stdout>"}`` capture format."""
    out: List[Dict[str, Any]] = []

    def eat(text: str) -> None:
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "metric" in doc \
                    and "value" in doc:
                out.append(doc)

    with open(path) as fh:
        text = fh.read()
    try:
        whole = json.loads(text)
    except ValueError:
        whole = None
    if isinstance(whole, dict) and "metric" in whole and "value" in whole:
        out.append(whole)
    elif isinstance(whole, dict) and isinstance(whole.get("tail"), str):
        eat(whole["tail"])
    else:
        eat(text)
    return out


def is_history(path: str) -> bool:
    """A metric-history file's first parseable line carries ``"m"``."""
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    return False
                return isinstance(doc, dict) and "m" in doc
    except OSError:
        pass
    return False


def _span_rate(recs: List[Record], name: str) -> Optional[float]:
    """Counter increase over the whole span / elapsed seconds."""
    pts = [(r.get("ts", 0.0), resolve_metric(r, name)) for r in recs]
    pts = [(t, v) for t, v in pts if v is not None]
    if len(pts) < 2 or pts[-1][0] <= pts[0][0] or pts[-1][1] < pts[0][1]:
        return None
    return (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0])


def summarize_history(path: str) -> Dict[str, Tuple[float, bool]]:
    """``{label: (value, lower_is_better)}`` over one history file."""
    recs = load_records(path)
    out: Dict[str, Tuple[float, bool]] = {}
    if not recs:
        return out
    span = max(1.0, recs[-1].get("ts", 0.0) - recs[0].get("ts", 0.0))
    for label, metric, agg, lower in HISTORY_FAMILIES:
        if agg == "rate":
            v = _span_rate(recs, metric)
        else:
            pts = windowed(recs, metric, window_s=span * 2, agg=agg,
                           prefer_interval=":" in metric)
            v = pts[0][1] if pts else None
        if v is not None:
            out[label] = (float(v), lower)
    return out


def summarize_bench(path: str) -> Dict[str, Tuple[float, bool]]:
    out: Dict[str, Tuple[float, bool]] = {}
    for doc in load_bench_lines(path):
        try:
            v = float(doc["value"])
        except (TypeError, ValueError):
            continue
        name = str(doc["metric"])
        out[name] = (v, lower_is_better(name, str(doc.get("unit", ""))))
    return out


def compare(a_path: str, b_path: str,
            noise: float = DEFAULT_NOISE) -> Dict[str, Any]:
    """Compare run ``a`` (baseline) against ``b`` (candidate).

    Returns ``{"rows": [...], "regressions": [...], "only_a": [...],
    "only_b": [...]}`` — a row regresses when the candidate moves in the
    bad direction by more than ``noise`` (relative; absolute when the
    baseline is 0, e.g. ``slo/breached`` going 0 → 1)."""
    kind = "history" if (is_history(a_path) and is_history(b_path)) \
        else "bench"
    summar = summarize_history if kind == "history" else summarize_bench
    a, b = summar(a_path), summar(b_path)
    rows, regressions = [], []
    for name in sorted(set(a) | set(b)):
        if name not in a or name not in b:
            continue
        (va, lower), (vb, _) = a[name], b[name]
        if va != 0:
            delta = (vb - va) / abs(va)
        else:
            delta = vb            # absolute movement off a zero baseline
        bad = delta > noise if lower else delta < -noise
        row = {"metric": name, "a": va, "b": vb,
               "delta_pct": round(delta * 100, 2),
               "direction": "lower_better" if lower else "higher_better",
               "regression": bad}
        rows.append(row)
        if bad:
            regressions.append(row)
    return {"kind": kind, "noise": noise, "rows": rows,
            "regressions": regressions,
            "only_a": sorted(set(a) - set(b)),
            "only_b": sorted(set(b) - set(a))}


def render(report: Dict[str, Any], a_path: str, b_path: str) -> str:
    lines = [f"compare ({report['kind']}): A={a_path}  B={b_path}  "
             f"noise band ±{report['noise'] * 100:.0f}%"]
    w = max((len(r["metric"]) for r in report["rows"]), default=10)
    for r in report["rows"]:
        mark = "REGRESSION" if r["regression"] else (
            "improved" if (r["delta_pct"] < 0) ==
            (r["direction"] == "lower_better") and
            abs(r["delta_pct"]) > report["noise"] * 100 else "~")
        lines.append(f"  {r['metric'].ljust(w)}  "
                     f"{r['a']:>12.4g} -> {r['b']:>12.4g}  "
                     f"{r['delta_pct']:>+8.2f}%  {mark}")
    for side, names in (("A", report["only_a"]), ("B", report["only_b"])):
        for n in names:
            lines.append(f"  {n.ljust(w)}  (only in {side}, not gated)")
    n_reg = len(report["regressions"])
    lines.append(f"{n_reg} regression(s) beyond the noise band"
                 if n_reg else "no regressions beyond the noise band")
    return "\n".join(lines)


def main_compare(a_path: str, b_path: str, noise: float = DEFAULT_NOISE,
                 as_json: bool = False, file=None) -> int:
    """CLI body for ``dstpu_report --compare`` → exit 1 on regression."""
    report = compare(a_path, b_path, noise=noise)
    out = file if file is not None else sys.stdout
    if as_json:
        print(json.dumps(report, indent=2), file=out)
    else:
        print(render(report, a_path, b_path), file=out)
    return 1 if report["regressions"] else 0
