"""Trace-summary CLI: per-span self-time breakdown of a dumped trace.

``python -m deepspeed_tpu.telemetry.summarize trace.json`` (or the
``bin/dstpu-trace`` wrapper) loads a Chrome trace-event JSON produced by
:meth:`deepspeed_tpu.telemetry.tracer.Tracer.dump` (or any tool emitting
the same format) and prints, per span name: call count, total wall time,
and SELF time — total minus time spent in nested child spans on the same
thread. Self time is the number that answers "where did step time go":
a ``train/step`` span that is 95% covered by its forward/backward/
optimizer children has ~5% self time (host-side glue).
"""

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, Iterable, List


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Load trace events from ``path`` — accepts both the object form
    (``{"traceEvents": [...]}``) and a bare event array."""
    with open(path) as fh:
        data = json.load(fh)
    if isinstance(data, dict):
        events = data.get("traceEvents", [])
    elif isinstance(data, list):
        events = data
    else:
        raise ValueError(f"{path}: not a Chrome trace (got {type(data)})")
    return [e for e in events if isinstance(e, dict)]


def self_times(events: Iterable[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Per-name aggregation over complete ('X') events:
    ``{name: {count, total_us, self_us}}``.

    Nesting is reconstructed per (pid, tid) track from ts/dur containment:
    events are swept in start order (ties: longer span first = parent), a
    stack tracks open spans, and each span's duration is charged against
    its innermost enclosing parent's self time.
    """
    stats: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"count": 0, "total_us": 0.0, "self_us": 0.0})
    tracks: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
    for e in events:
        if e.get("ph") == "X" and "ts" in e:
            tracks[(e.get("pid", 0), e.get("tid", 0))].append(e)

    def close(item) -> None:
        _end, child_us, e = item
        dur = float(e.get("dur", 0.0))
        rec = stats[str(e.get("name", "?"))]
        rec["count"] += 1
        rec["total_us"] += dur
        rec["self_us"] += max(0.0, dur - child_us)

    for track in tracks.values():
        track.sort(key=lambda e: (float(e["ts"]),
                                  -float(e.get("dur", 0.0))))
        stack: List[list] = []          # [end_us, child_us_accum, event]
        for e in track:
            ts = float(e["ts"])
            dur = float(e.get("dur", 0.0))
            while stack and stack[-1][0] <= ts + 1e-9:
                close(stack.pop())
            if stack:
                stack[-1][1] += dur
            stack.append([ts + dur, 0.0, e])
        while stack:
            close(stack.pop())
    return dict(stats)


def format_table(stats: Dict[str, Dict[str, float]], sort: str = "self",
                 top: int = 0) -> str:
    """Render the self-time table (sorted by ``self`` | ``total`` |
    ``count``; ``top`` > 0 truncates)."""
    if not stats:
        return "(no complete spans in trace)"
    key = {"self": lambda kv: -kv[1]["self_us"],
           "total": lambda kv: -kv[1]["total_us"],
           "count": lambda kv: -kv[1]["count"]}[sort]
    rows = sorted(stats.items(), key=key)
    if top > 0:
        rows = rows[:top]
    grand_self = sum(r["self_us"] for r in stats.values()) or 1.0
    width = max(24, max(len(n) for n, _ in rows) + 2)
    lines = [f"{'span':<{width}}{'count':>8}{'total ms':>12}"
             f"{'self ms':>12}{'self %':>8}"]
    for name, r in rows:
        lines.append(
            f"{name:<{width}}{int(r['count']):>8}"
            f"{r['total_us'] / 1e3:>12.3f}"
            f"{r['self_us'] / 1e3:>12.3f}"
            f"{100.0 * r['self_us'] / grand_self:>8.1f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-trace",
        description="Per-span self-time breakdown of a deepspeed_tpu "
                    "Chrome trace-event JSON dump")
    ap.add_argument("trace", help="trace file (tracer.dump output)")
    ap.add_argument("--sort", choices=("self", "total", "count"),
                    default="self", help="sort column (default: self)")
    ap.add_argument("--top", type=int, default=0,
                    help="show only the top N spans (0 = all)")
    args = ap.parse_args(argv)
    events = load_trace(args.trace)
    print(format_table(self_times(events), sort=args.sort, top=args.top))
    n_instant = sum(1 for e in events if e.get("ph") == "i")
    if n_instant:
        print(f"\n({n_instant} instant events not shown — e.g. comm/* "
              f"trace-time markers)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
