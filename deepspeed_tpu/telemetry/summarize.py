"""Trace-summary CLI: per-span self-time breakdown of a dumped trace.

``python -m deepspeed_tpu.telemetry.summarize trace.json`` (or the
``bin/dstpu-trace`` wrapper) loads a Chrome trace-event JSON produced by
:meth:`deepspeed_tpu.telemetry.tracer.Tracer.dump` (or any tool emitting
the same format) and prints, per span name: call count, total wall time,
and SELF time — total minus time spent in nested child spans on the same
thread. Self time is the number that answers "where did step time go":
a ``train/step`` span that is 95% covered by its forward/backward/
optimizer children has ~5% self time (host-side glue).

``dstpu-trace --request <trace_id> dump1.json hostB/`` is the
post-mortem assembler for request-scoped distributed traces
(:mod:`~deepspeed_tpu.telemetry.reqtrace`): it merges any number of
per-host dumps (files or directories of ``*.json``), keeps only the
spans stamped with that ``trace_id``, synthesizes Chrome flow events
from the ``parent_span_id`` → ``span_id`` edges so Perfetto draws the
cross-process arrows (router → prefill replica → handoff → decode
replica), verifies the parent/child chain is unbroken, and prints the
critical-path breakdown (queued / prefill / handoff / decode / replayed
/ stalled, with % of total). ``--out`` writes the merged trace JSON.
"""

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Tuple


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Load trace events from ``path`` — accepts both the object form
    (``{"traceEvents": [...]}``) and a bare event array."""
    with open(path) as fh:
        data = json.load(fh)
    if isinstance(data, dict):
        events = data.get("traceEvents", [])
    elif isinstance(data, list):
        events = data
    else:
        raise ValueError(f"{path}: not a Chrome trace (got {type(data)})")
    return [e for e in events if isinstance(e, dict)]


def expand_paths(paths: Iterable[str]) -> List[str]:
    """Files stay files; directories expand to their sorted ``*.json``
    entries (the multi-host dump layout: one trace dump per host)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(".json")))
        else:
            out.append(p)
    return out


def load_merged(paths: Iterable[str]
                ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Merge events from many dumps into one timeline. Each source
    file's pids are remapped to a unique range (two hosts both dumping
    pid 1234 must not share a Perfetto process track) and a
    ``process_name`` metadata event names each track after its source.
    Returns ``(events, metadata_events)``."""
    events: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    pid_map: Dict[Tuple[int, Any], int] = {}
    for i, path in enumerate(expand_paths(paths)):
        for e in load_trace(path):
            e = dict(e)
            key = (i, e.get("pid", 0))
            newpid = pid_map.get(key)
            if newpid is None:
                newpid = pid_map[key] = len(pid_map) + 1
                meta.append({"ph": "M", "name": "process_name",
                             "pid": newpid, "tid": 0,
                             "args": {"name": f"{os.path.basename(path)}"
                                              f":{e.get('pid', 0)}"}})
            e["pid"] = newpid
            events.append(e)
    return events, meta


def request_events(events: Iterable[Dict[str, Any]],
                   trace_id: str) -> List[Dict[str, Any]]:
    """The subset of ``events`` stamped with ``trace_id``."""
    return [e for e in events
            if isinstance(e.get("args"), dict)
            and e["args"].get("trace_id") == trace_id]


def flow_events(events: List[Dict[str, Any]]
                ) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Synthesize Chrome flow events ('s'/'f' pairs) from the
    ``parent_span_id`` → ``span_id`` edges of one request's span set, so
    Perfetto draws the cross-process arrows. Returns ``(flows,
    orphan_parent_ids)`` — a non-empty orphan list means the
    parent/child chain is broken (a leg's dump is missing)."""
    spans = [e for e in events if e.get("ph") == "X"
             and isinstance(e.get("args"), dict)]
    by_id: Dict[str, Dict[str, Any]] = {}
    for e in spans:
        sid = e["args"].get("span_id")
        if sid:
            by_id.setdefault(sid, e)
    flows: List[Dict[str, Any]] = []
    orphans: List[str] = []
    for e in spans:
        pid_ = e["args"].get("parent_span_id")
        sid = e["args"].get("span_id")
        if not pid_:
            continue
        parent = by_id.get(pid_)
        if parent is None:
            orphans.append(pid_)
            continue
        if parent is e:
            continue
        fid = f"req-{sid}"
        common = {"cat": "reqflow", "name": "request", "id": fid}
        flows.append({**common, "ph": "s", "ts": float(parent["ts"]),
                      "pid": parent.get("pid", 0),
                      "tid": parent.get("tid", 0)})
        flows.append({**common, "ph": "f", "bp": "e",
                      "ts": float(e["ts"]), "pid": e.get("pid", 0),
                      "tid": e.get("tid", 0)})
    return flows, sorted(set(orphans))


def format_critical_path(breakdown: Dict[str, float]) -> str:
    """Render a :func:`~deepspeed_tpu.telemetry.reqtrace.critical_path`
    attribution as aligned ``segment  ms  %`` lines."""
    total = breakdown.get("_total_ms", 0.0) or 1.0
    lines = [f"{'segment':<12}{'ms':>10}{'% of total':>12}"]
    segs = [(k, v) for k, v in breakdown.items() if k != "_total_ms"]
    for seg, ms in sorted(segs, key=lambda kv: -kv[1]):
        lines.append(f"{seg:<12}{ms:>10.2f}{100.0 * ms / total:>11.1f}%")
    lines.append(f"{'total':<12}{total:>10.2f}{100.0:>11.1f}%")
    return "\n".join(lines)


def assemble_request(paths: Iterable[str], trace_id: str,
                     out: Optional[str] = None) -> Dict[str, Any]:
    """``--request`` mode: merge dumps, filter to one trace, add flow
    events, optionally write the merged trace JSON. Returns a report
    dict (events, flows, orphans, breakdown, by_process)."""
    from deepspeed_tpu.telemetry.reqtrace import critical_path
    merged, meta = load_merged(paths)
    evs = request_events(merged, trace_id)
    flows, orphans = flow_events(evs)
    doc = {"traceEvents": sorted(evs + flows + meta,
                                 key=lambda e: float(e.get("ts", 0.0))),
           "displayTimeUnit": "ms",
           "otherData": {"tracer": "deepspeed_tpu.telemetry",
                         "request": trace_id}}
    if out and evs:
        parent = os.path.dirname(os.path.abspath(out))
        os.makedirs(parent, exist_ok=True)
        with open(out, "w") as fh:
            json.dump(doc, fh)
    by_process: Dict[Any, int] = defaultdict(int)
    for e in evs:
        by_process[e.get("pid", 0)] += 1
    return {"trace_id": trace_id, "events": evs, "flows": flows,
            "orphans": orphans, "doc": doc,
            "breakdown": critical_path(evs),
            "by_process": dict(by_process)}


def self_times(events: Iterable[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Per-name aggregation over complete ('X') events:
    ``{name: {count, total_us, self_us}}``.

    Nesting is reconstructed per (pid, tid) track from ts/dur containment:
    events are swept in start order (ties: longer span first = parent), a
    stack tracks open spans, and each span's duration is charged against
    its innermost enclosing parent's self time.
    """
    stats: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"count": 0, "total_us": 0.0, "self_us": 0.0})
    tracks: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
    for e in events:
        if e.get("ph") == "X" and "ts" in e:
            tracks[(e.get("pid", 0), e.get("tid", 0))].append(e)

    def close(item) -> None:
        _end, child_us, e = item
        dur = float(e.get("dur", 0.0))
        rec = stats[str(e.get("name", "?"))]
        rec["count"] += 1
        rec["total_us"] += dur
        rec["self_us"] += max(0.0, dur - child_us)

    for track in tracks.values():
        track.sort(key=lambda e: (float(e["ts"]),
                                  -float(e.get("dur", 0.0))))
        stack: List[list] = []          # [end_us, child_us_accum, event]
        for e in track:
            ts = float(e["ts"])
            dur = float(e.get("dur", 0.0))
            while stack and stack[-1][0] <= ts + 1e-9:
                close(stack.pop())
            if stack:
                stack[-1][1] += dur
            stack.append([ts + dur, 0.0, e])
        while stack:
            close(stack.pop())
    return dict(stats)


def format_table(stats: Dict[str, Dict[str, float]], sort: str = "self",
                 top: int = 0) -> str:
    """Render the self-time table (sorted by ``self`` | ``total`` |
    ``count``; ``top`` > 0 truncates)."""
    if not stats:
        return "(no complete spans in trace)"
    key = {"self": lambda kv: -kv[1]["self_us"],
           "total": lambda kv: -kv[1]["total_us"],
           "count": lambda kv: -kv[1]["count"]}[sort]
    rows = sorted(stats.items(), key=key)
    if top > 0:
        rows = rows[:top]
    grand_self = sum(r["self_us"] for r in stats.values()) or 1.0
    width = max(24, max(len(n) for n, _ in rows) + 2)
    lines = [f"{'span':<{width}}{'count':>8}{'total ms':>12}"
             f"{'self ms':>12}{'self %':>8}"]
    for name, r in rows:
        lines.append(
            f"{name:<{width}}{int(r['count']):>8}"
            f"{r['total_us'] / 1e3:>12.3f}"
            f"{r['self_us'] / 1e3:>12.3f}"
            f"{100.0 * r['self_us'] / grand_self:>8.1f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-trace",
        description="Per-span self-time breakdown of a deepspeed_tpu "
                    "Chrome trace-event JSON dump; --request assembles "
                    "one request's distributed trace from multi-host "
                    "dumps")
    ap.add_argument("trace", nargs="+",
                    help="trace file(s) or directories of dumps "
                         "(tracer.dump output)")
    ap.add_argument("--sort", choices=("self", "total", "count"),
                    default="self", help="sort column (default: self)")
    ap.add_argument("--top", type=int, default=0,
                    help="show only the top N spans (0 = all)")
    ap.add_argument("--request", metavar="TRACE_ID", default=None,
                    help="assemble the distributed trace of one request "
                         "across all given dumps (merged Perfetto trace "
                         "with flow events + critical-path breakdown)")
    ap.add_argument("--out", default=None,
                    help="with --request: write the merged trace JSON "
                         "here (load in ui.perfetto.dev)")
    ap.add_argument("--goodput", action="store_true",
                    help="append the goodput/badput attribution of the "
                         "trace (telemetry/goodput.py ledger sweep)")
    args = ap.parse_args(argv)
    if args.request:
        rep = assemble_request(args.trace, args.request, out=args.out)
        if not rep["events"]:
            print(f"trace_id {args.request}: no spans found in "
                  f"{len(expand_paths(args.trace))} dump(s) — was the "
                  f"trace retained? (tail sampling drops fast, "
                  f"unflagged requests)", file=sys.stderr)
            return 1
        n_proc = len(rep["by_process"])
        print(f"request {args.request}: {len(rep['events'])} spans "
              f"across {n_proc} process(es), "
              f"{len(rep['flows']) // 2} flow edges")
        if rep["orphans"]:
            print(f"WARNING: broken parent/child chain — "
                  f"{len(rep['orphans'])} parent span(s) missing "
                  f"({', '.join(rep['orphans'][:4])}) — a leg's dump "
                  f"was not provided", file=sys.stderr)
        print()
        print(format_critical_path(rep["breakdown"]))
        if args.out:
            print(f"\nmerged trace written to {args.out}")
        return 0
    events: List[Dict[str, Any]] = []
    for path in expand_paths(args.trace):
        events.extend(load_trace(path))
    print(format_table(self_times(events), sort=args.sort, top=args.top))
    n_instant = sum(1 for e in events if e.get("ph") == "i")
    if n_instant:
        print(f"\n({n_instant} instant events not shown — e.g. comm/* "
              f"trace-time markers)")
    if args.goodput:
        from deepspeed_tpu.telemetry import goodput as _goodput
        spans = [e for e in events if e.get("ph") == "X"]
        if spans:
            t0 = min(float(e["ts"]) for e in spans) / 1e6
            t1 = max(float(e["ts"]) + float(e.get("dur", 0.0))
                     for e in spans) / 1e6
            res = _goodput.attribute(events, t0, t1, base=0.0)
            sec = res["seconds"]
            print("\ngoodput attribution (trace extent "
                  f"{t1 - t0:.3f}s):")
            print(_goodput.format_ledger({
                "uptime_s": t1 - t0, "goodput_s": sec["goodput"],
                "badput": {c: sec[c] for c in _goodput.CATEGORIES
                           if c != "goodput"}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
