"""Compile-time explain layer: roofline & HBM-budget attribution.

PR 3/4 instrumented the *measured* side (spans, metrics, flight
recorder); this module adds the *static* side: lower the engine's jitted
step (and the serving prefill/decode programs) ahead of time and read
back what XLA already knows about the compiled program —

- ``cost_analysis()``: FLOPs and bytes accessed, fusion-accurate;
- ``memory_analysis()``: the HBM split (argument / output / temp /
  generated-code bytes) of the exact executable;
- the optimized HLO text: bytes moved by collectives (all-reduce,
  all-gather, reduce-scatter, all-to-all, collective-permute).

Combined with the per-platform peak tables (``PEAK_FLOPS_BF16`` /
``PEAK_HBM_BW`` in :mod:`~deepspeed_tpu.telemetry.sampler`, the ICI
table here) that yields a roofline: predicted step time =
max(compute, memory, comm) bound, published as ``roofline/*`` gauges and
compared against the measured ``train/step_time_ms`` so "% of roofline"
is a first-class health number (T3 / Big-Send-off framing: static cost
attribution paired with achieved-vs-peak measurement).

Everything degrades gracefully: backends whose ``cost_analysis`` returns
nothing (some CPU builds) still produce a report with the static byte
budget, and unknown platforms (CPU CI) report an "unknown" roofline
bound unless peaks are overridden (``--platform v5e`` models a target
chip from any host — nothing is allocated, lowering is abstract).

CLI: ``bin/dstpu-explain`` / ``python -m deepspeed_tpu.telemetry.explain``.
"""

import argparse
import json
import math
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.registry import registry as _registry
from deepspeed_tpu.telemetry.sampler import (HBM_CAPACITY, PEAK_FLOPS_BF16,
                                             PEAK_HBM_BW, hbm_capacity,
                                             peak_flops, peak_hbm_bw,
                                             warn_unknown_platform)

#: peak interconnect bandwidth, bytes/s per chip (public ICI specs,
#: aggregate over the chip's links; the comm side of the roofline)
PEAK_ICI_BW: Dict[str, float] = {
    "v7": 1200e9, "ironwood": 1200e9,
    "v6e": 448e9, "trillium": 448e9,
    "v5p": 600e9,
    "v5e": 200e9, "v5 lite": 200e9, "v5litepod": 200e9,
    "v4": 300e9,
    "v3": 82e9,
    "v2": 62e9,
}

#: most recent explain snapshots ({"train": ..., "serving": ...}) — the
#: flight recorder folds this into black boxes so dstpu-doctor can show
#: predicted vs achieved post mortem
last_report: Dict[str, Any] = {}

_DTYPE_BYTES = {"pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
                "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
                "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1}

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute")

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

#: one HLO instruction: ``name = <shape> <opcode>(...)`` where <shape>
#: is a single ``f32[8,64]{1,0}`` or a tuple ``(f32[...], f32[...])``
_INSTR_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>[a-z][a-z-]*)\(")


# ---------------------------------------------------------------------------
# cost extraction — THE cost-analysis helper (flops_profiler re-exports)
# ---------------------------------------------------------------------------

def abstractify(tree):
    """Pytree of arrays → ShapeDtypeStructs, keeping shardings when the
    leaves carry them (so lowering sees the real GSPMD layout). Nothing
    is allocated — 70B-scale programs explain for free."""
    import jax

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        sharding = getattr(x, "sharding", None)
        try:
            # only NamedShardings: uncommitted host arrays carry a
            # SingleDeviceSharding whose device set clashes with the
            # mesh-sharded params under one jitted computation
            if isinstance(sharding, jax.sharding.NamedSharding):
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=sharding)
        except Exception:
            pass
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree.map(leaf, tree)


def normalize_cost_analysis(cost: Any) -> Dict[str, float]:
    """``compiled.cost_analysis()`` → plain dict. Handles the dict /
    per-device-list return shapes across jax versions, and None/empty
    from backends without an implementation."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    return {str(k): float(v) for k, v in cost.items()
            if isinstance(v, (int, float)) and math.isfinite(float(v))}


def collective_stats_from_hlo(hlo_text: str) -> Dict[str, Dict[str, float]]:
    """Per-op collective traffic in optimized HLO text:
    ``{op: {"bytes": float, "count": int}}``.

    Counts each collective INSTRUCTION once. Async pairs are attributed
    to the ``-start`` op only (the ``-done`` merely unpacks the result),
    and a ``-start`` whose shape is a tuple — ``(operand_aliases,
    result)`` or the tupled variadic form — contributes the single
    LARGEST element of the tuple, not the sum: summing would double-count
    every async/fused collective (all-gather-start's tuple repeats the
    operand next to the gathered result; all-reduce-start's repeats the
    buffer on both sides; collective-permute-start adds tiny u32 context
    slots). The chunked ZeRO-3 overlap path fragments the whole-model
    gather into dozens of small async all-gathers, which made that
    double-count structural rather than occasional — ``count`` exposes
    the fragmentation (chunk count) instead.
    """
    stats: Dict[str, Dict[str, float]] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.search(line)
        if m is None:
            continue
        op = m.group("op")
        if op.endswith("-done"):
            continue                      # async pair: count the start only
        if op.endswith("-start"):
            op = op[:-len("-start")]
        if op not in _COLLECTIVE_OPS:
            continue
        best = 0.0
        for dt, dims in _SHAPE_RE.findall(m.group("shape")):
            nbytes = _DTYPE_BYTES.get(dt)
            if nbytes is None:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            best = max(best, float(n * nbytes))
        s = stats.setdefault(op, {"bytes": 0.0, "count": 0})
        s["bytes"] += best
        s["count"] += 1
    return stats


def collective_bytes_from_hlo(hlo_text: str) -> float:
    """Total bytes moved by collectives in optimized HLO text (the
    largest buffer of each collective instruction, summed). An
    approximation of wire traffic — good enough to rank the comm
    roofline bound. See :func:`collective_stats_from_hlo` for the
    per-op/per-chunk breakdown."""
    return sum(s["bytes"] for s in collective_stats_from_hlo(
        hlo_text).values())


@dataclass
class FunctionCost:
    """Per-compiled-function static costs (all bytes are per device —
    the compiled program is the SPMD per-device program)."""
    name: str
    available: bool = False           #: cost_analysis had real numbers
    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: float = 0.0
    output_bytes: float = 0.0
    temp_bytes: float = 0.0
    generated_code_bytes: float = 0.0
    collective_bytes: float = 0.0
    #: {op: {"bytes", "count"}} — per-op totals + instruction counts
    #: (chunked-overlap runs show count ≈ 2×chunks here)
    collective_stats: Dict[str, Dict[str, float]] = None
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


def analyze_compiled(name: str, compiled) -> FunctionCost:
    """Extract a :class:`FunctionCost` from a ``jax`` AOT-compiled
    object. Every source is best-effort; missing pieces stay 0."""
    fc = FunctionCost(name=name)
    try:
        cost = normalize_cost_analysis(compiled.cost_analysis())
    except Exception:
        cost = {}
    fc.flops = cost.get("flops", 0.0)
    fc.bytes_accessed = cost.get("bytes accessed", 0.0)
    fc.available = bool(cost) and (fc.flops > 0 or fc.bytes_accessed > 0)
    try:
        mem = compiled.memory_analysis()
        fc.argument_bytes = float(getattr(mem, "argument_size_in_bytes", 0))
        fc.output_bytes = float(getattr(mem, "output_size_in_bytes", 0))
        fc.temp_bytes = float(getattr(mem, "temp_size_in_bytes", 0))
        fc.generated_code_bytes = float(
            getattr(mem, "generated_code_size_in_bytes", 0))
    except Exception:
        pass
    try:
        fc.collective_stats = collective_stats_from_hlo(compiled.as_text())
        fc.collective_bytes = sum(s["bytes"]
                                  for s in fc.collective_stats.values())
    except Exception:
        pass
    return fc


def analyze_lowerable(name: str, fn: Callable, *abstract_args,
                      static_argnums=()) -> FunctionCost:
    """Lower + compile ``fn`` over abstract args (already-jitted
    functions lower directly; plain callables are jitted first) and
    extract its costs. Failures come back as an unavailable record with
    the error string, never an exception — explain must not take an
    engine down."""
    import jax
    try:
        target = fn if hasattr(fn, "lower") else \
            jax.jit(fn, static_argnums=static_argnums)
        compiled = target.lower(*abstract_args).compile()
        return analyze_compiled(name, compiled)
    except Exception as e:                          # noqa: BLE001
        return FunctionCost(name=name, error=f"{type(e).__name__}: {e}")


#: per-candidate cost reuse for batch explain (dstpu-tune): the same
#: (candidate key, function) pair is lowered once per process — the tuner
#: re-ranks, the bench A/B re-scores, and the CLI re-renders without
#: paying the XLA compile again
_COST_CACHE: Dict[str, FunctionCost] = {}


def clear_cost_cache() -> None:
    _COST_CACHE.clear()


def analyze_lowerable_cached(key: str, name: str, fn: Callable,
                             *abstract_args,
                             static_argnums=()) -> FunctionCost:
    """:func:`analyze_lowerable` behind the per-candidate cost cache.
    ``key`` must uniquely identify (function identity × abstract arg
    shapes) — the tuner uses the candidate's config key. Error records
    are cached too: a candidate that failed to lower once will fail the
    same way again, and re-lowering it per rank pass is the cost this
    cache exists to avoid."""
    hit = _COST_CACHE.get(key)
    if hit is not None:
        return hit
    fc = analyze_lowerable(name, fn, *abstract_args,
                           static_argnums=static_argnums)
    _COST_CACHE[key] = fc
    return fc


def roofline_from_cost(fc: FunctionCost, peaks: "Peaks") -> "Roofline":
    """FunctionCost → Roofline against ``peaks``, degrading gracefully:
    a record whose ``cost_analysis`` came back empty (some CPU builds)
    or that failed to lower scores as an all-zero roofline —
    ``bound='unknown'``, ``predicted_s == 0.0`` — instead of raising, so
    a mid-search candidate with no numbers is kept (ranked behind every
    known-bound candidate) and the sweep continues."""
    if fc is None or fc.error is not None or not fc.available:
        return Roofline(peak_flops=peaks.peak_flops, hbm_bw=peaks.hbm_bw,
                        ici_bw=peaks.ici_bw)
    return Roofline(flops=fc.flops, bytes=fc.bytes_accessed,
                    comm_bytes=fc.collective_bytes,
                    peak_flops=peaks.peak_flops, hbm_bw=peaks.hbm_bw,
                    ici_bw=peaks.ici_bw)


def batch_explain(items, peaks: "Peaks") -> List[Tuple[str, FunctionCost,
                                                       "Roofline"]]:
    """Batch-explain API for the autotuner: ``items`` is an iterable of
    ``(key, name, fn, abstract_args)``; each entry is lowered through the
    cost cache and scored with :func:`roofline_from_cost`. One bad
    candidate never aborts the batch — its record carries the error and
    an unknown-bound roofline."""
    out = []
    for key, name, fn, abstract_args in items:
        fc = analyze_lowerable_cached(key, name, fn, *abstract_args)
        out.append((key, fc, roofline_from_cost(fc, peaks)))
    return out


def analyze_fn(fn: Callable, *args, static_argnums=()) -> Dict[str, float]:
    """Compile ``fn`` for the current devices and return XLA cost
    analysis (the historical ``flops_profiler.analyze_fn`` API —
    re-exported from there)."""
    fc = analyze_lowerable("fn", fn, *args, static_argnums=static_argnums)
    out = {"flops": fc.flops, "bytes_accessed": fc.bytes_accessed}
    peak = fc.argument_bytes + fc.output_bytes + fc.temp_bytes
    if peak:
        out["peak_bytes"] = peak
    return out


def _cost(fn: Callable, *abstract_args) -> Dict[str, float]:
    """Historical ``flops_profiler._cost`` API: {'flops', 'bytes'}."""
    fc = analyze_lowerable("fn", fn, *abstract_args)
    return {"flops": fc.flops, "bytes": fc.bytes_accessed}


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

BOUND_CODES = {"unknown": 0, "compute": 1, "memory": 2, "comm": 3}


@dataclass
class Roofline:
    """max(compute, memory, comm) step-time model for one program.

    All inputs are per device: ``flops``/``bytes``/``comm_bytes`` from
    the compiled per-device program, peaks from the platform tables.
    Zero peaks (CPU, unknown chips) yield ``bound='unknown'`` and a zero
    prediction — callers must treat 0 as "no model", not "instant"."""
    flops: float = 0.0
    bytes: float = 0.0
    comm_bytes: float = 0.0
    peak_flops: float = 0.0
    hbm_bw: float = 0.0
    ici_bw: float = 0.0

    @property
    def compute_s(self) -> float:
        return self.flops / self.peak_flops if self.peak_flops else 0.0

    @property
    def memory_s(self) -> float:
        return self.bytes / self.hbm_bw if self.hbm_bw else 0.0

    @property
    def comm_s(self) -> float:
        return self.comm_bytes / self.ici_bw if self.ici_bw else 0.0

    @property
    def predicted_s(self) -> float:
        return max(self.compute_s, self.memory_s, self.comm_s)

    @property
    def bound(self) -> str:
        p = self.predicted_s
        if p <= 0.0:
            return "unknown"
        if p == self.comm_s and self.comm_bytes > 0:
            return "comm"
        if p == self.memory_s and self.memory_s >= self.compute_s:
            return "memory"
        return "compute"

    def pct_of(self, measured_s: Optional[float]) -> Optional[float]:
        """Predicted/measured as a percentage — 100% means the step runs
        at the roofline; None when either side is missing."""
        if not measured_s or measured_s <= 0 or self.predicted_s <= 0:
            return None
        return 100.0 * self.predicted_s / measured_s

    def to_dict(self, measured_s: Optional[float] = None) -> Dict[str, Any]:
        return {"flops": self.flops, "bytes": self.bytes,
                "comm_bytes": self.comm_bytes,
                "peak_flops": self.peak_flops, "hbm_bw": self.hbm_bw,
                "ici_bw": self.ici_bw,
                "compute_ms": self.compute_s * 1e3,
                "memory_ms": self.memory_s * 1e3,
                "comm_ms": self.comm_s * 1e3,
                "predicted_ms": self.predicted_s * 1e3,
                "bound": self.bound,
                "pct_of_roofline": self.pct_of(measured_s)}


@dataclass
class Peaks:
    """Resolved peak numbers + identity of the (possibly hypothetical)
    target platform."""
    kind: str = "cpu"
    peak_flops: float = 0.0
    hbm_bw: float = 0.0
    ici_bw: float = 0.0
    capacity: float = 0.0


def _platform_lookup(table: Dict[str, float], name: str) -> float:
    name = name.lower()
    for key, val in table.items():
        if key in name:
            return val
    return 0.0


def resolve_peaks(device: Any = None, platform: Optional[str] = None,
                  peak_flops_override: Optional[float] = None,
                  hbm_bw_override: Optional[float] = None,
                  ici_bw_override: Optional[float] = None) -> Peaks:
    """Peak numbers for the roofline: from the live device by default,
    from the spec tables when ``platform`` names a chip ("v5e", "v5p",
    …) — so a CPU host can model a TPU target — with per-number
    overrides on top."""
    if platform:
        warn_unknown_platform(platform, context="resolve_peaks")
        p = Peaks(kind=platform,
                  peak_flops=_platform_lookup(PEAK_FLOPS_BF16, platform),
                  hbm_bw=_platform_lookup(PEAK_HBM_BW, platform),
                  ici_bw=_platform_lookup(PEAK_ICI_BW, platform),
                  capacity=_platform_lookup(HBM_CAPACITY, platform))
    else:
        kind = "cpu"
        try:
            import jax
            dev = device if device is not None else jax.devices()[0]
            kind = str(getattr(dev, "device_kind", dev.platform))
        except Exception:
            dev = None
        p = Peaks(kind=kind, peak_flops=peak_flops(device),
                  hbm_bw=peak_hbm_bw(device),
                  ici_bw=_platform_lookup(PEAK_ICI_BW, kind.lower()),
                  capacity=hbm_capacity(device))
    if peak_flops_override:
        p.peak_flops = float(peak_flops_override)
    if hbm_bw_override:
        p.hbm_bw = float(hbm_bw_override)
    if ici_bw_override:
        p.ici_bw = float(ici_bw_override)
    return p


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

@dataclass
class ExplainReport:
    """Structured explain output (JSON-able via :meth:`to_dict`)."""
    kind: str = "train"                       #: "train" | "serving"
    platform: str = "cpu"
    n_devices: int = 1
    peaks: Peaks = field(default_factory=Peaks)
    functions: List[FunctionCost] = field(default_factory=list)
    #: (name, shape, dtype, global bytes, sharding spec) per param leaf
    params: List[Tuple[str, str, str, float, str]] = field(
        default_factory=list)
    #: HBM budget components, bytes per device
    budget: Dict[str, float] = field(default_factory=dict)
    roofline: Roofline = field(default_factory=Roofline)
    measured_step_ms: Optional[float] = None
    warnings: List[str] = field(default_factory=list)

    @property
    def budget_total(self) -> float:
        return sum(self.budget.values())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind, "platform": self.platform,
            "n_devices": self.n_devices,
            "peaks": dict(self.peaks.__dict__),
            "functions": [f.to_dict() for f in self.functions],
            "params": [list(p) for p in self.params],
            "budget": dict(self.budget),
            "budget_total": self.budget_total,
            "roofline": self.roofline.to_dict(
                (self.measured_step_ms or 0) / 1e3 or None),
            "measured_step_ms": self.measured_step_ms,
            "warnings": list(self.warnings),
        }


def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(b) < 1024.0 or unit == "TiB":
            return f"{b:.2f} {unit}" if unit != "B" else f"{b:.0f} B"
        b /= 1024.0
    return f"{b:.2f} TiB"


def _fmt_num(v: float) -> str:
    for thresh, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= thresh:
            return f"{v / thresh:.2f}{suffix}"
    return f"{v:.0f}"


def dispatch_waste() -> Optional[Dict[str, float]]:
    """Fused-decode dispatch accounting from the process-wide ``dispatch/*``
    counters, or None when no fused launch has run in this process.

    ``dead_fraction`` is the share of scan iterations burned on bucket
    rounding: fused scan lengths round up (``_FUSED_STEP_BUCKET``
    multiples on the generate path, pow2 on megasteps) so distinct window
    sizes share compiles, and every iteration past the traced ``limit``
    runs the full model forward with all rows dead."""
    scan = _registry.get("dispatch/scan_steps")
    dead = _registry.get("dispatch/dead_steps")
    if scan is None or not scan.value:
        return None
    dead_v = float(dead.value) if dead is not None else 0.0
    return {"scan_steps": float(scan.value), "dead_steps": dead_v,
            "dead_fraction": dead_v / float(scan.value)}


def dispatch_note(threshold: float = 0.10) -> Optional[str]:
    """One grep-able DISPATCH line when fused-decode bucket rounding burns
    more than ``threshold`` of all scan iterations; None otherwise."""
    w = dispatch_waste()
    if w is None or w["dead_fraction"] <= threshold:
        return None
    return (f"DISPATCH: {100.0 * w['dead_fraction']:.1f}% of fused decode "
            f"iterations were dead ({int(w['dead_steps'])} of "
            f"{int(w['scan_steps'])} scan steps) — window sizes land far "
            f"below their scan-length bucket; align max_new_tokens /"
            f" serving.megastep_tokens with the bucket size or lower it")


def verdict_line(report: "ExplainReport") -> str:
    """The one-line roofline verdict (rendered last, grep-able)."""
    rl = report.roofline
    measured_s = (report.measured_step_ms or 0) / 1e3 or None
    if rl.bound == "unknown":
        line = (f"ROOFLINE: unknown bound — no peak numbers for "
                f"'{report.peaks.kind}' (pass --platform/--peak-flops to "
                f"model a target chip); static costs only")
        if report.measured_step_ms:
            line += f"; measured {report.measured_step_ms:.2f} ms/step"
        return line
    line = (f"ROOFLINE: {rl.bound}-bound — predicted step "
            f"{rl.predicted_s * 1e3:.2f} ms "
            f"(compute {rl.compute_s * 1e3:.2f}, "
            f"memory {rl.memory_s * 1e3:.2f}, "
            f"comm {rl.comm_s * 1e3:.2f})")
    pct = rl.pct_of(measured_s)
    if pct is not None:
        line += (f"; measured {report.measured_step_ms:.2f} ms → "
                 f"{pct:.1f}% of roofline")
    return line


def render(report: ExplainReport) -> str:
    """Plain-text explain report: HBM-budget table, per-function
    FLOPs/bytes table, sharding layout, roofline verdict."""
    out: List[str] = []
    p = report.peaks
    out.append(f"== dstpu-explain report ({report.kind}) ==")
    out.append(
        f"target: {p.kind} x{report.n_devices} "
        f"(peak {_fmt_num(p.peak_flops)}FLOP/s, "
        f"HBM {_fmt_num(p.hbm_bw)}B/s, ICI {_fmt_num(p.ici_bw)}B/s, "
        f"capacity {_fmt_bytes(p.capacity) if p.capacity else 'unknown'})")
    out.append("")
    out.append("HBM budget (bytes per device):")
    out.append(f"  {'component':<28}{'bytes':>14}")
    for name, b in report.budget.items():
        out.append(f"  {name:<28}{_fmt_bytes(b):>14}")
    total = report.budget_total
    cap_note = ""
    if p.capacity:
        cap_note = (f"  ({100.0 * total / p.capacity:.1f}% of "
                    f"{_fmt_bytes(p.capacity)})")
    out.append(f"  {'total':<28}{_fmt_bytes(total):>14}{cap_note}")
    out.append("")
    out.append("per-function costs (per device, from XLA cost analysis):")
    out.append(f"  {'function':<22}{'flops':>10}{'bytes':>12}"
               f"{'args':>12}{'temps':>12}{'collective':>12}")
    for f in report.functions:
        if f.error:
            out.append(f"  {f.name:<22}unavailable ({f.error[:60]})")
            continue
        note = "" if f.available else "  (cost_analysis empty)"
        out.append(
            f"  {f.name:<22}{_fmt_num(f.flops):>10}"
            f"{_fmt_bytes(f.bytes_accessed):>12}"
            f"{_fmt_bytes(f.argument_bytes):>12}"
            f"{_fmt_bytes(f.temp_bytes):>12}"
            f"{_fmt_bytes(f.collective_bytes):>12}{note}")
        if f.collective_stats:
            # per-op breakdown with instruction counts — under the
            # chunked-overlap path the count is the chunk fan-out
            parts = ", ".join(
                f"{op} {_fmt_bytes(s['bytes'])} in {int(s['count'])} op(s)"
                for op, s in sorted(f.collective_stats.items()))
            out.append(f"  {'':<22}collectives: {parts}")
    if report.params:
        out.append("")
        top = sorted(report.params, key=lambda r: -r[3])[:12]
        out.append(f"param layout (top {len(top)} of {len(report.params)} "
                   f"leaves by bytes; global bytes):")
        out.append(f"  {'param':<34}{'shape':<20}{'dtype':<10}"
                   f"{'bytes':>12}  sharding")
        for name, shape, dtype, nbytes, spec in top:
            out.append(f"  {name[:33]:<34}{shape:<20}{dtype:<10}"
                       f"{_fmt_bytes(nbytes):>12}  {spec}")
    for w in report.warnings:
        out.append("")
        out.append(f"WARNING: {w}")
    note = dispatch_note()
    if note is not None:
        out.append("")
        out.append(note)
    out.append("")
    out.append(verdict_line(report))
    return "\n".join(out)


def publish_gauges(report: ExplainReport, registry=None) -> None:
    """Publish the report's roofline as ``roofline/*`` gauges (the
    static counterparts of the measured ``train/*`` series)."""
    reg = registry if registry is not None else _registry
    rl = report.roofline
    reg.gauge("roofline/flops_per_step",
              help="predicted FLOPs per step per device").set(rl.flops)
    reg.gauge("roofline/bytes_per_step",
              help="predicted HBM bytes per step per device").set(rl.bytes)
    reg.gauge("roofline/comm_bytes_per_step",
              help="predicted collective bytes per step per device").set(
        rl.comm_bytes)
    reg.gauge("roofline/predicted_step_ms",
              help="roofline-predicted step time (0 = no model)").set(
        rl.predicted_s * 1e3)
    reg.gauge("roofline/bound_code",
              help="0 unknown, 1 compute, 2 memory, 3 comm").set(
        BOUND_CODES[rl.bound])
    reg.gauge("roofline/hbm_budget_bytes",
              help="predicted HBM footprint per device").set(
        report.budget_total)
    reg.gauge("roofline/hbm_capacity_bytes",
              help="device HBM capacity (0 = unknown)").set(
        report.peaks.capacity)
    pct = rl.pct_of((report.measured_step_ms or 0) / 1e3 or None)
    if pct is not None:
        reg.gauge("roofline/pct",
                  help="predicted/measured step time, percent").set(pct)


# ---------------------------------------------------------------------------
# engine / serving explain
# ---------------------------------------------------------------------------

def _leaf_name(path) -> str:
    import jax
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(str(k.name))
        else:
            parts.append(str(k).strip(".[]'\""))
    return ".".join(parts) or "<root>"


def param_table(params) -> List[Tuple[str, str, str, float, str]]:
    """(name, shape, dtype, global bytes, sharding spec) per leaf."""
    import jax
    import numpy as np
    rows = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        nbytes = float(np.prod(shape, dtype=np.float64) *
                       np.dtype(dtype).itemsize) if dtype is not None else 0.0
        spec = ""
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            spec = str(getattr(sharding, "spec", sharding.__class__.__name__))
        rows.append((_leaf_name(path), str(list(shape)),
                     str(dtype), nbytes, spec))
    return rows


def _shard_bytes(tree) -> float:
    """Per-device bytes of a pytree: each leaf's shard size under its
    sharding (global size when unsharded/abstract)."""
    import jax
    import numpy as np
    total = 0.0
    for leaf in jax.tree.leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        try:
            if sharding is not None:
                shape = sharding.shard_shape(shape)
        except Exception:
            pass
        total += float(np.prod(shape, dtype=np.float64) *
                       np.dtype(dtype).itemsize)
    return total


def static_budget(engine) -> Dict[str, float]:
    """The compile-free part of the HBM budget (bytes per device):
    params / optimizer state / loss-scale shard sizes, plus — when the
    chunked ZeRO-3 overlap path is armed — the transient footprint of
    in-flight gathered chunks (prefetch+1 chunks live at once; they are
    freed after use but the budget must cover the peak). Pure metadata —
    never syncs the device."""
    budget: Dict[str, float] = {}
    params = getattr(engine, "params", None)
    if params is not None:
        budget["params"] = _shard_bytes(params)
    opt_state = getattr(engine, "opt_state", None)
    if opt_state:
        budget["optimizer_state"] = _shard_bytes(opt_state)
    scaler = getattr(engine, "loss_scale_state", None)
    if scaler is not None:
        budget["loss_scale_state"] = _shard_bytes(scaler)
    plan = getattr(engine, "_overlap_plan", None)
    if plan is not None:
        try:
            budget["overlap_gathered_chunks"] = float(plan.transient_bytes())
        except Exception:
            pass
    return budget


def _abstract_train_args(engine, sample_batch=None):
    """Abstract argument tuple for the engine's fused step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    gas = int(engine.config.gradient_accumulation_steps)
    if sample_batch is None:
        micro = max(1, int(engine.config.train_batch_size) // gas)
        tps = int(getattr(engine.model, "tokens_per_sample", None) or 128)
        sample_batch = {"input_ids": jax.ShapeDtypeStruct(
            (micro, tps), np.int32)}
    else:
        sample_batch = abstractify(sample_batch)
    stacked = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((gas,) + tuple(s.shape), s.dtype),
        sample_batch)
    try:
        # shard the abstract batch the way _place_stacked_batch would —
        # an unsharded (replicated) batch lowers to a program with no
        # grad all-reduce and gas*dp times the per-device flops, which
        # would poison both sides of the roofline
        from deepspeed_tpu.parallel.mesh import ZERO_AXES
        sp = engine.mesh.shape.get("seq", 1) > 1

        def shard(s):
            entries = [None, ZERO_AXES] + [None] * (len(s.shape) - 2)
            if sp and len(s.shape) >= 3:
                entries[2] = "seq"
            return jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=jax.sharding.NamedSharding(
                    engine.mesh, jax.sharding.PartitionSpec(*entries)))
        stacked = jax.tree.map(shard, stacked)
    except Exception:
        pass
    return (abstractify(engine.params),
            abstractify(engine.opt_state),
            abstractify(engine.loss_scale_state),
            stacked,
            jax.ShapeDtypeStruct((), jnp.int32),
            abstractify(jax.random.PRNGKey(0)))


def explain_engine(engine, measured_step_ms: Optional[float] = None,
                   sample_batch=None, platform: Optional[str] = None,
                   peak_flops_override: Optional[float] = None,
                   hbm_bw_override: Optional[float] = None,
                   ici_bw_override: Optional[float] = None
                   ) -> ExplainReport:
    """Lower the engine's jitted train step abstractly and build the
    full explain report. Costs one XLA compile of the step program (the
    executable is dropped afterwards); nothing runs on the device.

    Engine modes without a lowerable fused step (host-offload optimizer,
    1-bit, ZeRO++ flat storage) degrade to the static budget with the
    step function marked unavailable."""
    import jax
    tcfg = getattr(engine.config, "telemetry", None)
    peaks = resolve_peaks(
        platform=platform,
        peak_flops_override=peak_flops_override or
        (getattr(tcfg, "peak_flops_override", None) if not platform else None),
        hbm_bw_override=hbm_bw_override or
        (getattr(tcfg, "peak_hbm_bw_override", None) if not platform
         else None),
        ici_bw_override=ici_bw_override)
    report = ExplainReport(kind="train", platform=peaks.kind,
                           n_devices=jax.device_count(), peaks=peaks,
                           measured_step_ms=measured_step_ms)
    report.budget.update(static_budget(engine))
    try:
        report.params = param_table(engine.params)
    except Exception:
        pass

    fused = getattr(engine, "_fused_step", None)
    if fused is None:
        report.functions.append(FunctionCost(
            name="train_step",
            error="no fused step in this engine mode (host-offload/1-bit "
                  "paths run partly on the host)"))
    else:
        try:
            args = _abstract_train_args(engine, sample_batch)
        except Exception as e:                       # noqa: BLE001
            args = None
            report.functions.append(FunctionCost(
                name="train_step", error=f"{type(e).__name__}: {e}"))
        if args is not None:
            fc = analyze_lowerable("train_step", fused, *args)
            report.functions.append(fc)
            if fc.error is None:
                report.budget["step_temporaries"] = fc.temp_bytes
                if fc.generated_code_bytes:
                    report.budget["generated_code"] = \
                        fc.generated_code_bytes
    step = next((f for f in report.functions if f.name == "train_step"),
                None)
    if step is not None and step.error is None:
        report.roofline = Roofline(
            flops=step.flops, bytes=step.bytes_accessed,
            comm_bytes=step.collective_bytes,
            peak_flops=peaks.peak_flops, hbm_bw=peaks.hbm_bw,
            ici_bw=peaks.ici_bw)
        if not step.available:
            report.warnings.append(
                "cost_analysis returned no numbers on this backend — "
                "FLOPs/bytes read 0; the byte budget above is still exact")
    if peaks.capacity and report.budget_total > peaks.capacity:
        report.warnings.append(
            f"predicted HBM footprint {_fmt_bytes(report.budget_total)} "
            f"EXCEEDS device capacity {_fmt_bytes(peaks.capacity)} — "
            f"expect OOM; shard further (zero stage / tensor parallel), "
            f"shrink the batch, or offload")
    last_report["train"] = report.to_dict()
    return report


def explain_serving(engine, mode=("argmax",),
                    platform: Optional[str] = None) -> Dict[str, Any]:
    """Cost records for the serving engine's prefill and decode bucket
    programs (lowered abstractly over the engine's real packed-input
    layout). Returns ``{"prefill": {...}, "decode": {...}}`` where each
    record carries the :class:`FunctionCost` fields plus
    ``predicted_s`` — the roofline step-time prediction the frontend's
    SLO admission consumes (0.0 when no peak numbers exist)."""
    import jax
    import numpy as np
    from deepspeed_tpu.inference.engine_v2 import _bucket
    cfg = engine.config
    peaks = resolve_peaks(platform=platform)
    nb = _bucket(int(cfg.max_sequences))
    mb = engine.mb
    records: Dict[str, Any] = {}
    aparams = abstractify(engine.params)
    aarena = abstractify(engine.arena)
    arng = abstractify(jax.random.PRNGKey(0))
    for label, cb, fresh in (("prefill", int(cfg.prefill_chunk), True),
                             ("decode", 1, False)):
        packed = jax.ShapeDtypeStruct(
            (nb * cb + nb + nb + nb * mb + 2,), np.int32)
        try:
            jitted = engine._step_fn(nb, cb, mode, fresh=fresh)
            fc = analyze_lowerable(f"serving_{label}", jitted,
                                   aparams, aarena, packed, arng)
        except Exception as e:                       # noqa: BLE001
            fc = FunctionCost(name=f"serving_{label}",
                              error=f"{type(e).__name__}: {e}")
        rl = Roofline(flops=fc.flops, bytes=fc.bytes_accessed,
                      comm_bytes=fc.collective_bytes,
                      peak_flops=peaks.peak_flops, hbm_bw=peaks.hbm_bw,
                      ici_bw=peaks.ici_bw)
        rec = fc.to_dict()
        rec.update(n_bucket=nb, chunk=cb,
                   predicted_s=rl.predicted_s, bound=rl.bound)
        records[label] = rec
    records["platform"] = peaks.kind
    last_report["serving"] = records
    _registry.gauge(
        "roofline/prefill_predicted_ms",
        help="roofline-predicted serving prefill step (0 = no model)").set(
        records["prefill"]["predicted_s"] * 1e3)
    _registry.gauge(
        "roofline/decode_predicted_ms",
        help="roofline-predicted serving decode step (0 = no model)").set(
        records["decode"]["predicted_s"] * 1e3)
    return records


def startup_budget(engine, log=None) -> Dict[str, float]:
    """The always-on, compile-free engine-init budget check: log the
    static HBM budget, publish the gauges, and warn LOUDLY when the
    static footprint alone exceeds device capacity."""
    from deepspeed_tpu.utils.logging import log_dist, logger
    budget = static_budget(engine)
    total = sum(budget.values())
    cap = hbm_capacity()
    reg = _registry
    reg.gauge("roofline/hbm_budget_bytes",
              help="predicted HBM footprint per device").set(total)
    reg.gauge("roofline/hbm_capacity_bytes",
              help="device HBM capacity (0 = unknown)").set(cap)
    parts = ", ".join(f"{k}={_fmt_bytes(v)}" for k, v in budget.items())
    (log or log_dist)(
        f"HBM budget: {parts}; total {_fmt_bytes(total)}"
        + (f" of {_fmt_bytes(cap)} capacity "
           f"({100.0 * total / cap:.1f}%)" if cap else ""))
    if cap and total > cap:
        logger.error(
            f"HBM BUDGET EXCEEDED: static footprint {_fmt_bytes(total)} "
            f"> device capacity {_fmt_bytes(cap)} — params + optimizer "
            f"state alone do not fit; expect OOM before the first step "
            f"(shard further, shrink the model, or offload)")
    return budget


# ---------------------------------------------------------------------------
# CLI — bin/dstpu-explain
# ---------------------------------------------------------------------------

def _build_engine(args):
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.llama import llama3_config
    if args.config:
        with open(args.config) as fh:
            config = json.load(fh)
    else:
        config = {}
    config.setdefault("train_micro_batch_size_per_gpu",
                      max(1, args.batch // len(jax.devices())))
    config.setdefault("steps_per_print", 1000)
    from deepspeed_tpu.parallel.mesh import has_mesh
    if not has_mesh():
        ds.build_mesh(data=len(jax.devices()))
    model = llama3_config(args.size, max_seq_len=args.seq,
                          tie_embeddings=True)
    engine, *_ = ds.initialize(model=model, config=config,
                               rng=jax.random.PRNGKey(0))
    return engine, model


def _measure_steps(engine, model, n: int) -> float:
    """Run ``n`` real steps and return the best step time in ms (min —
    the compile lands on step 1, warmed by an extra throwaway step)."""
    import time

    import jax
    import numpy as np
    gb = int(engine.config.train_batch_size)
    seq = int(model.max_seq_len)
    rng = np.random.default_rng(0)
    batch = jax.device_put({"input_ids": rng.integers(
        0, model.vocab_size, size=(gb, seq), dtype=np.int32)})
    float(engine.train_batch(iter([batch])))          # compile + warm
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        float(engine.train_batch(iter([batch])))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-explain",
        description="Compile-time explain: lower the engine's jitted "
                    "step, read back XLA cost/memory analysis, and print "
                    "the HBM budget + roofline report. Works on a "
                    "CPU-only host (lowering is abstract); --platform "
                    "models a target chip's peaks.")
    ap.add_argument("--config", default=None,
                    help="DeepSpeedTPUConfig JSON (default: minimal "
                         "config like examples/pretrain.py)")
    ap.add_argument("--size", default="tiny",
                    help="llama3 preset (tiny/350m/1b/8b)")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--serving", action="store_true",
                    help="also lower the serving prefill/decode bucket "
                         "programs (ragged engine over the same model "
                         "size)")
    ap.add_argument("--platform", default=None,
                    help="model a target chip's peaks from any host "
                         "(v2/v3/v4/v5e/v5p/v6e)")
    ap.add_argument("--peak-flops", type=float, default=None,
                    help="override peak FLOPs/s per chip")
    ap.add_argument("--hbm-bw", type=float, default=None,
                    help="override peak HBM bytes/s per chip")
    ap.add_argument("--ici-bw", type=float, default=None,
                    help="override peak interconnect bytes/s per chip")
    ap.add_argument("--measured-ms", type=float, default=None,
                    help="a measured step time (ms) to compare against "
                         "the prediction (%% of roofline)")
    ap.add_argument("--measure", type=int, default=0, metavar="N",
                    help="run N real steps and use the best as the "
                         "measured step time")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured report as JSON")
    args = ap.parse_args(argv)

    engine, model = _build_engine(args)
    measured = args.measured_ms
    if args.measure:
        measured = _measure_steps(engine, model, args.measure)
    report = explain_engine(engine, measured_step_ms=measured,
                            platform=args.platform,
                            peak_flops_override=args.peak_flops,
                            hbm_bw_override=args.hbm_bw,
                            ici_bw_override=args.ici_bw)
    publish_gauges(report)
    serving_records = None
    if args.serving:
        from deepspeed_tpu.inference.engine_v2 import \
            RaggedInferenceEngineTPU
        seq_cap = max(64, args.seq)
        eng = RaggedInferenceEngineTPU(
            model, {"dtype": "float32", "num_blocks": 64,
                    "block_size": 16, "max_seq_len": seq_cap,
                    "prefill_chunk": 32, "max_sequences": 4})
        serving_records = explain_serving(eng, platform=args.platform)
    if args.json:
        doc = report.to_dict()
        if serving_records is not None:
            doc["serving"] = serving_records
        print(json.dumps(doc, indent=1, default=repr))
    else:
        print(render(report))
        if serving_records is not None:
            print()
            print("serving cost records:")
            for label in ("prefill", "decode"):
                r = serving_records[label]
                if r.get("error"):
                    print(f"  {label:<10}unavailable ({r['error'][:60]})")
                else:
                    print(f"  {label:<10}nb={r['n_bucket']} "
                          f"chunk={r['chunk']} "
                          f"flops={_fmt_num(r['flops'])} "
                          f"bytes={_fmt_bytes(r['bytes_accessed'])} "
                          f"predicted={r['predicted_s'] * 1e3:.3f} ms "
                          f"({r['bound']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
