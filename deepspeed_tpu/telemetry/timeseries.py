"""Durable per-host metric history: an append-only JSONL ring with
size-bounded rotation, coarse downsampling, and a small query API.

Every telemetry surface before this one was a point-in-time snapshot
(``/metrics`` shows the current registry, the flight recorder keeps a
bounded ring, the doctor speaks after a crash). This module gives the
registry a time axis: every flush appends one JSON line per host::

    {"ts": 1722947191.2, "step": 120, "host": "tpu-vm-3",
     "m": {"train/mfu": 0.41, "train/steps": 120.0,
           "serving/ttft_seconds": {"count": 64, "mean": 0.021,
                                    "p50": 0.017, "p90": ..., "p95": ...,
                                    "p99": ..., "min": ..., "max": ...,
                                    "interval": {"count": 8, "p95": ...}}}}

(the ``m`` dict is exactly :meth:`MetricsRegistry.snapshot` — counters/
gauges as floats, histograms as summary dicts with interval deltas).

**Rotation.** When an append would push the file past ``max_bytes``, the
oldest half of the records is downsampled (every ``downsample``-th kept)
and the file is rewritten atomically. Recent history stays dense, old
history gets progressively coarser, and disk stays bounded — the JSONL
analogue of an RRD.

**Queries.** :meth:`MetricHistory.records` range-scans by time or step;
:meth:`series` extracts one metric (``"train/mfu"`` or a histogram field
like ``"serving/ttft_seconds:p95"``); :meth:`rate` computes a counter's
per-second increase over a trailing window; :func:`merge_records` +
:func:`windowed` aggregate across multiple host files (the fleet view
and ``dstpu-report --compare`` build on these).

**Subscribers.** :meth:`subscribe` hooks fire on every append — the SLO
burn-rate engine (:mod:`~deepspeed_tpu.telemetry.slo`) rides the same
flush, so objectives are evaluated exactly as often as history is
written, with no extra registry lock pass.

A ``path=None`` history is memory-only (bounded deque): the SLO engine
still works in processes that don't want a file on disk.
"""

import json
import os
import socket
import threading
import time
from collections import deque
from typing import (Any, Callable, Dict, Iterable, List, Optional, Tuple,
                    Union)

from deepspeed_tpu.utils.logging import logger

DEFAULT_MAX_BYTES = 8 * 1024 * 1024
DEFAULT_DOWNSAMPLE = 2
#: memory-only mode / in-memory tail capacity (records)
DEFAULT_MEM_RECORDS = 512

Record = Dict[str, Any]


def resolve_metric(record: Record, name: str,
                   prefer_interval: bool = False) -> Optional[float]:
    """Read one metric out of a history record.

    ``name`` is ``"area/metric"`` for counters/gauges, or
    ``"area/metric:field"`` for a histogram summary field (``p50``,
    ``p90``, ``p95``, ``p99``, ``mean``, ``count``, ``min``, ``max``;
    default ``mean``). With ``prefer_interval`` the histogram's
    ``interval`` sub-summary wins when it has samples — and a record
    whose interval is EMPTY yields ``None`` (no traffic means no
    judgment, not a stale all-time percentile). Returns ``None`` when
    the record doesn't carry the metric.
    """
    base, _, field = name.partition(":")
    v = record.get("m", {}).get(base)
    if v is None:
        return None
    if not isinstance(v, dict):
        return None if field else float(v)
    field = field or "mean"
    if prefer_interval and "interval" in v:
        iv = v["interval"]
        if not iv.get("count"):
            return None
        if field in iv:
            return float(iv[field])
    out = v.get(field)
    return float(out) if out is not None else None


def _parse_line(line: str) -> Optional[Record]:
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except ValueError:
        return None                     # torn tail from a killed writer
    return rec if isinstance(rec, dict) and "m" in rec else None


def load_records(path: str) -> List[Record]:
    """All records in one history file, oldest first; corrupt/torn lines
    are skipped (an append racing a kill must not poison the reader)."""
    out: List[Record] = []
    with open(path) as fh:
        for line in fh:
            rec = _parse_line(line)
            if rec is not None:
                out.append(rec)
    return out


def merge_records(paths: Iterable[str]) -> List[Record]:
    """Records from several per-host history files, merged time-ordered
    (each record carries its ``host``, so the fleet stays attributable)."""
    out: List[Record] = []
    for p in paths:
        out.extend(load_records(p))
    out.sort(key=lambda r: (r.get("ts", 0.0), r.get("step", 0)))
    return out


def windowed(records: List[Record], name: str, window_s: float,
             agg: str = "mean",
             prefer_interval: bool = False) -> List[Tuple[float, float]]:
    """Aggregate one metric over fixed time windows across (possibly
    multi-host) records: ``[(window_start_ts, value), ...]``. ``agg`` is
    ``mean`` | ``max`` | ``min`` | ``sum`` | ``last``."""
    if window_s <= 0:
        raise ValueError(f"window_s must be > 0, got {window_s}")
    fns: Dict[str, Callable[[List[float]], float]] = {
        "mean": lambda vs: sum(vs) / len(vs), "max": max, "min": min,
        "sum": sum, "last": lambda vs: vs[-1]}
    if agg not in fns:
        raise ValueError(f"agg must be one of {sorted(fns)}, got {agg!r}")
    buckets: Dict[float, List[float]] = {}
    for rec in records:
        v = resolve_metric(rec, name, prefer_interval=prefer_interval)
        if v is None:
            continue
        key = float(rec.get("ts", 0.0)) // window_s * window_s
        buckets.setdefault(key, []).append(v)
    return [(k, fns[agg](vs)) for k, vs in sorted(buckets.items())]


class MetricHistory:
    """Append-only per-host metric history (JSONL ring) + query API."""

    def __init__(self, path: Optional[str] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 downsample: int = DEFAULT_DOWNSAMPLE,
                 host: Optional[str] = None,
                 mem_records: int = DEFAULT_MEM_RECORDS,
                 clock=time.time):
        self.path = os.path.abspath(path) if path else None
        self.max_bytes = int(max_bytes)
        self.downsample = max(2, int(downsample))
        self.host = host or socket.gethostname()
        self._clock = clock
        self._lock = threading.Lock()
        self._subs: List[Callable[[Record], None]] = []
        self._tail: deque = deque(maxlen=max(1, mem_records))
        self.appended = 0
        self.rotations = 0
        self._size = 0
        if self.path:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            try:
                self._size = os.path.getsize(self.path)
            except OSError:
                self._size = 0

    # -- writing ------------------------------------------------------------

    def subscribe(self, fn: Callable[[Record], None]) -> None:
        """Call ``fn(record)`` after every append (SLO engine hook)."""
        self._subs.append(fn)

    def append(self, step: int, metrics: Dict[str, Any]) -> Record:
        """Append one flush record; rotates first when the file would
        outgrow ``max_bytes``. Subscriber exceptions are logged, never
        raised into the flush path."""
        rec: Record = {"ts": float(self._clock()), "step": int(step),
                       "host": self.host, "m": metrics}
        line = json.dumps(rec, separators=(",", ":"), default=float) + "\n"
        with self._lock:
            self._tail.append(rec)
            self.appended += 1
            if self.path:
                if self._size + len(line) > self.max_bytes:
                    self._rotate_locked()
                with open(self.path, "a") as fh:
                    fh.write(line)
                self._size += len(line)
        for fn in list(self._subs):
            try:
                fn(rec)
            except Exception as e:                   # noqa: BLE001
                logger.warning(f"metric-history subscriber failed: {e}")
        return rec

    def _rotate_locked(self) -> None:
        """Downsample the oldest half (keep every ``downsample``-th
        record) and atomically rewrite. Repeated rotations coarsen old
        history further while the recent half stays dense."""
        try:
            recs = load_records(self.path)
        except OSError:
            recs = []
        split = len(recs) // 2
        kept = recs[:split][::self.downsample] + recs[split:]
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            for r in kept:
                fh.write(json.dumps(r, separators=(",", ":"),
                                    default=float) + "\n")
        os.replace(tmp, self.path)
        self._size = os.path.getsize(self.path)
        self.rotations += 1

    # -- queries ------------------------------------------------------------

    def records(self, start_ts: Optional[float] = None,
                end_ts: Optional[float] = None,
                start_step: Optional[int] = None,
                end_step: Optional[int] = None) -> List[Record]:
        """Range scan (inclusive bounds), oldest first — from the file
        when backed by one, else the in-memory tail."""
        if self.path and os.path.exists(self.path):
            recs = load_records(self.path)
        else:
            with self._lock:
                recs = list(self._tail)
        out = []
        for r in recs:
            if start_ts is not None and r.get("ts", 0.0) < start_ts:
                continue
            if end_ts is not None and r.get("ts", 0.0) > end_ts:
                continue
            if start_step is not None and r.get("step", 0) < start_step:
                continue
            if end_step is not None and r.get("step", 0) > end_step:
                continue
            out.append(r)
        return out

    def series(self, name: str, prefer_interval: bool = False,
               **range_kw) -> List[Tuple[float, int, float]]:
        """``[(ts, step, value), ...]`` for one metric (see
        :func:`resolve_metric` for the ``name`` grammar)."""
        out = []
        for r in self.records(**range_kw):
            v = resolve_metric(r, name, prefer_interval=prefer_interval)
            if v is not None:
                out.append((float(r.get("ts", 0.0)),
                            int(r.get("step", 0)), v))
        return out

    def rate(self, name: str, window_s: float = 60.0,
             end_ts: Optional[float] = None) -> Optional[float]:
        """Per-second increase of a counter-style metric over the
        trailing ``window_s`` (``prometheus rate()`` semantics, minus
        extrapolation). ``None`` with fewer than two in-window points;
        a counter reset (decrease) restarts from the reset point."""
        pts = self.series(name, end_ts=end_ts)
        if end_ts is None and pts:
            end_ts = pts[-1][0]
        pts = [p for p in pts if p[0] >= (end_ts or 0.0) - window_s]
        if len(pts) < 2:
            return None
        lo = pts[0]
        for p in pts[1:]:
            if p[2] < lo[2]:
                lo = p                  # reset — measure from here
        hi = pts[-1]
        if hi[0] <= lo[0]:
            return None
        return (hi[2] - lo[2]) / (hi[0] - lo[0])

    def last(self) -> Optional[Record]:
        with self._lock:
            if self._tail:
                return self._tail[-1]
        if self.path and os.path.exists(self.path):
            recs = load_records(self.path)
            return recs[-1] if recs else None
        return None


Union  # noqa: B018  (re-exported typing name used by annotations above)
