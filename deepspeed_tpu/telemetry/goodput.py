"""Goodput/badput wall-clock attribution ledger (``dstpu-goodput``).

The one question a fleet owner asks that no other telemetry layer
answers: *of every wall-clock second we pay for, how many produced
tokens or gradient steps?* The raw signals already exist — spans in the
tracer ring, the roofline compute/comm split, the resilience ledger's
injection→recovery pairs — but none of them closes the accounting.
This module does, the way T3 argues exposed-communication time must be
**attributed**, not just measured, before anyone can optimize it.

The :class:`GoodputLedger` classifies every second of process lifetime
into exactly one category (``CATEGORIES``):

- ``goodput`` — productive compute: ``train/step`` spans, and
  ``serving/engine_step`` spans with a non-empty running batch;
- ``init`` — process start until the first productive/compile/ckpt work;
- ``compile`` — XLA compilation (``compile/*`` spans emitted by the
  compile monitor);
- ``ckpt`` — checkpoint save/restore (``checkpoint/*`` spans);
- ``fault_recovery`` — injection→recovery intervals from the resilience
  ledger (:func:`deepspeed_tpu.resilience.faults.recovery_intervals`);
- ``comm_exposed`` — the roofline's per-step comm time minus the share
  the ``overlap/fraction`` gauge says was hidden under compute, carved
  OUT of goodput (T3-style: exposed communication is not goodput even
  though it happens inside a train step);
- ``input_stall`` — gaps between train steps on a training host
  (dataloader / host-input wait);
- ``idle`` — serving pumps with an empty running set, and gaps on a
  serving host (no admitted work);
- ``other`` — the residual that forces the ledger to sum to 100%.

Attribution is an interval sweep over the tracer ring: each instant of
the update window is assigned to the highest-priority overlapping
interval, so the categories sum to elapsed wall clock *by construction*
— the conservation property the tier-1 suite asserts. The ledger runs
off the existing ring + registry flush cadence; it adds nothing to any
hot path.

On top rides **profile-on-regression**: when the windowed goodput
fraction drops below ``telemetry.goodput.capture_threshold`` (or an SLO
breach latches while captures are armed), the
:class:`CaptureController` starts ONE bounded ``jax.profiler`` capture,
guarded by a cooldown, and records the dump path in the flight-recorder
black box — the expensive profile exists exactly for the windows worth
explaining.

CLI (``bin/dstpu-goodput``)::

    dstpu-goodput trace.json          # offline attribution of a dump
    dstpu-goodput --selftest          # synthetic-trace conservation check
"""

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.telemetry.tracer import Tracer, tracer as _global_tracer

#: the complete attribution taxonomy, highest-priority badput first is
#: NOT implied by order — see ``_PRIORITY``. Every literal here must be
#: documented in docs/observability.md (tools/check_metric_names.py
#: lints this, mirroring the resilience fault catalog).
CATEGORIES = ("goodput", "init", "compile", "ckpt", "fault_recovery",
              "comm_exposed", "input_stall", "idle", "other")

#: sweep priority when intervals overlap: a named cause beats generic
#: productivity (a recovery or compile spanning a train step is badput)
_PRIORITY = {"fault_recovery": 0, "compile": 1, "ckpt": 2,
             "goodput": 3, "idle": 4}

#: fleet/doctor alarm line: a fraction below this names its dominant
#: badput in the dstpu-doctor verdict ladder
LOW_GOODPUT_FRACTION = 0.5


def _classify_span(ev: Dict[str, Any]) -> Optional[str]:
    """Span event → ledger category (None: not an attribution source)."""
    name = ev.get("name", "")
    if name == "train/step":
        return "goodput"
    if name == "serving/engine_step":
        args = ev.get("args") or {}
        batch = args.get("batch")
        return "goodput" if (batch or 0) > 0 else "idle"
    if name.startswith("compile/"):
        return "compile"
    if name.startswith("checkpoint/"):
        return "ckpt"
    return None


def attribute(events: Sequence[Dict[str, Any]], t0: float, t1: float,
              base: float = 0.0,
              recovery_intervals: Sequence[Tuple[float, float, str]] = (),
              ) -> Dict[str, Any]:
    """Sweep attribution of the window ``[t0, t1]`` (seconds).

    ``events`` are Chrome trace-event dicts whose ``ts``/``dur`` are in
    microseconds relative to ``base`` (a :class:`Tracer`'s ``_t0``;
    pass 0 for an offline dump whose timestamps are already absolute).
    ``recovery_intervals`` are absolute ``(start, end, kind)`` seconds
    on the same clock.

    Returns ``{"seconds": {category: s}, "train_steps": n,
    "kinds": {...}, "first_work": t|None}`` with the guarantee
    ``sum(seconds.values()) == t1 - t0`` (within float epsilon) before
    any ``comm_exposed`` carving — conservation by construction.
    """
    sec = {c: 0.0 for c in CATEGORIES}
    if t1 <= t0:
        return {"seconds": sec, "train_steps": 0, "kinds": {},
                "first_work": None}
    ivals: List[Tuple[float, float, int]] = []  # (start, end, rank)
    kinds: Dict[str, int] = {}
    train_steps = 0
    first_work: Optional[float] = None
    serving_seen = False
    train_seen = False
    for ev in events:
        if ev.get("ph") != "X":
            continue
        cat = _classify_span(ev)
        if cat is None:
            continue
        s = base + float(ev.get("ts", 0.0)) / 1e6
        e = s + float(ev.get("dur", 0.0)) / 1e6
        if ev.get("name") == "serving/engine_step":
            serving_seen = True
        elif ev.get("name") == "train/step":
            train_seen = True
        if cat in ("goodput", "compile", "ckpt"):
            first_work = s if first_work is None else min(first_work, s)
        if ev.get("name") == "train/step" and t0 < e <= t1:
            train_steps += 1
        if e <= t0 or s >= t1:
            continue
        ivals.append((max(s, t0), min(e, t1), _PRIORITY[cat]))
    for (s, e, kind) in recovery_intervals:
        if e <= t0 or s >= t1:
            continue
        ivals.append((max(s, t0), min(e, t1),
                      _PRIORITY["fault_recovery"]))
        kinds[kind] = kinds.get(kind, 0) + 1
    rank_to_cat = {v: k for k, v in _PRIORITY.items()}
    gap_cat = ("input_stall" if train_seen and not serving_seen
               else "idle" if serving_seen
               else "other")
    bounds = sorted({t0, t1, *(s for s, _, _ in ivals),
                     *(e for _, e, _ in ivals)})
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        active = [r for s, e, r in ivals if s <= mid < e]
        if active:
            cat = rank_to_cat[min(active)]
        elif first_work is None or mid < first_work:
            cat = "init"
        else:
            cat = gap_cat
        sec[cat] += b - a
    return {"seconds": sec, "train_steps": train_steps, "kinds": kinds,
            "first_work": first_work}


class CaptureController:
    """One-shot, cooldown-guarded, bounded ``jax.profiler`` capture.

    Armed only when ``capture_threshold`` > 0. A windowed goodput
    fraction below the threshold (or a latched SLO breach) starts ONE
    capture of ``capture_duration_ms``; the next capture cannot start
    until ``capture_cooldown_s`` after the previous one began. Start and
    stop callables are injectable so tests stub the profiler out.
    """

    def __init__(self,
                 start_fn: Optional[Callable[[str], None]] = None,
                 stop_fn: Optional[Callable[[], None]] = None):
        self.threshold = 0.0
        self.cooldown_s = 600.0
        self.duration_ms = 2000.0
        self.dir: Optional[str] = None
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._active_path: Optional[str] = None
        self._stop_at: Optional[float] = None
        self._last_start: Optional[float] = None
        self.captures = 0
        self.paths: List[str] = []

    def configure(self, threshold: Optional[float] = None,
                  cooldown_s: Optional[float] = None,
                  duration_ms: Optional[float] = None,
                  dir: Optional[str] = None) -> None:
        if threshold is not None:
            self.threshold = float(threshold)
        if cooldown_s is not None:
            self.cooldown_s = float(cooldown_s)
        if duration_ms is not None:
            self.duration_ms = float(duration_ms)
        if dir is not None:
            self.dir = dir

    def _start(self, path: str) -> None:
        if self._start_fn is not None:
            self._start_fn(path)
            return
        from jax import profiler as jprof
        jprof.start_trace(path)

    def _stop(self) -> None:
        if self._stop_fn is not None:
            self._stop_fn()
            return
        from jax import profiler as jprof
        jprof.stop_trace()

    def poll(self, now: float, window_fraction: Optional[float],
             breach: bool = False) -> Optional[str]:
        """Advance the capture state machine. Returns the dump path when
        a capture STARTS this poll, else None. Never raises — a broken
        profiler must not take the ledger down."""
        if self._active_path is not None and self._stop_at is not None \
                and now >= self._stop_at:
            try:
                self._stop()
            except Exception:                        # noqa: BLE001
                pass
            try:
                from deepspeed_tpu.telemetry.flight_recorder import \
                    flight_recorder
                flight_recorder.record_event("goodput_capture_done",
                                             path=self._active_path)
            except Exception:                        # noqa: BLE001
                pass
            self._active_path = self._stop_at = None
        if self.threshold <= 0 or self._active_path is not None:
            return None
        dip = (window_fraction is not None
               and window_fraction < self.threshold)
        if not dip and not breach:
            return None
        if self._last_start is not None and \
                now - self._last_start < self.cooldown_s:
            return None
        root = self.dir or os.path.join(os.getcwd(),
                                        "dstpu_goodput_captures")
        path = os.path.join(
            root, time.strftime("capture_%Y%m%d_%H%M%S")
            + f"_{self.captures}")
        reason = ("slo_breach" if breach and not dip else
                  f"goodput_window={window_fraction:.3f}"
                  f"<{self.threshold:.3f}")
        try:
            os.makedirs(path, exist_ok=True)
            self._start(path)
        except Exception:                            # noqa: BLE001
            return None
        self._active_path = path
        self._stop_at = now + self.duration_ms / 1e3
        self._last_start = now
        self.captures += 1
        self.paths.append(path)
        try:
            from deepspeed_tpu.telemetry.flight_recorder import \
                flight_recorder
            flight_recorder.record_event("goodput_capture", path=path,
                                         reason=reason)
        except Exception:                            # noqa: BLE001
            pass
        return path


class GoodputLedger:
    """Per-host wall-clock attribution over the tracer ring.

    ``update()`` attributes the window since the previous update (the
    first update anchors at the tracer's ``_t0`` — process lifetime on
    the tracer clock), folds the per-category seconds into the running
    totals, publishes ``goodput/*`` gauges, and polls the capture
    controller. Callers invoke it on the existing registry-flush
    cadence; ``maybe_update()`` additionally rate-limits for callers on
    tighter loops.
    """

    def __init__(self, tracer: Optional[Tracer] = None):
        self.enabled = False
        self.window_s = 60.0
        self._tracer = tracer
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._last: Optional[float] = None
        self.seconds: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self.recovery_kinds: Dict[str, int] = {}
        self._first_work: Optional[float] = None
        self._roofline_compute_s = 0.0
        self._roofline_comm_s = 0.0
        #: (ts, cumulative goodput_s) samples for the windowed fraction
        self._samples: deque = deque(maxlen=4096)
        self._min_interval_s = 1.0
        self.capture = CaptureController()

    # -- configuration ------------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  window_s: Optional[float] = None,
                  capture_threshold: Optional[float] = None,
                  capture_cooldown_s: Optional[float] = None,
                  capture_duration_ms: Optional[float] = None,
                  capture_dir: Optional[str] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if window_s is not None:
                self.window_s = float(window_s)
            self.capture.configure(threshold=capture_threshold,
                                   cooldown_s=capture_cooldown_s,
                                   duration_ms=capture_duration_ms,
                                   dir=capture_dir)

    def set_roofline(self, compute_s: float, comm_s: float) -> None:
        """Feed the modeled per-step compute/comm split (the engine's
        explain pass holds these privately — no gauge carries them)."""
        with self._lock:
            self._roofline_compute_s = float(compute_s or 0.0)
            self._roofline_comm_s = float(comm_s or 0.0)

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._last = self._first_work = None
            self.seconds = {c: 0.0 for c in CATEGORIES}
            self.recovery_kinds = {}
            self._samples.clear()

    # -- attribution --------------------------------------------------------

    @property
    def _tr(self) -> Tracer:
        return self._tracer if self._tracer is not None else _global_tracer

    def _exposed_comm_per_step(self) -> float:
        """T3-style exposed communication per train step: modeled comm
        time minus the share the achieved ``overlap/fraction`` gauge
        says was hidden under compute."""
        comm = self._roofline_comm_s
        if comm <= 0:
            return 0.0
        frac = 0.0
        try:
            from deepspeed_tpu.telemetry.registry import registry
            g = registry.get("overlap/fraction")
            if g is not None:
                frac = min(1.0, max(0.0, float(g.value)))
        except Exception:                            # noqa: BLE001
            pass
        return max(0.0, comm - frac * min(self._roofline_compute_s, comm))

    def maybe_update(self, now: Optional[float] = None
                     ) -> Optional[Dict[str, Any]]:
        """``update()`` rate-limited to one sweep per second — the hook
        for callers on per-pump loops."""
        if not self.enabled:
            return None
        now = self._tr.now() if now is None else now
        if self._last is not None and \
                now - self._last < self._min_interval_s:
            return None
        return self.update(now)

    def update(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Attribute the window since the last update; publish gauges;
        poll the capture controller. Returns :meth:`summary`."""
        if not self.enabled:
            return None
        tr = self._tr
        now = tr.now() if now is None else now
        with self._lock:
            if self._t0 is None:
                self._t0 = self._last = tr._t0
            if now <= self._last:
                return self._summary_locked()
            try:
                from deepspeed_tpu.resilience.faults import \
                    recovery_intervals
                rec = recovery_intervals()
            except Exception:                        # noqa: BLE001
                rec = []
            res = attribute(tr.events(), self._last, now, base=tr._t0,
                            recovery_intervals=rec)
            delta = res["seconds"]
            if res["first_work"] is not None:
                self._first_work = (res["first_work"]
                                    if self._first_work is None
                                    else min(self._first_work,
                                             res["first_work"]))
            # carve exposed communication OUT of goodput, capped so the
            # ledger keeps conserving wall clock
            exposed = min(delta["goodput"],
                          self._exposed_comm_per_step()
                          * res["train_steps"])
            delta["goodput"] -= exposed
            delta["comm_exposed"] += exposed
            for c in CATEGORIES:
                self.seconds[c] += delta[c]
            for k, n in res["kinds"].items():
                self.recovery_kinds[k] = self.recovery_kinds.get(k, 0) + n
            self._last = now
            self._samples.append((now, self.seconds["goodput"]))
            wf = self._window_fraction_locked(now)
            summary = self._summary_locked()
        self._publish(summary, wf)
        breach = False
        try:
            from deepspeed_tpu.telemetry.registry import registry
            g = registry.get("slo/breached")
            breach = g is not None and float(g.value) > 0
        except Exception:                            # noqa: BLE001
            pass
        self.capture.poll(now, wf, breach=breach)
        summary["window_fraction"] = wf
        return summary

    def _window_fraction_locked(self, now: float) -> Optional[float]:
        """Goodput share of the trailing ``window_s`` seconds."""
        if not self._samples:
            return None
        anchor = None
        for ts, g in self._samples:
            if ts <= now - self.window_s:
                anchor = (ts, g)
            else:
                break
        if anchor is None:
            anchor = self._samples[0]
            # the whole history is shorter than the window: fall back to
            # the lifetime fraction so early dips still read correctly
            if now - (self._t0 or now) > 0:
                return self.seconds["goodput"] / (now - self._t0)
            return None
        dt = now - anchor[0]
        if dt <= 0:
            return None
        return max(0.0, min(1.0, (self.seconds["goodput"] - anchor[1])
                            / dt))

    # -- export -------------------------------------------------------------

    def _summary_locked(self) -> Dict[str, Any]:
        uptime = max(0.0, (self._last or 0.0) - (self._t0 or 0.0))
        badput = {c: round(self.seconds[c], 6) for c in CATEGORIES
                  if c != "goodput"}
        dominant = max(badput, key=badput.get) if uptime > 0 else None
        if dominant is not None and badput[dominant] <= 0:
            dominant = None
        return {
            "uptime_s": round(uptime, 6),
            "goodput_s": round(self.seconds["goodput"], 6),
            "fraction": (round(self.seconds["goodput"] / uptime, 6)
                         if uptime > 0 else None),
            "badput": badput,
            "dominant_badput": dominant,
            "dominant_badput_s": (badput[dominant]
                                  if dominant is not None else 0.0),
            "recovery_kinds": dict(self.recovery_kinds),
            "captures": self.capture.captures,
            "capture_paths": list(self.capture.paths),
        }

    def summary(self) -> Dict[str, Any]:
        """Ledger state as a JSON-safe dict (bench ``extra.goodput``,
        flight-recorder ``goodput`` section, doctor ingestion)."""
        with self._lock:
            s = self._summary_locked()
        s["window_fraction"] = None
        with self._lock:
            if self._last is not None:
                s["window_fraction"] = self._window_fraction_locked(
                    self._last)
        return s

    def _publish(self, summary: Dict[str, Any],
                 window_fraction: Optional[float]) -> None:
        try:
            from deepspeed_tpu.telemetry.registry import registry
            registry.gauge(
                "goodput/uptime_s",
                help="wall-clock seconds attributed by the ledger"
            ).set(summary["uptime_s"])
            if summary["fraction"] is not None:
                registry.gauge(
                    "goodput/fraction",
                    help="lifetime goodput share of wall clock, 0-1"
                ).set(summary["fraction"])
            if window_fraction is not None:
                registry.gauge(
                    "goodput/window_fraction",
                    help="goodput share over the trailing window, 0-1"
                ).set(window_fraction)
            for cat in CATEGORIES:
                # variable name on purpose: '{cat}_s' is not a whole
                # placeholder segment, so the literal-name lint would
                # reject the f-string spelling (docs carry the catalog
                # row goodput/<category>_s instead)
                name = "goodput/%s_s" % cat
                registry.gauge(
                    name,
                    help="seconds attributed to this ledger category"
                ).set(round(self.seconds[cat], 6))
            registry.gauge(
                "goodput/captures",
                help="profile-on-regression captures started"
            ).set(float(self.capture.captures))
        except Exception:                            # noqa: BLE001
            pass


#: process-wide ledger (armed by ``telemetry.configure`` /
#: ``telemetry.goodput.enabled``; the engine and serving frontend call
#: ``update()`` on their registry-flush cadence)
goodput_ledger = GoodputLedger()


# ---------------------------------------------------------------------------
# CLI (bin/dstpu-goodput)
# ---------------------------------------------------------------------------

def format_ledger(summary: Dict[str, Any]) -> str:
    """Render a ledger summary as an aligned category table."""
    uptime = summary.get("uptime_s") or 0.0
    rows = [("goodput", summary.get("goodput_s") or 0.0)]
    rows += sorted((summary.get("badput") or {}).items(),
                   key=lambda kv: -kv[1])
    lines = [f"{'category':<16}{'seconds':>12}{'% of wall':>11}"]
    for cat, s in rows:
        pct = 100.0 * s / uptime if uptime > 0 else 0.0
        lines.append(f"{cat:<16}{s:>12.3f}{pct:>10.1f}%")
    lines.append(f"{'total':<16}{uptime:>12.3f}{100.0:>10.1f}%")
    dom = summary.get("dominant_badput")
    if dom:
        lines.append(f"dominant badput: {dom} "
                     f"({summary.get('dominant_badput_s', 0.0):.3f}s)")
    if summary.get("captures"):
        lines.append(f"profiler captures: {summary['captures']} "
                     f"({', '.join(summary.get('capture_paths') or [])})")
    return "\n".join(lines)


def selftest() -> int:
    """Synthetic-trace conservation check (the tier-1 smoke): build a
    known timeline, attribute it, and verify the categories sum to the
    wall clock and land where they should."""
    tr = Tracer(buffer_events=1024)
    tr.configure(enabled=True)
    t0 = tr._t0
    tr.complete("compile/train_step", t0 + 1.0, t0 + 3.0)
    for i in range(5):
        tr.complete("train/step", t0 + 3.0 + i, t0 + 3.8 + i, step=i)
    tr.complete("checkpoint/save", t0 + 8.0, t0 + 9.0)
    led = GoodputLedger(tracer=tr)
    led.configure(enabled=True)
    s = led.update(t0 + 10.0)
    total = s["goodput_s"] + sum(s["badput"].values())
    ok = (abs(total - s["uptime_s"]) < 1e-6
          and abs(s["goodput_s"] - 4.0) < 1e-6
          and abs(s["badput"]["compile"] - 2.0) < 1e-6
          and abs(s["badput"]["ckpt"] - 1.0) < 1e-6
          and abs(s["badput"]["init"] - 1.0) < 1e-6)
    print(format_ledger(s))
    print(f"selftest: conservation "
          f"{'OK' if ok else 'FAILED'} (sum={total:.6f}s, "
          f"uptime={s['uptime_s']:.6f}s)")
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    """``dstpu-goodput``: offline goodput attribution of a Chrome
    trace-event dump, or ``--selftest`` for the synthetic conservation
    check."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="dstpu-goodput",
        description="Goodput/badput wall-clock attribution: classify "
                    "every second of a trace into the ledger taxonomy "
                    "(see docs/observability.md 'Goodput ledger').")
    ap.add_argument("trace", nargs="?", default=None,
                    help="Chrome trace-event JSON (tracer.dump output)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the synthetic-trace conservation check")
    ap.add_argument("--json", action="store_true",
                    help="emit the attribution as JSON")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.trace:
        ap.error("give a trace file or --selftest")
    from deepspeed_tpu.telemetry.summarize import load_trace
    events = load_trace(args.trace)
    spans = [e for e in events if e.get("ph") == "X"
             and _classify_span(e) is not None]
    if not spans:
        print(f"{args.trace}: no attributable spans (train/step, "
              f"serving/engine_step, compile/*, checkpoint/*)",
              file=sys.stderr)
        return 1
    t0 = min(float(e["ts"]) for e in spans) / 1e6
    t1 = max(float(e["ts"]) + float(e.get("dur", 0.0))
             for e in spans) / 1e6
    res = attribute(events, t0, t1, base=0.0)
    sec = res["seconds"]
    summary = {
        "uptime_s": round(t1 - t0, 6),
        "goodput_s": round(sec["goodput"], 6),
        "fraction": (round(sec["goodput"] / (t1 - t0), 6)
                     if t1 > t0 else None),
        "badput": {c: round(sec[c], 6) for c in CATEGORIES
                   if c != "goodput"},
        "train_steps": res["train_steps"],
    }
    bp = summary["badput"]
    dom = max(bp, key=bp.get)
    summary["dominant_badput"] = dom if bp[dom] > 0 else None
    summary["dominant_badput_s"] = bp[dom]
    if args.json:
        print(json.dumps(summary))
    else:
        print(format_ledger(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
