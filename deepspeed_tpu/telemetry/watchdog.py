"""Hang/straggler watchdog: a background heartbeat thread armed around
each training / serving step.

A TPU-pod hang has no crash to post-mortem: one host stalls (deadlocked
collective, wedged host thread, runaway compile) and every other host
blocks inside the next collective, silently burning the reservation. The
watchdog turns that into evidence: the engine arms it before each
``train_batch`` (the serving frontend before each decode step) and
disarms on completion; a missed deadline dumps

- **all-thread stacks** via :mod:`faulthandler` (names the wedged frame),
- the **flight-recorder black box** (last completed step + timeline),
- a **registry snapshot** (Prometheus text),

then either logs an error and keeps waiting (``action="warn"``) or kills
the process (``action="kill"``, exit code 124) so the launcher's restart
policy can take over.

Each arm/disarm also stamps a small **heartbeat file** (host, pid, step,
phase) when one is configured — ``launcher/agent.py`` exports
``DSTPU_HEARTBEAT_FILE`` into the worker env, and ``dstpu-doctor`` reads
the per-host heartbeats to name the straggler host whose step counter
stopped advancing.
"""

import faulthandler
import json
import os
import socket
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger

WATCHDOG_EXIT_CODE = 124


class Watchdog:
    """Deadline monitor over a single daemon thread.

    ``arm(label, step)`` sets the deadline; ``disarm()`` clears it. The
    monitor thread only ever *waits* — a disabled/disarmed watchdog costs
    one condition-variable notify per step.
    """

    def __init__(self, timeout_s: float = 300.0, action: str = "warn",
                 dump_dir: Optional[str] = None,
                 heartbeat_file: Optional[str] = None,
                 on_fire=None):
        if action not in ("warn", "kill"):
            raise ValueError(f"watchdog action must be 'warn' or 'kill', "
                             f"got {action!r}")
        self.timeout_s = float(timeout_s)
        self.action = action
        self.dump_dir = dump_dir or os.getcwd()
        self.heartbeat_file = heartbeat_file
        self._on_fire = on_fire          # test hook, called inside _fire
        self._cond = threading.Condition()
        self._deadline: Optional[float] = None
        self._label = ""
        self._step: Optional[int] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.fired = 0                   # total deadline misses

    # -- lifecycle ----------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="dstpu-watchdog", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()

    # -- arming -------------------------------------------------------------

    def arm(self, label: str, step: Optional[int] = None,
            timeout_s: Optional[float] = None) -> None:
        self._ensure_thread()
        with self._cond:
            self._label = label
            self._step = step
            self._deadline = time.monotonic() + \
                (timeout_s if timeout_s is not None else self.timeout_s)
            self._cond.notify_all()
        self._write_heartbeat("armed")

    def disarm(self) -> None:
        with self._cond:
            self._deadline = None
            self._cond.notify_all()
        self._write_heartbeat("idle")

    @contextmanager
    def guard(self, label: str, step: Optional[int] = None,
              timeout_s: Optional[float] = None):
        self.arm(label, step=step, timeout_s=timeout_s)
        try:
            yield
        finally:
            self.disarm()

    # -- heartbeat ----------------------------------------------------------

    def _write_heartbeat(self, phase: str) -> None:
        """Atomic heartbeat stamp for cross-host straggler attribution
        (best effort — a full disk must not take the step down)."""
        if not self.heartbeat_file:
            return
        try:
            doc = {"hostname": socket.gethostname(), "pid": os.getpid(),
                   "step": self._step, "label": self._label,
                   "phase": phase, "ts": time.time()}
            tmp = f"{self.heartbeat_file}.tmp.{os.getpid()}"
            os.makedirs(os.path.dirname(os.path.abspath(
                self.heartbeat_file)), exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self.heartbeat_file)
        except Exception:
            pass

    # -- the monitor loop ---------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                if self._deadline is None:
                    self._cond.wait()
                    continue
                remaining = self._deadline - time.monotonic()
                if remaining > 0:
                    self._cond.wait(timeout=remaining)
                    continue
                # deadline missed while still armed
                label, step = self._label, self._step
                self._deadline = None    # one dump per miss; re-armed next step
            self._fire(label, step)

    def _fire(self, label: str, step: Optional[int]) -> None:
        self.fired += 1
        pid = os.getpid()
        os.makedirs(self.dump_dir, exist_ok=True)
        stacks_path = os.path.join(self.dump_dir,
                                   f"watchdog_stacks_{pid}.txt")
        paths: Dict[str, Any] = {"stacks": stacks_path}
        try:
            with open(stacks_path, "w") as fh:
                fh.write(f"deepspeed_tpu watchdog: step {step!r} "
                         f"({label}) exceeded {self.timeout_s:.1f}s\n\n")
                faulthandler.dump_traceback(file=fh, all_threads=True)
        except Exception:
            paths["stacks"] = None
        try:
            from deepspeed_tpu.telemetry.flight_recorder import \
                flight_recorder
            flight_recorder.record_event(
                "watchdog", label=label, step=step,
                timeout_s=self.timeout_s, action=self.action)
            paths["blackbox"] = flight_recorder.dump(
                os.path.join(self.dump_dir, f"blackbox_watchdog_{pid}.json"),
                reason=f"watchdog:{label}")
        except Exception:
            paths["blackbox"] = None
        try:
            from deepspeed_tpu.telemetry.registry import registry
            metrics_path = os.path.join(self.dump_dir,
                                        f"watchdog_metrics_{pid}.prom")
            with open(metrics_path, "w") as fh:
                fh.write(registry.prometheus_text())
            paths["metrics"] = metrics_path
        except Exception:
            paths["metrics"] = None
        self._write_heartbeat("stalled")
        logger.error(
            f"WATCHDOG: step {step!r} ({label}) missed its "
            f"{self.timeout_s:.1f}s deadline — thread stacks at "
            f"{paths['stacks']}, black box at {paths['blackbox']}, "
            f"metrics at {paths['metrics']}; action={self.action}")
        if self._on_fire is not None:
            try:
                self._on_fire(label, step, paths)
            except Exception:
                pass
        if self.action == "kill":
            # stderr/files are already flushed; a hung step cannot be
            # unwound by an exception (the host thread is blocked inside
            # a collective/compile), so hard-exit and let the launcher's
            # restart policy take over
            os._exit(WATCHDOG_EXIT_CODE)
