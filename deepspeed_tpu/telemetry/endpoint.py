"""Live scrape endpoint: ``GET /metrics`` + ``GET /healthz``.

A stdlib ``ThreadingHTTPServer`` on a daemon thread — no new
dependencies, nothing on the training hot path (the registry snapshot is
taken under its own lock per scrape). Enabled by the
``telemetry.http_port`` config key (engine + serving frontend both wire
it); port 0 binds an ephemeral port (tests read ``server.port``).

``/metrics``  → 200, Prometheus text exposition of the process-wide
registry (``telemetry.metrics_text()``), so Prometheus/Grafana scrape
the same numbers the flight recorder snapshots.

``/healthz``  → liveness for load balancers / k8s probes. With a
watchdog heartbeat file configured (PR 4 writes one atomically per
step), stale-or-stalled heartbeats return 503 so a hung-but-alive
process is taken out of rotation; without one, reaching the server at
all is the liveness signal (200).
"""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from deepspeed_tpu.utils.logging import logger

#: heartbeats older than this are stale → /healthz 503
DEFAULT_FRESH_S = 120.0


class MetricsServer:
    """Daemon-thread HTTP server exposing /metrics and /healthz."""

    def __init__(self, port: int, host: str = "0.0.0.0",
                 heartbeat_file: Optional[str] = None,
                 fresh_s: float = DEFAULT_FRESH_S,
                 clock=time.time):
        self.heartbeat_file = heartbeat_file or \
            os.environ.get("DSTPU_HEARTBEAT_FILE")
        self.fresh_s = float(fresh_s)
        self._clock = clock
        #: degraded reasons keyed by source — the serving failure domain
        #: (while requeued requests drain) and the SLO burn-rate engine
        #: flip this independently: /healthz answers 503 while ANY source
        #: holds it, so a balancer stops routing NEW traffic to a replica
        #: that is still recovering or blowing its error budget
        self._degraded: Dict[str, str] = {}
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):     # scrapes stay quiet
                pass

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/metrics":
                    code, ctype, body = server._metrics()
                elif path == "/healthz":
                    code, ctype, body = server._healthz()
                else:
                    code, ctype, body = 404, "text/plain", "not found\n"
                payload = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dstpu-metrics-http",
            daemon=True)
        self._thread.start()
        logger.info(f"metrics endpoint on :{self.port} "
                    f"(/metrics, /healthz"
                    + (f", heartbeat={self.heartbeat_file}"
                       if self.heartbeat_file else "") + ")")

    def _metrics(self):
        try:
            from deepspeed_tpu.telemetry import metrics_text
            body = metrics_text()
            # histogram buckets may carry OpenMetrics exemplar suffixes
            # (`# {trace_id="..."} value`, from request tracing) —
            # advertise the OpenMetrics content type when they do, so
            # exemplar-aware scrapers ingest them; plain Prometheus
            # parsers read the same body either way (dstpu-top's parser
            # strips the suffix)
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8" if " # {" in body
                     else "text/plain; version=0.0.4")
            return 200, ctype, body
        except Exception as e:                       # noqa: BLE001
            return 500, "text/plain", f"metrics error: {e}\n"

    def set_degraded(self, degraded: bool, reason: Optional[str] = None,
                     source: str = "serving") -> None:
        """Flip /healthz into (or out of) degraded 503 for one
        ``source`` (e.g. ``"serving"`` while engine-fault retries drain,
        ``"slo"`` while an objective burns) — the process is alive (no
        restart wanted) but should be out of rotation. Clearing one
        source leaves the others' degradation standing."""
        if degraded:
            self._degraded[source] = reason or "degraded"
        else:
            self._degraded.pop(source, None)

    def _healthz(self):
        """200 when healthy; 503 when degraded, the heartbeat is stale,
        or the watchdog marked the process stalled."""
        if self._degraded:
            return 503, "application/json", json.dumps(
                {"status": "degraded",
                 "reason": "; ".join(self._degraded[k]
                                     for k in sorted(self._degraded))}
            ) + "\n"
        if not self.heartbeat_file:
            return 200, "application/json", '{"status": "ok"}\n'
        try:
            with open(self.heartbeat_file) as fh:
                hb = json.load(fh)
        except Exception as e:                       # noqa: BLE001
            return 503, "application/json", json.dumps(
                {"status": "no_heartbeat", "error": str(e)}) + "\n"
        age = self._clock() - float(hb.get("ts", 0.0))
        doc = {"status": "ok", "age_s": round(age, 3),
               "step": hb.get("step"), "phase": hb.get("phase")}
        if hb.get("phase") == "stalled":
            doc["status"] = "stalled"
            return 503, "application/json", json.dumps(doc) + "\n"
        if age > self.fresh_s:
            doc["status"] = "stale"
            return 503, "application/json", json.dumps(doc) + "\n"
        return 200, "application/json", json.dumps(doc) + "\n"

    def close(self) -> None:
        """Stop serving and release the port (idempotent)."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:                            # noqa: BLE001
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
