"""Step-stream anomaly detection: non-finite values, loss spikes,
grad-norm outliers, step-time regressions.

The engine feeds host-side step statistics in here (at monitor-flush
cadence, so no extra device syncs); each flagged anomaly becomes a tracer
instant, a registry counter bump, and a flight-recorder event, which is
how ``dstpu-doctor`` reconstructs the anomaly timeline after a run dies.

Detectors are deliberately simple and stateless-ish (rolling windows, no
learned baselines): the goal is "the run went sideways at step 4312, the
first bad leaf was ``params['decoder']['layers_7']['mlp']['wi']``", not a
forecasting system.
"""

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

#: rolling-window length for spike/z-score baselines
DEFAULT_WINDOW = 64
#: |z| above which a grad-norm sample is flagged
GRAD_NORM_Z_THRESHOLD = 6.0
#: loss must exceed window mean by this factor (and 3 sigma) to flag
LOSS_SPIKE_FACTOR = 2.0
#: step time above this multiple of the rolling median flags a regression
STEP_TIME_REGRESSION_FACTOR = 2.5
#: warm-up samples before spike/z-score/regression detectors arm
MIN_SAMPLES = 8


def first_flagged_path(flags: Any) -> Optional[str]:
    """Name the first truthy leaf of a pytree of per-leaf flags (the
    output of ``loss_scaler.global_check``) — e.g.
    ``['decoder']['layers_7']['mlp']['wi']``. Returns None when clean."""
    try:
        from jax import tree_util
        leaves = tree_util.tree_flatten_with_path(flags)[0]
        for path, leaf in leaves:
            try:
                if bool(leaf):
                    return tree_util.keystr(path)
            except Exception:
                import numpy as np
                if bool(np.any(np.asarray(leaf))):
                    return tree_util.keystr(path)
    except Exception:
        pass
    return None


class AnomalyDetector:
    """Rolling-window detector over the per-step (loss, grad_norm,
    step_time) stream. Thread-safe; all sinks best-effort."""

    def __init__(self, window: int = DEFAULT_WINDOW):
        self._lock = threading.Lock()
        self._loss: deque = deque(maxlen=window)
        self._grad_norm: deque = deque(maxlen=window)
        self._step_time: deque = deque(maxlen=window)
        self.anomalies: List[Dict[str, Any]] = []
        self._max_anomalies = 256

    # -- core ----------------------------------------------------------------

    def _flag(self, kind: str, step: Optional[int], value: Any = None,
              detail: str = "") -> Dict[str, Any]:
        rec = {"kind": kind, "step": step, "ts": time.time()}
        if value is not None:
            rec["value"] = value if isinstance(value, (int, float, str)) \
                else repr(value)
        if detail:
            rec["detail"] = detail
        with self._lock:
            self.anomalies.append(rec)
            del self.anomalies[:-self._max_anomalies]
        logger.warning(f"ANOMALY[{kind}] step={step} value={value} {detail}")
        try:
            from deepspeed_tpu.telemetry.registry import registry
            registry.counter("anomaly/count").inc()
            registry.counter(f"anomaly/{kind}").inc()
        except Exception:
            pass
        try:
            from deepspeed_tpu.telemetry.tracer import tracer
            tracer.instant(f"anomaly/{kind}", step=step, detail=detail)
        except Exception:
            pass
        try:
            from deepspeed_tpu.telemetry.flight_recorder import \
                flight_recorder
            flight_recorder.record_event("anomaly", anomaly=kind, step=step,
                                         value=rec.get("value"),
                                         detail=detail or None)
        except Exception:
            pass
        return rec

    @staticmethod
    def _stats(window) -> Optional[Dict[str, float]]:
        vals = [v for v in window if math.isfinite(v)]
        if len(vals) < MIN_SAMPLES:
            return None
        mean = sum(vals) / len(vals)
        var = sum((v - mean) ** 2 for v in vals) / len(vals)
        med = sorted(vals)[len(vals) // 2]
        return {"mean": mean, "std": math.sqrt(var), "median": med}

    # -- ingestion ------------------------------------------------------------

    def observe(self, step: int, loss: Optional[float] = None,
                grad_norm: Optional[float] = None,
                step_time_ms: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one step's host-side scalars; returns anomalies flagged by
        this call. Baselines update *after* the checks, so a spike doesn't
        instantly poison its own baseline."""
        out: List[Dict[str, Any]] = []
        if loss is not None:
            loss = float(loss)
            if not math.isfinite(loss):
                out.append(self._flag("nonfinite_loss", step, loss))
            else:
                s = self._stats(self._loss)
                if s and loss > s["mean"] * LOSS_SPIKE_FACTOR and \
                        loss > s["mean"] + 3.0 * s["std"]:
                    out.append(self._flag(
                        "loss_spike", step, loss,
                        f"window mean {s['mean']:.4g}"))
            self._loss.append(loss)
        if grad_norm is not None:
            grad_norm = float(grad_norm)
            if not math.isfinite(grad_norm):
                out.append(self._flag("nonfinite_grad", step, grad_norm))
            else:
                s = self._stats(self._grad_norm)
                if s and s["std"] > 0 and \
                        abs(grad_norm - s["mean"]) / s["std"] > \
                        GRAD_NORM_Z_THRESHOLD:
                    z = (grad_norm - s["mean"]) / s["std"]
                    out.append(self._flag(
                        "grad_norm_outlier", step, grad_norm, f"z={z:.1f}"))
            self._grad_norm.append(grad_norm)
        if step_time_ms is not None:
            step_time_ms = float(step_time_ms)
            s = self._stats(self._step_time)
            if s and s["median"] > 0 and \
                    step_time_ms > s["median"] * STEP_TIME_REGRESSION_FACTOR:
                out.append(self._flag(
                    "step_time_regression", step, step_time_ms,
                    f"rolling median {s['median']:.1f}ms"))
            self._step_time.append(step_time_ms)
        return out

    def report_nonfinite(self, step: int, leaf_path: Optional[str],
                         what: str = "grads") -> Dict[str, Any]:
        """Record a non-finite pytree hit from the engine's scoped check,
        naming the first offending leaf."""
        detail = f"first non-finite leaf in {what}: {leaf_path}" \
            if leaf_path else f"non-finite values in {what}"
        return self._flag(f"nonfinite_{what}", step, detail=detail)

    # -- export ---------------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            counts: Dict[str, int] = {}
            for a in self.anomalies:
                counts[a["kind"]] = counts.get(a["kind"], 0) + 1
            return {"total": len(self.anomalies), "by_kind": counts,
                    "anomalies": list(self.anomalies)}

    def clear(self) -> None:
        with self._lock:
            del self.anomalies[:]
            self._loss.clear()
            self._grad_norm.clear()
            self._step_time.clear()


#: process-wide anomaly detector
anomaly_detector = AnomalyDetector()
