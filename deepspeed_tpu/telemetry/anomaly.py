"""Step-stream anomaly detection: non-finite values, loss spikes,
grad-norm outliers, step-time regressions.

The engine feeds host-side step statistics in here (at monitor-flush
cadence, so no extra device syncs); each flagged anomaly becomes a tracer
instant, a registry counter bump, and a flight-recorder event, which is
how ``dstpu-doctor`` reconstructs the anomaly timeline after a run dies.

Detectors are deliberately simple and stateless-ish (rolling windows, no
learned baselines): the goal is "the run went sideways at step 4312, the
first bad leaf was ``params['decoder']['layers_7']['mlp']['wi']``", not a
forecasting system.
"""

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

#: rolling-window length for spike/z-score baselines
DEFAULT_WINDOW = 64
#: |z| above which a grad-norm sample is flagged
GRAD_NORM_Z_THRESHOLD = 6.0
#: loss must exceed window mean by this factor (and 3 sigma) to flag
LOSS_SPIKE_FACTOR = 2.0
#: step time above this multiple of the rolling median flags a regression
STEP_TIME_REGRESSION_FACTOR = 2.5
#: warm-up samples before spike/z-score/regression detectors arm
MIN_SAMPLES = 8
#: relative epsilon floor on the window std: a (near-)constant window
#: otherwise makes the z-score degenerate — float jitter over a ~0 std
#: flags noise as an anomaly (div-by-~0)
STD_EPS_REL = 1e-6
#: |z| of one layer's stat against ITS OWN rolling window above which
#: the localizer flags ``anomaly/layer_divergence``
LAYER_Z_THRESHOLD = 6.0
#: an expert whose windowed mean load sits below this fraction of the
#: uniform share (1/E) counts as dead → ``anomaly/expert_collapse``
DEAD_EXPERT_FRACTION = 0.1
#: health-cadence samples before the expert-collapse detector arms
EXPERT_MIN_SAMPLES = 4


def first_flagged_path(flags: Any) -> Optional[str]:
    """Name the first truthy leaf of a pytree of per-leaf flags (the
    output of ``loss_scaler.global_check``) — e.g.
    ``['decoder']['layers_7']['mlp']['wi']``. Returns None when clean."""
    try:
        from jax import tree_util
        leaves = tree_util.tree_flatten_with_path(flags)[0]
        for path, leaf in leaves:
            try:
                if bool(leaf):
                    return tree_util.keystr(path)
            except Exception:
                import numpy as np
                if bool(np.any(np.asarray(leaf))):
                    return tree_util.keystr(path)
    except Exception:
        pass
    return None


class AnomalyDetector:
    """Rolling-window detector over the per-step (loss, grad_norm,
    step_time) stream. Thread-safe; all sinks best-effort."""

    def __init__(self, window: int = DEFAULT_WINDOW):
        self._lock = threading.Lock()
        self._loss: deque = deque(maxlen=window)
        self._grad_norm: deque = deque(maxlen=window)
        self._step_time: deque = deque(maxlen=window)
        self.anomalies: List[Dict[str, Any]] = []
        self._max_anomalies = 256
        # per-layer/per-expert localizer state (telemetry/health.py
        # feeds these at the health cadence): one rolling window per
        # (stat, layer) and per expert
        self._layer_windows: Dict[str, List[deque]] = {}
        self._expert_load: List[deque] = []
        #: worst per-layer z seen on the LAST observe_layers call (set
        #: even below threshold — the health/worst_layer* gauges)
        self.last_layer_score: Optional[Dict[str, Any]] = None
        #: most recent flags, for latching into gauges / dstpu-top
        self.last_layer_divergence: Optional[Dict[str, Any]] = None
        self.last_expert_collapse: Optional[Dict[str, Any]] = None

    # -- core ----------------------------------------------------------------

    def _flag(self, kind: str, step: Optional[int], value: Any = None,
              detail: str = "", **extra: Any) -> Dict[str, Any]:
        rec = {"kind": kind, "step": step, "ts": time.time()}
        if value is not None:
            rec["value"] = value if isinstance(value, (int, float, str)) \
                else repr(value)
        if detail:
            rec["detail"] = detail
        if extra:   # localizer coordinates (layer=/z= or expert=/load=)
            rec.update(extra)
        with self._lock:
            self.anomalies.append(rec)
            del self.anomalies[:-self._max_anomalies]
        logger.warning(f"ANOMALY[{kind}] step={step} value={value} {detail}")
        try:
            from deepspeed_tpu.telemetry.registry import registry
            registry.counter("anomaly/count").inc()
            registry.counter(f"anomaly/{kind}").inc()
        except Exception:
            pass
        try:
            from deepspeed_tpu.telemetry.tracer import tracer
            tracer.instant(f"anomaly/{kind}", step=step, detail=detail)
        except Exception:
            pass
        try:
            from deepspeed_tpu.telemetry.flight_recorder import \
                flight_recorder
            flight_recorder.record_event("anomaly", anomaly=kind, step=step,
                                         value=rec.get("value"),
                                         detail=detail or None, **extra)
        except Exception:
            pass
        return rec

    @staticmethod
    def _stats(window) -> Optional[Dict[str, float]]:
        vals = [v for v in window if math.isfinite(v)]
        if len(vals) < MIN_SAMPLES:
            return None
        mean = sum(vals) / len(vals)
        var = sum((v - mean) ** 2 for v in vals) / len(vals)
        med = sorted(vals)[len(vals) // 2]
        # epsilon floor (relative to the window's own scale): a
        # constant window otherwise yields std≈0 and the z-score
        # divides float jitter by ~0 — see STD_EPS_REL
        std = max(math.sqrt(var), STD_EPS_REL * max(abs(mean), 1.0))
        return {"mean": mean, "std": std, "median": med}

    # -- ingestion ------------------------------------------------------------

    def observe(self, step: int, loss: Optional[float] = None,
                grad_norm: Optional[float] = None,
                step_time_ms: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one step's host-side scalars; returns anomalies flagged by
        this call. Baselines update *after* the checks, so a spike doesn't
        instantly poison its own baseline."""
        out: List[Dict[str, Any]] = []
        if loss is not None:
            loss = float(loss)
            if not math.isfinite(loss):
                out.append(self._flag("nonfinite_loss", step, loss))
            else:
                s = self._stats(self._loss)
                if s and loss > s["mean"] * LOSS_SPIKE_FACTOR and \
                        loss > s["mean"] + 3.0 * s["std"]:
                    out.append(self._flag(
                        "loss_spike", step, loss,
                        f"window mean {s['mean']:.4g}"))
            self._loss.append(loss)
        if grad_norm is not None:
            grad_norm = float(grad_norm)
            if not math.isfinite(grad_norm):
                out.append(self._flag("nonfinite_grad", step, grad_norm))
            else:
                s = self._stats(self._grad_norm)
                if s and s["std"] > 0 and \
                        abs(grad_norm - s["mean"]) / s["std"] > \
                        GRAD_NORM_Z_THRESHOLD:
                    z = (grad_norm - s["mean"]) / s["std"]
                    out.append(self._flag(
                        "grad_norm_outlier", step, grad_norm, f"z={z:.1f}"))
            self._grad_norm.append(grad_norm)
        if step_time_ms is not None:
            step_time_ms = float(step_time_ms)
            s = self._stats(self._step_time)
            if s and s["median"] > 0 and \
                    step_time_ms > s["median"] * STEP_TIME_REGRESSION_FACTOR:
                out.append(self._flag(
                    "step_time_regression", step, step_time_ms,
                    f"rolling median {s['median']:.1f}ms"))
            self._step_time.append(step_time_ms)
        return out

    def observe_layers(self, step: int,
                       grad_norms: Optional[Any] = None,
                       act_rms: Optional[Any] = None,
                       act_absmax: Optional[Any] = None,
                       z_threshold: Optional[float] = None
                       ) -> List[Dict[str, Any]]:
        """Per-layer z-score localization over the health-cadence stat
        vectors (telemetry/health.py): each layer is scored against ITS
        OWN rolling window, so a layer whose grad norm jumps 6σ off its
        own history flags ``layer_divergence`` naming the layer — even
        while the global grad norm stays unremarkable. Baselines update
        after the checks (a divergence doesn't instantly poison its own
        window); ``last_layer_score`` always records the worst |z| seen
        by this call, threshold or not, for the worst-layer gauges."""
        out: List[Dict[str, Any]] = []
        zt = LAYER_Z_THRESHOLD if z_threshold is None else float(z_threshold)
        worst = None
        for stat, series in (("grad_norm", grad_norms),
                             ("act_rms", act_rms),
                             ("act_absmax", act_absmax)):
            if series is None:
                continue
            wins = self._layer_windows.setdefault(stat, [])
            while len(wins) < len(series):
                wins.append(deque(maxlen=DEFAULT_WINDOW))
            for i, v in enumerate(series):
                v = float(v)
                win = wins[i]
                if math.isfinite(v):   # nonfinite is the global check's job
                    s = self._stats(win)
                    if s:
                        z = (v - s["mean"]) / s["std"]
                        if worst is None or abs(z) > abs(worst["z"]):
                            worst = {"layer": i, "stat": stat,
                                     "z": z, "value": v, "step": step}
                        if abs(z) > zt:
                            out.append(self._flag(
                                "layer_divergence", step, v,
                                f"layer {i} {stat} z={z:.1f} "
                                f"(window mean {s['mean']:.4g})",
                                layer=i, stat=stat, z=round(z, 2)))
                win.append(v)
        if worst is not None:
            self.last_layer_score = worst
        if out:
            self.last_layer_divergence = out[-1]
        return out

    def observe_experts(self, step: int, load: Any,
                        dead_fraction: Optional[float] = None
                        ) -> List[Dict[str, Any]]:
        """Expert-collapse localization over the per-expert load
        fractions: an expert whose WINDOWED MEAN load sits below
        ``dead_fraction`` of the uniform share 1/E — persistently, not a
        one-cadence dip — flags ``expert_collapse`` naming the expert."""
        out: List[Dict[str, Any]] = []
        e = len(load)
        if not e:
            return out
        df = DEAD_EXPERT_FRACTION if dead_fraction is None \
            else float(dead_fraction)
        thr = df / e
        while len(self._expert_load) < e:
            self._expert_load.append(deque(maxlen=DEFAULT_WINDOW))
        for i, v in enumerate(load):
            v = float(v)
            win = self._expert_load[i]
            win.append(v)
            if len(win) < EXPERT_MIN_SAMPLES:
                continue
            m = sum(win) / len(win)
            if m < thr:
                out.append(self._flag(
                    "expert_collapse", step, m,
                    f"expert {i} windowed load {m:.4f} < {thr:.4f} "
                    f"({df:.0%} of uniform 1/{e})",
                    expert=i, load=round(m, 6)))
        if out:
            self.last_expert_collapse = out[-1]
        return out

    def report_nonfinite(self, step: int, leaf_path: Optional[str],
                         what: str = "grads") -> Dict[str, Any]:
        """Record a non-finite pytree hit from the engine's scoped check,
        naming the first offending leaf."""
        detail = f"first non-finite leaf in {what}: {leaf_path}" \
            if leaf_path else f"non-finite values in {what}"
        return self._flag(f"nonfinite_{what}", step, detail=detail)

    # -- export ---------------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            counts: Dict[str, int] = {}
            for a in self.anomalies:
                counts[a["kind"]] = counts.get(a["kind"], 0) + 1
            return {"total": len(self.anomalies), "by_kind": counts,
                    "anomalies": list(self.anomalies)}

    def clear(self) -> None:
        with self._lock:
            del self.anomalies[:]
            self._loss.clear()
            self._grad_norm.clear()
            self._step_time.clear()
            self._layer_windows.clear()
            del self._expert_load[:]
            self.last_layer_score = None
            self.last_layer_divergence = None
            self.last_expert_collapse = None


#: process-wide anomaly detector
anomaly_detector = AnomalyDetector()
