"""Request-scoped distributed tracing: context propagation + tail sampling.

A request that enters :meth:`Router.submit` today may cross a prefill
replica, a KV-page handoff, a decode replica, a hedge race, and one or
more failover replays before its stream completes — five processes'
ring buffers, no causal identity. This module supplies that identity:

- :class:`TraceContext` — ``trace_id`` / ``span_id`` / ``parent_span_id``
  plus a small ``baggage`` dict, minted once per user request
  (``TraceContext.mint``) and forked per leg (``ctx.child``) so every
  span any process records carries the same ``trace_id`` and a correct
  parent edge. Contexts ride on the request objects themselves
  (``Request.trace`` / ``RouterRequest.trace``) — no thread-locals, the
  serving stack is poll-driven.

- :class:`ReqTrace` (module global ``reqtrace``) — the per-host
  **TraceBuffer** implementing tail-based sampling. Request-scoped spans
  are buffered per ``trace_id`` while the request is in flight; at
  completion the root owner calls :meth:`ReqTrace.finish` and the full
  span set is either flushed into the process tracer ring (it ended
  *interesting*: SLO-violating TTFT/TPOT, finish reason error/drained,
  any failover / hedge / re-prefill / kvtier-fallback flag, or the
  configured head-sample rate) or dropped wholesale with a
  ``trace/dropped_ok`` count. The buffer is bounded
  (``buffer_traces``); leaked traces evict oldest-first with a
  ``trace/buffer_evicted`` count.

- :func:`critical_path` — span set → wall-time attribution
  (queued / prefill / handoff / decode / replayed / stalled), the
  breakdown ``dstpu-doctor``'s "slow requests" section and
  ``dstpu-trace --request`` render.

Configured by the ``telemetry.reqtrace.*`` config block
(``enabled`` / ``head_sample`` / ``retain_slow_ms`` / ``buffer_traces``)
through :func:`deepspeed_tpu.telemetry.configure`.
"""

import os
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: per-trace span cap — a runaway stream must not grow one buffer entry
#: unboundedly; overflow spans are dropped and counted
MAX_EVENTS_PER_TRACE = 512
#: retained-trace summaries kept for the post-mortem (flight recorder /
#: dstpu-doctor "slow requests")
MAX_RETAINED_SUMMARIES = 64

#: finish reasons that always retain the trace
INTERESTING_REASONS = ("error", "drained")

#: span name → critical-path segment (see :func:`critical_path`)
SEGMENTS = {
    "serving/request/queued": "queued",
    "serving/request/prefill": "prefill",
    "router/handoff": "handoff",
    "serving/request/decode": "decode",
}


def _new_id() -> str:
    return os.urandom(8).hex()


def _count(name: str, by: float = 1, help: str = "") -> None:
    try:
        from deepspeed_tpu.telemetry.registry import registry
        registry.counter(name, help=help).inc(by)
    except Exception:                                    # noqa: BLE001
        pass


@dataclass
class TraceContext:
    """One request's causal identity on one leg of its journey.

    ``mint()`` starts a trace (root context, owner of the tail-sampling
    decision); ``child()`` forks a leg context whose spans parent to the
    forker. ``baggage`` is copied into every child and stamped into
    every span's args (keep it tiny: replica name, role, hedge/replay
    markers)."""

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    baggage: Dict[str, Any] = field(default_factory=dict)
    root: bool = False

    @classmethod
    def mint(cls, **baggage: Any) -> "TraceContext":
        return cls(trace_id=_new_id(), span_id=_new_id(), root=True,
                   baggage=dict(baggage))

    def child(self, **baggage: Any) -> "TraceContext":
        bg = dict(self.baggage)
        bg.update(baggage)
        return TraceContext(trace_id=self.trace_id, span_id=_new_id(),
                            parent_span_id=self.span_id, baggage=bg)

    def tags(self) -> Dict[str, Any]:
        """Args every span stamped with this context carries."""
        t: Dict[str, Any] = {"trace_id": self.trace_id,
                             "span_id": self.span_id}
        if self.parent_span_id:
            t["parent_span_id"] = self.parent_span_id
        t.update(self.baggage)
        return t


class ReqTrace:
    """Bounded per-host trace buffer with a tail-based retention policy.

    Spans arrive via :meth:`complete` / :meth:`instant` (same shapes the
    :class:`~deepspeed_tpu.telemetry.tracer.Tracer` records, tagged with
    the context's trace identity) and are held per ``trace_id``. The
    root context's owner calls :meth:`finish` when the stream completes;
    only then does the span set either enter the tracer ring (retained)
    or vanish (``trace/dropped_ok``). Interesting-ness can also be
    asserted mid-flight via :meth:`flag` (failover, hedge, re-prefill,
    kvtier fallback, breaker rejection, stall)."""

    def __init__(self, enabled: bool = False, head_sample: float = 0.0,
                 retain_slow_ms: float = 500.0, buffer_traces: int = 256):
        self.enabled = bool(enabled)
        self.head_sample = float(head_sample)
        self.retain_slow_ms = float(retain_slow_ms)
        self.buffer_traces = int(buffer_traces)
        self._lock = threading.RLock()
        #: trace_id → {"events": [...], "flags": [...]}
        self._pending: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._retained: deque = deque(maxlen=MAX_RETAINED_SUMMARIES)
        #: recently decided traces: spans arriving after the tail
        #: decision (a cancelled hedge loser draining on its replica's
        #: own thread) are dropped, not resurrected as leaked entries
        self._finished: "OrderedDict[str, None]" = OrderedDict()

    # -- configuration ------------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  head_sample: Optional[float] = None,
                  retain_slow_ms: Optional[float] = None,
                  buffer_traces: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if head_sample is not None:
                self.head_sample = max(0.0, min(1.0, float(head_sample)))
            if retain_slow_ms is not None:
                self.retain_slow_ms = float(retain_slow_ms)
            if buffer_traces is not None:
                self.buffer_traces = max(1, int(buffer_traces))

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._retained.clear()
            self._finished.clear()

    # -- context + span intake ----------------------------------------------

    def mint(self, **baggage: Any) -> Optional[TraceContext]:
        """Start a trace (None when tracing is disabled — callers pass
        the context through unconditionally; every sink tolerates
        ``ctx=None``)."""
        if not self.enabled:
            return None
        ctx = TraceContext.mint(**baggage)
        with self._lock:
            self._entry(ctx.trace_id)
        return ctx

    def _entry(self, trace_id: str) -> Dict[str, Any]:
        """Get-or-create the pending buffer entry (lock held by caller
        or taken here); evicts oldest when over ``buffer_traces``."""
        with self._lock:
            e = self._pending.get(trace_id)
            if e is None:
                while len(self._pending) >= self.buffer_traces:
                    self._pending.popitem(last=False)
                    _count("trace/buffer_evicted",
                           help="pending traces evicted before their "
                                "tail decision (leaked or over cap)")
                e = {"events": [], "flags": []}
                self._pending[trace_id] = e
            return e

    def _buffer(self, ev: Dict[str, Any], trace_id: str) -> None:
        with self._lock:
            if trace_id in self._finished:
                _count("trace/late_spans",
                       help="spans arriving after the trace's tail "
                            "decision (dropped)")
                return
            e = self._entry(trace_id)
            if len(e["events"]) >= MAX_EVENTS_PER_TRACE:
                _count("trace/span_overflow",
                       help="request spans dropped past the per-trace cap")
                return
            e["events"].append(ev)

    def complete(self, name: str, ctx: Optional[TraceContext],
                 start: float, end: float, tid: Optional[int] = None,
                 envelope: bool = False, **args: Any) -> None:
        """Buffer a retroactive span for ``ctx``'s trace. Each span gets
        its own ``span_id`` parented to the context; ``envelope=True``
        makes the span BE the context (span_id = ctx.span_id), so child
        contexts forked from it parent correctly across processes."""
        if ctx is None or not self.enabled:
            return
        from deepspeed_tpu.telemetry.tracer import tracer
        tags = ctx.tags()
        if not envelope:
            tags["parent_span_id"] = ctx.span_id
            tags["span_id"] = _new_id()
        ev = tracer._event(name, "X", (start - tracer._t0) * 1e6, tid,
                           {**tags, **args})
        ev["dur"] = max(0.0, (end - start) * 1e6)
        self._buffer(ev, ctx.trace_id)

    def instant(self, name: str, ctx: Optional[TraceContext],
                ts: Optional[float] = None, tid: Optional[int] = None,
                **args: Any) -> None:
        """Buffer a zero-duration marker for ``ctx``'s trace."""
        if ctx is None or not self.enabled:
            return
        import time
        from deepspeed_tpu.telemetry.tracer import tracer
        ts = time.monotonic() if ts is None else ts
        tags = ctx.tags()
        tags["parent_span_id"] = ctx.span_id
        tags["span_id"] = _new_id()
        ev = tracer._event(name, "i", (ts - tracer._t0) * 1e6, tid,
                           {**tags, **args})
        ev["s"] = "t"
        self._buffer(ev, ctx.trace_id)

    def flag(self, ctx: Optional[TraceContext], reason: str) -> None:
        """Mark the trace interesting regardless of its final latency
        (failover, hedge, reprefill, kvtier_fallback, rejected, stall)."""
        if ctx is None or not self.enabled:
            return
        with self._lock:
            if ctx.trace_id in self._finished:
                return
            flags = self._entry(ctx.trace_id)["flags"]
            if reason not in flags:
                flags.append(reason)

    # -- the tail decision ---------------------------------------------------

    def _head_sampled(self, trace_id: str) -> bool:
        """Deterministic per-trace head sample: every host keeps or drops
        the same traces without coordination."""
        if self.head_sample <= 0.0:
            return False
        return (int(trace_id[:8], 16) % 1_000_000) < \
            self.head_sample * 1_000_000

    def finish(self, ctx: Optional[TraceContext],
               reason: Optional[str] = None,
               ttft_s: Optional[float] = None,
               tpot_s: Optional[float] = None) -> bool:
        """The stream completed: decide the trace's fate. Returns True
        when the span set was retained (flushed into the tracer ring,
        visible in the next trace dump)."""
        if ctx is None or not self.enabled:
            return False
        with self._lock:
            entry = self._pending.pop(ctx.trace_id, None)
            self._finished[ctx.trace_id] = None
            while len(self._finished) > 4 * self.buffer_traces:
                self._finished.popitem(last=False)
        if entry is None:
            return False
        causes = list(entry["flags"])
        if reason in INTERESTING_REASONS:
            causes.append(f"reason:{reason}")
        ttft_ms = None if ttft_s is None else ttft_s * 1e3
        tpot_ms = None if tpot_s is None else tpot_s * 1e3
        if self.retain_slow_ms > 0:
            if ttft_ms is not None and ttft_ms >= self.retain_slow_ms:
                causes.append("slow_ttft")
            if tpot_ms is not None and tpot_ms >= self.retain_slow_ms:
                causes.append("slow_tpot")
        head = self._head_sampled(ctx.trace_id)
        if not causes and not head:
            _count("trace/dropped_ok",
                   help="uninteresting request traces dropped whole at "
                        "completion (tail-based sampling)")
            return False
        if head and not causes:
            causes.append("head_sample")
        from deepspeed_tpu.telemetry.tracer import tracer
        tracer.ingest(entry["events"])
        _count("trace/retained",
               help="request traces retained by tail-based sampling")
        breakdown = critical_path(entry["events"])
        summary = {
            "trace_id": ctx.trace_id,
            "reason": reason,
            "causes": causes,
            "ttft_ms": ttft_ms,
            "tpot_ms": tpot_ms,
            "total_ms": breakdown.pop("_total_ms", 0.0),
            "breakdown_ms": breakdown,
        }
        with self._lock:
            self._retained.append(summary)
        return True

    # -- post-mortem export --------------------------------------------------

    def retained(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(s) for s in self._retained]

    def post_mortem(self) -> Dict[str, Any]:
        """The flight recorder's ``reqtrace`` black-box section."""
        from deepspeed_tpu.telemetry.registry import registry
        from deepspeed_tpu.telemetry.tracer import tracer

        def _cval(name: str) -> float:
            m = registry.get(name)
            return float(m.value) if m is not None else 0.0

        with self._lock:
            pending = len(self._pending)
        return {"retained": self.retained(),
                "pending": pending,
                "dropped_ok": _cval("trace/dropped_ok"),
                "ring_dropped": float(tracer.dropped)}


def critical_path(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """Span set → per-segment wall-time attribution, in ms.

    Complete spans map to segments by name (:data:`SEGMENTS`); spans on
    a replay leg (``args.replay``) are charged to ``replayed`` instead of
    their nominal segment, and hedge-loser legs (``args.winner == 0``)
    are excluded — the loser ran off the critical path. ``stalled`` is
    the trace's total extent not covered by any attributed span (time
    the stream made no observable progress: queue-behind-handoff gaps,
    stall-detection windows, breaker backoff). Parallel legs can overlap,
    so segment sums are attribution, not a strict partition; ``stalled``
    clamps at 0. ``_total_ms`` carries the trace extent for callers that
    want percentages."""
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        return {"_total_ms": 0.0}
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)
    total_ms = (t1 - t0) / 1e3
    out: Dict[str, float] = {}
    attributed = 0.0
    for e in spans:
        seg = SEGMENTS.get(e.get("name"))
        if seg is None:
            continue
        args = e.get("args", {})
        if args.get("winner") == 0:
            continue
        if args.get("replay"):
            seg = "replayed"
        dur_ms = e.get("dur", 0.0) / 1e3
        out[seg] = out.get(seg, 0.0) + dur_ms
        attributed += dur_ms
    out["stalled"] = max(0.0, total_ms - attributed)
    out["_total_ms"] = total_ms
    return out


#: process-wide request-trace buffer (counterpart of ``tracer`` /
#: ``registry``; ``deepspeed_tpu.telemetry.configure`` wires its knobs)
reqtrace = ReqTrace()
