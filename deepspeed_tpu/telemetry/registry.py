"""Unified metrics registry: process-wide Counters / Gauges / Histograms.

One namespace for every subsystem's numbers — engine step time and MFU,
collective byte counts, serving latencies — instead of five private
counter dicts. Two egress paths:

- :meth:`MetricsRegistry.prometheus_text` renders the standard Prometheus
  text exposition format (serve it from any HTTP handler, or snapshot it
  in tests);
- :meth:`MetricsRegistry.flush_to_monitor` bridges a snapshot through the
  existing :class:`~deepspeed_tpu.monitor.monitor.MonitorMaster` writers,
  so TensorBoard/W&B/Comet/CSV keep working with zero extra config.

The :class:`Histogram` here is THE bucketing implementation for the repo
(``serving/metrics.py`` imports it back under its old name).
"""

import bisect
import math
import re
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple, Union

Event = Tuple[str, float, int]

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Metric name → valid Prometheus name (``train/step_time_ms`` →
    ``train_step_time_ms``)."""
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return f"{float(v):.10g}"


class Counter:
    """Monotonically increasing value."""

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, by: Union[int, float] = 1) -> None:
        if by < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self.value += by


class Gauge:
    """Last-written value."""

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, by: float = 1.0) -> None:
        with self._lock:
            self.value += by


class Histogram:
    """Fixed log-spaced buckets; O(log B) record, exact count/sum.

    ``bounds[i]`` is bucket i's inclusive upper edge; ``counts`` has one
    extra overflow slot so values ``> hi`` are never misfiled into the top
    regular bucket (``bounds[-1]`` is pinned to exactly ``hi`` — the
    geometric ladder's float rounding used to leave it a hair above or
    below, sending boundary values to the wrong side). ``vmin``/``vmax``
    track exact extremes regardless of bucketing.
    """

    def __init__(self, lo: float = 1e-4, hi: float = 100.0,
                 n_buckets: int = 40):
        if n_buckets < 2:
            raise ValueError("Histogram needs n_buckets >= 2")
        if not (0 < lo < hi):
            raise ValueError(f"Histogram needs 0 < lo < hi, got {lo}, {hi}")
        ratio = (hi / lo) ** (1.0 / (n_buckets - 1))
        self.bounds = [lo * ratio ** i for i in range(n_buckets)]
        self.bounds[-1] = float(hi)
        self.counts = [0] * (n_buckets + 1)
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self._lock = threading.Lock()

    def record(self, v: float) -> None:
        if not math.isfinite(v):
            return
        with self._lock:
            self.counts[bisect.bisect_left(self.bounds, v)] += 1
            self.count += 1
            self.total += v
            self.vmin = v if self.vmin is None else min(self.vmin, v)
            self.vmax = v if self.vmax is None else max(self.vmax, v)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket holding the p-th percentile sample
        (the exact ``vmax`` for samples in the overflow bucket)."""
        if not self.count:
            return 0.0
        target = p / 100.0 * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                if i >= len(self.bounds):
                    return self.vmax if self.vmax is not None \
                        else self.bounds[-1]
                return self.bounds[i]
        return self.vmax if self.vmax is not None else self.bounds[-1]

    def summary(self) -> Dict[str, float]:
        return {"count": self.count, "mean": self.mean,
                "p50": self.percentile(50), "p99": self.percentile(99),
                "min": self.vmin or 0.0, "max": self.vmax or 0.0}


Metric = Union[Counter, Gauge, Histogram]


class MetricsRegistry:
    """Named metric store with get-or-create accessors.

    Names use ``/`` namespacing (``train/mfu``, ``serving/ttft_seconds``);
    the Prometheus renderer sanitizes them. Histograms owned by per-object
    aggregators (e.g. one :class:`ServingMetrics` per frontend) register
    with ``replace=True`` so the registry always exposes the live one.
    """

    def __init__(self):
        self._metrics: "OrderedDict[str, Metric]" = OrderedDict()
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()

    # -- registration -------------------------------------------------------

    def register(self, name: str, metric: Metric, help: str = "",
                 replace: bool = False) -> Metric:
        with self._lock:
            if name in self._metrics and not replace:
                raise ValueError(f"metric {name!r} already registered")
            self._metrics[name] = metric
            if help or name not in self._help:
                self._help[name] = help
        return metric

    def _get_or_create(self, name: str, cls, help: str, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} is {type(m).__name__}, "
                        f"requested {cls.__name__}")
                return m
            m = cls(name, **kw) if cls is not Histogram else Histogram(**kw)
            self._metrics[name] = m
            if help or name not in self._help:
                self._help[name] = help
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help)

    def histogram(self, name: str, lo: float = 1e-4, hi: float = 100.0,
                  n_buckets: int = 40, help: str = "") -> Histogram:
        return self._get_or_create(name, Histogram, help,
                                   lo=lo, hi=hi, n_buckets=n_buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)
            self._help.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._help.clear()

    def names(self) -> List[str]:
        with self._lock:
            return list(self._metrics)

    # -- exposition ---------------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text exposition (v0.0.4) of every registered metric.
        Histogram buckets are rendered cumulatively with an explicit
        ``+Inf`` bucket, per the format spec."""
        with self._lock:
            items = list(self._metrics.items())
            helps = dict(self._help)
        lines: List[str] = []
        for name, m in items:
            pn = prom_name(name)
            if helps.get(name):
                lines.append(f"# HELP {pn} {helps[name]}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pn} counter")
                lines.append(f"{pn} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pn} gauge")
                lines.append(f"{pn} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {pn} histogram")
                acc = 0
                for bound, c in zip(m.bounds, m.counts):
                    acc += c
                    lines.append(
                        f'{pn}_bucket{{le="{_fmt(bound)}"}} {acc}')
                acc += m.counts[-1]
                lines.append(f'{pn}_bucket{{le="+Inf"}} {acc}')
                lines.append(f"{pn}_sum {_fmt(m.total)}")
                lines.append(f"{pn}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- monitor bridge -----------------------------------------------------

    def events(self, step: int = 0) -> List[Event]:
        """Snapshot as ``(name, value, step)`` monitor events. Histograms
        contribute mean/p99/count derived series (a TB scalar can't carry
        buckets)."""
        with self._lock:
            items = list(self._metrics.items())
        ev: List[Event] = []
        for name, m in items:
            if isinstance(m, (Counter, Gauge)):
                ev.append((name, float(m.value), step))
            elif isinstance(m, Histogram) and m.count:
                ev.append((f"{name}_mean", m.mean, step))
                ev.append((f"{name}_p99", m.percentile(99), step))
                ev.append((f"{name}_count", float(m.count), step))
        return ev

    def flush_to_monitor(self, monitor, step: int = 0) -> None:
        """Write a snapshot through a MonitorMaster (no-op when monitoring
        is disabled or absent)."""
        if monitor is None or not getattr(monitor, "enabled", False):
            return
        ev = self.events(step)
        if ev:
            monitor.write_events(ev)


#: process-wide registry (counterpart of the process-wide ``tracer``)
registry = MetricsRegistry()
