"""Unified metrics registry: process-wide Counters / Gauges / Histograms.

One namespace for every subsystem's numbers — engine step time and MFU,
collective byte counts, serving latencies — instead of five private
counter dicts. Two egress paths:

- :meth:`MetricsRegistry.prometheus_text` renders the standard Prometheus
  text exposition format (serve it from any HTTP handler, or snapshot it
  in tests);
- :meth:`MetricsRegistry.flush_to_monitor` bridges a snapshot through the
  existing :class:`~deepspeed_tpu.monitor.monitor.MonitorMaster` writers,
  so TensorBoard/W&B/Comet/CSV keep working with zero extra config.

The :class:`Histogram` here is THE bucketing implementation for the repo
(``serving/metrics.py`` imports it back under its old name).
"""

import bisect
import math
import re
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple, Union

Event = Tuple[str, float, int]

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Metric name → valid Prometheus name (``train/step_time_ms`` →
    ``train_step_time_ms``)."""
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return f"{float(v):.10g}"


def percentile_from_counts(bounds: List[float], counts: List[int],
                           total: float, p: float,
                           vmin: Optional[float] = None,
                           vmax: Optional[float] = None) -> float:
    """Interpolated percentile over log-spaced bucket counts.

    ``bounds[i]`` is bucket i's inclusive upper edge; ``counts`` may carry
    one extra trailing overflow slot. Within a regular bucket the value is
    placed log-linearly between the bucket's edges (the buckets are a
    geometric ladder, so log interpolation is the natural inverse) instead
    of snapping to the upper edge; the overflow bucket clamps to the
    tracked ``vmax``. The result is always clamped into [vmin, vmax] —
    exact extremes beat any interpolation the bucketing can offer.

    Shared by :meth:`Histogram.percentile`, the registry's interval
    snapshots, and the fleet view's Prometheus-scrape reconstruction.
    """
    if not total or total <= 0:
        return 0.0
    target = p / 100.0 * total
    v: Optional[float] = None
    acc = 0.0
    for i, c in enumerate(counts):
        acc += c
        if c > 0 and acc >= target:
            if i >= len(bounds):            # overflow bucket → exact max
                v = vmax if vmax is not None else float(bounds[-1])
            else:
                upper = float(bounds[i])
                lower = float(bounds[i - 1]) if i > 0 else (
                    vmin if vmin is not None and 0 < vmin < upper else None)
                if lower is None or lower <= 0 or upper <= lower:
                    v = upper
                else:
                    f = (target - (acc - c)) / c
                    v = lower * (upper / lower) ** f
            break
    if v is None:
        v = vmax if vmax is not None else float(bounds[-1])
    if vmin is not None:
        v = max(v, vmin)
    if vmax is not None:
        v = min(v, vmax)
    return v


class Counter:
    """Monotonically increasing value."""

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, by: Union[int, float] = 1) -> None:
        if by < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self.value += by


class Gauge:
    """Last-written value."""

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, by: float = 1.0) -> None:
        with self._lock:
            self.value += by


class Histogram:
    """Fixed log-spaced buckets; O(log B) record, exact count/sum.

    ``bounds[i]`` is bucket i's inclusive upper edge; ``counts`` has one
    extra overflow slot so values ``> hi`` are never misfiled into the top
    regular bucket (``bounds[-1]`` is pinned to exactly ``hi`` — the
    geometric ladder's float rounding used to leave it a hair above or
    below, sending boundary values to the wrong side). ``vmin``/``vmax``
    track exact extremes regardless of bucketing.

    Each bucket additionally carries one OpenMetrics *exemplar* slot
    (trace_id + exact value, latest sample wins): ``record(v,
    exemplar=trace_id)`` links the bucket to the request trace that
    landed in it, so a bad p99 bucket on a dashboard resolves to an
    openable trace instead of an anonymous count.
    """

    def __init__(self, lo: float = 1e-4, hi: float = 100.0,
                 n_buckets: int = 40):
        if n_buckets < 2:
            raise ValueError("Histogram needs n_buckets >= 2")
        if not (0 < lo < hi):
            raise ValueError(f"Histogram needs 0 < lo < hi, got {lo}, {hi}")
        ratio = (hi / lo) ** (1.0 / (n_buckets - 1))
        self.bounds = [lo * ratio ** i for i in range(n_buckets)]
        self.bounds[-1] = float(hi)
        self.counts = [0] * (n_buckets + 1)
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        #: bucket index → (trace_id, exact value); index ``n_buckets`` is
        #: the overflow (+Inf) bucket's slot
        self.exemplars: Dict[int, Tuple[str, float]] = {}
        self._lock = threading.Lock()

    def record(self, v: float, exemplar: Optional[str] = None) -> None:
        if not math.isfinite(v):
            return
        with self._lock:
            i = bisect.bisect_left(self.bounds, v)
            self.counts[i] += 1
            self.count += 1
            self.total += v
            self.vmin = v if self.vmin is None else min(self.vmin, v)
            self.vmax = v if self.vmax is None else max(self.vmax, v)
            if exemplar:
                self.exemplars[i] = (str(exemplar), float(v))

    def worst_exemplar(self) -> Optional[Tuple[str, float]]:
        """The exemplar in the highest occupied bucket that has one —
        the trace to open for this histogram's tail."""
        with self._lock:
            for i in sorted(self.exemplars, reverse=True):
                return self.exemplars[i]
        return None

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """p-th percentile, log-linearly interpolated within the bucket
        holding it (clamped to the exact ``vmin``/``vmax`` extremes; the
        overflow bucket reports ``vmax``) — SLO thresholds on p95/p99
        aren't quantized to bucket edges."""
        if not self.count:
            return 0.0
        return percentile_from_counts(self.bounds, self.counts, self.count,
                                      p, vmin=self.vmin, vmax=self.vmax)

    def summary(self) -> Dict[str, float]:
        return {"count": self.count, "mean": self.mean,
                "p50": self.percentile(50), "p99": self.percentile(99),
                "min": self.vmin or 0.0, "max": self.vmax or 0.0}


Metric = Union[Counter, Gauge, Histogram]


class MetricsRegistry:
    """Named metric store with get-or-create accessors.

    Names use ``/`` namespacing (``train/mfu``, ``serving/ttft_seconds``);
    the Prometheus renderer sanitizes them. Histograms owned by per-object
    aggregators (e.g. one :class:`ServingMetrics` per frontend) register
    with ``replace=True`` so the registry always exposes the live one.
    """

    def __init__(self):
        self._metrics: "OrderedDict[str, Metric]" = OrderedDict()
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()
        #: previous bucket counts per histogram, for the interval
        #: summaries in :meth:`snapshot` (percentiles over the samples
        #: since the LAST snapshot — what SLO burn windows judge)
        self._hist_prev: Dict[str, Tuple[List[int], float, int]] = {}

    # -- registration -------------------------------------------------------

    def register(self, name: str, metric: Metric, help: str = "",
                 replace: bool = False) -> Metric:
        with self._lock:
            if name in self._metrics and not replace:
                raise ValueError(f"metric {name!r} already registered")
            self._metrics[name] = metric
            if help or name not in self._help:
                self._help[name] = help
        return metric

    def _get_or_create(self, name: str, cls, help: str, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} is {type(m).__name__}, "
                        f"requested {cls.__name__}")
                return m
            m = cls(name, **kw) if cls is not Histogram else Histogram(**kw)
            self._metrics[name] = m
            if help or name not in self._help:
                self._help[name] = help
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help)

    def histogram(self, name: str, lo: float = 1e-4, hi: float = 100.0,
                  n_buckets: int = 40, help: str = "") -> Histogram:
        return self._get_or_create(name, Histogram, help,
                                   lo=lo, hi=hi, n_buckets=n_buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)
            self._help.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._help.clear()

    def names(self) -> List[str]:
        with self._lock:
            return list(self._metrics)

    # -- exposition ---------------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text exposition (v0.0.4) of every registered metric.
        Histogram buckets are rendered cumulatively with an explicit
        ``+Inf`` bucket, per the format spec. Buckets holding an exemplar
        append it OpenMetrics-style — ``... 5 # {trace_id="..."} 0.67`` —
        which exposition parsers must strip from the sample line (the
        fleet poller's does)."""
        with self._lock:
            items = list(self._metrics.items())
            helps = dict(self._help)
        lines: List[str] = []
        for name, m in items:
            pn = prom_name(name)
            if helps.get(name):
                lines.append(f"# HELP {pn} {helps[name]}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pn} counter")
                lines.append(f"{pn} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pn} gauge")
                lines.append(f"{pn} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {pn} histogram")
                with m._lock:
                    exemplars = dict(m.exemplars)
                acc = 0
                for i, (bound, c) in enumerate(zip(m.bounds, m.counts)):
                    acc += c
                    line = f'{pn}_bucket{{le="{_fmt(bound)}"}} {acc}'
                    if i in exemplars:
                        tid, ev = exemplars[i]
                        line += f' # {{trace_id="{tid}"}} {_fmt(ev)}'
                    lines.append(line)
                acc += m.counts[-1]
                line = f'{pn}_bucket{{le="+Inf"}} {acc}'
                if len(m.bounds) in exemplars:
                    tid, ev = exemplars[len(m.bounds)]
                    line += f' # {{trace_id="{tid}"}} {_fmt(ev)}'
                lines.append(line)
                lines.append(f"{pn}_sum {_fmt(m.total)}")
                lines.append(f"{pn}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- monitor / history bridge -------------------------------------------

    def snapshot(self, interval: bool = True
                 ) -> Dict[str, Union[float, Dict[str, Any]]]:
        """One-pass structured snapshot of every metric: counters/gauges
        as floats, histograms as their summary dict extended with p90/p95
        and (when ``interval``) an ``"interval"`` sub-summary over the
        samples recorded since the previous ``snapshot(interval=True)``
        call — all-time percentiles never recover after a bad patch, so
        SLO windows judge the interval numbers.

        This is the shared source for :meth:`flush_to_monitor`'s monitor
        events AND the metric-history sink (one lock pass feeds both).
        """
        with self._lock:
            items = list(self._metrics.items())
        snap: Dict[str, Union[float, Dict[str, Any]]] = {}
        for name, m in items:
            if isinstance(m, (Counter, Gauge)):
                snap[name] = float(m.value)
            elif isinstance(m, Histogram) and m.count:
                s: Dict[str, Any] = m.summary()
                s["p90"] = m.percentile(90)
                s["p95"] = m.percentile(95)
                if interval:
                    s["interval"] = self._interval_summary(name, m)
                snap[name] = s
        return snap

    def _interval_summary(self, name: str, m: Histogram) -> Dict[str, Any]:
        """Summary over the samples since the last snapshot: bucket-count
        deltas against the stored previous counts (a replaced/reshaped
        histogram resets the baseline)."""
        counts, total, count = list(m.counts), m.total, m.count
        prev = self._hist_prev.get(name)
        self._hist_prev[name] = (counts, total, count)
        if prev is None or len(prev[0]) != len(counts) or \
                any(c < pc for c, pc in zip(counts, prev[0])):
            dc, dtotal, dcount = counts, total, count
        else:
            dc = [c - pc for c, pc in zip(counts, prev[0])]
            dtotal, dcount = total - prev[1], count - prev[2]
        if dcount <= 0:
            return {"count": 0}
        return {
            "count": dcount, "mean": dtotal / dcount,
            "p50": percentile_from_counts(m.bounds, dc, dcount, 50,
                                          vmax=m.vmax),
            "p95": percentile_from_counts(m.bounds, dc, dcount, 95,
                                          vmax=m.vmax),
            "p99": percentile_from_counts(m.bounds, dc, dcount, 99,
                                          vmax=m.vmax),
        }

    def events(self, step: int = 0) -> List[Event]:
        """Snapshot as ``(name, value, step)`` monitor events. Histograms
        contribute mean/p99/count derived series (a TB scalar can't carry
        buckets)."""
        return self._events_from(self.snapshot(interval=False), step)

    @staticmethod
    def _events_from(snap: Dict[str, Union[float, Dict[str, Any]]],
                     step: int) -> List[Event]:
        ev: List[Event] = []
        for name, v in snap.items():
            if isinstance(v, dict):
                ev.append((f"{name}_mean", float(v["mean"]), step))
                ev.append((f"{name}_p99", float(v["p99"]), step))
                ev.append((f"{name}_count", float(v["count"]), step))
            else:
                ev.append((name, float(v), step))
        return ev

    def flush_to_monitor(self, monitor, step: int = 0,
                         history=None) -> None:
        """Write a snapshot through a MonitorMaster and/or a metric-
        history sink (:class:`~deepspeed_tpu.telemetry.timeseries.
        MetricHistory`). One :meth:`snapshot` call feeds both — the
        history record and the monitor events come from the same lock
        pass. No-op when monitoring is disabled/absent and no history
        sink is given."""
        want_monitor = monitor is not None and \
            getattr(monitor, "enabled", False)
        if not want_monitor and history is None:
            return
        snap = self.snapshot(interval=history is not None)
        if history is not None:
            history.append(step, snap)
        if want_monitor:
            ev = self._events_from(snap, step)
            if ev:
                monitor.write_events(ev)


#: process-wide registry (counterpart of the process-wide ``tracer``)
registry = MetricsRegistry()
