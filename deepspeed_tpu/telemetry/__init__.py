"""deepspeed_tpu.telemetry — unified tracing, metrics, and MFU/memory
accounting across the engine, comm layer, and serving frontend.

The reference threads observability through five disconnected pieces
(MonitorMaster events, SynchronizedWallClockTimer, comms logging, the
flops profiler, serving histograms); this package gives them one spine:

- :mod:`~deepspeed_tpu.telemetry.tracer` — nestable spans → Chrome/
  Perfetto trace-event JSON (+ optional jax.profiler annotations);
- :mod:`~deepspeed_tpu.telemetry.registry` — process-wide Counters/
  Gauges/Histograms with Prometheus text exposition and a MonitorMaster
  bridge;
- :mod:`~deepspeed_tpu.telemetry.sampler` — device-memory watermarks and
  MFU against the per-platform peak-FLOPs table;
- :mod:`~deepspeed_tpu.telemetry.summarize` — the trace self-time CLI
  (``python -m deepspeed_tpu.telemetry.summarize`` / ``bin/dstpu-trace``).

The diagnostics layer on top of that spine (PR 4) answers "why did the
run die, hang, or slow down":

- :mod:`~deepspeed_tpu.telemetry.flight_recorder` — always-on bounded
  ring of per-step records, serialized to a JSON black box on crash /
  preemption / hang / demand;
- :mod:`~deepspeed_tpu.telemetry.watchdog` — per-step deadline monitor
  that dumps all-thread stacks + the black box on a hung step;
- :mod:`~deepspeed_tpu.telemetry.compile_monitor` — XLA compile
  counts/durations and the recompilation-storm detector;
- :mod:`~deepspeed_tpu.telemetry.anomaly` — non-finite / loss-spike /
  grad-outlier / step-time-regression flags on the step stream;
- :mod:`~deepspeed_tpu.telemetry.doctor` — the ``dstpu-doctor`` CLI
  that turns per-host black boxes into a health report;
- :mod:`~deepspeed_tpu.telemetry.health` — in-graph model-health taps
  (per-layer training dynamics, MoE expert load) published as
  ``health/*`` gauges, with the per-layer anomaly localizer and the
  ``dstpu-health`` renderer.

The compile-time side (PR 5) answers "where was this step ALWAYS going
to spend its FLOPs, bytes, and HBM" before it runs:

- :mod:`~deepspeed_tpu.telemetry.explain` — lowers the jitted step /
  serving programs, reads back XLA cost+memory analysis, and builds the
  roofline + HBM-budget report (``bin/dstpu-explain``, ``roofline/*``
  gauges);
- :mod:`~deepspeed_tpu.telemetry.endpoint` — the live scrape server
  (``GET /metrics`` + ``GET /healthz``), ``telemetry.http_port`` config.

The time axis over all of it (PR 9):

- :mod:`~deepspeed_tpu.telemetry.timeseries` — durable per-host metric
  history (JSONL ring, size-bounded rotation + downsampling) recording
  every registry flush, with a range/rate/windowed query API;
- :mod:`~deepspeed_tpu.telemetry.slo` — config-declared objectives
  (``slo.objectives``) evaluated continuously with fast/slow
  multi-window burn-rate alerting (``slo/*`` gauges, /healthz 503,
  flight-recorder events, doctor verdicts);
- :mod:`~deepspeed_tpu.telemetry.fleet` — the ``dstpu-top`` live
  terminal fleet view over N /metrics + /healthz endpoints (or history
  files offline);
- :mod:`~deepspeed_tpu.telemetry.compare` — the ``dstpu_report
  --compare`` run-regression gate over BENCH JSONL / history files.

See docs/observability.md for the config reference, the trace-capture
workflow, the metric-name catalog, and post-mortem debugging.
"""

from deepspeed_tpu.telemetry.anomaly import (AnomalyDetector,  # noqa: F401
                                             anomaly_detector,
                                             first_flagged_path)
from deepspeed_tpu.telemetry.compile_monitor import (  # noqa: F401
    CompileMonitor, compile_monitor)
from deepspeed_tpu.telemetry.endpoint import MetricsServer  # noqa: F401
from deepspeed_tpu.telemetry.explain import (ExplainReport,  # noqa: F401
                                             FunctionCost, Roofline,
                                             analyze_fn, explain_engine,
                                             explain_serving,
                                             normalize_cost_analysis,
                                             publish_gauges, render,
                                             resolve_peaks)
from deepspeed_tpu.telemetry.flight_recorder import (  # noqa: F401
    FlightRecorder, flight_recorder, load_dump)
from deepspeed_tpu.telemetry.goodput import (GoodputLedger,  # noqa: F401
                                             goodput_ledger)
from deepspeed_tpu.telemetry.health import HealthMonitor  # noqa: F401
from deepspeed_tpu.telemetry.registry import (Counter, Gauge,  # noqa: F401
                                              Histogram, MetricsRegistry,
                                              registry)
from deepspeed_tpu.telemetry.reqtrace import (ReqTrace,  # noqa: F401
                                              TraceContext, critical_path,
                                              reqtrace)
from deepspeed_tpu.telemetry.slo import (Objective, SLOEngine,  # noqa: F401
                                         engine_from_config,
                                         evaluate_history)
from deepspeed_tpu.telemetry.timeseries import (MetricHistory,  # noqa: F401
                                                load_records, merge_records,
                                                resolve_metric, windowed)
from deepspeed_tpu.telemetry.sampler import (MemorySampler,  # noqa: F401
                                             device_memory_stats,
                                             host_rss_bytes, mfu,
                                             peak_flops)
from deepspeed_tpu.telemetry.tracer import Tracer, tracer  # noqa: F401
from deepspeed_tpu.telemetry.watchdog import Watchdog  # noqa: F401

__all__ = ["tracer", "Tracer", "registry", "MetricsRegistry", "Counter",
           "Gauge", "Histogram", "MemorySampler", "peak_flops", "mfu",
           "device_memory_stats", "host_rss_bytes", "configure",
           "metrics_text", "flight_recorder", "FlightRecorder",
           "load_dump", "Watchdog", "compile_monitor", "CompileMonitor",
           "anomaly_detector", "AnomalyDetector", "first_flagged_path",
           "ExplainReport", "FunctionCost", "Roofline", "analyze_fn",
           "explain_engine", "explain_serving", "normalize_cost_analysis",
           "publish_gauges", "render", "resolve_peaks", "MetricsServer",
           "MetricHistory", "load_records", "merge_records",
           "resolve_metric", "windowed", "Objective", "SLOEngine",
           "engine_from_config", "evaluate_history", "reqtrace",
           "ReqTrace", "TraceContext", "critical_path",
           "goodput_ledger", "GoodputLedger", "HealthMonitor"]


def configure(telemetry_config) -> None:
    """Apply a :class:`~deepspeed_tpu.config.config.TelemetryConfig` to
    the process-wide tracer. Enable-only: an engine whose config leaves
    telemetry off must not silence a tracer something else (bench
    ``--trace``, a test) already turned on. The ``reqtrace`` and
    ``goodput`` sub-blocks additionally arm their own layers (each has
    its own ``enabled`` gate); enabling goodput also enables the span
    tracer — the ledger attributes off the tracer ring."""
    if telemetry_config is None:
        return
    rt = getattr(telemetry_config, "reqtrace", None)
    if rt is not None and getattr(rt, "enabled", False):
        reqtrace.configure(
            enabled=True,
            head_sample=getattr(rt, "head_sample", None),
            retain_slow_ms=getattr(rt, "retain_slow_ms", None),
            buffer_traces=getattr(rt, "buffer_traces", None))
    gp = getattr(telemetry_config, "goodput", None)
    if gp is not None and getattr(gp, "enabled", False):
        tracer.configure(enabled=True)
        goodput_ledger.configure(
            enabled=True,
            window_s=getattr(gp, "window_s", None),
            capture_threshold=getattr(gp, "capture_threshold", None),
            capture_cooldown_s=getattr(gp, "capture_cooldown_s", None),
            capture_duration_ms=getattr(gp, "capture_duration_ms", None),
            capture_dir=getattr(gp, "capture_dir", None))
    if not getattr(telemetry_config, "enabled", False):
        return
    tracer.configure(
        enabled=True,
        buffer_events=getattr(telemetry_config, "trace_buffer_events", None),
        jax_annotations=getattr(telemetry_config, "jax_annotations", None))


def metrics_text() -> str:
    """Prometheus text exposition of the process-wide registry — the
    payload for a ``/metrics`` endpoint."""
    return registry.prometheus_text()
