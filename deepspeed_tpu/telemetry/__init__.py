"""deepspeed_tpu.telemetry — unified tracing, metrics, and MFU/memory
accounting across the engine, comm layer, and serving frontend.

The reference threads observability through five disconnected pieces
(MonitorMaster events, SynchronizedWallClockTimer, comms logging, the
flops profiler, serving histograms); this package gives them one spine:

- :mod:`~deepspeed_tpu.telemetry.tracer` — nestable spans → Chrome/
  Perfetto trace-event JSON (+ optional jax.profiler annotations);
- :mod:`~deepspeed_tpu.telemetry.registry` — process-wide Counters/
  Gauges/Histograms with Prometheus text exposition and a MonitorMaster
  bridge;
- :mod:`~deepspeed_tpu.telemetry.sampler` — device-memory watermarks and
  MFU against the per-platform peak-FLOPs table;
- :mod:`~deepspeed_tpu.telemetry.summarize` — the trace self-time CLI
  (``python -m deepspeed_tpu.telemetry.summarize`` / ``bin/dstpu-trace``).

See docs/observability.md for the config reference, the trace-capture
workflow, and the metric-name catalog.
"""

from deepspeed_tpu.telemetry.registry import (Counter, Gauge,  # noqa: F401
                                              Histogram, MetricsRegistry,
                                              registry)
from deepspeed_tpu.telemetry.sampler import (MemorySampler,  # noqa: F401
                                             device_memory_stats,
                                             host_rss_bytes, mfu,
                                             peak_flops)
from deepspeed_tpu.telemetry.tracer import Tracer, tracer  # noqa: F401

__all__ = ["tracer", "Tracer", "registry", "MetricsRegistry", "Counter",
           "Gauge", "Histogram", "MemorySampler", "peak_flops", "mfu",
           "device_memory_stats", "host_rss_bytes", "configure",
           "metrics_text"]


def configure(telemetry_config) -> None:
    """Apply a :class:`~deepspeed_tpu.config.config.TelemetryConfig` to
    the process-wide tracer. Enable-only: an engine whose config leaves
    telemetry off must not silence a tracer something else (bench
    ``--trace``, a test) already turned on."""
    if telemetry_config is None or \
            not getattr(telemetry_config, "enabled", False):
        return
    tracer.configure(
        enabled=True,
        buffer_events=getattr(telemetry_config, "trace_buffer_events", None),
        jax_annotations=getattr(telemetry_config, "jax_annotations", None))


def metrics_text() -> str:
    """Prometheus text exposition of the process-wide registry — the
    payload for a ``/metrics`` endpoint."""
    return registry.prometheus_text()
