"""``dstpu-top`` — live terminal fleet view over N hosts' telemetry.

Two sources, one table:

- **live**: poll each target's ``GET /metrics`` (Prometheus text) and
  ``GET /healthz`` (JSON) — the endpoints every engine / serving
  frontend already serves (``telemetry.http_port``). Rates and interval
  percentiles come from successive polls (cumulative counter / bucket
  deltas), so the table shows what happened since the last refresh, not
  all-time averages.
- **offline** (``--history a.jsonl b.jsonl``): tail per-host metric
  history files (:mod:`~deepspeed_tpu.telemetry.timeseries`) — same
  table from a dead run's artifacts, no sockets. Useful in post-mortems
  and in tests (``--once`` renders one frame and exits).

Columns per host: health status, step, step rate, MFU, queue depth,
TTFT p95 / TPOT p99 (interval), token throughput, worst SLO burn, and
staleness (seconds since the host last reported). Fleet aggregates are
republished as ``fleet/*`` gauges in the local registry so a
supervising process can scrape its own ``/metrics`` for
``fleet/hosts_degraded`` and alert on the aggregate.

Usage::

    dstpu-top host-a:9090 host-b:9090          # live, refresh loop
    dstpu-top --once --json host-a:9090        # one machine-readable poll
    dstpu-top --once --history /tmp/h*.jsonl   # offline post-mortem view
"""

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.registry import (percentile_from_counts,
                                              registry)
from deepspeed_tpu.telemetry.timeseries import load_records, resolve_metric

DEFAULT_INTERVAL_S = 2.0
DEFAULT_TIMEOUT_S = 2.0

#: prometheus-flattened metric names the table reads (registry names
#: with ``/`` → ``_``, see MetricsRegistry.prometheus_text)
STEP_COUNTERS = ("train_steps", "serving_engine_steps")
TOKEN_COUNTERS = ("serving_tokens_out", "train_tokens")
MFU_GAUGES = ("train_mfu", "roofline_step_mfu")
QUEUE_GAUGES = ("serving_queue_depth", "serving_queue_depth_mean")
BURN_GAUGES = ("slo_worst_burn",)

#: history-record (un-flattened) names for offline mode
H_STEP = ("train/steps", "serving/engine_steps")
H_TOKENS = ("serving/tokens_out", "train/tokens")
H_MFU = ("train/mfu", "roofline/step_mfu")
H_QUEUE = ("serving/queue_depth:mean", "serving/queue_depth")
H_BURN = ("slo/worst_burn",)

#: numeric replica-state encoding published by the serving router
#: (``router/replica/{name}/state`` gauges) → display names
ROUTER_STATES = {0.0: "healthy", 1.0: "half-open", 2.0: "open",
                 3.0: "draining", 4.0: "dead"}
_ROUTER_STATE_RE = re.compile(r"^router_replica_(.+)_state$")
#: autoscaler per-pool gauges (``autoscale/target/{pool}`` and
#: ``autoscale/replicas/{pool}`` after prometheus name sanitization)
_AUTOSCALE_RE = re.compile(r"^autoscale_(target|replicas)_(.+)$")


_EXEMPLAR_RE = re.compile(
    r'\s#\s\{trace_id="([^"]*)"\}\s+(\S+)\s*$')


def parse_prometheus_text(text: str) -> Dict[str, Any]:
    """Prometheus text exposition → ``{flat_name: float}`` for scalars
    plus ``{name: {"buckets": [(le, cum), ...], "sum": s, "count": n}}``
    for histograms. OpenMetrics exemplar suffixes on bucket lines
    (``... # {trace_id="..."} value``) are captured into the
    histogram's ``"exemplars"`` list as ``{"le", "trace_id", "value"}``
    dicts. Tolerates unknown lines (forward compatible)."""
    out: Dict[str, Any] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        exemplar = None
        m = _EXEMPLAR_RE.search(line)
        if m:
            line = line[:m.start()].rstrip()
            try:
                exemplar = (m.group(1), float(m.group(2)))
            except ValueError:
                exemplar = (m.group(1), None)
        try:
            key, val = line.rsplit(None, 1)
            fval = float(val)
        except ValueError:
            continue
        if key.endswith("}") and '_bucket{le="' in key:
            name, le = key[:-2].split('_bucket{le="', 1)
            h = hists.setdefault(name, {"buckets": [], "sum": 0.0,
                                        "count": 0.0})
            le_f = float("inf") if le == "+Inf" else float(le)
            h["buckets"].append((le_f, fval))
            if exemplar is not None:
                h.setdefault("exemplars", []).append(
                    {"le": le_f, "trace_id": exemplar[0],
                     "value": exemplar[1]})
        elif key.endswith("_sum") and key[:-4] in hists:
            hists[key[:-4]]["sum"] = fval
        elif key.endswith("_count") and key[:-6] in hists:
            hists[key[:-6]]["count"] = fval
        elif "{" not in key:
            out[key] = fval
    out.update(hists)
    return out


def worst_exemplar(h: Any) -> Optional[Dict[str, Any]]:
    """The highest-bucket exemplar of a parsed histogram — the trace_id
    to feed ``dstpu-trace --request`` for this histogram's tail."""
    if not isinstance(h, dict):
        return None
    exs = h.get("exemplars") or []
    if not exs:
        return None
    return max(exs, key=lambda e: e.get("le", 0.0))


def hist_percentile(h: Dict[str, Any], p: float,
                    prev: Optional[Dict[str, Any]] = None
                    ) -> Optional[float]:
    """Percentile from parsed exposition buckets; when ``prev`` (the
    previous poll of the same histogram) is given and compatible, judge
    only the samples recorded between the two polls."""
    buckets = sorted(h.get("buckets", []))
    if not buckets:
        return None
    cum = [c for _, c in buckets]
    if prev is not None:
        pb = sorted(prev.get("buckets", []))
        if len(pb) == len(buckets) and \
                all(abs(a[0] - b[0]) < 1e-12 or (a[0] == b[0])
                    for a, b in zip(pb, buckets)):
            pc = [c for _, c in pb]
            if all(c >= q for c, q in zip(cum, pc)):
                cum = [c - q for c, q in zip(cum, pc)]
    counts = [cum[0]] + [cum[i] - cum[i - 1] for i in range(1, len(cum))]
    total = cum[-1]
    if total <= 0:
        return None
    bounds = [le for le, _ in buckets if le != float("inf")]
    return percentile_from_counts(bounds, counts, int(total), p,
                                  vmax=bounds[-1] if bounds else None)


def _first(d: Dict[str, Any], names) -> Optional[float]:
    for n in names:
        v = d.get(n)
        if isinstance(v, (int, float)):
            return float(v)
    return None


class HostSample:
    """One poll of one host, plus derivatives vs the previous poll."""

    def __init__(self, target: str):
        self.target = target
        self.ts: Optional[float] = None
        self.ok = False
        self.status = "down"
        self.reason = ""
        self.metrics: Dict[str, Any] = {}
        self.prev_metrics: Dict[str, Any] = {}
        self.prev_ts: Optional[float] = None

    def _rate(self, names) -> Optional[float]:
        if self.prev_ts is None or self.ts is None or \
                self.ts <= self.prev_ts:
            return None
        cur = _first(self.metrics, names)
        prev = _first(self.prev_metrics, names)
        if cur is None or prev is None or cur < prev:
            return None
        return (cur - prev) / (self.ts - self.prev_ts)

    def row(self, now: float) -> Dict[str, Any]:
        m = self.metrics
        ttft = m.get("serving_ttft_seconds")
        tpot = m.get("serving_tpot_seconds")
        gp = goodput_state(m)
        return {
            "host": self.target,
            "status": self.status,
            "reason": self.reason,
            "step": _first(m, STEP_COUNTERS),
            "step_rate": self._rate(STEP_COUNTERS),
            "mfu": _first(m, MFU_GAUGES),
            "queue": _first(m, QUEUE_GAUGES),
            "ttft_p95_ms": None if not isinstance(ttft, dict) else
            _ms(hist_percentile(ttft, 95,
                                self.prev_metrics.get(
                                    "serving_ttft_seconds"))),
            "tpot_p99_ms": None if not isinstance(tpot, dict) else
            _ms(hist_percentile(tpot, 99,
                                self.prev_metrics.get(
                                    "serving_tpot_seconds"))),
            "tok_rate": self._rate(TOKEN_COUNTERS),
            "burn": _first(m, BURN_GAUGES),
            "stale_s": None if self.ts is None else max(0.0, now - self.ts),
            "router": router_states(m),
            "autoscale": autoscale_targets(m),
            "kvtier": kvtier_state(m),
            "exemplars": latency_exemplars(m),
            "health": health_state(m),
            "goodput_pct": None if gp is None else
            100.0 * gp["fraction"],
            "goodput": gp,
        }


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else v * 1000.0


def router_states(metrics: Dict[str, Any]) -> Optional[Dict[str, str]]:
    """Per-replica router state from a host's parsed exposition
    (``router_replica_<name>_state`` gauges); None when the host does
    not run a router."""
    states = {}
    for key, val in metrics.items():
        m = _ROUTER_STATE_RE.match(key)
        if m and isinstance(val, (int, float)):
            states[m.group(1)] = ROUTER_STATES.get(float(val),
                                                   f"state_{val:g}")
    return dict(sorted(states.items())) or None


def kvtier_state(metrics: Dict[str, Any]) -> Optional[Dict[str, float]]:
    """Host-tier residency + flow from a host's parsed exposition
    (``kvtier_*`` gauges/counters published by serving/kvtier.py); None
    when the host runs no KV tier."""
    out = {}
    for short, name in (("dram", "kvtier_dram_pages"),
                        ("nvme", "kvtier_nvme_pages"),
                        ("hits", "kvtier_hits"),
                        ("spills", "kvtier_spills"),
                        ("adopts", "kvtier_adopts")):
        v = metrics.get(name)
        if isinstance(v, (int, float)):
            out[short] = float(v)
    return out or None


def health_state(metrics: Dict[str, Any]) -> Optional[Dict[str, float]]:
    """Model-health localizer state from a host's parsed exposition
    (the ``health_*`` gauges telemetry/health.py publishes). Reported
    only while the anomaly latch is up — a healthy host stays one line
    in the table. None when health telemetry is off or quiet."""
    flag = metrics.get("health_anomaly")
    if not isinstance(flag, (int, float)) or flag <= 0:
        return None
    out = {}
    for short, name in (("layer", "health_worst_layer"),
                        ("z", "health_worst_layer_z"),
                        ("dead", "health_dead_experts"),
                        ("expert", "health_worst_expert"),
                        ("load", "health_worst_expert_load")):
        v = metrics.get(name)
        if isinstance(v, (int, float)):
            out[short] = float(v)
    return out or None


def goodput_state(metrics: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Goodput ledger state from a host's parsed exposition (the
    ``goodput_*`` gauges telemetry/goodput.py publishes): lifetime
    fraction plus the dominant badput category and its seconds. None
    when the host does not run the ledger."""
    frac = metrics.get("goodput_fraction")
    if not isinstance(frac, (int, float)):
        return None
    from deepspeed_tpu.telemetry.goodput import CATEGORIES
    badput = {}
    for cat in CATEGORIES:
        if cat == "goodput":
            continue
        v = metrics.get(f"goodput_{cat}_s")
        if isinstance(v, (int, float)) and v > 0:
            badput[cat] = float(v)
    dominant = max(badput, key=badput.get) if badput else None
    return {"fraction": float(frac), "badput": badput,
            "dominant_badput": dominant,
            "dominant_badput_s": badput.get(dominant, 0.0)}


def latency_exemplars(metrics: Dict[str, Any]
                      ) -> Optional[Dict[str, Dict[str, Any]]]:
    """Worst-bucket latency exemplars from a host's parsed exposition —
    the trace_ids an operator feeds ``dstpu-trace --request`` to see
    exactly where the tail request's time went. None when the host
    exposes no exemplars (request tracing off)."""
    out: Dict[str, Dict[str, Any]] = {}
    for short, name in (("ttft", "serving_ttft_seconds"),
                        ("tpot", "serving_tpot_seconds"),
                        ("router_ttft", "router_ttft_seconds")):
        ex = worst_exemplar(metrics.get(name))
        if ex is not None:
            out[short] = ex
    return out or None


def autoscale_targets(metrics: Dict[str, Any]) -> \
        Optional[Dict[str, Dict[str, int]]]:
    """Per-pool ``live/target`` replica counts from a host's parsed
    exposition (``autoscale_target_<pool>`` / ``autoscale_replicas_``
    ``<pool>`` gauges); None when the host runs no autoscaler."""
    pools: Dict[str, Dict[str, int]] = {}
    for key, val in metrics.items():
        m = _AUTOSCALE_RE.match(key)
        if m and isinstance(val, (int, float)):
            what, pool = m.group(1), m.group(2)
            pools.setdefault(pool, {})[
                "target" if what == "target" else "live"] = int(val)
    return dict(sorted(pools.items())) or None


def _http_get(url: str, timeout: float) -> Tuple[int, str]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:                   # 503 carries body
        return e.code, e.read().decode("utf-8", "replace")


def poll_host(sample: HostSample, timeout: float = DEFAULT_TIMEOUT_S,
              clock=time.monotonic) -> HostSample:
    """Refresh one live host sample from /metrics + /healthz.

    ``clock`` stamps the sample time used for staleness and rate math;
    it defaults to ``time.monotonic`` so an NTP wall-clock step between
    polls can neither inflate staleness nor flip a rate negative — the
    serving router's circuit breaker reuses this poller, and a breaker
    that flaps on clock adjustments would drain a healthy replica."""
    base = sample.target if "://" in sample.target \
        else f"http://{sample.target}"
    sample.prev_metrics, sample.prev_ts = sample.metrics, sample.ts
    try:
        _, text = _http_get(f"{base}/metrics", timeout)
        sample.metrics = parse_prometheus_text(text)
        sample.ts = clock()
        sample.ok = True
    except Exception as e:                                # noqa: BLE001
        sample.ok = False
        sample.status, sample.reason = "down", str(e)
        return sample
    try:
        code, body = _http_get(f"{base}/healthz", timeout)
        doc = json.loads(body)
        sample.status = doc.get("status", "ok" if code == 200 else "bad")
        sample.reason = doc.get("reason", "")
    except Exception as e:                                # noqa: BLE001
        sample.status, sample.reason = "no_healthz", str(e)
    return sample


def rows_from_history(paths: List[str],
                      clock=time.time) -> List[Dict[str, Any]]:
    """Offline mode: one table row per host from history files (last
    record per host; rates from the last two records)."""
    by_host: Dict[str, List[Dict[str, Any]]] = {}
    for p in paths:
        for rec in load_records(p):
            by_host.setdefault(rec.get("host", p), []).append(rec)
    now = clock()
    rows = []
    for host, recs in sorted(by_host.items()):
        recs.sort(key=lambda r: (r.get("ts", 0.0), r.get("step", 0)))
        last = recs[-1]

        def metric(names, prefer_interval=False, rec=last):
            for n in names:
                v = resolve_metric(rec, n, prefer_interval=prefer_interval)
                if v is not None:
                    return v
            return None

        def rate(names):
            if len(recs) < 2:
                return None
            a, b = recs[-2], recs[-1]
            dt = b.get("ts", 0.0) - a.get("ts", 0.0)
            va, vb = metric(names, rec=a), metric(names, rec=b)
            if dt <= 0 or va is None or vb is None or vb < va:
                return None
            return (vb - va) / dt

        breached = metric(("slo/breached",))
        gfrac = metric(("goodput/fraction",))
        gp = None
        if gfrac is not None:
            from deepspeed_tpu.telemetry.goodput import CATEGORIES
            badput = {}
            for cat in CATEGORIES:
                if cat == "goodput":
                    continue
                v = metric((f"goodput/{cat}_s",))
                if v is not None and v > 0:
                    badput[cat] = float(v)
            dominant = max(badput, key=badput.get) if badput else None
            gp = {"fraction": float(gfrac), "badput": badput,
                  "dominant_badput": dominant,
                  "dominant_badput_s": badput.get(dominant, 0.0)}
        health = None
        if metric(("health/anomaly",)):
            health = {}
            for short, name in (("layer", "health/worst_layer"),
                                ("z", "health/worst_layer_z"),
                                ("dead", "health/dead_experts"),
                                ("expert", "health/worst_expert"),
                                ("load", "health/worst_expert_load")):
                v = metric((name,))
                if v is not None:
                    health[short] = float(v)
            health = health or None
        rows.append({
            "host": host,
            "status": "degraded" if breached else "ok",
            "reason": "slo breach" if breached else "",
            "step": metric(H_STEP),
            "step_rate": rate(H_STEP),
            "mfu": metric(H_MFU),
            "queue": metric(H_QUEUE),
            "ttft_p95_ms": _ms(metric(("serving/ttft_seconds:p95",),
                                      prefer_interval=True)),
            "tpot_p99_ms": _ms(metric(("serving/tpot_seconds:p99",),
                                      prefer_interval=True)),
            "tok_rate": rate(H_TOKENS),
            "burn": metric(H_BURN),
            "stale_s": max(0.0, now - last.get("ts", now)),
            "health": health,
            "goodput_pct": None if gp is None else
            100.0 * gp["fraction"],
            "goodput": gp,
        })
    return rows


def publish_fleet_gauges(rows: List[Dict[str, Any]]) -> None:
    """Republish fleet aggregates into the local registry so whoever
    runs dstpu-top can itself be scraped."""
    registry.gauge("fleet/hosts").set(float(len(rows)))
    registry.gauge("fleet/hosts_degraded").set(
        float(sum(1 for r in rows if r["status"] not in ("ok",))))
    stales = [r["stale_s"] for r in rows if r["stale_s"] is not None]
    registry.gauge("fleet/staleness_s_max").set(max(stales, default=0.0))
    burns = [r["burn"] for r in rows if r["burn"] is not None]
    registry.gauge("fleet/worst_burn").set(max(burns, default=0.0))
    fracs = [r["goodput_pct"] / 100.0 for r in rows
             if r.get("goodput_pct") is not None]
    if fracs:
        registry.gauge(
            "fleet/goodput_fraction",
            help="mean lifetime goodput fraction over reporting hosts"
        ).set(sum(fracs) / len(fracs))


_COLS = [
    ("HOST", "host", "{}", 22),
    ("STAT", "status", "{}", 9),
    ("STEP", "step", "{:.0f}", 8),
    ("STEP/S", "step_rate", "{:.2f}", 7),
    ("MFU", "mfu", "{:.3f}", 6),
    ("QUEUE", "queue", "{:.1f}", 6),
    ("TTFT*", "ttft_p95_ms", "{:.1f}", 8),
    ("TPOT*", "tpot_p99_ms", "{:.1f}", 8),
    ("TOK/S", "tok_rate", "{:.1f}", 8),
    ("BURN", "burn", "{:.2f}", 6),
    ("GOOD%", "goodput_pct", "{:.0f}", 5),
    ("STALE", "stale_s", "{:.0f}s", 6),
]


def render_table(rows: List[Dict[str, Any]]) -> str:
    """Fixed-width fleet table (``*`` columns are interval p95/p99 ms)."""
    lines = [" ".join(h.ljust(w) for h, _, _, w in _COLS)]
    for r in rows:
        cells = []
        for _, key, fmt, w in _COLS:
            v = r.get(key)
            cell = "-" if v is None else fmt.format(v)
            cells.append(cell[:w].ljust(w))
        lines.append(" ".join(cells))
        if r.get("reason"):
            lines.append(f"    └─ {r['reason']}")
        if r.get("router"):
            pairs = " ".join(f"{n}={s}" for n, s in r["router"].items())
            lines.append(f"    └─ router: {pairs}")
        if r.get("autoscale"):
            pairs = " ".join(
                f"{pool}={d.get('live', '?')}/{d.get('target', '?')}"
                for pool, d in r["autoscale"].items())
            lines.append(f"    └─ autoscale (live/target): {pairs}")
        if r.get("kvtier"):
            pairs = " ".join(f"{k}={v:g}"
                             for k, v in r["kvtier"].items())
            lines.append(f"    └─ kvtier: {pairs}")
        if r.get("exemplars"):
            pairs = " ".join(
                f"{k}={e.get('trace_id')}"
                + (f"@{e['value'] * 1e3:.0f}ms"
                   if isinstance(e.get("value"), (int, float)) else "")
                for k, e in r["exemplars"].items())
            lines.append(f"    └─ tail exemplars: {pairs}")
        h = r.get("health")
        if h:
            bits = []
            if "layer" in h:
                bits.append(f"worst layer {h['layer']:.0f}"
                            + (f" z={h['z']:+.1f}" if "z" in h else ""))
            if h.get("dead"):
                bits.append(
                    f"dead experts {h['dead']:.0f}"
                    + (f" (worst {h['expert']:.0f}@{h['load']:.4f})"
                       if "expert" in h else ""))
            if bits:
                lines.append("    └─ health: " + ", ".join(bits))
        gp = r.get("goodput")
        if gp and gp.get("dominant_badput"):
            lines.append(f"    └─ badput: dominant "
                         f"{gp['dominant_badput']} "
                         f"({gp['dominant_badput_s']:.1f}s)")
    degraded = sum(1 for r in rows if r["status"] not in ("ok",))
    lines.append(f"hosts: {len(rows)}  degraded: {degraded}  "
                 f"(* = interval percentile, ms)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-top",
        description="live terminal fleet view over dstpu /metrics + "
                    "/healthz endpoints, or offline over metric history "
                    "files")
    ap.add_argument("targets", nargs="*",
                    help="host:port of /metrics endpoints to poll")
    ap.add_argument("--history", nargs="+", default=None, metavar="FILE",
                    help="offline mode: per-host metric history JSONL "
                         "files instead of live endpoints")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (CI / tests); exit "
                         "0 healthy, 2 degraded/down hosts, 3 fleet "
                         "goodput below --min-goodput")
    ap.add_argument("--min-goodput", type=float, default=None,
                    metavar="FRAC",
                    help="with --once: exit 3 when the fleet mean "
                         "goodput fraction (hosts running the ledger) "
                         "is below this floor, 0-1")
    ap.add_argument("--json", action="store_true",
                    help="emit rows as JSON instead of the table")
    ap.add_argument("--interval", type=float, default=DEFAULT_INTERVAL_S,
                    help="refresh period, seconds (default %(default)s)")
    ap.add_argument("--timeout", type=float, default=DEFAULT_TIMEOUT_S,
                    help="per-request HTTP timeout, seconds")
    args = ap.parse_args(argv)
    if bool(args.targets) == bool(args.history):
        ap.error("give either live targets or --history files (not both)")

    samples = [HostSample(t) for t in args.targets]
    first = True
    while True:
        if args.history:
            rows = rows_from_history(args.history)
        else:
            # same monotonic clock poll_host stamps samples with — the
            # staleness column must not move when NTP steps the wall clock
            now = time.monotonic()
            rows = [poll_host(s, timeout=args.timeout).row(now)
                    for s in samples]
        publish_fleet_gauges(rows)
        if args.json:
            out = json.dumps(rows, default=float)
        else:
            out = render_table(rows)
        if not args.once and not first and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")          # clear + home
        print(out)
        if args.once:
            degraded = sum(1 for r in rows
                           if r["status"] not in ("ok",))
            if degraded:
                return 2        # degraded outranks the goodput floor
            if args.min_goodput is not None:
                fracs = [r["goodput_pct"] / 100.0 for r in rows
                         if r.get("goodput_pct") is not None]
                if fracs and sum(fracs) / len(fracs) < args.min_goodput:
                    return 3
            return 0
        first = False
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
