"""deepspeed_tpu.telemetry.health — model-health observability: on-device
per-layer training dynamics + MoE expert-load telemetry.

The fused train step computes health statistics IN-GRAPH every step when
``telemetry.health.enabled`` is set (per-layer gradient/parameter/update
norms from the optimizer side, activation RMS/absmax and MoE router
load/entropy from the forward's layer scan — all static-flag branches
baked at trace time, so on- and off-cadence steps execute the identical
program and nothing ever retraces). The engine hands the device arrays to
:class:`HealthMonitor.note` every step; off-cadence steps drop the refs
without any host transfer, and every ``telemetry.health.every``-th step
does ONE batched ``jax.device_get`` and publishes:

- ``health/layer/{i}/*`` per-layer gauges (grad_norm, param_norm,
  update_ratio, act_rms, act_absmax, aux_loss);
- ``health/expert/{e}/load`` + routing aggregates (entropy, dead count);
- worst-layer / worst-expert + the latched ``health/anomaly`` flag that
  dstpu-top renders as a per-host health sub-line.

The same host-side vectors feed the per-layer z-score localizer
(:meth:`AnomalyDetector.observe_layers` / ``observe_experts``), which
names WHICH layer or expert diverged — ``anomaly/layer_divergence`` /
``anomaly/expert_collapse`` flags that latch into the flight-recorder
black box and surface as dstpu-doctor LAYER DIVERGENCE / EXPERT COLLAPSE
verdicts.

``bin/dstpu-health`` renders the history offline (per-layer sparkline /
heatmap table over metric-history JSONL), live (``--watch`` over a
``/metrics`` endpoint), and self-checks the whole chain (``--selftest``:
a seeded divergence drill — one layer's grads scaled, one expert starved
— asserting the localizer and the doctor name exactly them).
"""

import argparse
import json
import math
import re
import sys
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from deepspeed_tpu.utils.logging import logger

#: per-layer stat keys the engine/forward emit, in catalog order; the
#: vector for each becomes ``health/layer/{i}/<key>`` gauges
PER_LAYER_KEYS = ("grad_norm", "param_norm", "update_ratio",
                  "act_rms", "act_absmax", "aux_loss")

#: every ``health/*`` stat name this module publishes — linted by
#: tools/check_metric_names.py against docs/observability.md (mirrors
#: the fault-kind / goodput-category catalogs): an undocumented health
#: stat is a gauge nobody can interpret from the runbook
HEALTH_STATS = (
    "health/layer/{i}/grad_norm",
    "health/layer/{i}/param_norm",
    "health/layer/{i}/update_ratio",
    "health/layer/{i}/act_rms",
    "health/layer/{i}/act_absmax",
    "health/layer/{i}/aux_loss",
    "health/expert/{e}/load",
    "health/router_entropy",
    "health/dead_experts",
    "health/aux_loss",
    "health/layers",
    "health/worst_layer",
    "health/worst_layer_z",
    "health/worst_expert",
    "health/worst_expert_load",
    "health/anomaly",
)

#: publish cadences the ``health/anomaly`` flag stays latched after the
#: last localizer hit (so a scrape/top poll between cadences still
#: sees it)
LATCH_CADENCES = 4

#: default fetch/publish cadence (steps) when unconfigured
DEFAULT_EVERY = 50

_LAYER_RE = re.compile(r"^health_layer_(\d+)_([a-z0-9_]+)$")
_EXPERT_RE = re.compile(r"^health_expert_(\d+)_load$")

_BLOCKS = "▁▂▃▄▅▆▇█"


class HealthMonitor:
    """Engine-side cadence gate + publisher.

    The engine calls :meth:`note` EVERY step with the device-resident
    stat pytree the jitted step returned; the monitor drops off-cadence
    refs unfetched (zero extra host round-trips) and on cadence performs
    one batched transfer, publishes the ``health/*`` gauges, and feeds
    the anomaly localizer.
    """

    def __init__(self, every: int = DEFAULT_EVERY, max_layers: int = 0,
                 z_threshold: Optional[float] = None,
                 dead_fraction: Optional[float] = None,
                 detector: Optional[Any] = None):
        self.every = max(1, int(every))
        self.max_layers = max(0, int(max_layers))
        self.z_threshold = z_threshold
        self.dead_fraction = dead_fraction
        self._detector = detector
        self._latch = 0
        #: last published host-side payload (tests / debugging)
        self.last: Optional[Dict[str, Any]] = None

    @property
    def detector(self):
        if self._detector is None:
            from deepspeed_tpu.telemetry.anomaly import anomaly_detector
            self._detector = anomaly_detector
        return self._detector

    # -- engine hook ---------------------------------------------------------

    def note(self, step: int, stats: Optional[Dict[str, Any]] = None,
             aux_loss: Optional[Any] = None) -> Optional[List[Dict[str, Any]]]:
        """Per-step hook. ``stats``/``aux_loss`` are device arrays (or
        None); only every ``self.every``-th step transfers and publishes.
        Returns the localizer flags raised by this publish (None when the
        step was off-cadence)."""
        if stats is None and aux_loss is None:
            return None
        if step % self.every:
            return None
        try:
            import jax
            stats, aux_loss = jax.device_get((stats, aux_loss))
        except Exception:
            logger.warning("health: device fetch failed", exc_info=True)
            return None
        return self.publish(step, stats, aux_loss=aux_loss)

    # -- publish -------------------------------------------------------------

    def publish(self, step: int, stats: Optional[Dict[str, Any]],
                aux_loss: Optional[float] = None) -> List[Dict[str, Any]]:
        """Publish HOST-side stats as gauges + run the localizer. Split
        from :meth:`note` so drills/tests can inject synthetic vectors
        without a device in the loop."""
        import numpy as np
        from deepspeed_tpu.telemetry.registry import registry

        def g(name: str, v: float) -> None:
            registry.gauge(name).set(float(v))

        stats = dict(stats or {})
        if aux_loss is not None and np.ndim(aux_loss) == 0:
            g("train/aux_loss", aux_loss)
            g("health/aux_loss", aux_loss)

        per_layer: Dict[str, Any] = {}
        layers = 0
        for k in PER_LAYER_KEYS:
            v = stats.get(k)
            if v is None:
                continue
            arr = np.asarray(v, dtype=np.float64).reshape(-1)
            per_layer[k] = arr
            layers = max(layers, len(arr))
        if layers:
            g("health/layers", layers)
            cap = self.max_layers or layers
            for k, arr in per_layer.items():
                for i in range(min(cap, len(arr))):
                    g(f"health/layer/{i}/{k}", arr[i])

        load = None
        el = stats.get("expert_load")
        if el is not None:
            el = np.asarray(el, dtype=np.float64)
            # forward taps stack [L, E] — average the MoE layers for the
            # per-expert gauges; the localizer sees the same aggregate
            load = el.reshape(-1, el.shape[-1]).mean(axis=0) \
                if el.ndim > 1 else el
            for i, v in enumerate(load):
                g(f"health/expert/{i}/load", v)
            from deepspeed_tpu.telemetry.anomaly import DEAD_EXPERT_FRACTION
            df = self.dead_fraction if self.dead_fraction is not None \
                else DEAD_EXPERT_FRACTION
            dead = int((load < df / max(len(load), 1)).sum())
            g("health/dead_experts", dead)
            wi = int(load.argmin())
            g("health/worst_expert", wi)
            g("health/worst_expert_load", load[wi])
        re_ = stats.get("router_entropy")
        if re_ is not None:
            g("health/router_entropy", float(np.mean(re_)))

        flags: List[Dict[str, Any]] = []
        det = self.detector
        if det is not None:
            if any(k in per_layer for k in ("grad_norm", "act_rms",
                                            "act_absmax")):
                flags += det.observe_layers(
                    step, grad_norms=per_layer.get("grad_norm"),
                    act_rms=per_layer.get("act_rms"),
                    act_absmax=per_layer.get("act_absmax"),
                    z_threshold=self.z_threshold)
            if load is not None and len(load):
                flags += det.observe_experts(
                    step, load, dead_fraction=self.dead_fraction)
            ws = getattr(det, "last_layer_score", None)
            if ws:
                g("health/worst_layer", ws["layer"])
                g("health/worst_layer_z", ws["z"])
        if flags:
            self._latch = LATCH_CADENCES
        g("health/anomaly", 1.0 if self._latch > 0 else 0.0)
        if self._latch > 0:
            self._latch -= 1
        self.last = {"step": step, "layers": layers,
                     "stats": {k: v.tolist() for k, v in per_layer.items()},
                     "expert_load": None if load is None else load.tolist(),
                     "flags": flags}
        return flags


# ---------------------------------------------------------------------------
# Offline / live rendering (dstpu-health)
# ---------------------------------------------------------------------------

def _flatten(record: Dict[str, Any]) -> Dict[str, float]:
    """History record → flat {prom_name: value} (same shape as a parsed
    /metrics exposition), so one rendering path serves both modes."""
    out: Dict[str, float] = {}
    for k, v in record.get("m", {}).items():
        if isinstance(v, (int, float)):
            out[k.replace("/", "_")] = float(v)
    return out


def sparkline(vals: Sequence[float], width: int = 32) -> str:
    """Unicode block sparkline, normalized over the series' own range."""
    vals = [v for v in vals if v is not None and math.isfinite(v)]
    if not vals:
        return ""
    if len(vals) > width:
        # downsample: mean over equal chunks keeps spikes visible enough
        # while the table stays one terminal line per layer
        chunk = len(vals) / width
        vals = [sum(vals[int(j * chunk):max(int(j * chunk) + 1,
                                            int((j + 1) * chunk))])
                / max(1, len(vals[int(j * chunk):max(int(j * chunk) + 1,
                                                     int((j + 1) * chunk))]))
                for j in range(width)]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _BLOCKS[0] * len(vals)
    return "".join(_BLOCKS[min(len(_BLOCKS) - 1,
                               int((v - lo) / span * len(_BLOCKS)))]
                   for v in vals)


def _series_z(series: List[float]) -> Optional[float]:
    """z of the last sample against the rest of its own series (same
    epsilon-floored convention as the online localizer)."""
    head, last = series[:-1], series[-1]
    head = [v for v in head if math.isfinite(v)]
    if len(head) < 2 or not math.isfinite(last):
        return None
    mean = sum(head) / len(head)
    var = sum((v - mean) ** 2 for v in head) / len(head)
    std = max(math.sqrt(var), 1e-6 * max(abs(mean), 1.0))
    return (last - mean) / std


def report_from_frames(frames: List[Dict[str, float]],
                       stat: str = "grad_norm") -> Dict[str, Any]:
    """Flat metric frames (oldest first) → structured health report."""
    layer_series: Dict[int, List[float]] = {}
    expert_series: Dict[int, List[float]] = {}
    for fr in frames:
        for k, v in fr.items():
            m = _LAYER_RE.match(k)
            if m and m.group(2) == stat:
                layer_series.setdefault(int(m.group(1)), []).append(v)
                continue
            m = _EXPERT_RE.match(k)
            if m:
                expert_series.setdefault(int(m.group(1)), []).append(v)
    last = frames[-1] if frames else {}
    layers = [{"layer": i, "series": s, "last": s[-1],
               "z": _series_z(s)}
              for i, s in sorted(layer_series.items())]
    experts = [{"expert": i, "series": s, "last": s[-1]}
               for i, s in sorted(expert_series.items())]
    agg = {k: last.get("health_" + k)
           for k in ("layers", "router_entropy", "dead_experts",
                     "worst_layer", "worst_layer_z", "worst_expert",
                     "worst_expert_load", "anomaly", "aux_loss")
           if last.get("health_" + k) is not None}
    return {"stat": stat, "frames": len(frames), "layers": layers,
            "experts": experts, "aggregates": agg}


def render_report(report: Dict[str, Any], width: int = 32) -> str:
    out: List[str] = []
    agg = report["aggregates"]
    out.append(f"== dstpu-health · {report['stat']} · "
               f"{report['frames']} sample(s) ==")
    if agg:
        bits = [f"{k}={agg[k]:.4g}" for k in
                ("router_entropy", "dead_experts", "aux_loss") if k in agg]
        if "worst_layer" in agg:
            bits.append(f"worst_layer={int(agg['worst_layer'])} "
                        f"(z={agg.get('worst_layer_z', 0.0):+.1f})")
        if agg.get("anomaly"):
            bits.append("ANOMALY LATCHED")
        if bits:
            out.append("  " + "  ".join(bits))
    if not report["layers"]:
        out.append(f"  (no health/layer/*/{report['stat']} samples — is "
                   f"telemetry.health enabled and the cadence reached?)")
    else:
        out.append("")
        out.append(f"  {'layer':>5}  {'history':<{width}}  "
                   f"{'last':>10}  {'z':>6}")
        for row in report["layers"]:
            z = f"{row['z']:+.1f}" if row["z"] is not None else "-"
            out.append(f"  {row['layer']:>5}  "
                       f"{sparkline(row['series'], width):<{width}}  "
                       f"{row['last']:>10.4g}  {z:>6}")
    if report["experts"]:
        out.append("")
        out.append(f"  {'expert':>6}  {'load':<{width}}  {'last':>10}")
        for row in report["experts"]:
            out.append(f"  {row['expert']:>6}  "
                       f"{sparkline(row['series'], width):<{width}}  "
                       f"{row['last']:>10.4g}")
    return "\n".join(out)


def _fetch_frame(url: str, timeout: float = 5.0) -> Dict[str, float]:
    from urllib.request import urlopen
    from deepspeed_tpu.telemetry.fleet import parse_prometheus_text
    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urlopen(url, timeout=timeout) as resp:
        return parse_prometheus_text(resp.read().decode("utf-8", "replace"))


def watch(url: str, stat: str, interval: float, once: bool,
          as_json: bool, max_frames: int = 64) -> int:
    frames: deque = deque(maxlen=max_frames)
    while True:
        try:
            frames.append(_fetch_frame(url))
        except Exception as e:
            print(f"dstpu-health: fetch {url} failed: {e}", file=sys.stderr)
            if once:
                return 2
            time.sleep(interval)
            continue
        report = report_from_frames(list(frames), stat=stat)
        if as_json:
            print(json.dumps({k: v for k, v in report.items()
                              if k != "layers"} |
                             {"layers": [{k2: v2 for k2, v2 in r.items()
                                          if k2 != "series"}
                                         for r in report["layers"]]}))
        else:
            if not once:
                print("\x1b[2J\x1b[H", end="")
            print(render_report(report))
        if once:
            return 0
        time.sleep(interval)


# ---------------------------------------------------------------------------
# Selftest: the seeded divergence drill as a tier-1 smoke
# ---------------------------------------------------------------------------

def selftest() -> int:
    """Synthetic end-to-end drill: 8 layers / 4 experts, layer 5's grad
    norm scaled 100x late in the run, expert 2 starved throughout.
    Passes iff the localizer names EXACTLY that layer and expert, the
    gauges landed, dstpu-doctor's verdict names the layer, and the
    offline renderer draws the table."""
    import numpy as np
    from deepspeed_tpu.telemetry.anomaly import AnomalyDetector
    from deepspeed_tpu.telemetry.registry import registry
    from deepspeed_tpu.telemetry import doctor

    L, E, DIV_LAYER, DEAD_EXPERT = 8, 4, 5, 2
    det = AnomalyDetector()
    mon = HealthMonitor(every=1, detector=det)
    frames: List[Dict[str, float]] = []
    failures: List[str] = []

    for step in range(1, 25):
        base = np.array([0.01 * (1 + i) for i in range(L)])
        # deterministic jitter: realistic non-constant windows
        base = base * (1.0 + 0.001 * ((step * 7 + np.arange(L)) % 5 - 2))
        if step >= 20:
            base[DIV_LAYER] *= 100.0          # the seeded divergence
        load = np.full(E, (1.0 - 0.001) / (E - 1))
        load[DEAD_EXPERT] = 0.001             # the starved expert
        stats = {"grad_norm": base, "param_norm": np.ones(L),
                 "update_ratio": np.full(L, 1e-3),
                 "act_rms": np.ones(L), "act_absmax": np.ones(L) * 3,
                 "aux_loss": np.full(L, 0.01 / L),
                 "expert_load": np.tile(load, (L, 1)),
                 "router_entropy": np.full(L, 1.2)}
        mon.publish(step, stats, aux_loss=0.01)
        snap = registry.snapshot(interval=False)
        frames.append({k.replace("/", "_"): v for k, v in snap.items()
                       if k.startswith("health/")
                       and isinstance(v, (int, float))})

    div_layers = {a.get("layer") for a in det.anomalies
                  if a["kind"] == "layer_divergence"}
    dead_experts = {a.get("expert") for a in det.anomalies
                    if a["kind"] == "expert_collapse"}
    if div_layers != {DIV_LAYER}:
        failures.append(f"localizer named layers {sorted(div_layers)}, "
                        f"want exactly {{{DIV_LAYER}}}")
    if dead_experts != {DEAD_EXPERT}:
        failures.append(f"localizer named experts {sorted(dead_experts)}, "
                        f"want exactly {{{DEAD_EXPERT}}}")

    snap = registry.snapshot(interval=False)
    for name in (f"health/layer/{DIV_LAYER}/grad_norm",
                 f"health/expert/{DEAD_EXPERT}/load",
                 "health/dead_experts", "health/worst_layer",
                 "health/anomaly", "train/aux_loss"):
        if name not in snap:
            failures.append(f"gauge {name} never published")
    if snap.get("health/anomaly") != 1.0:
        failures.append("health/anomaly flag not latched after the drill")
    if snap.get("health/worst_layer") != float(DIV_LAYER):
        failures.append(f"health/worst_layer={snap.get('health/worst_layer')}"
                        f", want {DIV_LAYER}")

    events = [{**{k: v for k, v in rec.items() if k != "kind"},
               "kind": "anomaly", "anomaly": rec["kind"]}
              for rec in det.anomalies]
    report = doctor.analyze([{"meta": {"hostname": "selftest"},
                              "steps": [], "events": events}])
    verdict = report["verdict"]
    if not verdict.startswith("LAYER DIVERGENCE") or \
            f"layer {DIV_LAYER}" not in verdict:
        failures.append(f"doctor verdict doesn't name the layer: {verdict!r}")

    table = render_report(report_from_frames(frames))
    if f"{DIV_LAYER:>5}" not in table or "expert" not in table:
        failures.append("renderer dropped the layer/expert tables")

    print(f"dstpu-health selftest: drill over {L} layers / {E} experts, "
          f"divergence seeded into layer {DIV_LAYER} @ step 20, expert "
          f"{DEAD_EXPERT} starved")
    print(f"  localizer: layer_divergence={sorted(div_layers)} "
          f"expert_collapse={sorted(dead_experts)}")
    print(f"  doctor: {verdict}")
    for f in failures:
        print(f"  FAIL: {f}")
    print(f"dstpu-health selftest: "
          f"{'FAILED' if failures else 'OK'}")
    return 1 if failures else 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-health",
        description="Per-layer model-health view: sparkline/heatmap "
                    "table over metric-history JSONL, live over /metrics "
                    "(--watch), or the seeded-divergence selftest.")
    ap.add_argument("history", nargs="*",
                    help="metric-history JSONL file(s) "
                         "(telemetry.history_file)")
    ap.add_argument("--stat", default="grad_norm",
                    choices=list(PER_LAYER_KEYS),
                    help="per-layer stat to render (default grad_norm)")
    ap.add_argument("--last", type=int, default=64, metavar="N",
                    help="use the last N history records (default 64)")
    ap.add_argument("--watch", metavar="URL", default=None,
                    help="poll a /metrics endpoint (host:port or URL) "
                         "and render live")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--watch poll seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="with --watch: render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--selftest", action="store_true",
                    help="run the seeded divergence drill (tier-1 smoke)")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.watch:
        return watch(args.watch, args.stat, args.interval, args.once,
                     args.json)
    if not args.history:
        ap.error("give history JSONL file(s), --watch URL, or --selftest")
    from deepspeed_tpu.telemetry.timeseries import merge_records
    records = merge_records(args.history)
    if args.last > 0:
        records = records[-args.last:]
    frames = [_flatten(r) for r in records]
    report = report_from_frames(frames, stat=args.stat)
    if args.json:
        print(json.dumps(report))
    else:
        print(render_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
