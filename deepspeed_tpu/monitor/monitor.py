"""Experiment monitoring fan-out.

Reference: deepspeed/monitor/monitor.py:30 (MonitorMaster → TensorBoard /
W&B / CSV writers; events written from engine.py:2822). Same fan-out
design; writers degrade to no-ops when their backend isn't installed.
Events are ``(name, value, step)`` triples.
"""

import csv
import os
from typing import List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class _Writer:
    enabled = False

    def write_events(self, events: List[Event]) -> None:  # pragma: no cover
        raise NotImplementedError


class TensorBoardMonitor(_Writer):
    """Reference monitor/tensorboard.py."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
            out = os.path.join(cfg.output_path or "runs", cfg.job_name)
            self.writer = SummaryWriter(log_dir=out)
            self.enabled = True
        except Exception as exc:
            logger.warning(f"tensorboard monitor disabled: {exc}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()


class WandbMonitor(_Writer):
    """Reference monitor/wandb.py."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            import wandb
            wandb.init(project=cfg.project, group=cfg.group, entity=cfg.team)
            self.wandb = wandb
            self.enabled = True
        except Exception as exc:
            logger.warning(f"wandb monitor disabled: {exc}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.wandb.log({name: value}, step=step)


class CSVMonitor(_Writer):
    """Reference monitor/csv_monitor.py — one csv per metric name."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        self.dir = os.path.join(cfg.output_path or "csv_monitor", cfg.job_name)
        os.makedirs(self.dir, exist_ok=True)
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            fname = os.path.join(self.dir,
                                 name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as fh:
                w = csv.writer(fh)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class MonitorMaster(_Writer):
    """Reference monitor/monitor.py:MonitorMaster — rank-0 fan-out."""

    def __init__(self, monitor_config):
        import jax
        self._is_rank0 = jax.process_index() == 0
        self.writers: List[_Writer] = []
        if self._is_rank0:
            for w in (TensorBoardMonitor(monitor_config.tensorboard),
                      WandbMonitor(monitor_config.wandb),
                      CSVMonitor(monitor_config.csv_monitor)):
                if w.enabled:
                    self.writers.append(w)
        self.enabled = bool(self.writers)

    def write_events(self, events: List[Event]) -> None:
        for w in self.writers:
            w.write_events(events)
