"""Experiment monitoring fan-out.

Reference: deepspeed/monitor/monitor.py:30 (MonitorMaster → TensorBoard /
W&B / Comet / CSV writers; events written from engine.py:2822). Same fan-out
design; writers degrade to no-ops when their backend isn't installed.
Events are ``(name, value, step)`` triples.
"""

import csv
import os
from typing import List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class _Writer:
    enabled = False

    def write_events(self, events: List[Event]) -> None:  # pragma: no cover
        raise NotImplementedError


class TensorBoardMonitor(_Writer):
    """Reference monitor/tensorboard.py."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
            out = os.path.join(cfg.output_path or "runs", cfg.job_name)
            self.writer = SummaryWriter(log_dir=out)
            self.enabled = True
        except Exception as exc:
            logger.warning(f"tensorboard monitor disabled: {exc}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()


class WandbMonitor(_Writer):
    """Reference monitor/wandb.py."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            import wandb
            wandb.init(project=cfg.project, group=cfg.group, entity=cfg.team)
            self.wandb = wandb
            self.enabled = True
        except Exception as exc:
            logger.warning(f"wandb monitor disabled: {exc}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.wandb.log({name: value}, step=step)


class CometMonitor(_Writer):
    """Reference monitor/comet.py — comet_ml experiment logging.

    Degrades to a no-op when comet_ml is not installed (it is not baked
    into the TPU image), matching the other writers' behavior."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            import comet_ml
            self.experiment = comet_ml.start(
                api_key=cfg.api_key or None,
                workspace=cfg.workspace or None,
                project=cfg.project or None,
                mode=cfg.mode or None,
                online=cfg.online,
                experiment_key=cfg.experiment_key or None,
            )
            if cfg.experiment_name:
                self.experiment.set_name(cfg.experiment_name)
            self.interval = max(1, int(cfg.samples_log_interval))
            self._last_logged: dict = {}
            self.enabled = True
        except Exception as exc:
            logger.warning(f"comet monitor disabled: {exc}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            # per-metric throttle (reference comet.py EventsLogScheduler):
            # a metric's FIRST occurrence always logs; afterwards only
            # when >= samples_log_interval steps passed since its last
            # send — comet rate-limits server-side, unlike TB/CSV
            last = self._last_logged.get(name)
            if last is not None and step - last < self.interval:
                continue
            self._last_logged[name] = step
            self.experiment.log_metric(name, value, step=step)


class CSVMonitor(_Writer):
    """Reference monitor/csv_monitor.py — one csv per metric name.

    Files are held open across batches (a per-event open/close was ~all
    of the write cost) and flushed after every ``write_events`` batch, so
    rows reach the OS even when the process dies without a clean close
    (crash, ``os._exit``)."""

    def __init__(self, cfg):
        self.enabled = False
        self._files: dict = {}     # metric name -> (file handle, csv writer)
        if not cfg.enabled:
            return
        self.dir = os.path.join(cfg.output_path or "csv_monitor", cfg.job_name)
        os.makedirs(self.dir, exist_ok=True)
        self.enabled = True

    def _writer_for(self, name: str):
        entry = self._files.get(name)
        if entry is None:
            fname = os.path.join(self.dir,
                                 name.replace("/", "_") + ".csv")
            os.makedirs(os.path.dirname(fname), exist_ok=True)
            new = not os.path.exists(fname) or os.path.getsize(fname) == 0
            fh = open(fname, "a", newline="")
            entry = (fh, csv.writer(fh))
            if new:
                entry[1].writerow(["step", name])
            self._files[name] = entry
        return entry

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        touched = set()
        for name, value, step in events:
            fh, w = self._writer_for(name)
            w.writerow([step, value])
            touched.add(name)
        for name in touched:
            self._files[name][0].flush()

    def close(self) -> None:
        for fh, _ in self._files.values():
            fh.close()
        self._files.clear()


class MonitorMaster(_Writer):
    """Reference monitor/monitor.py:MonitorMaster — rank-0 fan-out."""

    def __init__(self, monitor_config):
        import jax
        self._is_rank0 = jax.process_index() == 0
        self.writers: List[_Writer] = []
        if self._is_rank0:
            for w in (TensorBoardMonitor(monitor_config.tensorboard),
                      WandbMonitor(monitor_config.wandb),
                      CometMonitor(monitor_config.comet),
                      CSVMonitor(monitor_config.csv_monitor)):
                if w.enabled:
                    self.writers.append(w)
        self.enabled = bool(self.writers)

    def write_events(self, events: List[Event]) -> None:
        for w in self.writers:
            w.write_events(events)
