"""Progressive Layer Drop (PLD).

Reference: ``runtime/progressive_layer_drop.py:10``
(``ProgressiveLayerDrop``: theta schedule ``(1-theta)·exp(-gamma·t) +
theta``) and the Bert PLD paper's per-layer keep probability (deeper
layers drop more). The reference mutates module attributes each step; here
the schedule is host-side and the stochastic depth itself is a functional
helper composed into a scanned decoder: the per-layer residual branch is
multiplied by a Bernoulli keep/(keep_prob) factor — inverted-dropout
scaling so eval needs no rescale.
"""

import math
from typing import Tuple

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:
    """theta(t) schedule (reference progressive_layer_drop.py:10)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        """Reference update_state: theta ramps from 1 (keep everything)
        down to the configured floor."""
        self.current_theta = (1.0 - self.theta) * math.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True,
                "pld_theta": self.get_theta()}


def layer_keep_probs(num_layers: int, theta: float) -> jnp.ndarray:
    """Per-layer keep probability: p_l = 1 - l/L · (1 - theta) — shallow
    layers almost always run, deep layers drop toward theta (PLD paper
    eq. 2, reference basic usage in the Bert example)."""
    l = jnp.arange(1, num_layers + 1, dtype=jnp.float32)
    return 1.0 - (l / num_layers) * (1.0 - theta)


def pld_keep_mask(rng: jax.Array, num_layers: int, theta: float
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sample this step's keep decisions. Returns (mask [L] of 0/1,
    scale [L]) where scale = 1/p for inverted scaling of kept layers."""
    p = layer_keep_probs(num_layers, theta)
    keep = (jax.random.uniform(rng, (num_layers,)) < p).astype(jnp.float32)
    return keep, keep / jnp.maximum(p, 1e-6)


def apply_pld_branch(keep_scale: jax.Array, residual: jax.Array,
                     branch_out: jax.Array) -> jax.Array:
    """One block's stochastic-depth combine: x + keep/p · f(x). Use inside
    the layer scan with ``keep_scale = scale[l]``."""
    return residual + keep_scale * branch_out
