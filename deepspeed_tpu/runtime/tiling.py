"""TiledLinear — memory-bounded matmul by tile sweep.

Reference: ``runtime/tiling.py`` (``TiledLinear`` splits a big Linear
into in/out tile sub-linears so ZeRO-3 only gathers one tile at a time).
TPU version: a ``lax.scan`` (optionally rematerialized) over weight
tiles — peak live memory is one tile + the accumulator; XLA overlaps the
tile gathers with compute. Used by ALST's TiledMLP for arbitrary-length
sequences (reference runtime/sequence_parallel/ulysses_sp.py:838).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def tiled_linear(x: jax.Array, w: jax.Array,
                 bias: Optional[jax.Array] = None,
                 in_splits: int = 1, out_splits: int = 1,
                 remat: bool = True) -> jax.Array:
    """x: [..., In] @ w: [In, Out] (+bias) with the contraction and/or
    output dimension swept in tiles.

    in_splits > 1: accumulate partial products over input tiles
    (reference TiledLinear in_splits); out_splits > 1: concatenate output
    tiles. Peak live weight = one [In/is, Out/os] tile.
    """
    d_in, d_out = w.shape
    if d_in % in_splits or d_out % out_splits:
        raise ValueError(f"weight {w.shape} not divisible by splits "
                         f"({in_splits}, {out_splits})")
    ti = d_in // in_splits
    to = d_out // out_splits

    def one_out_tile(wo, bo):
        """[..., In] x [In, to] via in-tile accumulation."""
        if in_splits == 1:
            y = x @ wo
        else:
            w_t = wo.reshape(in_splits, ti, to)
            x_t = jnp.moveaxis(x.reshape(x.shape[:-1] + (in_splits, ti)),
                               -2, 0)                  # [is, ..., ti]

            def body(acc, wt_xt):
                wt, xt = wt_xt
                return acc + xt @ wt, None

            step = jax.checkpoint(body) if remat else body
            acc0 = jnp.zeros(x.shape[:-1] + (to,), x.dtype)
            y, _ = lax.scan(step, acc0, (w_t, x_t))
        return y + bo if bo is not None else y

    if out_splits == 1:
        return one_out_tile(w, bias)
    w_o = jnp.moveaxis(w.reshape(d_in, out_splits, to), 1, 0)
    b_o = (jnp.reshape(bias, (out_splits, to)) if bias is not None
           else None)

    def out_body(_, wb):
        wo, bo = wb if b_o is not None else (wb, None)
        return None, one_out_tile(wo, bo)

    xs = (w_o, b_o) if b_o is not None else w_o
    _, tiles = lax.scan(out_body, None, xs)            # [os, ..., to]
    # [os, ..., to] → [..., os, to] → [..., os*to] keeps tile order
    return jnp.moveaxis(tiles, 0, -2).reshape(x.shape[:-1] + (d_out,))
