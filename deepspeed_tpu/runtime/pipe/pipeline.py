"""Pipeline parallelism over the 'pipe' mesh axis.

Reference: ``deepspeed/runtime/pipe`` — ``PipelineModule`` (module.py:86)
partitions a layer list across stages, ``PipelineEngine`` (engine.py:60)
executes a hand-written instruction schedule (1F1B, schedule.py:189) with
explicit P2P sends (p2p.py:46). The TPU-native re-design:

- the **stacked layer pytree** ([L, ...] leaves — models/transformer.py)
  is sharded on its leading axis over 'pipe': stage s holds layers
  [s·L/S, (s+1)·L/S) — exactly PipelineModule's uniform partition;
- the schedule is a **collective-permute pipeline** inside a
  partial-manual ``shard_map`` over 'pipe': M microbatches flow through
  S stages in M+S-1 ticks, activations hopping stage→stage via
  ``lax.ppermute`` (nearest-neighbour ICI, the P2P of p2p.py:46);
- **backward is autodiff**: grad-of-ppermute is the reverse permute, so
  reverse-mode AD yields the mirror-image backward schedule (GPipe-style
  all-forward/all-backward; per-stage ``jax.checkpoint`` bounds activation
  memory — the bubble fraction (S-1)/(M+S-1) matches 1F1B, which only
  improves memory, already handled by remat);
- embeddings/final-norm/head stay replicated across 'pipe'; every stage
  computes the embed of its incoming tick and the loss runs once on the
  collected last-stage outputs (tied-weight allreduce of module.py:454 is
  subsumed by XLA's gradient psum over the replicated embed).

Other mesh axes (data/expert for ZeRO, 'model' for TP, 'seq') remain
*automatic* inside the shard_map, so pipeline composes with ZeRO/TP/SP.
"""

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import transformer
from deepspeed_tpu.models.transformer import DecoderConfig


def pipeline_partition_specs(base_specs, stages: int):
    """Add the 'pipe' sharding on the stacked-layer leading axis
    (reference: PipelineModule partition by 'uniform', module.py:393)."""
    if stages <= 1:
        return base_specs

    def add_pipe(spec):
        entries = list(spec)
        if entries:
            assert entries[0] is None, f"layer dim already sharded: {spec}"
            entries[0] = "pipe"
        return P(*entries)

    out = dict(base_specs)
    out["layers"] = jax.tree.map(add_pipe, base_specs["layers"],
                                 is_leaf=lambda x: isinstance(x, P))
    return out


def _stage_forward(cfg: DecoderConfig, local_layers, x, sin, cos,
                   attn_fn, moe_fn, remat_policy: Optional[str]):
    """Run this stage's L/S layers (scan, optional per-block remat)."""
    block = partial(transformer.decoder_block, cfg, attn_fn=attn_fn,
                    moe_fn=moe_fn)

    def body(carry, layer_params):
        out, aux = block(layer_params, carry, sin, cos)
        return out, aux

    if remat_policy and remat_policy != "none":
        body = jax.checkpoint(
            body, policy=transformer.resolve_remat_policy(remat_policy))
    x, aux = lax.scan(body, x, local_layers)
    return x, jnp.sum(aux)


def pipelined_loss(cfg: DecoderConfig, params, tokens, labels,
                   attn_fn=None, moe_fn=None,
                   remat_policy: Optional[str] = None,
                   mesh=None, num_stages: Optional[int] = None):
    """tokens/labels: [M, B, T] stacked microbatches → scalar token-mean CE.

    Must be called under jit with ``params['layers']`` sharded over 'pipe'
    on the leading axis (pipeline_partition_specs).
    """
    from deepspeed_tpu.parallel.mesh import get_mesh
    mesh = mesh or get_mesh()
    S = num_stages or mesh.shape["pipe"]
    attn_fn = attn_fn or transformer.dot_product_attention
    M, b, t = tokens.shape
    d = cfg.hidden_size

    def per_stage(local_layers, embed, final_norm, head, tokens, labels):
        sid = lax.axis_index("pipe")
        positions = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None], (b, t))
        if cfg.pos_emb == "rope":
            sin, cos = transformer.rope_table(cfg, positions)
        else:
            sin = cos = jnp.zeros((b, t, 0), jnp.float32)

        def embed_mb(tok):
            x = embed["tokens"][tok]
            if cfg.pos_emb == "learned":
                x = x + embed["pos"][positions]
            return x

        perm = [(i, (i + 1) % S) for i in range(S)]
        buf = jnp.zeros((b, t, d), embed["tokens"].dtype)
        buf = lax.pcast(buf, ("pipe",), to="varying")
        collected = jnp.zeros((M, b, t, d), jnp.float32)
        collected = lax.pcast(collected, ("pipe",), to="varying")
        aux_total = lax.pcast(jnp.zeros((), jnp.float32), ("pipe",),
                              to="varying")

        for step in range(M + S - 1):
            mb_in = min(step, M - 1)           # microbatch entering stage 0
            x_in = jnp.where(sid == 0, embed_mb(tokens[mb_in]), buf)
            x_out, aux = _stage_forward(cfg, local_layers, x_in, sin, cos,
                                        attn_fn, moe_fn, remat_policy)
            valid = jnp.logical_and(step >= sid,
                                    step - sid < M).astype(jnp.float32)
            # each stage's aux covers only its own L/S layers, so the psum
            # over 'pipe' below reassembles the full-model layer sum per
            # microbatch; dividing by M gives the per-microbatch mean,
            # matching the non-pipeline loss exactly
            aux_total = aux_total + aux * valid / M
            mb_out = step - (S - 1)            # microbatch leaving last stage
            if 0 <= mb_out < M:
                keep = (sid == S - 1).astype(x_out.dtype)
                collected = collected.at[mb_out].set(
                    x_out.astype(jnp.float32) * keep)
            buf = lax.ppermute(x_out, "pipe", perm)

        # share last-stage activations with every stage (psum of one-hot
        # contribution), then compute the loss identically everywhere —
        # keeps the program SPMD and the loss replicated for the engine
        collected = lax.psum(collected, "pipe")
        xs = collected.reshape(M * b, t, d).astype(embed["tokens"].dtype)
        norm_params = {"final_norm": final_norm, "embed": embed}
        if head is not None:
            norm_params["lm_head"] = head
        xn = transformer._norm(cfg, final_norm, xs)
        loss = transformer.chunked_cross_entropy(
            cfg, norm_params, xn, labels.reshape(M * b, t))
        aux_all = lax.psum(aux_total, "pipe")
        return loss + aux_all

    head = params.get("lm_head")
    base_specs = (
        jax.tree.map(lambda _: P("pipe"), params["layers"]),
        jax.tree.map(lambda _: P(), params["embed"]),
        jax.tree.map(lambda _: P(), params["final_norm"]),
    )
    if head is None:
        def entry(local_layers, embed, final_norm, tokens, labels):
            return per_stage(local_layers, embed, final_norm, None,
                             tokens, labels)
        fn = jax.shard_map(entry, mesh=mesh,
                           in_specs=base_specs + (P(), P()),
                           out_specs=P(), axis_names={"pipe"})
        return fn(params["layers"], params["embed"], params["final_norm"],
                  tokens, labels)
    fn = jax.shard_map(per_stage, mesh=mesh,
                       in_specs=base_specs + (P(), P(), P()),
                       out_specs=P(), axis_names={"pipe"})
    return fn(params["layers"], params["embed"], params["final_norm"],
              head, tokens, labels)
