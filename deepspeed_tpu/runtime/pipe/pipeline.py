"""Pipeline parallelism over the 'pipe' mesh axis.

Reference: ``deepspeed/runtime/pipe`` — ``PipelineModule`` (module.py:86)
partitions a layer list across stages, ``PipelineEngine`` (engine.py:60)
executes a hand-written instruction schedule (1F1B, schedule.py:189) with
explicit P2P sends (p2p.py:46). The TPU-native re-design:

- the **stacked layer pytree** ([L, ...] leaves — models/transformer.py)
  is sharded on its leading axis over 'pipe': stage s holds layers
  [s·L/S, (s+1)·L/S) — exactly PipelineModule's uniform partition;
- the schedule is a **collective-permute pipeline** inside a
  partial-manual ``shard_map`` over 'pipe': M microbatches flow through
  S stages in M+S-1 ticks, activations hopping stage→stage via
  ``lax.ppermute`` (nearest-neighbour ICI, the P2P of p2p.py:46);
- **backward is autodiff**: grad-of-ppermute is the reverse permute, so
  reverse-mode AD yields the mirror-image backward schedule (GPipe-style
  all-forward/all-backward; per-stage ``jax.checkpoint`` bounds activation
  memory — the bubble fraction (S-1)/(M+S-1) matches 1F1B, which only
  improves memory, already handled by remat);
- embeddings/final-norm/head stay replicated across 'pipe'; every stage
  computes the embed of its incoming tick and the loss runs once on the
  collected last-stage outputs (tied-weight allreduce of module.py:454 is
  subsumed by XLA's gradient psum over the replicated embed).

Other mesh axes (data/expert for ZeRO, 'model' for TP, 'seq') remain
*automatic* inside the shard_map, so pipeline composes with ZeRO/TP/SP.
"""

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import transformer
from deepspeed_tpu.models.transformer import DecoderConfig


def pipeline_partition_specs(base_specs, stages: int):
    """Add the 'pipe' sharding on the stacked-layer leading axis
    (reference: PipelineModule partition by 'uniform', module.py:393)."""
    if stages <= 1:
        return base_specs

    def add_pipe(spec):
        entries = list(spec)
        if entries:
            assert entries[0] is None, f"layer dim already sharded: {spec}"
            entries[0] = "pipe"
        return P(*entries)

    out = dict(base_specs)
    out["layers"] = jax.tree.map(add_pipe, base_specs["layers"],
                                 is_leaf=lambda x: isinstance(x, P))
    return out


def _pack_embed(cfg: DecoderConfig, params):
    """Embed tree threaded through shard_map: BLOOM's
    word_embeddings_layernorm rides along under a reserved key so the
    stage-0 embed can apply it (and its grads come back in the same
    tree)."""
    em = dict(params["embed"])
    if cfg.embed_norm:
        em["_embed_norm"] = params["embed_norm"]
    return em


def _pack_head(params):
    """Untied-head tree threaded through shard_map: lm_head plus Phi's
    lm_head_bias, so the stage loss (chunked CE reads both keys) and the
    grads reassembly see every head leaf. None when tied."""
    if "lm_head" not in params:
        return None
    head = {"lm_head": params["lm_head"]}
    if "lm_head_bias" in params:
        head["lm_head_bias"] = params["lm_head_bias"]
    return head


def _apply_embed(cfg: DecoderConfig, em, tok, positions):
    """Stage-0 embed: delegates to the shared transformer.embed_tokens
    (one home for Gemma scaling / learned pos / BLOOM embed norm)."""
    return transformer.embed_tokens(cfg, em, tok, positions,
                                    em.get("_embed_norm"))


def _stage_forward(cfg: DecoderConfig, local_layers, x, sin, cos,
                   attn_fn, moe_fn, remat_policy: Optional[str],
                   local_mask=None):
    """Run this stage's ceil(L/S) layers (scan, optional per-block remat).

    ``local_mask`` ([C] bool) marks PADDING layers inactive — the balanced
    partition for L % S != 0 (reference PipelineModule partition_balanced,
    module.py:393): every stage runs the same static layer count (SPMD
    over 'pipe' — the tick critical path is max stage cost, exactly what
    the reference's balanced split minimizes), and a padded stage's dummy
    iterations are value-identity with exactly-zero parameter gradients."""
    block = partial(transformer.decoder_block, cfg, attn_fn=attn_fn,
                    moe_fn=moe_fn)

    def body(carry, inp):
        if local_mask is None:
            layer_params = inp
        else:
            layer_params, active = inp
        carry = checkpoint_name(carry, "block_in")
        out, aux = block(layer_params, carry, sin, cos)
        if local_mask is not None:
            out = jnp.where(active, out, carry)
            aux = jnp.where(active, aux, 0.0)
        return out, aux

    if remat_policy and remat_policy != "none":
        body = jax.checkpoint(
            body, policy=transformer.resolve_remat_policy(remat_policy))
    xs = local_layers if local_mask is None else (local_layers, local_mask)
    x, aux = lax.scan(body, x, xs)
    return x, jnp.sum(aux)


def pipelined_loss(cfg: DecoderConfig, params, tokens, labels,
                   attn_fn=None, moe_fn=None,
                   remat_policy: Optional[str] = None,
                   mesh=None, num_stages: Optional[int] = None,
                   ce_budget_bytes: Optional[int] = None,
                   ce_logits_dtype=None, layer_mask=None):
    """tokens/labels: [M, B, T] stacked microbatches → scalar token-mean CE.

    Must be called under jit with ``params['layers']`` sharded over 'pipe'
    on the leading axis (pipeline_partition_specs). ``layer_mask`` ([L']
    bool, L' = S·ceil(L/S)): balanced partition for indivisible layer
    counts — see _stage_forward.
    """
    from deepspeed_tpu.parallel.mesh import get_mesh
    mesh = mesh or get_mesh()
    S = num_stages or mesh.shape["pipe"]
    attn_fn = attn_fn or transformer.default_attention(cfg)
    M, b, t = tokens.shape
    d = cfg.hidden_size

    def per_stage(local_layers, local_mask, embed, final_norm, head,
                  tokens, labels):
        sid = lax.axis_index("pipe")
        positions = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None], (b, t))
        if cfg.pos_emb == "rope":
            sin, cos = transformer.rope_table(cfg, positions)
        else:
            sin = cos = jnp.zeros((b, t, 0), jnp.float32)

        def embed_mb(tok):
            return _apply_embed(cfg, embed, tok, positions)

        perm = [(i, (i + 1) % S) for i in range(S)]
        buf = jnp.zeros((b, t, d), embed["tokens"].dtype)
        buf = lax.pcast(buf, ("pipe",), to="varying")
        collected = jnp.zeros((M, b, t, d), jnp.float32)
        collected = lax.pcast(collected, ("pipe",), to="varying")
        aux_total = lax.pcast(jnp.zeros((), jnp.float32), ("pipe",),
                              to="varying")

        for step in range(M + S - 1):
            mb_in = min(step, M - 1)           # microbatch entering stage 0
            x_in = jnp.where(sid == 0, embed_mb(tokens[mb_in]), buf)
            x_out, aux = _stage_forward(cfg, local_layers, x_in, sin, cos,
                                        attn_fn, moe_fn, remat_policy,
                                        local_mask)
            valid = jnp.logical_and(step >= sid,
                                    step - sid < M).astype(jnp.float32)
            # each stage's aux covers only its own L/S layers, so the psum
            # over 'pipe' below reassembles the full-model layer sum per
            # microbatch; dividing by M gives the per-microbatch mean,
            # matching the non-pipeline loss exactly
            aux_total = aux_total + aux * valid / M
            mb_out = step - (S - 1)            # microbatch leaving last stage
            if 0 <= mb_out < M:
                keep = (sid == S - 1).astype(x_out.dtype)
                collected = collected.at[mb_out].set(
                    x_out.astype(jnp.float32) * keep)
            buf = lax.ppermute(x_out, "pipe", perm)

        # share last-stage activations with every stage (psum of one-hot
        # contribution), then compute the loss identically everywhere —
        # keeps the program SPMD and the loss replicated for the engine
        collected = lax.psum(collected, "pipe")
        xs = collected.reshape(M * b, t, d).astype(embed["tokens"].dtype)
        norm_params = {"final_norm": final_norm, "embed": embed}
        if head is not None:
            norm_params.update(head)   # lm_head (+ lm_head_bias, Phi)
        xn = transformer._norm(cfg, final_norm, xs)
        loss = transformer.chunked_cross_entropy(
            cfg, norm_params, xn, labels.reshape(M * b, t),
            budget_bytes=ce_budget_bytes, logits_dtype=ce_logits_dtype)
        aux_all = lax.psum(aux_total, "pipe")
        return loss + aux_all

    head = _pack_head(params)
    embed_in = _pack_embed(cfg, params)
    n_stacked = jax.tree.leaves(params["layers"])[0].shape[0]
    mask = jnp.ones((n_stacked,), bool) if layer_mask is None \
        else jnp.asarray(layer_mask, bool)
    base_specs = (
        jax.tree.map(lambda _: P("pipe"), params["layers"]),
        P("pipe"),
        jax.tree.map(lambda _: P(), embed_in),
        jax.tree.map(lambda _: P(), params["final_norm"]),
    )
    if head is None:
        def entry(local_layers, local_mask, embed, final_norm, tokens,
                  labels):
            return per_stage(local_layers, local_mask, embed, final_norm,
                             None, tokens, labels)
        fn = jax.shard_map(entry, mesh=mesh,
                           in_specs=base_specs + (P(), P()),
                           out_specs=P(), axis_names={"pipe"})
        return fn(params["layers"], mask, embed_in, params["final_norm"],
                  tokens, labels)
    fn = jax.shard_map(per_stage, mesh=mesh,
                       in_specs=base_specs
                       + (jax.tree.map(lambda _: P(), head), P(), P()),
                       out_specs=P(), axis_names={"pipe"})
    return fn(params["layers"], mask, embed_in, params["final_norm"],
              head, tokens, labels)


# ---------------------------------------------------------------------------
# 1F1B schedule (reference runtime/pipe/schedule.py:189 TrainSchedule)
# ---------------------------------------------------------------------------

def pipelined_loss_and_grads_1f1b(cfg: DecoderConfig, params, tokens,
                                  labels, scale=1.0, attn_fn=None,
                                  moe_fn=None,
                                  remat_policy: Optional[str] = None,
                                  mesh=None,
                                  num_stages: Optional[int] = None,
                                  ce_budget_bytes: Optional[int] = None,
                                  ce_logits_dtype=None, layer_mask=None):
    """One-forward-one-backward pipeline step → (loss, grads).

    Reference ``schedule.py:189`` (TrainSchedule): each tick a stage runs
    one microbatch forward AND one backward, so only the in-flight
    activations are stashed — activation memory is O(S), independent of
    the microbatch count M (GPipe's autodiff path above holds all M).

    Mechanics: backward is EXPLICIT per-microbatch ``jax.vjp`` with a
    recompute-from-stash design — the stash holds only each in-flight
    microbatch's stage INPUT ([K, B, T, D], K = min(M, 2S-1)); the vjp
    re-runs the stage forward (the same price per-layer remat already
    pays). Timing: stage s forwards microbatch i at tick i+s and backwards
    microbatch j at tick j + 2(S-1) - s; activation/grad hops ride
    ``lax.ppermute`` in opposite directions. The last stage seeds dy from
    the loss-head vjp in the same tick its forward lands, which is what
    makes the schedule 1F1B rather than all-forward/all-backward.

    ``scale`` multiplies the cotangent seeds (fp16 loss scaling); the
    returned loss is unscaled.
    """
    from deepspeed_tpu.parallel.mesh import get_mesh
    mesh = mesh or get_mesh()
    S = num_stages or mesh.shape["pipe"]
    attn_fn = attn_fn or transformer.default_attention(cfg)
    M, b, t = tokens.shape
    d = cfg.hidden_size
    K = min(M, 2 * S - 1)
    T = M + 2 * (S - 1)

    def per_stage(local_layers, local_mask, embed, final_norm, head,
                  tokens, labels):
        sid = lax.axis_index("pipe")
        is_last = (sid == S - 1)
        positions = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None], (b, t))
        if cfg.pos_emb == "rope":
            sin, cos = transformer.rope_table(cfg, positions)
        else:
            sin = cos = jnp.zeros((b, t, 0), jnp.float32)

        def embed_mb(em, tok):
            return _apply_embed(cfg, em, tok, positions)

        def stage_fn(ly, x):
            y, aux = _stage_forward(cfg, ly, x, sin, cos, attn_fn, moe_fn,
                                    remat_policy, local_mask)
            # for dense models aux is a CONSTANT zero — invariant on
            # 'pipe' — and jax.vjp would then reject the varying cotangent
            # seed below; one zero-valued element of x makes it varying
            # without changing the math
            aux = aux + x[0, 0, 0].astype(jnp.float32) * 0.0
            return y, aux

        has_head = head is not None

        def head_loss(fn_, em_, hd_, y, lbl):
            """Token-mean CE of one microbatch's last-stage output,
            differentiable w.r.t. the replicated tail params."""
            np_ = {"final_norm": fn_, "embed": em_}
            if has_head:
                np_.update(hd_)   # lm_head (+ lm_head_bias, Phi)
            xn = transformer._norm(cfg, fn_, y)
            return transformer.chunked_cross_entropy(
                cfg, np_, xn, lbl, budget_bytes=ce_budget_bytes,
                logits_dtype=ce_logits_dtype)

        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        perm_rev = [(i, (i - 1) % S) for i in range(S)]
        dtype = embed["tokens"].dtype
        varying = lambda x: lax.pcast(x, ("pipe",), to="varying")
        zeros_f32 = lambda tree: jax.tree.map(
            lambda x: varying(jnp.zeros(x.shape, jnp.float32)), tree)
        tadd = lambda a, g: jax.tree.map(
            lambda x, y: x + y.astype(jnp.float32), a, g)

        # replicated-param grad accumulators stay INVARIANT on 'pipe':
        # each tick's contribution comes back from vjp already psummed
        # (invariant cotangent), so the accumulator is the global sum on
        # every stage and satisfies its P() out_spec directly
        inv_zeros = lambda tree: jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), tree)
        carry0 = dict(
            stash=varying(jnp.zeros((K, b, t, d), dtype)),
            buf=varying(jnp.zeros((b, t, d), dtype)),
            dbuf=varying(jnp.zeros((b, t, d), jnp.float32)),
            g_layers=zeros_f32(local_layers),
            g_embed=inv_zeros(embed),
            g_norm=inv_zeros(final_norm),
            g_head=inv_zeros(head) if has_head else (),
            loss=varying(jnp.zeros((), jnp.float32)),
        )

        def tick_body(c, tick):
            # ---------------- forward slot: microbatch i = tick - sid
            i = tick - sid
            fwd_valid = jnp.logical_and(i >= 0, i < M)
            i_c = jnp.clip(i, 0, M - 1)
            tok_i = lax.dynamic_index_in_dim(tokens, i_c, 0,
                                             keepdims=False)
            x_in = jnp.where(sid == 0, embed_mb(embed, tok_i), c["buf"])
            x_out, aux = stage_fn(local_layers, x_in)
            loss_total = c["loss"] + aux * fwd_valid / M
            slot_f = jnp.mod(i_c, K)
            old = lax.dynamic_index_in_dim(c["stash"], slot_f, 0,
                                           keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                c["stash"], jnp.where(fwd_valid, x_in, old), slot_f, 0)

            # ---------------- backward slot: j = tick - 2(S-1) + sid
            j = tick - 2 * (S - 1) + sid
            bwd_valid = jnp.logical_and(j >= 0, j < M)
            j_c = jnp.clip(j, 0, M - 1)
            x_saved = lax.dynamic_index_in_dim(stash, jnp.mod(j_c, K), 0,
                                               keepdims=False)
            (y_re, _aux_re), stage_vjp = jax.vjp(stage_fn, local_layers,
                                                 x_saved)
            lbl_j = lax.dynamic_index_in_dim(labels, j_c, 0,
                                             keepdims=False)
            if has_head:
                ce_j, head_vjp = jax.vjp(
                    lambda fn_, em_, hd_, y: head_loss(fn_, em_, hd_, y,
                                                       lbl_j),
                    final_norm, embed, head, y_re)
            else:
                ce_j, head_vjp = jax.vjp(
                    lambda fn_, em_, y: head_loss(fn_, em_, None, y,
                                                  lbl_j),
                    final_norm, embed, y_re)
            last_seed = (scale / M) * bwd_valid * is_last
            cots = head_vjp(jnp.float32(1.0) * last_seed)
            if has_head:
                dnorm_j, dembed_j, dhead_j, dy_last = cots
            else:
                dnorm_j, dembed_j, dy_last = cots
            loss_total = loss_total + (ce_j / M) * bwd_valid * is_last
            dy = jnp.where(is_last, dy_last.astype(jnp.float32), c["dbuf"])
            dy = dy * bwd_valid                     # mask invalid ticks
            aux_seed = (scale / M) * bwd_valid
            dlayers_j, dx_j = stage_vjp((dy.astype(y_re.dtype),
                                         jnp.float32(1.0) * aux_seed))
            # stage 0: fold dx into the embedding grads
            tok_j = lax.dynamic_index_in_dim(tokens, j_c, 0,
                                             keepdims=False)
            _, em_vjp = jax.vjp(lambda em: embed_mb(em, tok_j), embed)
            (dembed0,) = em_vjp((dx_j * (sid == 0)).astype(x_in.dtype))

            out = dict(
                stash=stash,
                buf=lax.ppermute(x_out, "pipe", perm_fwd),
                dbuf=lax.ppermute(dx_j.astype(jnp.float32), "pipe",
                                  perm_rev),
                g_layers=tadd(c["g_layers"], dlayers_j),
                g_embed=tadd(tadd(c["g_embed"], dembed_j), dembed0),
                g_norm=tadd(c["g_norm"], dnorm_j),
                g_head=tadd(c["g_head"], dhead_j) if has_head else (),
                loss=loss_total,
            )
            return out, None

        c, _ = lax.scan(tick_body, carry0, jnp.arange(T, dtype=jnp.int32))
        g_layers, g_embed, g_norm, g_head, loss_total = (
            c["g_layers"], c["g_embed"], c["g_norm"],
            c["g_head"] if has_head else None, c["loss"])

        loss = lax.psum(loss_total, "pipe")
        # NO explicit psum on the replicated-param grads: jax.vjp w.r.t. an
        # INVARIANT (replicated) input inside the manual region already
        # inserts the psum over 'pipe' to keep the cotangent invariant —
        # every stage's accumulator therefore already holds the global sum
        # (psumming again would double-count; caught by the GPipe parity
        # test). The per-stage layer grads (varying inputs) get no such
        # implicit psum and stay stage-local, matching their P('pipe')
        # out_spec.
        if g_head is not None:
            return loss, g_layers, g_embed, g_norm, g_head
        return loss, g_layers, g_embed, g_norm

    layer_specs = jax.tree.map(lambda _: P("pipe"), params["layers"])
    rep = lambda tree: jax.tree.map(lambda _: P(), tree)
    head = _pack_head(params)
    embed_in = _pack_embed(cfg, params)
    n_stacked = jax.tree.leaves(params["layers"])[0].shape[0]
    mask = jnp.ones((n_stacked,), bool) if layer_mask is None \
        else jnp.asarray(layer_mask, bool)
    in_specs = (layer_specs, P("pipe"), rep(embed_in),
                rep(params["final_norm"]))
    if head is None:
        def entry(ll, lm, em, fn_, tk, lb):
            return per_stage(ll, lm, em, fn_, None, tk, lb)
        out = jax.shard_map(
            entry, mesh=mesh, in_specs=in_specs + (P(), P()),
            out_specs=(P(), layer_specs, rep(embed_in),
                       rep(params["final_norm"])),
            axis_names={"pipe"})(params["layers"], mask, embed_in,
                                 params["final_norm"], tokens, labels)
        loss, g_layers, g_embed, g_norm = out
        grads = {"layers": g_layers, "embed": g_embed,
                 "final_norm": g_norm}
    else:
        out = jax.shard_map(
            per_stage, mesh=mesh, in_specs=in_specs + (rep(head), P(), P()),
            out_specs=(P(), layer_specs, rep(embed_in),
                       rep(params["final_norm"]), rep(head)),
            axis_names={"pipe"})(params["layers"], mask, embed_in,
                                 params["final_norm"], head, tokens,
                                 labels)
        loss, g_layers, g_embed, g_norm, g_head = out
        grads = {"layers": g_layers, "embed": g_embed,
                 "final_norm": g_norm, **g_head}
    if cfg.embed_norm:
        grads["embed_norm"] = grads["embed"].pop("_embed_norm")
    grads = {k: grads[k] for k in params}     # preserve key order
    return loss, grads
