"""Learning-rate schedules.

TPU-native equivalent of the reference's ``runtime/lr_schedules.py``
(LRRangeTest:277, OneCycle:375, WarmupLR:637, WarmupDecayLR:730,
WarmupCosineLR:781). Instead of stateful torch schedulers mutating
``optimizer.param_groups``, each schedule here is a pure function
``step -> lr`` (jit-friendly: steps may be traced int arrays), built from a
config block and fed to the engine's jitted train step.
"""

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[Any], Any]   # step (int or traced) -> lr (float array)

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


def constant_lr(lr: float) -> Schedule:
    def fn(step):
        return jnp.float32(lr)
    return fn


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Schedule:
    """Reference LRRangeTest (lr_schedules.py:277): lr grows from min_lr by
    ``rate`` per (possibly fractional) step interval — LR range test a la
    Smith 2017."""
    def fn(step):
        s = jnp.asarray(step, jnp.float32) / lr_range_test_step_size
        if lr_range_test_staircase:
            s = jnp.floor(s)
        return jnp.float32(lr_range_test_min_lr) * \
            (1.0 + s * lr_range_test_step_rate)
    return fn


def one_cycle(cycle_min_lr: float,
              cycle_max_lr: float,
              decay_lr_rate: float = 0.0,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: Optional[int] = None,
              decay_step_size: int = 0,
              **_ignored) -> Schedule:
    """Reference OneCycle (lr_schedules.py:375): linear up over the first
    phase, linear down over the second, then optional decay below min.
    (Momentum cycling of the reference is handled by the engine when the
    optimizer exposes beta1 — omitted round 1.)"""
    second = cycle_second_step_size or cycle_first_step_size

    def fn(step):
        s = jnp.asarray(step, jnp.float32)
        up_frac = jnp.clip(s / cycle_first_step_size, 0.0, 1.0)
        down_frac = jnp.clip((s - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle_lr = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * \
            jnp.where(s <= cycle_first_step_size, up_frac, 1.0 - down_frac)
        post = s - (cycle_first_step_size + second)
        if decay_lr_rate > 0 and decay_step_size > 0:
            decay_intervals = jnp.floor(jnp.maximum(post, 0.0) / decay_step_size)
            decayed = cycle_min_lr / (1.0 + decay_intervals * decay_lr_rate)
            return jnp.where(post > 0, decayed, in_cycle_lr).astype(jnp.float32)
        return jnp.where(post > 0, cycle_min_lr, in_cycle_lr).astype(jnp.float32)
    return fn


def _warmup_frac(step, warmup_num_steps: int, warmup_type: str):
    s = jnp.asarray(step, jnp.float32)
    w = jnp.float32(max(warmup_num_steps, 1))
    if warmup_type == WARMUP_LOG_RATE:
        # reference: inverse_log_warm_up * log(step + 1)
        frac = jnp.log1p(jnp.minimum(s, w)) / jnp.log1p(w)
    else:
        frac = jnp.clip(s / w, 0.0, 1.0)
    return frac


def warmup_lr(warmup_min_lr: float = 0.0,
              warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = WARMUP_LOG_RATE,
              **_ignored) -> Schedule:
    """Reference WarmupLR (lr_schedules.py:637): warm up then hold max."""
    def fn(step):
        frac = _warmup_frac(step, warmup_num_steps, warmup_type)
        return jnp.float32(warmup_min_lr) + \
            (warmup_max_lr - warmup_min_lr) * frac
    return fn


def warmup_decay_lr(total_num_steps: int,
                    warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001,
                    warmup_num_steps: int = 1000,
                    warmup_type: str = WARMUP_LOG_RATE,
                    **_ignored) -> Schedule:
    """Reference WarmupDecayLR (lr_schedules.py:730): warm up then linear
    decay to 0 at total_num_steps."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps,
                     warmup_type)

    def fn(step):
        s = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (total_num_steps - s) /
            jnp.float32(max(total_num_steps - warmup_num_steps, 1)),
            0.0, 1.0)
        # reference get_lr: min_lr + delta_lr * gamma — decays TO min_lr
        decayed = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * decay
        return jnp.where(s < warmup_num_steps, base(step),
                         decayed).astype(jnp.float32)
    return fn


def warmup_cosine_lr(total_num_steps: int,
                     warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000,
                     cos_min_ratio: float = 0.0001,
                     warmup_type: str = WARMUP_LINEAR_RATE,
                     base_lr: float = 1.0,
                     **_ignored) -> Schedule:
    """Reference WarmupCosineLR (lr_schedules.py:781): ratios are relative to
    the optimizer's base lr."""
    def fn(step):
        s = jnp.asarray(step, jnp.float32)
        wfrac = _warmup_frac(step, warmup_num_steps, warmup_type)
        warm_ratio = warmup_min_ratio + (1.0 - warmup_min_ratio) * wfrac
        progress = jnp.clip(
            (s - warmup_num_steps) /
            jnp.float32(max(total_num_steps - warmup_num_steps, 1)),
            0.0, 1.0)
        cos_ratio = cos_min_ratio + (1.0 - cos_min_ratio) * \
            0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        ratio = jnp.where(s < warmup_num_steps, warm_ratio, cos_ratio)
        return (base_lr * ratio).astype(jnp.float32)
    return fn


#: reference lr_schedules.py VALID_LR_SCHEDULES
_SCHEDULES: Dict[str, Callable[..., Schedule]] = {
    "lrrangetest": lr_range_test,
    "onecycle": one_cycle,
    "warmuplr": warmup_lr,
    "warmupdecaylr": warmup_decay_lr,
    "warmupcosinelr": warmup_cosine_lr,
}


def build_schedule(name: Optional[str], params: Optional[Dict[str, Any]],
                   base_lr: float) -> Schedule:
    """Build from the config "scheduler" block (reference
    runtime/config.py:get_scheduler_name); None → constant base_lr."""
    if not name:
        return constant_lr(base_lr)
    key = name.lower()
    if key not in _SCHEDULES:
        raise ValueError(f"unknown scheduler '{name}'; known: {sorted(_SCHEDULES)}")
    params = dict(params or {})
    if key == "warmupcosinelr":
        params.setdefault("base_lr", base_lr)
    return _SCHEDULES[key](**params)
