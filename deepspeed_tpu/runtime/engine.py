"""The deepspeed_tpu training engine.

TPU-native re-design of the reference's ``DeepSpeedEngine``
(runtime/engine.py:206) and ``deepspeed.initialize``
(deepspeed/__init__.py:78). The reference wraps a torch module and drives
training through gradient hooks, flat fp16 partitions, and a hand-built
collective schedule. Here the engine owns:

- a **functional model spec** (init/loss pair over a params pytree),
- a **ZeRO sharding plan** (runtime/zero/sharding.py) mapping stage 0–3 to
  param/grad/optimizer-state shardings over the mesh,
- **one jitted train step** — forward, backward, (fp16 unscale/overflow),
  global-norm clip, optimizer update, LR schedule — donated in-place; XLA
  emits the reduce-scatter / allgather pattern of the corresponding ZeRO
  stage from the sharding annotations alone,
- GAS accounting (`forward`/`backward`/`step` parity API plus the fused
  `train_batch` fast path with a `lax.scan` over microbatches),
- checkpointing, monitoring, throughput timing.

API parity map (reference runtime/engine.py):
  forward:2222  backward:2478  step:2653  train_batch (pipe engine:337)
  save_checkpoint:3621  load_checkpoint:3273
"""

import os
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, Iterator, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm, telemetry
from deepspeed_tpu.config import DeepSpeedTPUConfig
from deepspeed_tpu.ops.optimizers import Optimizer, build_optimizer
from deepspeed_tpu.parallel.mesh import (ZERO_AXES, build_mesh,
                                         get_data_parallel_world_size,
                                         has_mesh, get_mesh, mesh_from_config)
from deepspeed_tpu.runtime.loss_scaler import (LossScaleState, check_overflow,
                                               init_loss_scale, update_scale)
from deepspeed_tpu.runtime.lr_schedules import Schedule, build_schedule
from deepspeed_tpu.resilience.faults import fault_injector, record_recovery
from deepspeed_tpu.runtime.zero.sharding import ZeroShardingPlan
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

Pytree = Any
Batch = Dict[str, jax.Array]
#: loss_fn(params, batch, rng) -> loss | (loss, metrics-dict)
LossFn = Callable[[Pytree, Batch, jax.Array], Any]


def _sample_difficulty(sample) -> int:
    """Fallback curriculum difficulty = sequence length of the first sized
    leaf. ``len(sample)`` on a dict sample would count its KEYS — a constant
    that silently disables difficulty gating. 0-d array leaves (scalar ids
    etc.) are skipped: they pass ``hasattr(__len__)`` but ``len()`` raises."""
    for leaf in jax.tree.leaves(sample):
        if hasattr(leaf, "ndim"):          # numpy / jax array
            if leaf.ndim:
                return int(np.shape(leaf)[0])
            continue
        if hasattr(leaf, "__len__"):       # list / str sample
            return len(leaf)
    return 0


@dataclass
class ModelSpec:
    """Functional model contract consumed by the engine.

    The TPU answer to "pass a torch.nn.Module": parameters are an explicit
    pytree; ``loss_fn`` is pure; ``partition_specs`` carries the model's
    tensor-parallel/FSDP layout (the AutoTP + zero.Init analogue)."""
    init_fn: Callable[[jax.Array], Pytree]
    loss_fn: LossFn
    #: base PartitionSpec pytree (TP and, for stage 3, FSDP axes); None →
    #: fully replicated base
    partition_specs: Optional[Pytree] = None
    #: approximate FLOPs per token for MFU reporting (6*N for dense decoders)
    flops_per_token: Optional[float] = None
    #: tokens per sample (seq len) for throughput accounting
    tokens_per_sample: Optional[int] = None
    #: pipeline-parallel loss over STACKED microbatches [M, B, ...] —
    #: set by the factory when pipeline.stages > 1; the engine then runs
    #: the whole microbatch set in one call (reference PipelineEngine
    #: train_batch:337 — forward()/backward() are not supported, matching
    #: the reference's restriction)
    pipeline_loss_fn: Optional[Callable[[Pytree, Batch, jax.Array], Any]] = None
    #: 1F1B path: (params, batch, rng, scale) -> (loss, grads) — explicit
    #: per-microbatch backward (runtime/pipe 1F1B schedule); preferred over
    #: pipeline_loss_fn's autodiff GPipe when set
    pipeline_grad_fn: Optional[Callable[..., Any]] = None
    #: the DecoderConfig this spec was built from (set by model_factory);
    #: lets the hybrid engine spin up an inference engine over the same
    #: params (reference runtime/hybrid_engine.py)
    decoder_config: Optional[Any] = None
    #: ZeRO-3 chunked-overlap hook: (mesh, abstract_params) ->
    #: Optional[OverlapPlan]. Set by the factory when
    #: zero_optimization.overlap_comm is on; the engine calls it from the
    #: standard fused-step path once mesh + abstract params exist, and
    #: the factory arms loss_fn with the returned plan's layer_loop
    #: (runtime/zero/overlap.py)
    configure_overlap: Optional[Callable[..., Any]] = None


@dataclass
class _ParkedShards:
    """Host copy of a multi-host array's LOCAL shards (offload_states)."""
    shape: Tuple[int, ...]
    dtype: Any
    shards: Dict[Any, np.ndarray]


class DeepSpeedTPUEngine:
    """See module docstring. Construct via :func:`initialize`."""

    def __init__(self,
                 model: ModelSpec,
                 config: DeepSpeedTPUConfig,
                 mesh: Optional[Mesh] = None,
                 params: Optional[Pytree] = None,
                 rng: Optional[jax.Array] = None,
                 training_data=None):
        comm.init_distributed()
        self.model = model
        self.config = config
        self.mesh = mesh or (get_mesh() if has_mesh() else mesh_from_config(config))
        self.dp_world_size = get_data_parallel_world_size(self.mesh)
        config.resolve_batch_sizes(self.dp_world_size)

        self.zero_stage = config.zero_optimization.stage
        self.fp16_enabled = config.fp16.enabled is True
        self.bf16_enabled = (config.bf16.enabled is True or
                             (not self.fp16_enabled and
                              config.compute_dtype == "bfloat16"))
        self.compute_dtype = {"float16": jnp.float16,
                              "bfloat16": jnp.bfloat16,
                              "float32": jnp.float32}[config.compute_dtype]

        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.global_samples = 0

        # sanity checks (reference engine.py:1123 is_sanity_checks_enabled:
        # NaN/Inf guards + cross-rank dataloader consistency :520). Two
        # modes: True/"debug" → global jax_debug_nans (raises at the op
        # that produced the NaN, but de-optimizes every jitted fn);
        # "scoped" → keep full-speed jit and run loss_scaler.global_check
        # over the step's pytrees instead, naming the first bad leaf
        # through telemetry/anomaly.py (costs one scalar sync per step).
        self._scoped_nan_check = config.check_nan_inf == "scoped"
        self._scoped_check_jit = None
        if config.check_nan_inf and not self._scoped_nan_check:
            jax.config.update("jax_debug_nans", True)
            log_dist("sanity checks on: jax_debug_nans enabled")
        elif self._scoped_nan_check:
            log_dist("sanity checks on: scoped per-leaf finite check")

        # -- optimizer & schedule ------------------------------------------
        self.offload_enabled = (
            config.zero_optimization.offload_optimizer.device.value
            in ("cpu", "nvme"))
        self.offload_overlap = False
        self._host_future = None
        self._zenflow = None
        self._param_stream = None
        if config.zero_optimization.zenflow is not None \
                and config.zero_optimization.offload_optimizer.device.value \
                != "cpu":
            # 'nvme' must be rejected too: NVMeOffloadOptimizer keeps
            # master/moments on disk (master=None), which the ZenFlow
            # selection/tail sweep cannot address.
            raise ValueError(
                "zenflow requires offload_optimizer.device='cpu' (the tail "
                "optimizer lives on the host — reference zenflow engine)")
        if config.zero_optimization.zenflow is not None and \
                config.zero_optimization.offload_param.device.value != "none":
            raise ValueError(
                "zenflow and offload_param are mutually exclusive "
                "streaming schedules; enable one")
        from deepspeed_tpu.ops.onebit import ONEBIT_NAMES
        self._onebit_enabled = config.optimizer.type.lower() \
            .replace("-", "").replace("_", "") in \
            tuple(n.replace("_", "") for n in ONEBIT_NAMES)
        if self._onebit_enabled:
            # the Optimizer object only contributes base_lr/hyperparams;
            # the 1-bit step path (ops/onebit.py) owns the update, so the
            # 1-bit-only knobs must not reach the adam factory
            _onebit_only = ("freeze_step", "max_coeff", "min_coeff",
                            "coeff_beta", "var_freeze_step",
                            "var_update_scaler", "local_step_scaler",
                            "local_step_clipper")
            opt_params = {k: v for k, v in
                          (config.optimizer.params or {}).items()
                          if k not in _onebit_only}
            self.optimizer, base_lr = build_optimizer("adamw", opt_params)
        else:
            self.optimizer, base_lr = build_optimizer(
                config.optimizer.type, config.optimizer.params)
        self.lr_schedule: Schedule = build_schedule(
            config.scheduler.type, config.scheduler.params, base_lr)

        # -- params (sharded at init — the zero.Init analogue) -------------
        rng = rng if rng is not None else jax.random.PRNGKey(config.seed)
        self._init_params_and_state(params, rng)

        # -- loss scaling ---------------------------------------------------
        self.loss_scale_state = init_loss_scale(
            config.fp16.loss_scale, config.fp16.initial_scale_power,
            config.fp16.hysteresis) if self.fp16_enabled else \
            LossScaleState(jnp.float32(1.0), jnp.zeros((), jnp.int32),
                           jnp.zeros((), jnp.int32))
        self.dynamic_loss_scale = self.fp16_enabled and config.fp16.loss_scale == 0

        # -- jitted functions ----------------------------------------------
        self._build_step_functions()

        # -- grad accumulation buffers -------------------------------------
        self._acc_grads: Optional[Pytree] = None
        self._acc_count = 0
        self._pending_loss = None

        # -- aux ------------------------------------------------------------
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=int(self.config.train_batch_size),
            steps_per_output=config.steps_per_print)
        self.monitor = self._build_monitor()
        self._monitor_pending = []
        self.training_dataloader = self._build_dataloader(training_data)
        self.lr_scheduler = self.lr_schedule   # parity name
        self._init_telemetry()

        log_dist(
            f"engine ready: zero_stage={self.zero_stage} dtype="
            f"{config.compute_dtype} dp={self.dp_world_size} "
            f"micro_batch={config.train_micro_batch_size_per_gpu} "
            f"gas={config.gradient_accumulation_steps} "
            f"train_batch={config.train_batch_size}")

    # ------------------------------------------------------------------ init

    def _base_specs(self) -> Pytree:
        if self.model.partition_specs is not None:
            return self.model.partition_specs
        # fully replicated base layout matching the params structure
        return jax.tree.map(lambda p: P(*([None] * np.ndim(p))),
                            self._abstract_params)

    def _init_params_and_state(self, params: Optional[Pytree],
                               rng: jax.Array) -> None:
        dtype = self.compute_dtype

        def cast_init(r):
            p = self.model.init_fn(r)
            if dtype == jnp.float32:
                return p
            # cast the whole model to the compute dtype (reference
            # engine.py:_configure_distributed_model half conversion)
            return jax.tree.map(
                lambda x: x.astype(dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, p)

        self._abstract_params = jax.eval_shape(cast_init, rng)
        base_specs = self._base_specs()
        self.plan = ZeroShardingPlan(self.mesh, self.zero_stage, base_specs,
                                     self._abstract_params)
        zcfg = self.config.zero_optimization
        self._zeropp_enabled = bool(zcfg.zero_quantized_weights or
                                    zcfg.zero_quantized_gradients)
        if self._zeropp_enabled:
            # ZeRO++ swaps in flat sharded storage + explicit quantized
            # collectives (runtime/zero/zeropp.py)
            from deepspeed_tpu.runtime.zero.zeropp import (init_zeropp_state,
                                                           validate_zeropp)
            validate_zeropp(self)
            init_zeropp_state(self, params, rng)
            return
        if self._onebit_enabled:
            # validate HERE so an offload/pipeline config errors instead
            # of silently taking the offload init path below
            from deepspeed_tpu.ops.onebit import validate_onebit
            validate_onebit(self)
        param_sh = self.plan.param_shardings()
        if params is None:
            init_jit = jax.jit(cast_init, out_shardings=param_sh)
            self.params = init_jit(rng)
        else:
            self.params = jax.device_put(
                jax.tree.map(lambda x: x.astype(dtype)
                             if jnp.issubdtype(x.dtype, jnp.floating) and
                             dtype != jnp.float32 else x, params), param_sh)
        self._param_shardings = param_sh
        if self.offload_enabled:
            # ZeRO-Offload: optimizer state in host DRAM; ZeRO-Infinity:
            # on NVMe via the windowed aio sweep (runtime/zero/infinity.py)
            off_cfg = self.config.zero_optimization.offload_optimizer
            param_tier = self.config.zero_optimization.offload_param \
                .device.value
            if param_tier != "none" and off_cfg.device.value == "cpu" \
                    and not off_cfg.superoffload:
                # the param tier stores master/params/grads in ONE
                # file-backed tier; 'cpu' maps it onto /dev/shm (DRAM)
                import dataclasses as _dc
                from deepspeed_tpu.config.config import OffloadDeviceEnum
                off_cfg = off_cfg.model_copy(update={
                    "device": OffloadDeviceEnum.nvme,
                    "nvme_path": off_cfg.nvme_path or
                    f"/dev/shm/dstpu_tier_{os.getpid()}"})
            if off_cfg.device.value == "nvme":
                from deepspeed_tpu.runtime.zero.infinity import (
                    DEFAULT_WINDOW, NVMeOffloadOptimizer)
                if not off_cfg.nvme_path:
                    raise ValueError("offload_optimizer.device='nvme' "
                                     "requires nvme_path")
                self.host_optimizer = NVMeOffloadOptimizer(
                    self._abstract_params, self.config.optimizer.type,
                    self.config.optimizer.params, dtype,
                    nvme_path=off_cfg.nvme_path,
                    window=off_cfg.buffer_size or DEFAULT_WINDOW,
                    aio_threads=off_cfg.buffer_count)
            elif off_cfg.superoffload:
                from deepspeed_tpu.runtime.zero.superoffload import (
                    SuperOffloadOptimizer)
                self.host_optimizer = SuperOffloadOptimizer(
                    self._abstract_params, self.config.optimizer.type,
                    self.config.optimizer.params, dtype,
                    bucket_size=off_cfg.buffer_size or (1 << 22))
            else:
                from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
                self.host_optimizer = HostOffloadOptimizer(
                    self._abstract_params, self.config.optimizer.type,
                    self.config.optimizer.params, dtype)
            self.host_optimizer.init_from(self.params)
            self.opt_state = {}
            self._state_shardings = {}
            self._param_stream = None
            if param_tier != "none":
                from deepspeed_tpu.runtime.zero.param_stream import (
                    ParamStreamCoordinator)
                self._param_stream = ParamStreamCoordinator(self)
            return
        self.host_optimizer = None
        if self._onebit_enabled:
            from deepspeed_tpu.ops.onebit import (init_onebit_state,
                                                  validate_onebit)
            validate_onebit(self)
            init_onebit_state(self)
            return
        abstract_state = jax.eval_shape(self.optimizer.init, self.params)
        state_sh = self.plan.opt_state_shardings(abstract_state)
        self.opt_state = jax.jit(self.optimizer.init,
                                 out_shardings=state_sh)(self.params)
        self._state_shardings = state_sh

    # ------------------------------------------------------------- jit build

    def _batch_sharding(self, batch_like) -> Pytree:
        """Shard batch dim over DP axes (and seq dim over 'seq' if SP>1)."""
        sp = self.mesh.shape["seq"] > 1

        def spec_for(x):
            nd = np.ndim(x)
            if nd == 0:
                return NamedSharding(self.mesh, P())
            entries = [ZERO_AXES] + [None] * (nd - 1)
            if sp and nd >= 2:
                entries[1] = "seq"
            return NamedSharding(self.mesh, P(*entries))
        return jax.tree.map(spec_for, batch_like)

    def _compute_loss_and_grads(self, params, batch, scale, rng):
        def scaled_loss(p):
            out = self.model.loss_fn(p, batch, rng)
            loss, metrics = (out if isinstance(out, tuple) else (out, {}))
            return loss * scale, (loss, metrics)
        grads, (loss, metrics) = jax.grad(scaled_loss, has_aux=True)(params)
        grads = jax.lax.with_sharding_constraint(
            grads, self.plan.grad_shardings())
        return loss, metrics, grads

    def _apply_update(self, params, opt_state, scaler, grads, step, gas,
                      fwd_metrics=None):
        cfg = self.config
        inv = 1.0 / (scaler.scale * gas)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
        overflow = check_overflow(grads) if self.fp16_enabled else \
            jnp.zeros((), bool)
        # global grad norm (reference get_global_norm + clip_grad_norm_)
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in jax.tree.leaves(grads))
        grad_norm = jnp.sqrt(sq)
        # per-layer health norms use the same pre-clip convention as the
        # global grad norm above
        unclipped = grads
        if cfg.gradient_clipping > 0:
            clip = jnp.minimum(1.0, cfg.gradient_clipping /
                               (grad_norm + 1e-6))
            grads = jax.tree.map(lambda g: g * clip, grads)
        lr = self.lr_schedule(step)
        new_params, new_opt = self.optimizer.update(
            grads, opt_state, params, lr)
        if self.fp16_enabled:
            new_params = jax.tree.map(
                lambda n, o: jnp.where(overflow, o, n), new_params, params)
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(overflow, o, n), new_opt, opt_state)
            scaler = update_scale(
                scaler, overflow, dynamic=self.dynamic_loss_scale,
                scale_window=cfg.fp16.loss_scale_window,
                min_scale=cfg.fp16.min_loss_scale,
                delayed_shift=cfg.fp16.hysteresis,
                consecutive_hysteresis=cfg.fp16.consecutive_hysteresis)
        new_params = jax.lax.with_sharding_constraint(
            new_params, self._param_shardings)
        metrics = {"lr": lr, "grad_norm": grad_norm,
                   "loss_scale": scaler.scale,
                   "overflow": overflow.astype(jnp.int32)}
        if fwd_metrics and "aux_loss" in fwd_metrics:
            metrics["aux_loss"] = fwd_metrics["aux_loss"]
        if getattr(self, "_health_enabled", False):
            health = self._per_layer_health(params, unclipped, new_params)
            fh = (fwd_metrics or {}).get("health")
            if fh:
                health = {**health, **fh}
            if health:
                metrics["health"] = health
        return new_params, new_opt, scaler, metrics

    @staticmethod
    def _per_layer_health(params, grads, new_params):
        """In-graph per-layer training dynamics over the stacked
        ``params['layers']`` subtree (under the scanned-decoder layout
        every leaf there carries a leading [L] layer axis): per-layer
        grad norm, param norm, and the update/param ratio — the classic
        divergence precursors. Pure [L]-vector reductions fused into the
        step program; models without a stacked ``layers`` subtree simply
        contribute no per-layer optimizer stats."""
        if not (isinstance(params, dict) and "layers" in params):
            return {}

        def per_layer_sq(tree):
            tot = None
            for leaf in jax.tree.leaves(tree):
                if leaf.ndim < 1:
                    continue
                s = jnp.sum(jnp.square(leaf.astype(jnp.float32)),
                            axis=tuple(range(1, leaf.ndim)))
                tot = s if tot is None else tot + s
            return tot

        g = per_layer_sq(grads["layers"])
        if g is None:
            return {}
        p = per_layer_sq(params["layers"])
        u = per_layer_sq(jax.tree.map(
            lambda n, o: n.astype(jnp.float32) - o.astype(jnp.float32),
            new_params["layers"], params["layers"]))
        param_norm = jnp.sqrt(p)
        return {"grad_norm": jnp.sqrt(g), "param_norm": param_norm,
                "update_ratio": jnp.sqrt(u) / (param_norm + 1e-12)}

    def _accumulate_grads(self, params, batch, scale, rng):
        """Shared GAS scan: stacked microbatches [gas, ...] → (fp32 grad
        sum carrying the ZeRO grad shardings, per-micro losses, loss_fn
        metrics pytree stacked on a leading [gas] axis)."""
        def micro(carry, mb):
            acc, r = carry
            r, sub = jax.random.split(r)
            loss, m, grads = self._compute_loss_and_grads(
                params, mb, scale, sub)
            acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), acc, grads)
            return (acc, r), (loss, m)

        zero = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                            params)
        zero = jax.lax.with_sharding_constraint(
            zero, self.plan.grad_shardings())
        (acc, _), (losses, fwd) = jax.lax.scan(micro, (zero, rng), batch)
        return acc, losses, fwd

    def _build_step_functions(self) -> None:
        gas = int(self.config.gradient_accumulation_steps)
        #: ZeRO-3 chunked-overlap plan; stays None on every path that
        #: doesn't run the standard fused step (zeropp/onebit/offload/
        #: pipeline fall through to monolithic collectives)
        self._overlap_plan = None

        if getattr(self, "_zeropp_enabled", False):
            from deepspeed_tpu.runtime.zero.zeropp import build_zeropp_step
            build_zeropp_step(self)
            return

        if getattr(self, "_onebit_enabled", False):
            from deepspeed_tpu.ops.onebit import build_onebit_step
            build_onebit_step(self)
            return

        if self.offload_enabled:
            if self.model.pipeline_loss_fn is not None:
                raise ValueError(
                    "pipeline parallelism with offload_optimizer.device="
                    "'cpu' is not supported yet — the host step would "
                    "bypass the pipeline schedule")
            self.offload_overlap = bool(
                self.config.zero_optimization.offload_optimizer.overlap)
            if self.offload_overlap and self.fp16_enabled:
                raise ValueError(
                    "offload_optimizer.overlap requires bf16/fp32 — fp16 "
                    "dynamic loss scaling needs the synchronous overflow "
                    "signal (ZenFlow has the same restriction)")
            layout = self.host_optimizer.layout
            # grads leave the device as ONE flat transfer-dtype array
            # (reference copies bit16 grads to pinned host buffers on a side
            # stream, stage_1_and_2.py:1332; here one D2H of the flat concat)
            transfer_dtype = self.compute_dtype

            def grads_only(params, batch, scale, rng):
                acc, losses, _fm = self._accumulate_grads(params, batch,
                                                          scale, rng)
                acc = jax.tree.map(lambda g: g * (1.0 / gas), acc)
                return layout.flatten_device(acc, transfer_dtype), \
                    jnp.mean(losses)

            self._offload_grad_step = jax.jit(grads_only)

            # flat compute-dtype master → params pytree with shardings
            self._offload_unflatten = jax.jit(
                lambda flat: layout.unflatten_device(
                    flat, [self.compute_dtype] * len(layout.shapes)),
                out_shardings=self._param_shardings)
            self._host_future = None
            self._fused_step = None
            zf_cfg = self.config.zero_optimization.zenflow
            if zf_cfg is not None:
                if self.fp16_enabled:
                    raise ValueError(
                        "zenflow requires bf16/fp32 (reference restriction:"
                        " fp16 loss scaling needs a synchronous overflow "
                        "signal)")
                if self.config.zero_optimization.offload_optimizer.superoffload:
                    raise ValueError(
                        "zenflow and superoffload are mutually exclusive "
                        "host-step pipelines; enable one")
                from deepspeed_tpu.runtime.zero.zenflow import (
                    ZenFlowCoordinator)
                self._zenflow = ZenFlowCoordinator(self)

            def single_grad(params, batch, scale, rng):
                loss, _m, grads = self._compute_loss_and_grads(
                    params, batch, scale, rng)
                return loss, grads

            self._grad_step = jax.jit(single_grad)
            self._acc_add = jax.jit(
                lambda acc, grads: jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), acc, grads),
                donate_argnums=(0,))
            self._update_step = None
            self._rng = jax.random.PRNGKey(self.config.seed + 1)
            return

        if self.model.pipeline_loss_fn is not None:
            # pipeline path: the schedule consumes all M microbatches in
            # one traced program; loss is already the mean over them.
            # 1F1B (pipeline_grad_fn) computes grads explicitly per
            # microbatch; GPipe (pipeline_loss_fn) goes through autodiff.
            def pipe_step(params, opt_state, scaler, batch, step, rng):
                if self.model.pipeline_grad_fn is not None:
                    loss, grads = self.model.pipeline_grad_fn(
                        params, batch, rng, scaler.scale)
                else:
                    def scaled(p):
                        loss = self.model.pipeline_loss_fn(p, batch, rng)
                        return loss * scaler.scale, loss
                    grads, loss = jax.grad(scaled, has_aux=True)(params)
                grads = jax.lax.with_sharding_constraint(
                    grads, self.plan.grad_shardings())
                params, opt_state, scaler, metrics = self._apply_update(
                    params, opt_state, scaler, grads, step, 1)
                metrics["loss"] = loss
                return params, opt_state, scaler, metrics

            self._fused_step = jax.jit(pipe_step, donate_argnums=(0, 1, 2))
            self._grad_step = None
            self._acc_add = None
            self._update_step = None
            self._rng = jax.random.PRNGKey(self.config.seed + 1)
            return

        if self.model.configure_overlap is not None:
            # arm the chunked ZeRO-3 collective pipeline BEFORE tracing:
            # the hook stores the plan in the factory's loss_fn closure,
            # so every step function traced below picks up the chunked
            # layer loop (runtime/zero/overlap.py)
            self._overlap_plan = self.model.configure_overlap(
                self.mesh, self._abstract_params)
            if self._overlap_plan is not None:
                from deepspeed_tpu.runtime.zero import overlap as _overlap
                _overlap.verify_scheduler_flags()
                self._overlap_plan.publish_static_gauges()

        # fused train_batch step: batch leaves have leading [gas, ...] dim
        def fused_step(params, opt_state, scaler, batch, step, rng):
            # runs at trace time only: the zero-retrace guarantee for the
            # health taps is asserted against this counter
            telemetry.compile_monitor.count_trace("engine/fused_step")
            if gas == 1:
                mb = jax.tree.map(lambda x: x[0], batch)
                rng, sub = jax.random.split(rng)
                loss, fwd, acc = self._compute_loss_and_grads(
                    params, mb, scaler.scale, sub)
                losses = loss[None]
            else:
                # accumulate in fp32 over microbatches (reference knob
                # gradient_accumulation_dtype); the accumulator carries the
                # grad shardings so ZeRO-2+ keeps it scattered across steps
                acc, losses, fwd = self._accumulate_grads(
                    params, batch, scaler.scale, rng)
                # collapse the [gas] axis: means throughout (act_absmax
                # becomes a mean-of-maxes across microbatches)
                fwd = jax.tree.map(lambda x: jnp.mean(x, axis=0), fwd)
            params, opt_state, scaler, metrics = self._apply_update(
                params, opt_state, scaler, acc, step, gas, fwd_metrics=fwd)
            metrics["loss"] = jnp.mean(losses)
            return params, opt_state, scaler, metrics

        self._fused_step = jax.jit(
            fused_step, donate_argnums=(0, 1, 2),
            static_argnames=())

        # parity API pieces
        def grad_step(params, batch, scale, rng):
            loss, metrics, grads = self._compute_loss_and_grads(
                params, batch, scale, rng)
            return loss, grads

        self._grad_step = jax.jit(grad_step)

        def acc_add(acc, grads):
            return jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), acc, grads)

        self._acc_add = jax.jit(acc_add, donate_argnums=(0,))

        def update_step(params, opt_state, scaler, grads, step):
            return self._apply_update(params, opt_state, scaler, grads,
                                      step, gas)

        self._update_step = jax.jit(update_step, donate_argnums=(0, 1, 2, 3))

        self._rng = jax.random.PRNGKey(self.config.seed + 1)

    # ----------------------------------------------------------- parity API

    def is_gradient_accumulation_boundary(self) -> bool:
        """Reference engine.py:is_gradient_accumulation_boundary."""
        gas = int(self.config.gradient_accumulation_steps)
        return (self.micro_steps + 1) % gas == 0

    def forward(self, batch: Batch) -> jax.Array:
        """Compute loss (+ cache grads for the following backward).

        Not supported under pipeline parallelism — use train_batch
        (reference: PipelineEngine raises the same way, pipe/engine.py).

        The reference runs autograd lazily; jax computes loss and grads in
        one fused call here — ``backward`` then folds the cached grads into
        the accumulator, preserving the 3-call API exactly."""
        if self._grad_step is None:
            raise RuntimeError(
                "forward()/backward()/step() are not supported with "
                "pipeline parallelism or the ZeRO++ quantized path; use "
                "train_batch() (reference pipe/engine.py restriction)")
        if self._param_stream is not None:
            raise RuntimeError(
                "forward()/backward()/step() are not supported under "
                "offload_param (layer-streamed schedule); use train_batch()")
        if self._step_t0 is None:           # first micro of the window
            self._step_t0 = telemetry.tracer.now()
            if self._watchdog is not None:
                self._watchdog.arm("forward", step=self.global_steps)
        self._rng, sub = jax.random.split(self._rng)
        batch = self._place_batch(batch)
        with telemetry.tracer.span("train/forward", step=self.global_steps):
            loss, grads = self._grad_step(self.params, batch,
                                          self.loss_scale_state.scale, sub)
        self._pending_grads = grads
        self._pending_loss = loss
        return loss

    def backward(self, loss: jax.Array) -> jax.Array:
        """Fold pending grads into the accumulator (reference engine.py:2478)."""
        if getattr(self, "_pending_grads", None) is None:
            raise RuntimeError("backward() called without forward()")
        with telemetry.tracer.span("train/backward", step=self.global_steps):
            if self._acc_grads is None:
                self._acc_grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32), self._pending_grads)
            else:
                self._acc_grads = self._acc_add(self._acc_grads,
                                                self._pending_grads)
        self._pending_grads = None
        self.micro_steps += 1
        return loss

    def step(self) -> None:
        """Optimizer step at GAS boundary (reference engine.py:2653)."""
        gas = int(self.config.gradient_accumulation_steps)
        if self.micro_steps % gas != 0:
            return
        if self._acc_grads is None:
            raise RuntimeError("step() called with no accumulated gradients")
        if self.offload_enabled:
            with telemetry.tracer.span("train/optimizer",
                                       step=self.global_steps):
                grads = jax.tree.map(lambda g: g / gas, self._acc_grads)
                metrics = self._host_step(grads)
            self._acc_grads = None
            self.global_steps += 1
            self.global_samples += int(self.config.train_batch_size)
            self._last_metrics = metrics
            self._close_step_span()
            self._write_monitor(metrics)
            return
        with telemetry.tracer.span("train/optimizer", step=self.global_steps):
            self.params, self.opt_state, self.loss_scale_state, metrics = \
                self._update_step(self.params, self.opt_state,
                                  self.loss_scale_state, self._acc_grads,
                                  jnp.int32(self.global_steps))
        self._acc_grads = None
        self.global_steps += 1
        self.global_samples += int(self.config.train_batch_size)
        if self.fp16_enabled and int(jax.device_get(metrics["overflow"])):
            self.skipped_steps += 1
        metrics = self._note_health(metrics)
        self._last_metrics = metrics
        self._close_step_span()
        self._write_monitor(metrics)

    def train_batch(self, data_iter: Optional[Iterator[Batch]] = None
                    ) -> jax.Array:
        """Fused whole-step path (reference PipelineEngine.train_batch:337 —
        here the non-pipeline fast path; pipeline engine overrides)."""
        gas = int(self.config.gradient_accumulation_steps)
        own_data = data_iter is None
        it = data_iter if data_iter is not None else self._own_data_iterator()
        # chaos hook (resilience/faults.py): a scheduled preempt delivers
        # SIGTERM here — this step completes and the elastic agent commits
        # at its boundary; a nonfinite_grad advisory poisons THIS step
        # (handled after the batch is consumed, like an overflow skip)
        chaos = fault_injector.fire("train_step", step=self.global_steps)
        micros = [next(it) for _ in range(gas)]
        batch = jax.tree.map(lambda *xs: jnp.stack(xs), *micros)
        if self.config.check_nan_inf:
            self._check_batch_consistency(micros, local=own_data)
        batch = self._place_stacked_batch(batch, local=own_data)
        if "nonfinite_grad" in chaos:
            return self._skip_poisoned_step(gas)
        self.tput_timer.start()
        self._step_t0 = telemetry.tracer.now()
        if self._watchdog is not None:
            self._watchdog.arm("train_batch", step=self.global_steps)
        self._rng, sub = jax.random.split(self._rng)
        if self._param_stream is not None or self._zenflow is not None:
            runner = self._param_stream or self._zenflow
            loss = runner.train_step(batch, sub)
            self.global_steps += 1
            self.micro_steps += gas
            self.global_samples += int(self.config.train_batch_size)
            if self.curriculum_scheduler is not None:
                self.curriculum_scheduler.update_difficulty(self.global_steps)
            self.tput_timer.stop(sync=loss)
            self._close_step_span()
            self._write_monitor(self._last_metrics)
            return loss
        if self.offload_enabled:
            # dispatch device fwd/bwd first (async); with overlap the host
            # Adam for the PREVIOUS step runs while this executes
            flat_g, loss = self._offload_grad_step(
                self.params, batch, self.loss_scale_state.scale, sub)
            lr = float(jax.device_get(
                self.lr_schedule(jnp.int32(self.global_steps))))
            scale = float(jax.device_get(self.loss_scale_state.scale)) \
                if self.fp16_enabled else 1.0
            # SuperOffload consumes the DEVICE array (bucketed fetch
            # pipelined against the sweep); the plain path fetches once.
            # Keyed off the optimizer actually built — the config flag
            # alone could disagree (e.g. device='nvme' wins over it)
            from deepspeed_tpu.runtime.zero.superoffload import (
                SuperOffloadOptimizer)
            superoffload = isinstance(self.host_optimizer,
                                      SuperOffloadOptimizer)
            g_arg = flat_g if superoffload else np.asarray(flat_g)
            if self.offload_overlap:
                self._drain_host_step()          # apply step t-1's update
                self._host_future = self.host_optimizer.step_flat_async(
                    g_arg, lr, grad_clip=self.config.gradient_clipping,
                    loss_scale=scale,
                    wait_on=getattr(self, "_last_upload", None))
                metrics = dict(getattr(self, "_last_host_metrics", None) or
                               {"grad_norm": 0.0, "overflow": 0, "lr": lr})
            else:
                metrics = self._apply_host_result(
                    self.host_optimizer.step_flat(
                        g_arg, lr, grad_clip=self.config.gradient_clipping,
                        loss_scale=scale))
            metrics["loss"] = loss
            self.global_steps += 1
            self.micro_steps += gas
            self.global_samples += int(self.config.train_batch_size)
            if self.curriculum_scheduler is not None:
                self.curriculum_scheduler.update_difficulty(self.global_steps)
            self._last_metrics = metrics
            self.tput_timer.stop(sync=loss)
            self._close_step_span()
            self._write_monitor(metrics)
            return loss
        self.params, self.opt_state, self.loss_scale_state, metrics = \
            self._fused_step(self.params, self.opt_state,
                             self.loss_scale_state, batch,
                             jnp.int32(self.global_steps), sub)
        self.global_steps += 1
        self.micro_steps += gas
        self.global_samples += int(self.config.train_batch_size)
        if self.curriculum_scheduler is not None:
            self.curriculum_scheduler.update_difficulty(self.global_steps)
        if self.fp16_enabled and int(jax.device_get(metrics["overflow"])):
            self.skipped_steps += 1
        metrics = self._note_health(metrics)
        self._last_metrics = metrics
        loss = metrics["loss"]
        self.tput_timer.stop(sync=loss)
        self._close_step_span()
        self._write_monitor(metrics)
        return loss

    def _skip_poisoned_step(self, gas: int) -> jax.Array:
        """Recovery path for an injected ``nonfinite_grad``: treat the
        step exactly like an fp16 overflow skip — the batch is consumed,
        the host rng advances, every counter moves, but params/opt_state
        stay untouched and the returned loss is NaN. Keeping the rng and
        counter discipline identical to a real step is what lets a
        chaos run keep bitwise resume parity with an uninterrupted one."""
        self._rng, _ = jax.random.split(self._rng)
        self.global_steps += 1
        self.micro_steps += gas
        self.global_samples += int(self.config.train_batch_size)
        self.skipped_steps += 1
        if self.curriculum_scheduler is not None:
            self.curriculum_scheduler.update_difficulty(self.global_steps)
        metrics = {"loss": float("nan"), "grad_norm": float("nan"),
                   "overflow": 1}
        self._last_metrics = metrics
        record_recovery("skip_nonfinite", step=self.global_steps)
        self._close_step_span()
        self._write_monitor(metrics)
        return jnp.float32(float("nan"))

    def _check_batch_consistency(self, micros, local: bool = False) -> None:
        """Cross-process dataloader consistency (reference
        check_dataloader_inputs_same_across_ranks engine.py:520): every
        process must feed the same global batch or the SPMD step silently
        trains on garbage. Hash ALL microbatches, allgather, compare.

        ``local`` is the provenance flag from ``train_batch`` (own engine
        dataloader → per-process slices whose contents legitimately differ);
        a size heuristic alone can't distinguish a user iterator that merely
        happens to yield global-batch-sized leaves."""
        if jax.process_count() <= 1:
            return
        import hashlib
        h = hashlib.sha256()
        for leaf in jax.tree.leaves(micros):
            leaf = np.asarray(leaf)
            if local and leaf.ndim:
                # per-process local slices: contents legitimately differ;
                # the invariant is structural (same shapes/dtypes) plus
                # identical loader schedule, checked via seed/epoch below
                h.update(repr((leaf.shape, str(leaf.dtype))).encode())
            else:
                h.update(np.ascontiguousarray(leaf).tobytes())
        if self.training_dataloader is not None:
            h.update(repr((self.training_dataloader.seed,
                           self.training_dataloader.epoch)).encode())
        if self.data_sampler is not None:
            # sampler position must agree or the per-process slices come
            # from different steps and assemble a garbage global batch
            h.update(repr(self.data_sampler.state_dict()).encode())
        digest = np.frombuffer(h.digest()[:8], np.int64)
        from jax.experimental import multihost_utils
        all_digests = multihost_utils.process_allgather(digest)
        if not np.all(all_digests == digest):
            raise RuntimeError(
                "sanity check failed: dataloader batches differ across "
                "processes (reference engine.py:520 check)")

    def eval_batch(self, data_iter: Optional[Iterator[Batch]] = None
                   ) -> jax.Array:
        """Forward-only loss over one global batch — no gradients, no
        state change (reference PipelineEngine.eval_batch / engine eval
        usage). Works in every engine mode, including ZeRO++ flat storage
        (params unflattened on the fly), pipeline (GPipe loss fn), and the
        offload_param tier (forward-only layer streaming)."""
        if self._param_stream is not None:
            if data_iter is None:
                raise ValueError("eval_batch needs an explicit data_iter")
            gas = int(self.config.gradient_accumulation_steps)
            losses = [self._param_stream.eval_step(next(data_iter))
                      for _ in range(gas)]
            return jnp.mean(jnp.stack(losses))
        if self.offload_enabled:
            self._drain_host_step()     # overlap mode: apply the pending
            #                             update or we'd eval stale weights
        if data_iter is None:
            raise ValueError(
                "eval_batch needs an explicit data_iter — consuming the "
                "engine's training iterator would silently skip training "
                "samples (reference eval_batch takes its own loader)")
        gas = int(self.config.gradient_accumulation_steps)
        it = data_iter
        micros = [next(it) for _ in range(gas)]
        batch = jax.tree.map(lambda *xs: jnp.stack(xs), *micros)
        if self.config.check_nan_inf:
            self._check_batch_consistency(micros)
        batch = self._place_stacked_batch(batch)
        # derive an eval key WITHOUT advancing the training rng stream —
        # eval must not perturb training reproducibility
        sub = jax.random.fold_in(self._rng, self.global_steps)
        if getattr(self, "_eval_step", None) is None:
            if self.model.pipeline_loss_fn is not None:
                def eval_fn(params, batch, rng):
                    return self.model.pipeline_loss_fn(params, batch, rng)
            else:
                def eval_fn(params, batch, rng):
                    def micro(carry, mb):
                        r = carry
                        r, s = jax.random.split(r)
                        out = self.model.loss_fn(self._eval_params(params),
                                                 mb, s)
                        loss = out[0] if isinstance(out, tuple) else out
                        return r, loss
                    _, losses = jax.lax.scan(micro, rng, batch)
                    return jnp.mean(losses)
            self._eval_step = jax.jit(eval_fn)
        return self._eval_step(self.params, batch, sub)

    def _eval_params(self, params):
        """Engine-mode params view for evaluation (ZeRO++ stores flat)."""
        if getattr(self, "_zeropp_enabled", False):
            layout = self._zeropp_layout
            return layout.unflatten_device(params[:layout.total])
        return params

    def _apply_host_result(self, result) -> Dict[str, Any]:
        """Upload the host step's flat master (ONE device_put + jitted
        unflatten) and fold in overflow/loss-scale bookkeeping."""
        new_flat, metrics = result
        if new_flat is None:          # fp16 overflow: skip
            self.skipped_steps += 1
        else:
            # split transfer from compute: _last_upload tracks ONLY the H2D
            # DMA of the host buffer, so the next host step can block on it
            # (buffer-reuse hazard) without waiting on queued device work
            flat_dev = jnp.asarray(new_flat)
            self._last_upload = flat_dev
            self.params = self._offload_unflatten(flat_dev)
        if self.fp16_enabled:
            from deepspeed_tpu.runtime.loss_scaler import update_scale
            self.loss_scale_state = update_scale(
                self.loss_scale_state,
                jnp.asarray(bool(metrics["overflow"])),
                dynamic=self.dynamic_loss_scale,
                scale_window=self.config.fp16.loss_scale_window,
                min_scale=self.config.fp16.min_loss_scale,
                delayed_shift=self.config.fp16.hysteresis,
                consecutive_hysteresis=self.config.fp16.consecutive_hysteresis)
        self._last_host_metrics = dict(metrics)
        return dict(metrics)

    def _drain_host_step(self) -> None:
        """Wait for an in-flight overlapped host step and apply it."""
        if getattr(self, "_zenflow", None) is not None:
            self._zenflow.drain()
        if getattr(self, "_host_future", None) is not None:
            fut, self._host_future = self._host_future, None
            self._apply_host_result(fut.result())

    def _host_step(self, grads: Pytree) -> Dict[str, Any]:
        """ZeRO-Offload update from a grads pytree (3-call parity path)."""
        lr = float(jax.device_get(
            self.lr_schedule(jnp.int32(self.global_steps))))
        scale = float(jax.device_get(self.loss_scale_state.scale)) \
            if self.fp16_enabled else 1.0
        flat_g = self.host_optimizer.layout.flatten_np(grads)
        return self._apply_host_result(self.host_optimizer.step_flat(
            flat_g, lr, grad_clip=self.config.gradient_clipping,
            loss_scale=scale))

    def _own_data_iterator(self):
        """Persistent epoch-advancing iterator over the engine dataloader
        (reference: the engine owns training_dataloader, deepspeed_io:2035)."""
        if self.training_dataloader is None:
            raise RuntimeError(
                "train_batch() without data_iter requires training_data at "
                "initialize()")
        if getattr(self, "_data_iter", None) is None:
            from deepspeed_tpu.runtime.dataloader import RepeatingLoader
            self._data_iter = iter(RepeatingLoader(self.training_dataloader))
        return self._data_iter

    # -------------------------------------------------------------- batches

    def _put_global(self, x, sharding, batch_dim: int, local: bool):
        """Assemble a global array on ``sharding``. Two multi-host modes
        (reference DistributedSampler rank sharding vs replicated input):
        when the batch came from the engine's own dataloader (``local``),
        each leaf's batch dim is ``global/process_count`` — this process's
        slice, assembled zero-copy via
        ``jax.make_array_from_process_local_data``. User-supplied batches
        are identical on every process and device_put scatters them (the
        size check alone can't distinguish a slice from e.g. a broadcast
        [1, ...] mask leaf, so ``local`` is decided by provenance)."""
        x = jnp.asarray(x) if not isinstance(x, (np.ndarray, jax.Array)) \
            else x
        pc = jax.process_count()
        if local and pc > 1 and np.ndim(x) > batch_dim:
            global_b = int(self.config.train_micro_batch_size_per_gpu) \
                * self.dp_world_size
            if x.shape[batch_dim] * pc == global_b:
                gshape = list(x.shape)
                gshape[batch_dim] = global_b
                return jax.make_array_from_process_local_data(
                    sharding, np.asarray(x), tuple(gshape))
        return jax.device_put(jnp.asarray(x), sharding)

    def _place_batch(self, batch: Batch, local: bool = False) -> Batch:
        sh = self._batch_sharding(batch)
        return jax.tree.map(
            lambda x, s: self._put_global(x, s, 0, local), batch, sh)

    def _place_stacked_batch(self, batch: Batch, local: bool = False
                             ) -> Batch:
        """batch leaves: [gas, B, ...] — shard B (dim 1) over DP."""
        sp = self.mesh.shape["seq"] > 1

        def spec_for(x):
            nd = np.ndim(x)
            entries = [None, ZERO_AXES] + [None] * (nd - 2)
            if sp and nd >= 3:
                entries[2] = "seq"
            return NamedSharding(self.mesh, P(*entries))
        sh = jax.tree.map(spec_for, batch)
        return jax.tree.map(
            lambda x, s: self._put_global(x, s, 1, local), batch, sh)

    def _build_dataloader(self, training_data):
        self.curriculum_scheduler = None
        self.data_sampler = None
        if training_data is None:
            return None
        from deepspeed_tpu.runtime.dataloader import DeepSpeedTPUDataLoader
        micro = int(self.config.train_micro_batch_size_per_gpu)
        de = self.config.data_efficiency
        sampler = None
        if de.enabled and (de.curriculum_learning.get("enabled")
                           or de.data_sampling.get("enabled")):
            gas = int(self.config.gradient_accumulation_steps)
            # reference deepspeed_io:2035 builds DeepSpeedDataSampler when
            # data-efficiency sampling/curriculum is on; difficulty metric
            # comes from the analyzer output (here: config-provided values,
            # a .npy path, or per-sample len() as the fallback metric)
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler \
                import CurriculumScheduler
            from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
                DeepSpeedDataSampler)
            if de.curriculum_learning.get("enabled"):
                cl = {k: v for k, v in de.curriculum_learning.items()
                      if k != "enabled"}
                self.curriculum_scheduler = CurriculumScheduler(cl)
            ds_cfg = de.data_sampling
            metric = ds_cfg.get("metric_values")
            if metric is None and ds_cfg.get("metric_path"):
                metric = np.load(ds_cfg["metric_path"])
            if metric is None:
                metric = [_sample_difficulty(training_data[i])
                          for i in range(len(training_data))]
                if len(set(metric)) <= 1:
                    msg = ("the fallback difficulty metric (first-array-leaf "
                           "length) is constant over this dataset, so "
                           "difficulty gating is a no-op — provide "
                           "'metric_values' or 'metric_path' (reference: "
                           "data_analyzer.py output files)")
                    if ds_cfg.get("enabled"):
                        # the user explicitly asked for metric-driven
                        # sampling: a silent no-op would be a lie
                        raise ValueError(f"data_sampling: {msg}")
                    # curriculum-only over fixed-length data: pacing by
                    # steps still works, difficulty gating just passes all
                    logger.warning(f"curriculum_learning: {msg}")
            if len(metric) != len(training_data):
                raise ValueError(
                    f"data_sampling metric has {len(metric)} entries but "
                    f"training_data has {len(training_data)} samples")
            sampler = DeepSpeedDataSampler(
                metric, batch_size=micro * self.dp_world_size,
                curriculum=self.curriculum_scheduler,
                dp_rank=jax.process_index(), dp_world=jax.process_count(),
                seed=de.seed, micro_steps_per_global_step=gas)
            self.data_sampler = sampler
        return DeepSpeedTPUDataLoader(
            training_data,
            micro_batch_size=micro,
            dp_world_size=self.dp_world_size,
            seed=self.config.seed,
            data_sampler=sampler)

    # ------------------------------------------------------------ telemetry

    def _init_telemetry(self) -> None:
        tcfg = self.config.telemetry
        telemetry.configure(tcfg)   # enable-only; never silences the tracer
        # arm the trace-time collective recorder from its config block
        # (jit is lazy — the step traces on the first train_batch, after
        # this runs)
        from deepspeed_tpu.comm.comms_logger import comms_logger
        comms_logger.configure(self.config)
        if tcfg.enabled and tcfg.trace_file:
            import atexit
            atexit.register(telemetry.tracer.dump, tcfg.trace_file)
        self._step_t0: Optional[float] = None
        self._mem_sampler = telemetry.MemorySampler() \
            if tcfg.sample_memory else None
        self._peak_flops = tcfg.peak_flops_override or \
            telemetry.peak_flops()
        fpt = getattr(self.model, "flops_per_token", None) or 0.0
        tps = getattr(self.model, "tokens_per_sample", None) or 0
        #: total model FLOPs per optimizer step across the whole batch
        #: (flops_per_token already counts fwd+bwd, the 6N convention)
        self._flops_per_step = fpt * tps * int(self.config.train_batch_size)
        # -- diagnostics layer (always-on flight recorder; opt-in watchdog)
        telemetry.flight_recorder.configure(
            max_steps=tcfg.flight_recorder_steps, path=tcfg.blackbox_path)
        telemetry.flight_recorder.set_meta(
            zero_stage=self.zero_stage, dtype=self.config.compute_dtype,
            dp_world_size=self.dp_world_size,
            train_batch_size=int(self.config.train_batch_size))
        telemetry.flight_recorder.install_excepthook()
        telemetry.compile_monitor.install(
            storm_threshold=tcfg.compile_storm_threshold)
        wcfg = tcfg.watchdog
        self._watchdog = telemetry.Watchdog(
            timeout_s=wcfg.step_timeout_s, action=wcfg.action,
            dump_dir=wcfg.dump_dir,
            heartbeat_file=wcfg.heartbeat_file or
            os.environ.get("DSTPU_HEARTBEAT_FILE") or None) \
            if wcfg.enabled else None
        # -- compile-time explain (PR 5): the static HBM budget is always
        # logged (pure metadata, no compile); the full roofline explain —
        # one extra XLA compile of the step — is opt-in
        self._roofline_predicted_s = 0.0
        # roofline terms kept for the overlap-fraction gauge: achieved
        # compute/comm overlap needs modeled compute_s and comm_s
        self._roofline_compute_s = 0.0
        self._roofline_comm_s = 0.0
        from deepspeed_tpu.telemetry import explain as _explain
        try:
            _explain.startup_budget(self)
        except Exception as e:                       # noqa: BLE001
            logger.debug(f"startup HBM budget skipped: {e}")
        if tcfg.explain_startup:
            try:
                report = _explain.explain_engine(self)
                _explain.publish_gauges(report)
                self._roofline_predicted_s = report.roofline.predicted_s
                self._roofline_compute_s = report.roofline.compute_s
                self._roofline_comm_s = report.roofline.comm_s
                log_dist("\n" + _explain.render(report))
            except Exception as e:                   # noqa: BLE001
                logger.warning(f"explain_startup failed (non-fatal): {e}")
        # goodput ledger: feed it the modeled compute/comm split so the
        # comm_exposed category can be carved out of train-step time
        telemetry.goodput_ledger.set_roofline(self._roofline_compute_s,
                                              self._roofline_comm_s)
        # -- model-health taps (telemetry/health.py): stats are computed
        # in-graph EVERY step behind a static build-time flag (identical
        # program on- and off-cadence → zero retraces); ``every`` only
        # gates the host-side fetch/publish below
        hcfg = tcfg.health
        self._health_enabled = bool(hcfg.enabled)
        self._health_monitor = None
        if hcfg.enabled:
            from deepspeed_tpu.telemetry.health import HealthMonitor
            self._health_monitor = HealthMonitor(
                every=hcfg.every, max_layers=hcfg.max_layers,
                z_threshold=hcfg.z_threshold,
                dead_fraction=hcfg.dead_fraction)
        # -- resilience: arm the deterministic fault injector from config
        # (env DSTPU_FAULT_PLAN is merged inside arm()) and push the
        # checkpoint IO retry knobs into the store module
        rcfg = getattr(self.config, "resilience", None)
        if rcfg is not None:
            from deepspeed_tpu.checkpoint import store as _ckpt_store
            _ckpt_store.IO_RETRIES = int(rcfg.ckpt_io_retries)
            _ckpt_store.IO_BACKOFF_S = float(rcfg.ckpt_io_backoff_s)
            if rcfg.fault_plan or os.environ.get("DSTPU_FAULT_PLAN"):
                fault_injector.arm(rcfg.fault_plan)
        self._metrics_server = None
        if tcfg.http_port is not None:
            import atexit
            from deepspeed_tpu.telemetry.endpoint import MetricsServer
            try:
                self._metrics_server = MetricsServer(
                    tcfg.http_port,
                    heartbeat_file=wcfg.heartbeat_file or
                    os.environ.get("DSTPU_HEARTBEAT_FILE") or None)
                atexit.register(self._metrics_server.close)
            except Exception as e:                   # noqa: BLE001
                logger.warning(
                    f"metrics endpoint on :{tcfg.http_port} failed: {e}")
        # -- metric history + SLO burn-rate engine: a history_file key or
        # any slo.objectives turns continuous evaluation on (the history
        # runs memory-only when no file is configured); the SLO engine
        # subscribes to history appends, so one registry snapshot per
        # flush feeds the file, the burn gauges, /healthz, and the
        # flight recorder together
        self._metric_history = None
        self._slo = None
        scfg = getattr(self.config, "slo", None)
        if tcfg.history_file or (scfg is not None and scfg.objectives):
            from deepspeed_tpu.telemetry.slo import engine_from_config
            from deepspeed_tpu.telemetry.timeseries import MetricHistory
            try:
                self._metric_history = MetricHistory(
                    path=tcfg.history_file,
                    max_bytes=tcfg.history_max_bytes,
                    downsample=tcfg.history_downsample)
                self._slo = engine_from_config(
                    scfg, healthz=self._metrics_server)
                if self._slo is not None:
                    self._metric_history.subscribe(self._slo.observe)
                    log_dist(f"SLO engine armed: "
                             f"{[o.describe() for o in self._slo.objectives]}")
            except Exception as e:                   # noqa: BLE001
                logger.warning(f"metric history/SLO init failed: {e}")
                self._metric_history = self._slo = None

    def _record_step_telemetry(self, dt_s: float) -> None:
        """Per-step registry metrics (always on — the registry is cheap).

        ``dt_s`` is HOST wall time for the step: under jax async dispatch
        it measures dispatch + any host work, not device latency, except
        on steps something synced (ThroughputTimer reporting steps, host
        optimizer sweeps). The MFU gauge inherits this caveat; the synced
        per-interval throughput line remains the calibrated number."""
        reg = telemetry.registry
        reg.counter("train/steps", help="optimizer steps completed").inc()
        if dt_s > 0:
            reg.histogram(
                "train/step_time_ms", lo=1e-2, hi=1e6,
                help="host wall time per optimizer step (ms)"
            ).record(dt_s * 1e3)
            reg.gauge(
                "train/mfu",
                help="model FLOPs utilization vs peak (0 when peak unknown)"
            ).set(telemetry.mfu(self._flops_per_step, dt_s,
                                n_devices=jax.device_count(),
                                peak=self._peak_flops or None))
            # step-time regression detection (host wall time, already a
            # float — no sync); loss/grad anomalies ride the batched
            # monitor flush instead (see _flush_monitor)
            telemetry.anomaly_detector.observe(self.global_steps,
                                               step_time_ms=dt_s * 1e3)
            if self._roofline_predicted_s > 0:
                reg.gauge(
                    "roofline/pct",
                    help="predicted/measured step time, percent"
                ).set(100.0 * self._roofline_predicted_s / dt_s)
            if getattr(self, "_overlap_plan", None) is not None:
                from deepspeed_tpu.runtime.zero.overlap import (
                    overlap_fraction)
                frac = overlap_fraction(self._roofline_compute_s,
                                        self._roofline_comm_s, dt_s)
                if frac is not None:
                    reg.gauge(
                        "overlap/fraction",
                        help="achieved compute/comm overlap, 0-1 "
                             "(hidden share of min(compute_s, comm_s))"
                    ).set(frac)
        if self._mem_sampler is not None and \
                self.global_steps % max(1, self.config.steps_per_print) == 0:
            self._mem_sampler.sample()
        # goodput ledger sweep (rate-limited internally; no-op when
        # telemetry.goodput is off) BEFORE the history flush so the
        # goodput/* gauges land in the same history record
        telemetry.goodput_ledger.maybe_update()
        # metric history: when the monitor is enabled the history rides
        # _flush_monitor's registry pass; without one (the common case)
        # feed it here on its own cadence so SLOs still evaluate
        if self._metric_history is not None and \
                (self.monitor is None or not self.monitor.enabled):
            every = getattr(self.config.telemetry, "history_every", 0) or \
                max(1, self.config.steps_per_print)
            if self.global_steps % max(1, every) == 0:
                telemetry.registry.flush_to_monitor(
                    None, self.global_steps, history=self._metric_history)
        # flight recorder: one dict append; loss/grad_norm/loss_scale stay
        # DEVICE scalars until a dump resolves them (no pipeline stall)
        m = getattr(self, "_last_metrics", None) or {}
        telemetry.flight_recorder.record_step(
            self.global_steps, kind="train", dur_s=dt_s,
            loss=m.get("loss"), grad_norm=m.get("grad_norm"),
            loss_scale=m.get("loss_scale") if self.fp16_enabled else None,
            skipped_steps=self.skipped_steps or None)

    def _scoped_finite_check(self) -> None:
        """``check_nan_inf="scoped"``: per-leaf finite check over the
        just-updated params — a non-finite grad propagates through the
        optimizer update, and fp16 overflow-skipped steps keep the old
        (finite) params, so this never false-positives on a handled
        overflow. Costs the mode's one documented scalar sync per step;
        a hit names the first bad leaf through telemetry/anomaly.py."""
        if not self._scoped_nan_check or self._param_stream is not None \
                or self.params is None:
            return
        from deepspeed_tpu.runtime.loss_scaler import global_check
        if self._scoped_check_jit is None:
            self._scoped_check_jit = jax.jit(global_check)
        bad, flags = self._scoped_check_jit(self.params)
        if bool(jax.device_get(bad)):
            path = telemetry.first_flagged_path(jax.device_get(flags))
            telemetry.anomaly_detector.report_nonfinite(
                self.global_steps, path, what="params")

    def _close_step_span(self) -> None:
        """Close the whole-step window opened by the first forward() of the
        accumulation window (or by train_batch): emit the ``train/step``
        span and the per-step registry metrics."""
        t1 = telemetry.tracer.now()
        t0 = self._step_t0 if self._step_t0 is not None else t1
        self._step_t0 = None
        if self._watchdog is not None:
            self._watchdog.disarm()
        telemetry.tracer.complete("train/step", t0, t1,
                                  step=self.global_steps)
        self._record_step_telemetry(t1 - t0)
        self._scoped_finite_check()

    # -------------------------------------------------------------- monitor

    def _build_monitor(self):
        try:
            from deepspeed_tpu.monitor.monitor import MonitorMaster
            return MonitorMaster(self.config.monitor_config)
        except Exception:
            return None

    def _note_health(self, metrics):
        """Route the in-graph model-health stats (vector-valued, computed
        every step — telemetry/health.py) out of the step metrics and into
        the HealthMonitor's cadence gate. Off-cadence steps drop the device
        refs unfetched — no transfer, no sync; the scalar metrics left in
        the dict keep flowing to the monitor/flight-recorder paths."""
        if not isinstance(metrics, dict):
            return metrics
        health = metrics.pop("health", None)
        hm = getattr(self, "_health_monitor", None)
        if hm is None or (health is None and "aux_loss" not in metrics):
            return metrics
        try:
            hm.note(self.global_steps, health,
                    aux_loss=metrics.get("aux_loss"))
        except Exception as e:                       # noqa: BLE001
            logger.warning(f"health telemetry publish failed: {e}")
        return metrics

    def _write_monitor(self, metrics: Dict[str, jax.Array]) -> None:
        # every step is RECORDED (the reference writes monitor events each
        # step when enabled, engine.py:2822 — decimating would drop TB/W&B
        # loss-curve resolution), but device scalars are held as futures and
        # fetched in one batched device_get on reporting steps: a per-step
        # float() here would block on the just-dispatched step and stall the
        # async/offload-overlap pipeline (see ThroughputTimer.stop)
        if self.monitor is None or not self.monitor.enabled:
            return
        self._monitor_pending.append(
            (self.global_steps,
             {k: v for k, v in metrics.items() if np.ndim(v) == 0}))
        if self.global_steps % max(1, self.config.steps_per_print) == 0:
            self._flush_monitor()

    def _flush_monitor(self) -> None:
        if not self._monitor_pending:
            return
        pending, self._monitor_pending = self._monitor_pending, []
        fetched = jax.device_get([m for _, m in pending])   # ONE transfer
        events = [(f"Train/{k}", float(val), step)
                  for (step, _), vals in zip(pending, fetched)
                  for k, val in vals.items()]
        self.monitor.write_events(events)
        # anomaly detection over the just-fetched host floats — same
        # batched cadence, so it never adds a device sync of its own
        for (step, _), vals in zip(pending, fetched):
            telemetry.anomaly_detector.observe(
                step,
                loss=vals.get("loss"),
                grad_norm=vals.get("grad_norm"))
            # MoE load-balancing pressure as a first-class gauge, visible
            # without the full health cadence (rides the same fetch)
            if "aux_loss" in vals:
                telemetry.registry.gauge(
                    "train/aux_loss",
                    help="MoE load-balancing auxiliary loss").set(
                    float(vals["aux_loss"]))
        # registry snapshot rides the same flush cadence (MFU, step-time
        # histogram aggregates, mem/* watermarks, comm/* counters); the
        # metric history + SLO evaluation share the same single lock pass
        telemetry.registry.flush_to_monitor(self.monitor, self.global_steps,
                                            history=self._metric_history)

    # ------------------------------------------------------------ utilities

    def get_lr(self) -> float:
        return float(jax.device_get(self.lr_schedule(jnp.int32(self.global_steps))))

    def get_global_grad_norm(self) -> Optional[float]:
        m = getattr(self, "_last_metrics", None)
        return float(jax.device_get(m["grad_norm"])) if m else None

    @property
    def train_micro_batch_size_per_gpu(self) -> int:
        return int(self.config.train_micro_batch_size_per_gpu)

    def train_batch_size(self) -> int:
        return int(self.config.train_batch_size)

    def gradient_accumulation_steps(self) -> int:
        return int(self.config.gradient_accumulation_steps)

    def loss_scale(self) -> float:
        return float(jax.device_get(self.loss_scale_state.scale))

    # ------------------------------------------------- offload/reload states

    def offload_states(self, include: Optional[Tuple[str, ...]] = None
                       ) -> None:
        """Move params/optimizer state to host DRAM and FREE the device
        buffers (reference runtime/zero/offload_states.py:90 +
        engine.offload_states — used to park a training engine while an
        inference engine owns HBM, e.g. RLHF generation phases)."""
        include = tuple(include or ("params", "opt_state"))
        if getattr(self, "_offloaded_states", None):
            raise RuntimeError("states already offloaded; reload first")
        def to_host(x):
            if not isinstance(x, jax.Array):
                return np.asarray(x)
            if x.is_fully_addressable:
                return np.asarray(jax.device_get(x))
            # multi-host sharded array: park only THIS process's shards
            # (device_get on the global array would raise); reload
            # reassembles via make_array_from_callback
            return _ParkedShards(
                shape=x.shape, dtype=x.dtype,
                shards={s.index: np.asarray(s.data)
                        for s in x.addressable_shards})

        parked: Dict[str, Any] = {}
        for name in include:
            tree = getattr(self, name)
            # `tree` may be a dict pytree OR one flat jax.Array (ZeRO++)
            if tree is None or (isinstance(tree, dict) and not tree):
                continue
            host = jax.tree.map(to_host, tree)
            for leaf in jax.tree.leaves(tree):
                if isinstance(leaf, jax.Array):
                    leaf.delete()          # actually release HBM
            parked[name] = host
            setattr(self, name, None)
        self._offloaded_states = parked

    def reload_states(self) -> None:
        """Restore offloaded states to device with their original
        shardings (reference engine.reload_states)."""
        parked = getattr(self, "_offloaded_states", None)
        if not parked:
            return
        shardings = {"params": self._param_shardings,
                     "opt_state": self._state_shardings}

        def restore(host, sh):
            if isinstance(host, _ParkedShards):
                return jax.make_array_from_callback(
                    host.shape, sh, lambda idx: host.shards[idx])
            return jax.device_put(host, sh)

        for name, host in parked.items():
            sh_tree = shardings[name]
            setattr(self, name, jax.tree.map(
                restore, host, sh_tree,
                is_leaf=lambda x: isinstance(x, _ParkedShards)))
        self._offloaded_states = None

    # --------------------------------------------------------- checkpointing

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict[str, Any]] = None,
                        save_latest: bool = True,
                        async_save: bool = False) -> None:
        """Reference engine.py:3621. Sharded universal format: each process
        writes its own shard fragments with full-array index metadata, so
        any later mesh/stage reloads with no converter (ds_to_universal is
        unnecessary) and no host ever gathers the full model.
        ``async_save`` commits on a background thread after a synchronous
        device→host snapshot (reference: DecoupledCheckpointEngine)."""
        from deepspeed_tpu.checkpoint.store import save_checkpoint as _save
        self._flush_monitor()         # don't lose buffered metric events
        if self.offload_enabled:
            self._drain_host_step()   # overlapped update must land first
        tag = tag or f"global_step{self.global_steps}"
        params = self.params if self._param_stream is None \
            else self._param_stream.full_params_np()
        state = {
            "params": params,
            "opt_state": self.opt_state,
            "loss_scale": self.loss_scale_state,
        }
        meta = {
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "global_samples": self.global_samples,
            "optimizer": self.optimizer.hyperparams,
            "client_state": client_state or {},
            "offload": self.offload_enabled,
            "data_sampler": (self.data_sampler.state_dict()
                             if self.data_sampler is not None else None),
            # exact-resume state: host PRNG key + dataloader cursor. With
            # these a preempt-at-step-k resume replays the SAME rng splits
            # and batch sequence the uninterrupted run would have seen
            "rng": np.asarray(jax.device_get(self._rng)).tolist(),
            "dataloader": (self.training_dataloader.state_dict()
                           if self.training_dataloader is not None and
                           hasattr(self.training_dataloader, "state_dict")
                           else None),
        }
        root = _save(save_dir, tag, state, meta, save_latest=save_latest,
                     async_save=async_save)
        if self.offload_enabled:
            np.savez(os.path.join(root, "host_optimizer.npz"),
                     **self.host_optimizer.state_dict())

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_module_strict: bool = True,
                        **_kw) -> Tuple[Optional[str], Dict[str, Any]]:
        """Reference engine.py:3273."""
        from deepspeed_tpu.checkpoint.store import load_checkpoint as _load
        if self.offload_enabled:
            self._drain_host_step()
        if self._param_stream is not None:
            # tier mode: params land on the HOST (cpu backend) and seed the
            # file store — the whole point is they don't fit device HBM
            cpu0 = jax.local_devices(backend="cpu")[0]
            sds = jax.sharding.SingleDeviceSharding(cpu0)
            tmpl = jax.tree.map(
                lambda s: np.zeros(s.shape, s.dtype),
                self._param_stream._abstract)
            state, meta, tag = _load(
                load_dir, tag, {"params": tmpl},
                {"params": jax.tree.map(lambda _: sds, tmpl)},
                strict=frozenset({"params"}) if load_module_strict
                else frozenset())
            if state is None:
                return None, {}
            with jax.default_device(cpu0):
                self._param_stream._seed_store(
                    jax.tree.map(jnp.asarray, state["params"]))
            host_path = os.path.join(load_dir, tag, "host_optimizer.npz")
            if load_optimizer_states and os.path.exists(host_path):
                self.host_optimizer.load_state_dict(dict(np.load(host_path)))
            else:
                # cross-mode checkpoint: rebuild the tiered master from
                # the loaded params (moments start fresh)
                self.host_optimizer.init_from(state["params"])
            self._param_stream._reload_resident()
            self.global_steps = meta.get("global_steps", 0)
            self.micro_steps = meta.get("micro_steps", 0)
            self.global_samples = meta.get("global_samples", 0)
            self._restore_resume_state(meta)
            return tag, meta.get("client_state", {})
        shardings = {
            "params": self._param_shardings,
            "loss_scale": jax.tree.map(lambda _: self.plan.replicated(),
                                       self.loss_scale_state),
        }
        templates = {
            "params": self.params,
            "loss_scale": self.loss_scale_state,
        }
        if load_optimizer_states and not self.offload_enabled:
            # only assemble (and strict-check) device optimizer state when it
            # will actually be consumed — a params-only resume or a cross-mode
            # load (offload checkpoints carry host_optimizer.npz instead)
            # must not fail on opt_state leaves it would discard anyway
            templates["opt_state"] = self.opt_state
            shardings["opt_state"] = self._state_shardings
        # load_module_strict gates MODULE (params) strictness only, as in the
        # reference; optimizer-state completeness is never waived by it —
        # opting out of a structural params check must not silently accept a
        # truncated optimizer state
        strict = frozenset(templates) if load_module_strict \
            else frozenset(templates) - {"params"}
        state, meta, tag = _load(load_dir, tag, templates, shardings,
                                 strict=strict)
        if state is None:
            return None, {}
        self.params = state["params"]
        if load_optimizer_states and self.offload_enabled:
            host_path = os.path.join(load_dir, tag, "host_optimizer.npz")
            if os.path.exists(host_path):
                self.host_optimizer.load_state_dict(dict(np.load(host_path)))
            else:
                # checkpoint from a non-offload run: rebuild master from
                # the loaded params (universal reshape across offload modes)
                self.host_optimizer.init_from(self.params)
        elif load_optimizer_states and not self.offload_enabled:
            if "opt_state" in state:
                self.opt_state = state["opt_state"]
            elif not self._onebit_enabled:
                # offload-run checkpoint (optimizer lives in
                # host_optimizer.npz) loaded into a non-offload engine:
                # rebuild device state from the LOADED params — fresh
                # moments, master = restored weights (mirror of the
                # init_from branch above)
                log_dist("checkpoint has no device opt_state group — "
                         "rebuilding from loaded params (cross-mode resume)")
                self.opt_state = jax.jit(
                    self.optimizer.init,
                    out_shardings=self._state_shardings)(self.params)
        if "loss_scale" in state:
            ls = state["loss_scale"]
            self.loss_scale_state = LossScaleState(*jax.tree.leaves(ls)) \
                if not isinstance(ls, LossScaleState) else ls
        self.global_steps = meta.get("global_steps", 0)
        self.micro_steps = meta.get("micro_steps", 0)
        self.skipped_steps = meta.get("skipped_steps", 0)
        self.global_samples = meta.get("global_samples", 0)
        if self.data_sampler is not None and meta.get("data_sampler"):
            self.data_sampler.load_state_dict(meta["data_sampler"])
        self._restore_resume_state(meta)
        return tag, meta.get("client_state", {})

    def _restore_resume_state(self, meta: Dict[str, Any]) -> None:
        """Restore the exact-resume extras (host rng key + dataloader
        cursor) from checkpoint meta. Older checkpoints simply lack the
        keys — resume still works, just without bitwise parity."""
        if meta.get("rng") is not None:
            self._rng = jnp.asarray(
                np.asarray(meta["rng"], dtype=np.uint32))
        if meta.get("dataloader") and self.training_dataloader is not None \
                and hasattr(self.training_dataloader, "load_state_dict"):
            self.training_dataloader.load_state_dict(meta["dataloader"])
            # drop any half-consumed iterator so the next train_batch
            # builds a fresh one starting AT the restored cursor
            self._data_iter = None


# ---------------------------------------------------------------------------
# initialize()
# ---------------------------------------------------------------------------

def initialize(model: Union[ModelSpec, Any] = None,
               config: Union[str, Dict[str, Any], DeepSpeedTPUConfig, None] = None,
               mesh: Optional[Mesh] = None,
               params: Optional[Pytree] = None,
               rng: Optional[jax.Array] = None,
               training_data=None,
               loss_fn: Optional[LossFn] = None,
               config_params=None,
               **_kw):
    """Reference deepspeed/__init__.py:78. Returns
    (engine, optimizer, dataloader, lr_scheduler) for API parity."""
    cfg = DeepSpeedTPUConfig.from_any(config if config is not None
                                      else config_params)
    spec = _coerce_model_spec(model, cfg, loss_fn)
    engine = DeepSpeedTPUEngine(spec, cfg, mesh=mesh, params=params, rng=rng,
                                training_data=training_data)
    return engine, engine.optimizer, engine.training_dataloader, \
        engine.lr_schedule


def _coerce_model_spec(model, cfg: DeepSpeedTPUConfig,
                       loss_fn: Optional[LossFn]) -> ModelSpec:
    if isinstance(model, ModelSpec):
        return model
    from deepspeed_tpu.models.transformer import DecoderConfig
    if isinstance(model, DecoderConfig):
        from deepspeed_tpu.runtime.model_factory import decoder_model_spec
        return decoder_model_spec(model, cfg)
    raise TypeError(
        "model must be a ModelSpec or a models.transformer.DecoderConfig; "
        f"got {type(model)}")
