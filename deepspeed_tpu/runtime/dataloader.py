"""Distributed dataloader.

Reference: runtime/dataloader.py (DeepSpeedDataLoader with
DistributedSampler) + engine.deepspeed_io:2035. TPU-native difference: one
process drives all local devices, so the loader yields **global**
microbatches of size micro_batch × dp_world; the engine shards the batch
dim over the DP mesh axes on device_put. Single-process scope for now:
multi-host loading (per-process slices assembled via
``jax.make_array_from_process_local_data``) is a planned follow-on and is
NOT yet implemented here.
"""

import math
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax
import numpy as np


class DeepSpeedTPUDataLoader:
    """Iterate a map-style dataset (indexable, len()) as global microbatches.

    Items may be dicts of arrays or tuples (input_ids, labels). A
    ``collate_fn`` may override batching.
    """

    def __init__(self, dataset, micro_batch_size: int, dp_world_size: int,
                 seed: int = 0, shuffle: bool = True, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None):
        self.dataset = dataset
        self.micro_batch_size = micro_batch_size
        self.dp_world_size = dp_world_size
        self.global_batch = micro_batch_size * dp_world_size
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.epoch = 0
        if len(dataset) < self.global_batch:
            raise ValueError(
                f"dataset of {len(dataset)} items smaller than one global "
                f"microbatch ({self.global_batch})")

    def __len__(self) -> int:
        n = len(self.dataset) // self.global_batch
        if not self.drop_last and len(self.dataset) % self.global_batch:
            n += 1
        return n

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        usable = len(order) - (len(order) % self.global_batch
                               if self.drop_last else 0)
        for start in range(0, usable, self.global_batch):
            idx = order[start:start + self.global_batch]
            if len(idx) < self.global_batch:
                if self.drop_last:
                    return
                # pad by wrapping (keeps static shapes for jit)
                idx = np.concatenate(
                    [idx, order[:self.global_batch - len(idx)]])
            yield self.collate_fn([self.dataset[int(i)] for i in idx])


def _default_collate(items: Sequence[Any]) -> Dict[str, np.ndarray]:
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(it[k]) for it in items])
                for k in first}
    if isinstance(first, (tuple, list)):
        names = ["input_ids", "labels"][:len(first)]
        return {n: np.stack([np.asarray(it[i]) for it in items])
                for i, n in enumerate(names)}
    return {"input_ids": np.stack([np.asarray(it) for it in items])}


class RepeatingLoader:
    """Reference runtime/dataloader.py:RepeatingLoader — wrap a loader to
    restart (epoch++) when exhausted."""

    def __init__(self, loader):
        self.loader = loader
        self._iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self._iter = iter(self.loader)
            return next(self._iter)
