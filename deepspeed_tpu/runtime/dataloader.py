"""Distributed dataloader.

Reference: runtime/dataloader.py (DeepSpeedDataLoader with
DistributedSampler) + engine.deepspeed_io:2035. TPU-native difference: one
process drives all local devices, so rank sharding happens at **process**
granularity, not device granularity. Each process loads only its
``global_batch / process_count`` slice of every global microbatch (the
analogue of the reference's DistributedSampler rank sharding); the engine
assembles the jax global array from the per-process slices via
``jax.make_array_from_process_local_data``. On one process the slice is
the whole batch and placement degenerates to a plain ``device_put``.

Curriculum / data-efficiency sampling (reference
``data_sampling/data_sampler.py:36`` + engine ``deepspeed_io``:2035) plugs
in as a ``data_sampler``: when given, the loader draws per-step index
batches from the sampler (difficulty-gated by the CurriculumScheduler)
instead of epoch-shuffled sequential order.
"""

from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax
import numpy as np


class DeepSpeedTPUDataLoader:
    """Iterate a map-style dataset (indexable, len()) as per-process slices
    of global microbatches.

    Items may be dicts of arrays or tuples (input_ids, labels). A
    ``collate_fn`` may override batching. ``process_index`` /
    ``process_count`` default to the jax runtime's; every process must
    construct the loader with the same seed so the shuffled orders agree
    and the slices partition each global batch.
    """

    def __init__(self, dataset, micro_batch_size: int, dp_world_size: int,
                 seed: int = 0, shuffle: bool = True, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 data_sampler=None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        self.dataset = dataset
        self.micro_batch_size = micro_batch_size
        self.dp_world_size = dp_world_size
        self.global_batch = micro_batch_size * dp_world_size
        self.process_index = (jax.process_index() if process_index is None
                              else int(process_index))
        self.process_count = (jax.process_count() if process_count is None
                              else int(process_count))
        if self.global_batch % self.process_count:
            raise ValueError(
                f"global microbatch {self.global_batch} not divisible by "
                f"process_count {self.process_count}")
        self.local_batch = self.global_batch // self.process_count
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.data_sampler = data_sampler
        self.epoch = 0
        #: microbatches already served this epoch — the resume cursor. A
        #: fresh ``iter()`` continues FROM the cursor (the epoch order is
        #: deterministic in (seed, epoch), so position is the whole
        #: dataloader state); ``set_epoch`` rewinds it to 0.
        self._cursor = 0
        if len(dataset) < self.global_batch:
            raise ValueError(
                f"dataset of {len(dataset)} items smaller than one global "
                f"microbatch ({self.global_batch})")

    def __len__(self) -> int:
        n = len(self.dataset) // self.global_batch
        if not self.drop_last and len(self.dataset) % self.global_batch:
            n += 1
        return n

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self._cursor = 0

    def state_dict(self) -> Dict[str, int]:
        """Exact resume cursor: (epoch, microbatches served within it).
        Checkpointed by the engine so a preempted-and-resumed run feeds
        the training loop the SAME batch sequence the uninterrupted run
        would have seen (resume parity)."""
        return {"epoch": int(self.epoch), "cursor": int(self._cursor),
                "seed": int(self.seed)}

    def load_state_dict(self, sd: Dict[str, int]) -> None:
        if int(sd.get("seed", self.seed)) != self.seed:
            raise ValueError(
                f"dataloader seed mismatch on resume: checkpoint has "
                f"{sd['seed']}, loader built with {self.seed} — the "
                f"shuffled orders would diverge silently")
        self.epoch = int(sd.get("epoch", 0))
        self._cursor = int(sd.get("cursor", 0))

    def _local_slice(self, idx: np.ndarray) -> np.ndarray:
        """This process's contiguous slice of a global index batch. The
        engine reassembles the global array from these slices, so slice i
        must cover the batch rows process i's devices own — contiguous
        process-major, matching mesh construction from jax.devices()."""
        start = self.process_index * self.local_batch
        return idx[start:start + self.local_batch]

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.data_sampler is not None:
            return self._sampler_iter()
        return self._epoch_iter()

    def _sampler_iter(self) -> Iterator[Dict[str, np.ndarray]]:
        # the sampler itself shards per process (dp_rank=process_index);
        # it yields this process's index slice per step, forever
        for idx in self.data_sampler:
            yield self.collate_fn([self.dataset[int(i)] for i in idx])

    def _epoch_iter(self) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        usable = len(order) - (len(order) % self.global_batch
                               if self.drop_last else 0)
        # the epoch order is a pure function of (seed, epoch), so resuming
        # is just skipping ``cursor`` microbatches' worth of indices —
        # no data is loaded for the skipped span
        for start in range(self._cursor * self.global_batch, usable,
                           self.global_batch):
            idx = order[start:start + self.global_batch]
            if len(idx) < self.global_batch:
                if self.drop_last:
                    return
                # pad by wrapping (keeps static shapes for jit)
                idx = np.concatenate(
                    [idx, order[:self.global_batch - len(idx)]])
            idx = self._local_slice(idx)
            self._cursor += 1
            yield self.collate_fn([self.dataset[int(i)] for i in idx])


def _default_collate(items: Sequence[Any]) -> Dict[str, np.ndarray]:
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(it[k]) for it in items])
                for k in first}
    if isinstance(first, (tuple, list)):
        names = ["input_ids", "labels"][:len(first)]
        return {n: np.stack([np.asarray(it[i]) for it in items])
                for i, n in enumerate(names)}
    return {"input_ids": np.stack([np.asarray(it) for it in items])}


class RepeatingLoader:
    """Reference runtime/dataloader.py:RepeatingLoader — wrap a loader to
    restart (epoch++) when exhausted."""

    def __init__(self, loader):
        self.loader = loader
        self._iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self._iter = iter(self.loader)
            return next(self._iter)
