"""Bridge from model configs to engine ModelSpecs.

Plays the role of the reference's module-injection policies
(module_inject/replace_module.py:189) — instead of mutating torch modules,
we compose the functional transformer core with the attention / MoE
implementation selected by the DeepSpeed config, and attach the sharding
plan (partition_specs) for AutoTP + ZeRO-3.
"""

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.config import DeepSpeedTPUConfig
from deepspeed_tpu.models import transformer
from deepspeed_tpu.models.transformer import (DecoderConfig,
                                              cross_entropy_loss,
                                              dot_product_attention)
from deepspeed_tpu.utils.logging import logger


#: pluggable attention implementations (the analogue of the reference's
#: inference/v2/modules registry: config-selected layer impls behind a
#: stable interface). Users register a custom ``attn_fn(q, k, v, causal=,
#: q_offset=)`` and select it via ``attention_impl`` in the config.
_ATTENTION_REGISTRY = {}


def register_attention_impl(name: str, fn) -> None:
    """Reference inference/v2/modules registry (ConfigBundle → impl)."""
    _ATTENTION_REGISTRY[name] = fn


def select_attention(ds_cfg: DeepSpeedTPUConfig,
                     dec_cfg: Optional[DecoderConfig] = None):
    """Pick the attention implementation from the config (reference: the
    replace_with_kernel_inject seam + DistributedAttention wrapping,
    sequence/layer.py:331).

    ``attention_impl``: 'auto' → chunked-XLA flash-style attention (never
    materializes [T,T]; every op is an einsum XLA tiles onto the MXU —
    robust on all TPU runtimes); 'pallas_flash' → the Pallas kernel;
    'naive' → reference dot-product (tests/short seqs)."""
    import jax as _jax
    on_tpu = _jax.default_backend() == "tpu"
    sp = ds_cfg.sequence_parallel
    impl = ds_cfg.attention_impl
    if impl in _ATTENTION_REGISTRY:
        if sp.size > 1:
            # the builtin impls get ring/Ulysses wrapping below; silently
            # running a raw custom impl on sequence shards would compute
            # wrong attention — make the combination an explicit error
            raise ValueError(
                f"attention_impl '{impl}' (registered) does not compose "
                f"with sequence_parallel.size={sp.size}: custom impls "
                f"must handle the 'seq' axis themselves — register an "
                f"SP-aware fn or use a builtin impl")
        if dec_cfg is not None and dec_cfg.layer_window_pattern:
            # forward_hidden feeds a traced per-layer `window=` kwarg —
            # a registered impl with the documented (q, k, v, causal=,
            # q_offset=) signature would TypeError at trace time
            raise ValueError(
                f"attention_impl '{impl}' (registered) does not support "
                f"per-layer attention windows (layer_window_pattern); "
                f"use a builtin impl for GPT-Neo-class models")
        if dec_cfg is not None and (dec_cfg.pos_emb == "alibi"
                                    or dec_cfg.sliding_window is not None
                                    or not dec_cfg.causal):
            from deepspeed_tpu.utils.logging import warning_once
            kind = ("ALiBi" if dec_cfg.pos_emb == "alibi" else
                    "sliding-window" if dec_cfg.sliding_window is not None
                    else "bidirectional (encoder)")
            warning_once(
                f"attention_impl '{impl}' (registered) is used as-is for "
                f"a model with {kind} attention — the impl itself must "
                f"apply the bias/window/non-causal mask or results will "
                f"silently differ")
        return _ATTENTION_REGISTRY[impl]
    if impl not in ("auto", "pallas_flash", "xla_chunked", "naive",
                    "fpdt"):
        raise ValueError(
            f"unknown attention_impl '{impl}'; expected 'auto'|"
            f"'pallas_flash'|'xla_chunked'|'naive'|'fpdt' or a name "
            f"registered via register_attention_impl "
            f"({sorted(_ATTENTION_REGISTRY)})")
    if impl == "fpdt":
        # FPDT chunked attention (reference fpdt_layer.py:510): q-chunked
        # online softmax with the KV store in pinned host DRAM — the
        # 256K+ single-chip regime, where even the flash kernel's
        # backward transients ([T, q_dim] q/k/v + dq/dk/dv) overflow
        # HBM. DSTPU_FPDT_CHUNK tunes the q/KV chunk (default 4096).
        if sp.size > 1:
            raise ValueError(
                "attention_impl 'fpdt' composes with sequence parallel "
                "by chunking each shard's local sequence — but the SP "
                "wrappers are applied instead of it today; use "
                "'auto' with sequence_parallel, or fpdt on one chip")
        if dec_cfg is not None and (
                not dec_cfg.causal or dec_cfg.pos_emb == "alibi"
                or dec_cfg.sliding_window is not None
                or dec_cfg.layer_window_pattern):
            raise ValueError(
                "attention_impl 'fpdt' supports full-causal decoders "
                "only (no ALiBi/sliding-window/encoder)")
        from deepspeed_tpu.parallel.fpdt import fpdt_attention
        return partial(fpdt_attention,
                       chunk=int(os.environ.get("DSTPU_FPDT_CHUNK",
                                                4096)))
    if dec_cfg is not None and dec_cfg.layer_window_pattern:
        # per-layer alternating windows (GPT-Neo): the window is a traced
        # scalar fed from the layer scan, which only the masked reference
        # path supports — the static block-skip kernels need a
        # compile-time window
        if sp.size > 1:
            raise ValueError(
                "sequence_parallel with per-layer attention windows "
                "(layer_window_pattern) is not supported")
        if impl in ("pallas_flash", "xla_chunked"):
            # honor the explicit kernel choice with a loud error, not a
            # silent downgrade
            raise ValueError(
                f"attention_impl '{impl}' cannot apply per-layer traced "
                f"windows (layer_window_pattern); use 'auto' or 'naive' "
                f"for GPT-Neo-class models")
        return dot_product_attention
    if dec_cfg is not None and not dec_cfg.causal:
        # encoders (BERT): bidirectional attention. The Pallas flash
        # kernel and the SP wrappers are causal-only today — route to
        # the chunked-XLA path (full T² is inherent here anyway).
        if sp.size > 1:
            raise ValueError(
                "sequence_parallel with a bidirectional (encoder) model "
                "is not supported; use DP/TP for BERT-class models")
        if impl == "pallas_flash":
            raise ValueError(
                "attention_impl 'pallas_flash' is causal-only; use "
                "'auto'/'xla_chunked'/'naive' for encoder (BERT-class) "
                "models")
        if impl == "naive":
            return partial(dot_product_attention, causal=False)
        from deepspeed_tpu.ops.xla_attention import chunked_attention
        return partial(chunked_attention, causal=False)
    if dec_cfg is not None and dec_cfg.pos_emb == "alibi":
        # ALiBi (BLOOM) adds a per-head score bias; the Pallas flash
        # kernel has no bias port, and head-sharded SP would need the
        # matching slope slice per shard — route to the chunked-XLA path
        # (still never materializes [T,T]) with slopes baked in.
        if sp.size > 1:
            raise ValueError("sequence_parallel with an ALiBi model is "
                             "not supported; use DP/TP/PP for BLOOM-class "
                             "models")
        from deepspeed_tpu.models.transformer import alibi_slopes
        from deepspeed_tpu.ops.xla_attention import chunked_attention
        return partial(chunked_attention,
                       alibi=alibi_slopes(dec_cfg.num_heads))
    window = dec_cfg.sliding_window if dec_cfg is not None else None
    if window is not None and sp.size > 1:
        raise ValueError(
            "sequence_parallel with sliding-window attention is not "
            "supported yet (the ring/Ulysses wrappers assume full causal "
            "attention); unset sliding_window or sequence_parallel")
    if sp.size > 1 and sp.mode == "ring":
        from deepspeed_tpu.parallel.ring import ring_attention
        return partial(ring_attention, axis_name="seq")
    wkw = {} if window is None else {"window": window}
    if impl == "pallas_flash" or (impl == "auto" and on_tpu and
                                  not os.environ.get("DSTPU_NO_PALLAS_ATTN")):
        # mesh-aware Pallas flash kernel — the TPU default: measured
        # 56.7% (512-element blocks, 512 MB CE budget, bf16 chunk logits) vs 45.5% MFU for the chunked-XLA
        # path on the 1.27B seq-2048 bench (v5e); shard_map head-sharding over
        # ('model','seq') IS the Ulysses all-to-all when sp > 1.
        # Unsupported shapes fall back inside flash_attention_sharded.
        # Sliding-window models pass `window` through: the kernel skips
        # out-of-window key blocks entirely (T·window FLOPs, not T²).
        from deepspeed_tpu.ops.flash_attention import flash_attention_sharded
        return partial(flash_attention_sharded, **wkw) if wkw \
            else flash_attention_sharded
    if sp.size > 1:
        from deepspeed_tpu.parallel.ulysses import distributed_attention
        return partial(distributed_attention, axis_name="seq")
    if impl == "naive" or (impl == "auto" and not on_tpu):
        return partial(dot_product_attention, **wkw) if wkw \
            else dot_product_attention
    from deepspeed_tpu.ops.xla_attention import chunked_attention
    return partial(chunked_attention, **wkw) if wkw else chunked_attention


def select_moe(dec_cfg: DecoderConfig, ds_cfg: DeepSpeedTPUConfig):
    if not dec_cfg.num_experts:
        return None
    if ds_cfg.moe.impl == "dropless":
        if ds_cfg.moe.ep_size > 1:
            raise ValueError(
                "moe.impl='dropless' requires ep_size=1: dropless "
                "dispatch has data-dependent per-expert counts, which "
                "cannot cross an EP all-to-all with static shapes. Use "
                "the capacity impl for expert parallelism.")
        if ds_cfg.pipeline.stages > 1:
            raise ValueError(
                "moe.impl='dropless' does not compose with pipeline "
                "parallelism: the pipeline already runs layers inside a "
                "shard_map over 'pipe', and the dropless per-shard "
                "dispatch is itself a shard_map (nested manual meshes "
                "conflict, same restriction as PP+SP). Use the capacity "
                "impl with pipeline stages.")
        from deepspeed_tpu.parallel.moe import dropless_moe_layer
        return partial(dropless_moe_layer,
                       top_k=dec_cfg.num_experts_per_tok,
                       aux_loss_coef=ds_cfg.moe.aux_loss_coef,
                       norm_topk=dec_cfg.norm_topk_prob)
    from deepspeed_tpu.parallel.moe import moe_layer
    return partial(moe_layer,
                   top_k=dec_cfg.num_experts_per_tok,
                   capacity_factor=ds_cfg.moe.capacity_factor,
                   min_capacity=ds_cfg.moe.min_capacity,
                   drop_tokens=ds_cfg.moe.drop_tokens,
                   aux_loss_coef=ds_cfg.moe.aux_loss_coef,
                   ep_axis="expert" if ds_cfg.moe.ep_size > 1 else None,
                   norm_topk=dec_cfg.norm_topk_prob)


def decoder_model_spec(dec_cfg: DecoderConfig,
                       ds_cfg: DeepSpeedTPUConfig):
    """Build the engine ModelSpec for the flagship decoder family.

    Batch contract: {"input_ids": [B,T] int32, "labels": [B,T] int32
    (optional; defaults to shifted input_ids)}.
    """
    from deepspeed_tpu.runtime.engine import ModelSpec

    if (ds_cfg.moe.use_residual and dec_cfg.num_experts
            and not dec_cfg.moe_residual):
        # Residual-MoE via the DeepSpeed config knob (reference
        # moe/layer.py use_residual) — architecture flag, so it folds
        # into the model config before init/loss/specs are built
        import dataclasses
        dec_cfg = dataclasses.replace(dec_cfg, moe_residual=True)

    if ds_cfg.activation_checkpointing.ffn_chunk:
        # FPDT sequence-chunked MLP (memory knob, not architecture —
        # but the forward reads it from the model config)
        import dataclasses
        dec_cfg = dataclasses.replace(
            dec_cfg,
            ffn_chunk=int(ds_cfg.activation_checkpointing.ffn_chunk))

    attn_fn = select_attention(ds_cfg, dec_cfg)
    moe_fn = select_moe(dec_cfg, ds_cfg)
    remat = ds_cfg.activation_checkpointing.policy
    if ds_cfg.activation_checkpointing.cpu_checkpointing and \
            not remat.startswith("offload"):
        # reference cpu_checkpointing knob: checkpointed activations live
        # in host memory — map to the host-offload analogue of the chosen
        # recompute profile (models/transformer.resolve_remat_policy)
        upgraded = {"save_attn_out": "offload_save_attn_out",
                    "save_attn_kernel": "offload_save_attn_kernel",
                    "save_attn_qkv": "offload_attn_qkv"}.get(
            remat, "offload_full")
        logger.info(f"cpu_checkpointing: remat policy "
                    f"'{remat}' -> '{upgraded}' (host-DRAM activations)")
        remat = upgraded
    ce_budget = None if ds_cfg.chunked_ce_budget_mb is None \
        else int(ds_cfg.chunked_ce_budget_mb) * 1024 * 1024
    # values validated by the config model (Literal)
    ce_dtype = jnp.bfloat16 if ds_cfg.ce_logits_dtype in ("bf16",
                                                          "bfloat16") \
        else None

    def init_fn(rng):
        return transformer.init_params(dec_cfg, rng)

    # RTS (reference top1gating:225 use_rts): random capacity-slot
    # priority, keyed from the engine's per-step rng — only meaningful
    # when capacity can drop tokens
    use_rts = (moe_fn is not None and ds_cfg.moe.use_rts
               and ds_cfg.moe.drop_tokens
               and ds_cfg.moe.impl == "capacity")

    def _moe_for_step(rng):
        """moe_fn for one step: RTS-wrapped when enabled, raw otherwise
        (the ONE selection point for all three loss paths)."""
        return _rts_moe(rng) if use_rts else moe_fn

    def _rts_moe(rng):
        """Wrap moe_fn with a PER-LAYER rts key: the layer scan traces
        its body once, so per-layer variation must come from traced
        layer data — fold the step rng with a bitcast of one router
        element (distinct across layers; equal values would only make
        two layers share a permutation, never corrupt routing)."""
        def mf(c, p, x):
            # f32 upcast first: bf16 params bitcast to int16, not int32
            lk = jax.random.fold_in(rng, lax.bitcast_convert_type(
                p["router"].reshape(-1)[0].astype(jnp.float32),
                jnp.int32))
            return moe_fn(c, p, x, rts_key=lk)
        return mf

    # Model-health taps (telemetry/health.py): bake the static flag into
    # a REPLACED config instance used only by this loss_fn's forward —
    # init/specs/pipeline/param_stream/inference keep the untapped
    # dec_cfg and its 2-tuple forward contract. The flag never flips
    # mid-run, so every step traces the identical program.
    _hcfg = ds_cfg.telemetry.health
    health_taps = bool(_hcfg.enabled and _hcfg.activations)
    if health_taps:
        import dataclasses
        taps_cfg = dataclasses.replace(dec_cfg, health_taps=True)

    # ZeRO-3 chunked-overlap plan, filled in by the engine (which owns
    # the mesh + abstract params) via ModelSpec.configure_overlap; while
    # unset, loss_fn runs the plain monolithic layer scan
    _ovl = {"plan": None}

    def loss_fn(params, batch, rng):
        tokens = batch["input_ids"]
        if "labels" in batch:
            labels = batch["labels"]
        else:
            labels = jnp.concatenate(
                [tokens[:, 1:], jnp.full_like(tokens[:, :1], -100)], axis=1)
        mf = _moe_for_step(rng)
        # encoder extras (BERT): pad masking is correctness-critical for
        # bidirectional attention (decoder batches right-pad + label
        # -100, which the causal mask already handles)
        enc = {}
        if not dec_cfg.causal:
            if "attention_mask" in batch:
                enc["attention_mask"] = batch["attention_mask"]
            if "token_type_ids" in batch:
                enc["token_type_ids"] = batch["token_type_ids"]
        plan = _ovl["plan"]
        hstats = None
        if health_taps:
            hidden, aux, hstats = transformer.forward_hidden(
                taps_cfg, params, tokens, attn_fn=attn_fn, moe_fn=mf,
                remat_policy=remat,
                layer_loop=plan.layer_loop if plan is not None else None,
                **enc)
        else:
            hidden, aux = transformer.forward_hidden(
                dec_cfg, params, tokens, attn_fn=attn_fn, moe_fn=mf,
                remat_policy=remat,
                layer_loop=plan.layer_loop if plan is not None else None,
                **enc)
        loss = transformer.chunked_cross_entropy(dec_cfg, params, hidden,
                                                 labels,
                                                 budget_bytes=ce_budget,
                                                 logits_dtype=ce_dtype)
        total = loss + aux if moe_fn is not None else loss
        metrics = {}
        if moe_fn is not None:
            # satellite: surface load-balancing pressure as
            # train/aux_loss even without the health cadence
            metrics["aux_loss"] = aux
        if hstats is not None:
            metrics["health"] = hstats
        return (total, metrics) if metrics else total

    tp = ds_cfg.tensor_parallel.enabled
    mics = int(ds_cfg.zero_optimization.mics_shard_size or 0) > 1
    specs = transformer.partition_specs(
        dec_cfg, zero_stage=ds_cfg.zero_optimization.stage, tp=tp,
        mics=mics)

    pipeline_loss_fn = None
    pipeline_grad_fn = None
    stages = ds_cfg.pipeline.stages
    if stages > 1:
        from deepspeed_tpu.runtime.pipe.pipeline import (
            pipeline_partition_specs, pipelined_loss,
            pipelined_loss_and_grads_1f1b)
        # balanced partition for L % S != 0 (reference PipelineModule
        # partition_balanced, pipe/module.py:393): pad the stacked layers
        # to S·ceil(L/S) with zero (identity) layers and mask them — every
        # stage runs ceil(L/S) real-or-dummy layers, so the tick critical
        # path equals the reference's balanced split (max stage cost);
        # dummy layers are value-identity with exactly-zero grads.
        # Embed/head never imbalance stages here: both are computed
        # replicated across 'pipe' by construction (the reference weighs
        # them into the split because ITS stages own them exclusively).
        import math as _math
        _L = dec_cfg.num_layers
        _cap = _math.ceil(_L / stages)
        _pad = _cap * stages - _L
        pipe_layer_mask = None
        if _pad:
            import numpy as _np
            pipe_layer_mask = _np.arange(_cap * stages) < _L
            _base_init = init_fn

            def init_fn(rng):                            # noqa: F811
                p = dict(_base_init(rng))
                p["layers"] = jax.tree.map(
                    lambda a: jnp.pad(
                        a, [(0, _pad)] + [(0, 0)] * (a.ndim - 1)),
                    p["layers"])
                return p
            logger.info(
                f"pipeline: {_L} layers over {stages} stages — balanced "
                f"split via {_pad} masked padding layer(s), "
                f"{_cap}/stage critical path")
        if not dec_cfg.causal or not dec_cfg.prenorm:
            # the pipeline stages assume the pre-LN decoder layout
            # (final_norm leaf, causal attention); silently pipelining a
            # BERT would KeyError deep in the schedule
            raise ValueError(
                "pipeline parallelism does not support encoder "
                "(bidirectional / post-LN) models; use DP/TP for "
                "BERT-class models")
        if dec_cfg.layer_window_pattern:
            # pipeline stages build decoder_block without the per-layer
            # window feed — training would silently run full attention
            # on GPT-Neo's local layers
            raise ValueError(
                "pipeline parallelism does not support per-layer "
                "attention windows (layer_window_pattern); use DP/TP "
                "for GPT-Neo-class models")
        if ds_cfg.sequence_parallel.size > 1:
            # the SP attention wrappers are shard_maps over 'seq'; nesting
            # them inside the pipeline's partial-manual 'pipe' region
            # trips a JAX manual-axes conflict — an honest error beats a
            # cryptic trace (use PP×TP×DP, or SP without PP)
            raise ValueError(
                "pipeline parallelism does not compose with "
                "sequence_parallel yet; drop one of the two (PP composes "
                "with TP/DP/ZeRO; SP composes with TP/DP/ZeRO/EP)")
        if tp:
            # vocab-sharded embeddings inside the partial-manual 'pipe'
            # region hit an XLA SPMD gather-partitioning CHECK failure;
            # replicate embed/lm_head across 'model' under PP (vocab ~vd
            # is small next to the layer stack — the reference keeps
            # embeddings replicated per pipeline stage too, pipe/module.py
            # tied layers)
            from jax.sharding import PartitionSpec as _P
            def _drop_model(spec):
                return _P(*(None if a == "model" else a for a in spec))
            specs["embed"] = jax.tree.map(
                _drop_model, specs["embed"],
                is_leaf=lambda x: isinstance(x, _P))
            if "lm_head" in specs:
                specs["lm_head"] = _drop_model(specs["lm_head"])
        specs = pipeline_partition_specs(specs, stages)

        # the pipeline schedule is itself a shard_map; a nested
        # shard_map'd flash kernel can't run inside it — use the XLA
        # attention there (pallas-inside-pipeline is future work)
        from deepspeed_tpu.ops.flash_attention import flash_attention_sharded
        pipe_attn = dot_product_attention \
            if attn_fn is flash_attention_sharded else attn_fn

        def _pipe_labels(tokens, batch):
            if "labels" in batch:
                return batch["labels"]
            return jnp.concatenate(
                [tokens[:, :, 1:],
                 jnp.full_like(tokens[:, :, :1], -100)], axis=2)

        def pipeline_loss_fn(params, batch, rng):
            tokens = batch["input_ids"]            # [M, B, T]
            return pipelined_loss(dec_cfg, params, tokens,
                                  _pipe_labels(tokens, batch),
                                  attn_fn=pipe_attn,
                                  moe_fn=_moe_for_step(rng),
                                  remat_policy=remat or "full",
                                  num_stages=stages,
                                  ce_budget_bytes=ce_budget,
                                  ce_logits_dtype=ce_dtype,
                                  layer_mask=pipe_layer_mask)

        if ds_cfg.pipeline.schedule == "1f1b":
            def pipeline_grad_fn(params, batch, rng, scale):
                tokens = batch["input_ids"]        # [M, B, T]
                return pipelined_loss_and_grads_1f1b(
                    dec_cfg, params, tokens, _pipe_labels(tokens, batch),
                    scale=scale, attn_fn=pipe_attn,
                    moe_fn=_moe_for_step(rng),
                    remat_policy=remat or "full", num_stages=stages,
                    ce_budget_bytes=ce_budget, ce_logits_dtype=ce_dtype,
                    layer_mask=pipe_layer_mask)
        elif ds_cfg.pipeline.schedule != "gpipe":
            raise ValueError(
                f"pipeline.schedule must be '1f1b' or 'gpipe', got "
                f"'{ds_cfg.pipeline.schedule}'")

    configure_overlap = None
    zcfg = ds_cfg.zero_optimization
    if zcfg.overlap_comm and zcfg.stage == 3 and stages <= 1:
        def configure_overlap(mesh, abstract_params):
            """Engine hook: build the chunked-overlap plan once mesh and
            abstract params exist, and arm loss_fn with it. Returns the
            plan (or None when the mesh can't run the chunked path)."""
            from deepspeed_tpu.runtime.zero.overlap import build_overlap_plan
            plan = build_overlap_plan(
                mesh, specs["layers"], abstract_params["layers"], zcfg,
                num_experts=dec_cfg.num_experts or 0)
            _ovl["plan"] = plan
            if plan is not None:
                logger.info(plan.describe())
            return plan

    n = dec_cfg.num_params()
    return ModelSpec(init_fn=init_fn, loss_fn=loss_fn,
                     partition_specs=specs,
                     flops_per_token=6.0 * n,
                     tokens_per_sample=dec_cfg.max_seq_len,
                     pipeline_loss_fn=pipeline_loss_fn,
                     pipeline_grad_fn=pipeline_grad_fn,
                     decoder_config=dec_cfg,
                     configure_overlap=configure_overlap)
