"""Variable batch size + LR scaling for length-heterogeneous corpora.

Reference: ``runtime/data_pipeline/data_sampling/variable_batch_size_and_lr
.py`` (``batch_by_seqlens``:23, ``scale_lr``:149, ``VariableBatchSizeLR``
:226) — pack sequences into microbatches holding ~``max_tokens`` tokens
each ("Attention is all you need" §5.1 batching), then scale the LR per
step by the realized batch size (linear / sqrt rule).

TPU-first difference: the reference pads each batch to its own max seqlen,
so every batch has a fresh shape — fine for eager torch, poison for XLA,
where every distinct shape is a recompile. Here packed batches are padded
up to a small set of static **seqlen buckets** (powers of two by default),
so the engine's jitted step compiles once per bucket and is reused across
the run. LR scaling is a pure schedule wrapper (a ``step -> lr`` function,
like everything in :mod:`runtime/lr_schedules`), so it composes with any
base schedule and checkpoints for free (state = step count, as in the
reference's ``state_dict``).
"""

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

Schedule = Callable[[int], float]


# ---------------------------------------------------------------------------
# Packing
# ---------------------------------------------------------------------------

def batch_by_seqlens(seqlens: Sequence[int],
                     max_tokens: int,
                     min_batch_size: int = 1,
                     max_batch_size: Optional[int] = None,
                     sequence_picking_order: str = "dataloader",
                     seed: Optional[int] = None,
                     ) -> Tuple[List[List[int]], List[int], List[int]]:
    """Pack sample indices into microbatches of ≤ ``max_tokens`` tokens.

    Returns ``(microbatch_ids, batch_sizes, batch_max_seqlens)`` where
    ``microbatch_ids[i]`` is the list of dataset indices in microbatch i,
    ``batch_sizes[i]`` its sequence count (drives LR scaling), and
    ``batch_max_seqlens[i]`` its longest sequence (drives bucket choice).

    ``sequence_picking_order``: 'dataloader' (given order), 'random', or
    'seqlen' (ascending — minimizes padding, maximizes shape reuse).
    Samples longer than ``max_tokens`` are dropped with a warning, as in
    the reference.
    """
    if sequence_picking_order not in ("dataloader", "random", "seqlen"):
        raise ValueError(f"unknown sequence_picking_order "
                         f"'{sequence_picking_order}'")
    pairs = [(int(l), i) for i, l in enumerate(seqlens)]
    long_ids = [i for l, i in pairs if l > max_tokens]
    if long_ids:
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "variable_batch: dropping %d samples longer than max_tokens=%d",
            len(long_ids), max_tokens)
        pairs = [p for p in pairs if p[0] <= max_tokens]
    if sequence_picking_order == "random":
        rng = np.random.default_rng(seed)
        rng.shuffle(pairs)
    elif sequence_picking_order == "seqlen":
        pairs.sort()

    microbatch_ids: List[List[int]] = []
    batch_sizes: List[int] = []
    batch_max_seqlens: List[int] = []
    dropped_small = 0
    cur: List[Tuple[int, int]] = []
    cur_tokens = 0
    for l, i in pairs:
        over_tokens = cur_tokens + l > max_tokens
        over_count = max_batch_size is not None and len(cur) >= max_batch_size
        if cur and (over_tokens or over_count):
            if len(cur) >= min_batch_size:
                microbatch_ids.append([i_ for _, i_ in cur])
                batch_sizes.append(len(cur))
                batch_max_seqlens.append(max(l_ for l_, _ in cur))
            else:
                dropped_small += len(cur)
            cur, cur_tokens = [], 0
        cur.append((l, i))
        cur_tokens += l
    if cur:
        if len(cur) >= min_batch_size:
            microbatch_ids.append([i_ for _, i_ in cur])
            batch_sizes.append(len(cur))
            batch_max_seqlens.append(max(l_ for l_, _ in cur))
        else:
            dropped_small += len(cur)
    if dropped_small:
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "variable_batch: dropped %d samples from groups smaller than "
            "min_batch_size=%d", dropped_small, min_batch_size)
    return microbatch_ids, batch_sizes, batch_max_seqlens


def seqlen_bucket(max_seqlen: int, buckets: Optional[Sequence[int]] = None,
                  multiple: int = 128) -> int:
    """Round a batch's max seqlen up to a static compile bucket.

    Default buckets are powers of two ≥ 128 (each distinct bucket is one
    XLA compilation of the train step; log2 growth bounds the compile
    count). Pass explicit ``buckets`` to pin them."""
    if buckets is not None:
        for b in sorted(buckets):
            if max_seqlen <= b:
                return int(b)
        raise ValueError(f"max_seqlen {max_seqlen} exceeds largest bucket "
                         f"{max(buckets)}")
    return max(multiple, 1 << int(math.ceil(math.log2(max_seqlen))))


# ---------------------------------------------------------------------------
# LR scaling
# ---------------------------------------------------------------------------

def scale_lr(base_batch_size: int, batch_size: int, base_lr: float = 1.0,
             method: str = "linear") -> float:
    """Linear Scaling Rule (Goyal et al.) / sqrt rule (Krizhevsky) /
    'none'."""
    if method == "linear":
        return base_lr * batch_size / base_batch_size
    if method == "sqrt":
        return base_lr * math.sqrt(batch_size / base_batch_size)
    if method is None or str(method).lower() == "none":
        return base_lr
    raise ValueError(f"unknown lr_scaling_method '{method}'")


def variable_batch_lr_schedule(base_schedule: Schedule,
                               base_batch_size: int,
                               batch_sizes: Sequence[int],
                               method: str = "linear") -> Schedule:
    """Wrap any ``step -> lr`` schedule so each step's LR is scaled by
    that step's realized batch size (reference VariableBatchSizeLR.step,
    :279). Steps past the packed plan reuse the last batch size."""
    sizes = np.asarray(batch_sizes, np.int64)

    def fn(step: int) -> float:
        bs = int(sizes[min(int(step), len(sizes) - 1)])
        return scale_lr(base_batch_size, bs, base_schedule(step), method)

    return fn


# ---------------------------------------------------------------------------
# Dataloader
# ---------------------------------------------------------------------------

class VariableBatchDataLoader:
    """Iterate packed microbatches as padded, DP-sharded numpy dicts.

    Each yielded batch is ``{"input_ids": [nb, sb] int32,
    "attention_mask": [nb, sb] int32}`` where BOTH dims are rounded up to
    power-of-two buckets — distinct shapes are what trigger XLA
    recompiles, so the compile count is O(log² sizes), not O(batches).
    Padding rows have ``attention_mask == 0`` everywhere; consumers must
    mask the loss with it (e.g. ``labels = where(mask, ids, -100)``).
    ``dataset[i]`` must return a 1-D int sequence. DP sharding splits the
    microbatch's sequences across ranks (a rank left with no sequences
    yields an all-padding batch so every rank still steps in lockstep —
    no sample is ever duplicated into the gradient).
    """

    def __init__(self, dataset, seqlens: Sequence[int], max_tokens: int,
                 dp_rank: int = 0, dp_world: int = 1,
                 buckets: Optional[Sequence[int]] = None,
                 pad_token_id: int = 0,
                 sequence_picking_order: str = "seqlen",
                 seed: Optional[int] = None):
        self.dataset = dataset
        self.pad_token_id = int(pad_token_id)
        self.dp_rank, self.dp_world = int(dp_rank), int(dp_world)
        self.buckets = buckets
        (self.microbatch_ids, self.batch_sizes,
         self.batch_max_seqlens) = batch_by_seqlens(
             seqlens, max_tokens,
             sequence_picking_order=sequence_picking_order, seed=seed)

    def __len__(self) -> int:
        return len(self.microbatch_ids)

    def lr_schedule(self, base_schedule: Schedule, base_batch_size: int,
                    method: str = "linear") -> Schedule:
        return variable_batch_lr_schedule(base_schedule, base_batch_size,
                                          self.batch_sizes, method)

    def __iter__(self):
        for ids, max_len in zip(self.microbatch_ids,
                                self.batch_max_seqlens):
            mine = ids[self.dp_rank::self.dp_world]
            bucket = seqlen_bucket(max_len, self.buckets)
            # batch bucket from the GLOBAL per-rank ceiling so every DP
            # rank yields the SAME shape this step (SPMD lockstep)
            per_rank = -(-len(ids) // self.dp_world)
            nb = 1 << max(per_rank - 1, 0).bit_length()
            input_ids = np.full((nb, bucket), self.pad_token_id, np.int32)
            mask = np.zeros((nb, bucket), np.int32)
            for r, idx in enumerate(mine):
                seq = np.asarray(self.dataset[idx], np.int32)
                input_ids[r, :len(seq)] = seq
                mask[r, :len(seq)] = 1
            yield {"input_ids": input_ids, "attention_mask": mask}
