"""Distributed map-reduce data analysis (curriculum metric computation).

Reference: ``runtime/data_pipeline/data_sampling/data_analyzer.py`` (885
LoC): ``run_map``:199 — each worker iterates ITS contiguous split of the
dataset and persists per-worker metric files; ``run_reduce``:437 — merge
the worker files into global index files (``<metric>_sample_to_metric``,
``<metric>_index_to_sample``, ``<metric>_index_to_metric``) that
``DeepSpeedDataSampler`` consumes for curriculum scheduling.

This implementation keeps the reference's architecture — contiguous
per-worker splits, on-disk intermediate files, a reduce that any single
worker can run once every map shard landed — with numpy .npy files instead
of the reference's mmap indexed-dataset builders (same role, no torch
dependency, and byte-reproducible: the reduced outputs are IDENTICAL
regardless of how many workers produced the map shards, which the 2-proc
vs 1-proc fixture asserts).

Metric types (reference data_analyzer.py:63):
- ``single_value_per_sample`` — one value per sample; reduce emits
  sample→metric, the difficulty-sorted sample index, and sorted values.
- ``accumulate_value_over_samples`` — a running vector sum (e.g. vocab
  frequency); reduce emits the element-wise total.
"""

import glob
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

MetricFn = Callable[[Any], Any]

SINGLE = "single_value_per_sample"
ACCUMULATE = "accumulate_value_over_samples"


class DistributedDataAnalyzer:
    """Map-reduce metric computation over an indexed dataset.

    ``num_workers``/``worker_id`` follow the reference's convention (one
    OS process per worker — the launcher's process env or any scheduler).
    Each worker calls :meth:`run_map`; then :meth:`run_reduce` (any one
    worker, or a separate process) merges. :meth:`run_map_reduce` does
    both with a file-based barrier, matching the reference's
    ``run_map_reduce``:445 convenience entry point.
    """

    def __init__(self, dataset,
                 metric_names: List[str],
                 metric_functions: List[MetricFn],
                 metric_types: Optional[List[str]] = None,
                 save_path: str = "./data_analysis",
                 num_workers: int = 1,
                 worker_id: int = 0,
                 batch_size: int = 64):
        if len(metric_names) != len(metric_functions):
            raise ValueError("metric_names and metric_functions must pair")
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types or
                                 [SINGLE] * len(metric_names))
        for t in self.metric_types:
            if t not in (SINGLE, ACCUMULATE):
                raise ValueError(f"unknown metric_type '{t}'")
        self.save_path = save_path
        self.num_workers = int(num_workers)
        self.worker_id = int(worker_id)
        self.batch_size = int(batch_size)

    # ------------------------------------------------------------------ map
    def _split(self) -> range:
        """Contiguous per-worker split (reference run_map_helper:151
        splits the dataset index range evenly across workers)."""
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        lo = min(self.worker_id * per, n)
        return range(lo, min(lo + per, n))

    def run_map(self) -> None:
        """Compute this worker's metric shard and persist it."""
        split = self._split()
        for name, fn, mtype in zip(self.metric_names, self.metric_functions,
                                   self.metric_types):
            mdir = os.path.join(self.save_path, name)
            os.makedirs(mdir, exist_ok=True)
            if mtype == SINGLE:
                vals = np.asarray([fn(self.dataset[i]) for i in split])
            else:
                acc = None
                for i in split:
                    v = np.asarray(fn(self.dataset[i]))
                    acc = v.copy() if acc is None else acc + v
                vals = acc if acc is not None else np.zeros(0)
            shard = os.path.join(mdir, f"worker{self.worker_id}.npy")
            np.save(shard + ".tmp.npy", vals)
            os.replace(shard + ".tmp.npy", shard)   # atomic publish
            # The meta json must land atomically too: a concurrent reducer
            # polls for this exact filename and must never see a partial
            # write (it is the map->reduce barrier token).
            meta_path = os.path.join(mdir, f"worker{self.worker_id}.json")
            with open(meta_path + ".tmp", "w") as fh:
                json.dump({"start": split.start, "stop": split.stop,
                           "num_workers": self.num_workers,
                           "type": mtype}, fh)
            os.replace(meta_path + ".tmp", meta_path)
        logger.info(f"data analyzer map: worker {self.worker_id}/"
                    f"{self.num_workers} wrote samples "
                    f"[{split.start}, {split.stop})")

    # --------------------------------------------------------------- reduce
    def _wait_for_shards(self, mdir: str, timeout: float
                         ) -> Dict[str, dict]:
        """Poll until every worker's meta json is present and parsable;
        return {path: parsed meta}, ordered by path."""
        deadline = time.time() + timeout
        metas: Dict[str, dict] = {}
        while True:
            for mpath in sorted(glob.glob(os.path.join(mdir,
                                                       "worker*.json"))):
                if mpath in metas:   # atomic publish: valid stays valid
                    continue
                # Publishes are atomic (os.replace), but tolerate a shard
                # from an older non-atomic writer or a torn NFS view:
                # an unparsable meta is "not landed yet", retried until
                # the deadline rather than crashing the reducer.
                # ValueError covers JSONDecodeError AND the
                # UnicodeDecodeError a garbage-bytes read raises.
                try:
                    with open(mpath) as fh:
                        metas[mpath] = json.load(fh)
                except (ValueError, OSError):
                    continue
            if len(metas) >= self.num_workers:
                return dict(sorted(metas.items()))
            if time.time() > deadline:
                raise TimeoutError(
                    f"reduce: only {len(metas)}/{self.num_workers} map "
                    f"shards under {mdir} after {timeout}s")
            time.sleep(0.2)

    def run_reduce(self, timeout: float = 300.0) -> None:
        """Merge worker shards into the global index files the sampler
        consumes (reference merge_map_results:279). Outputs per metric:

        - ``<name>_sample_to_metric.npy`` — value per sample index
        - ``<name>_index_to_sample.npy`` — sample indices, difficulty-sorted
          (stable; ties keep dataset order — deterministic across runs)
        - ``<name>_index_to_metric.npy`` — the sorted values
        - ``<name>_metric_value.npy`` — accumulate-type total
        - ``index.json`` — coverage + min/max summary
        """
        for name, mtype in zip(self.metric_names, self.metric_types):
            mdir = os.path.join(self.save_path, name)
            metas = self._wait_for_shards(mdir, timeout)
            shards = []
            for mpath, meta in metas.items():
                vals = np.load(mpath[:-len(".json")] + ".npy")
                shards.append((meta["start"], meta["stop"], vals))
            shards.sort(key=lambda s: s[0])
            if mtype == SINGLE:
                expect = 0
                for start, stop, vals in shards:
                    if start != expect or len(vals) != stop - start:
                        raise ValueError(
                            f"reduce: shard coverage broken at {start} "
                            f"(expected {expect}) under {mdir}")
                    expect = stop
                if expect != len(self.dataset):
                    raise ValueError(
                        f"reduce: shards cover [0, {expect}) but dataset "
                        f"has {len(self.dataset)} samples")
                s2m = np.concatenate([v for _, _, v in shards])
                order = np.argsort(s2m, kind="stable")
                np.save(os.path.join(mdir, f"{name}_sample_to_metric.npy"),
                        s2m)
                np.save(os.path.join(mdir, f"{name}_index_to_sample.npy"),
                        order)
                np.save(os.path.join(mdir, f"{name}_index_to_metric.npy"),
                        s2m[order])
                summary = {"num_samples": int(len(s2m)),
                           "min": float(s2m.min()), "max": float(s2m.max())}
            else:
                total = None
                for _, _, vals in shards:
                    if vals.size:
                        total = vals.copy() if total is None else \
                            total + vals
                np.save(os.path.join(mdir, f"{name}_metric_value.npy"),
                        total if total is not None else np.zeros(0))
                summary = {"num_samples": int(len(self.dataset))}
            with open(os.path.join(mdir, "index.json"), "w") as fh:
                json.dump({"metric": name, "type": mtype,
                           "num_workers": len(shards), **summary}, fh,
                          sort_keys=True)
            logger.info(f"data analyzer reduce: merged {len(shards)} "
                        f"shards for '{name}'")

    def run_map_reduce(self, timeout: float = 300.0) -> None:
        """Map, then reduce on worker 0 (file-based barrier: reduce waits
        for every worker's shard to land — reference run_map_reduce:445
        barriers on a comm group; an offline analysis job has no mesh)."""
        self.run_map()
        if self.worker_id == 0:
            self.run_reduce(timeout=timeout)


def load_metric(save_path: str, metric_name: str,
                kind: str = "sample_to_metric") -> np.ndarray:
    """Read a reduced metric file (what ``data_sampling.metric_path``
    points at): kind ∈ sample_to_metric | index_to_sample |
    index_to_metric | metric_value."""
    return np.load(os.path.join(save_path, metric_name,
                                f"{metric_name}_{kind}.npy"))
