"""Memory-mapped indexed token dataset.

Reference: ``runtime/data_pipeline/data_sampling/indexed_dataset.py`` (the
Megatron-style ``.bin`` token stream + ``.idx`` offsets format,
``MMapIndexedDataset``/``MMapIndexedDatasetBuilder``). Same two-file
design, simplified header; documents are variable-length int token
sequences, reads are zero-copy ``np.memmap`` slices — the right host-side
layout for feeding a TPU input pipeline (no per-item pickling).

Format::

    <stem>.bin   raw little-endian tokens, all docs concatenated
    <stem>.idx   magic | version | dtype_code | n_docs | u64 offsets[n+1]
"""

import os
import struct
from typing import Iterable, List, Optional, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1
_DTYPES = {1: np.uint16, 2: np.int32, 3: np.int64, 4: np.uint8}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


class IndexedDatasetBuilder:
    """Streaming writer (reference MMapIndexedDatasetBuilder)."""

    def __init__(self, stem: str, dtype=np.int32):
        self.stem = stem
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(stem + ".bin", "wb")
        self._offsets: List[int] = [0]

    def add_doc(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, self.dtype)
        self._bin.write(arr.tobytes())
        self._offsets.append(self._offsets[-1] + arr.size)

    def finalize(self) -> None:
        self._bin.close()
        with open(self.stem + ".idx", "wb") as fh:
            fh.write(_MAGIC)
            fh.write(struct.pack("<HHQ", _VERSION,
                                 _DTYPE_CODES[self.dtype],
                                 len(self._offsets) - 1))
            fh.write(np.asarray(self._offsets, np.uint64).tobytes())


class IndexedDataset:
    """Zero-copy reader (reference MMapIndexedDataset)."""

    def __init__(self, stem: str):
        with open(stem + ".idx", "rb") as fh:
            magic = fh.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{stem}.idx: bad magic {magic!r}")
            version, code, n = struct.unpack("<HHQ", fh.read(12))
            if version != _VERSION:
                raise ValueError(f"unsupported version {version}")
            self.dtype = np.dtype(_DTYPES[code])
            self.offsets = np.frombuffer(fh.read(8 * (n + 1)), np.uint64)
        self.data = np.memmap(stem + ".bin", dtype=self.dtype, mode="r")

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i: int) -> np.ndarray:
        a, b = int(self.offsets[i]), int(self.offsets[i + 1])
        return self.data[a:b]

    def doc_lengths(self) -> np.ndarray:
        return (self.offsets[1:] - self.offsets[:-1]).astype(np.int64)


def build_indexed_dataset(stem: str, docs: Iterable[Sequence[int]],
                          dtype=np.int32) -> IndexedDataset:
    b = IndexedDatasetBuilder(stem, dtype)
    for d in docs:
        b.add_doc(d)
    b.finalize()
    return IndexedDataset(stem)
