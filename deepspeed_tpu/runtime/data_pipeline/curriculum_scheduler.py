"""Curriculum learning scheduler.

Reference: ``runtime/data_pipeline/curriculum_scheduler.py:11`` —
difficulty (e.g. sequence length) ramps with a fixed_linear /
fixed_root / fixed_discrete / custom schedule. Consumed by the engine's
dataloader to truncate/bucket samples per step.
"""

import math
from typing import Any, Callable, Dict, Optional

from deepspeed_tpu.utils.logging import logger


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        assert "curriculum_type" in config and "min_difficulty" in config \
            and "max_difficulty" in config, \
            "curriculum config needs curriculum_type/min/max_difficulty"
        self.ctype = config["curriculum_type"]
        self.min = int(config["min_difficulty"])
        self.max = int(config["max_difficulty"])
        self.current = self.min
        cfg = config.get("schedule_config", {})
        if self.ctype in ("fixed_linear", "fixed_root"):
            self.total_step = int(cfg["total_curriculum_step"])
            self.diff_step = int(cfg.get("difficulty_step", 8))
            self.root = float(cfg.get("root_degree", 2)) \
                if self.ctype == "fixed_root" else 1.0
        elif self.ctype == "fixed_discrete":
            self.difficulties = list(cfg["difficulty"])
            self.max_steps = list(cfg["max_step"])
            assert len(self.difficulties) == len(self.max_steps) + 1
        elif self.ctype == "custom":
            self.custom_fn: Optional[Callable[[int], int]] = None
        else:
            raise ValueError(f"unknown curriculum_type {self.ctype}")

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_fn = fn

    def get_difficulty(self, global_steps: int) -> int:
        if self.ctype == "custom":
            assert self.custom_fn is not None, \
                "custom curriculum needs set_custom_get_difficulty"
            return self.custom_fn(global_steps)
        if self.ctype == "fixed_discrete":
            for d, s in zip(self.difficulties, self.max_steps):
                if global_steps <= s:
                    return d
            return self.difficulties[-1]
        frac = min(1.0, global_steps / max(self.total_step, 1))
        frac = frac ** (1.0 / self.root)
        diff = self.min + (self.max - self.min) * frac
        diff = int(diff // self.diff_step * self.diff_step)
        return max(self.min, min(self.max, diff))

    def update_difficulty(self, global_steps: int) -> int:
        self.current = self.get_difficulty(global_steps)
        return self.current
