"""Random-LTD — random layerwise token dropping.

Reference: ``runtime/data_pipeline/data_routing/basic_layer.py``
(RandomLayerTokenDrop) + ``scheduler.py`` (token-keep schedule) +
``csrc/random_ltd/`` (token_sort / gather_scatter CUDA kernels). The
method: during training, middle layers process only a random SUBSET of
tokens; dropped tokens skip the layer (residual identity) and rejoin
afterwards — big FLOP savings early in training with a schedule ramping
back to full sequence.

TPU design: the CUDA gather/scatter kernels become ``jnp.take`` /
scatter-add, which XLA lowers to efficient dynamic-gather; the kept-token
count is a HOST-side schedule value (static per compiled step, like the
reference's per-interval update — retrace happens only when the schedule
moves, every ``schedule_period`` steps).
"""

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Linear token-keep schedule (reference data_routing/scheduler.py):
    from ``start_tokens`` kept per sequence up to the full ``max_tokens``
    over ``schedule_period``-step increments of ``schedule_step``."""

    def __init__(self, start_tokens: int, max_tokens: int,
                 schedule_step: int, schedule_period: int):
        self.start_tokens = int(start_tokens)
        self.max_tokens = int(max_tokens)
        self.schedule_step = int(schedule_step)
        self.schedule_period = max(int(schedule_period), 1)

    def keep_count(self, global_step: int) -> int:
        inc = (global_step // self.schedule_period) * self.schedule_step
        return int(min(self.start_tokens + inc, self.max_tokens))

    def state_dict(self) -> Dict[str, int]:
        return {"start_tokens": self.start_tokens,
                "max_tokens": self.max_tokens}


def random_ltd_indices(rng: jax.Array, batch: int, seq: int, keep: int
                       ) -> jax.Array:
    """[B, keep] sorted kept-token indices, independent per row
    (reference token_sort_ kernel: random perm then sort the kept
    prefix — order is preserved so attention stays causal)."""
    noise = jax.random.uniform(rng, (batch, seq))
    picked = jnp.argsort(noise, axis=1)[:, :keep]
    return jnp.sort(picked, axis=1)


def random_ltd_layer(layer_fn: Callable[[jax.Array], jax.Array],
                     x: jax.Array, rng: jax.Array, keep: int
                     ) -> jax.Array:
    """Apply ``layer_fn`` to a random kept subset of tokens; dropped
    tokens pass through untouched (reference RandomLayerTokenDrop.forward
    gather → layer → scatter)."""
    b, t, d = x.shape
    if keep >= t:
        return layer_fn(x)
    idx = random_ltd_indices(rng, b, t, keep)            # [B, K]
    gathered = jnp.take_along_axis(x, idx[..., None], axis=1)  # [B, K, D]
    out = layer_fn(gathered)
    # scatter back over the kept positions; dropped rows keep x (identity)
    return x.at[jnp.arange(b)[:, None], idx].set(out.astype(x.dtype))
