"""Curriculum-aware deterministic data sampler.

Reference: ``runtime/data_pipeline/data_sampling/data_sampler.py:36``
(``DeepSpeedDataSampler``) — difficulty-bucketed sampling driven by the
CurriculumScheduler, deterministic across resumes (state = consumed
samples), DP-sharded. The reference clusters samples by a difficulty
metric and draws from the allowed-difficulty pool each step; this does the
same with numpy index arithmetic.
"""

from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler)


class DeepSpeedDataSampler:
    """Yields per-step index batches from the pool of samples whose
    difficulty ≤ the curriculum's current value.

    ``metric_values[i]`` is sample i's difficulty (e.g. sequence length,
    from :mod:`data_analyzer`). State for checkpoint/resume is just
    ``consumed_samples`` (reference state_dict:*)."""

    def __init__(self, metric_values: Sequence[float],
                 batch_size: int,
                 curriculum: Optional[CurriculumScheduler] = None,
                 dp_rank: int = 0, dp_world: int = 1, seed: int = 0,
                 micro_steps_per_global_step: int = 1):
        self.metric = np.asarray(metric_values, np.float64)
        self.order = np.argsort(self.metric, kind="stable")
        self.sorted_metric = self.metric[self.order]
        self.batch_size = int(batch_size)
        if self.batch_size % dp_world:
            raise ValueError(f"batch_size {batch_size} not divisible by "
                             f"dp_world {dp_world}")
        self.curriculum = curriculum
        self.dp_rank, self.dp_world = dp_rank, dp_world
        self.seed = seed
        self.consumed_samples = 0
        self.step = 0
        # with gradient accumulation the sampler yields gas index batches
        # per optimizer step; the curriculum schedule is expressed in
        # GLOBAL steps (reference CurriculumScheduler semantics), so
        # difficulty is keyed to step // gas
        self.micro_steps_per_global_step = max(
            1, int(micro_steps_per_global_step))

    def _pool(self) -> np.ndarray:
        """Indices allowed at the current difficulty (sorted pool
        prefix)."""
        if self.curriculum is None:
            return self.order
        limit = self.curriculum.get_difficulty(
            self.step // self.micro_steps_per_global_step)
        hi = np.searchsorted(self.sorted_metric, limit, side="right")
        hi = max(hi, min(self.batch_size, len(self.order)))
        return self.order[:hi]

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        pool = self._pool()
        rng = np.random.default_rng(self.seed + self.step)
        picks = rng.choice(pool, size=self.batch_size,
                           replace=len(pool) < self.batch_size)
        self.step += 1
        self.consumed_samples += self.batch_size
        per = self.batch_size // self.dp_world
        return picks[self.dp_rank * per:(self.dp_rank + 1) * per]

    # -- checkpoint (reference data_sampler state_dict/load_state_dict) ----

    def state_dict(self) -> Dict[str, int]:
        return {"consumed_samples": self.consumed_samples,
                "step": self.step}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self.consumed_samples = int(state["consumed_samples"])
        self.step = int(state["step"])


class DataAnalyzer:
    """Single-process convenience wrapper over the distributed map-reduce
    analyzer (runtime/data_pipeline/data_analyzer.py — the reference
    data_analyzer.py analogue; use DistributedDataAnalyzer directly for
    multi-worker analysis over datasets bigger than one host pass)."""

    def __init__(self, dataset, metric_fn=None):
        self.dataset = dataset
        self.metric_fn = metric_fn or (lambda doc: len(doc))

    def run(self, save_stem: Optional[str] = None) -> np.ndarray:
        vals = np.asarray([self.metric_fn(self.dataset[i])
                           for i in range(len(self.dataset))], np.float64)
        if save_stem:
            np.save(save_stem + ".metric.npy", vals)
            np.save(save_stem + ".order.npy", np.argsort(vals,
                                                         kind="stable"))
        return vals
