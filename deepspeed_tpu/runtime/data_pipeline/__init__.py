from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (  # noqa: F401
    CurriculumScheduler)
from deepspeed_tpu.runtime.data_pipeline.variable_batch import (  # noqa: F401
    VariableBatchDataLoader, batch_by_seqlens, scale_lr,
    variable_batch_lr_schedule)
