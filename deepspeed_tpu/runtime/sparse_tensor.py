"""Sparse gradients for embedding tables.

Reference: ``runtime/sparse_tensor.py`` (``SparseTensor`` wrapping torch
sparse COO grads) + the engine's sparse-grad allreduce
(engine.py:3023–3095: gather indices/values across DP, deduplicate,
scatter-add). On TPU dense embedding grads are usually fine (XLA
scatter-add is fast), but for huge vocab × small batch the sparse
exchange is the bandwidth win, so the same (indices, values) exchange is
provided over ``lax.all_gather``.
"""

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass
class SparseTensor:
    """COO rows of an [V, D] dense tensor (reference SparseTensor)."""
    indices: jax.Array      # [N] int32 row ids
    values: jax.Array       # [N, D]
    dense_shape: Tuple[int, int]

    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    @staticmethod
    def from_dense(dense: jax.Array, rows: jax.Array) -> "SparseTensor":
        """Extract the given rows (e.g. the batch's unique token ids)."""
        return SparseTensor(indices=rows.astype(jnp.int32),
                            values=dense[rows],
                            dense_shape=tuple(dense.shape))


def sparse_embedding_grad(tokens: jax.Array, dout: jax.Array,
                          vocab_size: int) -> SparseTensor:
    """Build the embedding-table gradient sparsely from the batch: row
    ids are the flattened tokens, values the output grads — never
    materializing the [V, D] dense grad (reference: torch sparse
    embedding backward)."""
    flat_tok = tokens.reshape(-1)
    flat_g = dout.reshape(-1, dout.shape[-1])
    return SparseTensor(indices=flat_tok.astype(jnp.int32), values=flat_g,
                        dense_shape=(vocab_size, dout.shape[-1]))


def sparse_allreduce(st: SparseTensor, axis_name: str) -> SparseTensor:
    """DP allreduce of a sparse grad: all_gather indices+values, keep COO
    (duplicates combine lazily at ``to_dense``'s scatter-add) — the
    reference's sparse_allreduce_bucket without the dense round-trip.
    Must run inside shard_map; result rows = world × local rows."""
    idx = lax.all_gather(st.indices, axis_name, tiled=True)
    vals = lax.all_gather(st.values, axis_name, tiled=True)
    world = lax.psum(1, axis_name)
    return SparseTensor(indices=idx, values=vals / world,
                        dense_shape=st.dense_shape)
