"""ZeRO-Offload: optimizer states + step on the host.

Reference: ``runtime/zero/stage_1_and_2.py`` cpu_offload path (grads
copied to pinned host buffers :1332, DeepSpeedCPUAdam step on the flat
fp32 partition) and ``ops/adam/cpu_adam.py``. TPU version: the fp32
master weights and Adam moments live in host DRAM as ONE flat numpy
buffer (the reference's flat partition layout); each step the device
grads are fetched, the native SIMD Adam sweeps the flat buffer, and the
updated master is cast back to the compute dtype and device_put.

This trades step latency for HBM: the device holds only compute-dtype
params + transient grads — the config that lets a 16G v5e train models
whose Adam state would need 3x more memory (reference claim: 13B on one
V100-32G, docs/_pages/training.md:77).
"""

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.host_adam import HostAdam
from deepspeed_tpu.utils.logging import log_dist

Pytree = Any


class FlatLayout:
    """Stable flatten/unflatten between a params pytree and one fp32 buf."""

    def __init__(self, abstract_params: Pytree):
        leaves, self.treedef = jax.tree_util.tree_flatten(abstract_params)
        self.shapes = [tuple(x.shape) for x in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = np.cumsum([0] + self.sizes)
        self.total = int(self.offsets[-1])
        self.dtypes = [x.dtype for x in leaves]

    def flatten_np(self, tree: Pytree) -> np.ndarray:
        leaves = self.treedef.flatten_up_to(tree)
        out = np.empty(self.total, np.float32)
        for leaf, off, size, shape in zip(leaves, self.offsets, self.sizes,
                                          self.shapes):
            arr = np.asarray(jax.device_get(leaf), np.float32)
            out[off:off + size] = arr.reshape(-1)
        return out

    def unflatten(self, flat: np.ndarray, dtypes=None) -> Pytree:
        dtypes = dtypes or self.dtypes
        leaves = []
        for off, size, shape, dt in zip(self.offsets, self.sizes,
                                        self.shapes, dtypes):
            leaves.append(flat[off:off + size].reshape(shape).astype(dt))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class HostOffloadOptimizer:
    """Engine-facing optimizer whose state lives in host DRAM."""

    def __init__(self, abstract_params: Pytree, opt_name: str,
                 opt_params: dict, compute_dtype):
        name = opt_name.lower()
        if name not in ("adam", "adamw", "fusedadam"):
            raise ValueError(
                f"offload_optimizer supports Adam family only (reference "
                f"DeepSpeedCPUAdam); got '{opt_name}'")
        p = dict(opt_params or {})
        p.pop("lr", None)
        betas = p.pop("betas", (0.9, 0.999))
        self.layout = FlatLayout(abstract_params)
        self.adam = HostAdam(self.layout.total,
                             beta1=float(betas[0]), beta2=float(betas[1]),
                             eps=float(p.pop("eps", 1e-8)),
                             weight_decay=float(p.pop("weight_decay", 0.0)),
                             adamw_mode=(name == "adamw"))
        self.compute_dtype = compute_dtype
        self.master: Optional[np.ndarray] = None
        self.hyperparams = {"name": f"host_{name}", "offload": "cpu",
                            "betas": betas}
        log_dist(f"ZeRO-Offload host optimizer: {self.layout.total / 1e6:.1f}M "
                 f"elements in host DRAM "
                 f"({self.layout.total * 12 / 2**30:.2f} GiB opt state)")

    def init_from(self, params: Pytree) -> None:
        self.master = self.layout.flatten_np(params)

    def step(self, grads: Pytree, lr: float, grad_clip: float = 0.0,
             loss_scale: float = 1.0) -> Tuple[Pytree, dict]:
        """Host step → (new device-dtype params pytree, metrics)."""
        flat_g = self.layout.flatten_np(grads)
        if loss_scale != 1.0:
            flat_g *= 1.0 / loss_scale
        overflow = not np.isfinite(flat_g).all()
        norm = self.adam.grad_norm(flat_g)
        metrics = {"grad_norm": norm, "overflow": int(overflow), "lr": lr}
        if overflow:
            return None, metrics
        if grad_clip > 0 and norm > grad_clip:
            flat_g *= grad_clip / (norm + 1e-6)
        self.adam.step(self.master, flat_g, lr=lr)
        new_params = self.layout.unflatten(
            self.master, [self.compute_dtype] * len(self.layout.shapes))
        return new_params, metrics

    # -- checkpoint support -------------------------------------------------

    def state_dict(self) -> dict:
        return {"master": self.master, "exp_avg": self.adam.exp_avg,
                "exp_avg_sq": self.adam.exp_avg_sq,
                "step": self.adam.step_count}

    def load_state_dict(self, state: dict) -> None:
        self.master = np.asarray(state["master"], np.float32).copy()
        self.adam.exp_avg = np.asarray(state["exp_avg"], np.float32).copy()
        self.adam.exp_avg_sq = np.asarray(state["exp_avg_sq"],
                                          np.float32).copy()
        self.adam.step_count = int(state["step"])
