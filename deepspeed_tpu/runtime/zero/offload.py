"""ZeRO-Offload: optimizer states + step on the host.

Reference: ``runtime/zero/stage_1_and_2.py`` cpu_offload path (grads
copied to pinned host buffers :1332, DeepSpeedCPUAdam step on the flat
fp32 partition), ``ops/adam/cpu_adam.py``, and the ZenFlow stall-free
variant (runtime/zenflow/engine.py:14, zenflow_stage_1_and_2.py:47). TPU
version: the fp32 master weights and Adam moments live in host DRAM as ONE
flat numpy buffer (the reference's flat partition layout). Each step:

1. the jitted grad step emits ONE flat transfer-dtype gradient array
   (device-side concat — not a per-leaf Python fetch loop),
2. a single D2H fetch hands it to the host worker thread, where the native
   SIMD Adam sweeps the flat buffer (bf16 grads are widened in C++),
3. the updated master is narrowed to the compute dtype in C++ and uploaded
   as ONE flat device_put; a jitted unflatten restores the params pytree
   with its shardings.

With ``offload_optimizer.overlap`` the host step for step t runs while
the device computes step t+1's gradients — the device never stalls on
the host; updates apply one step late. The FULL ZenFlow design
(selective on-device top-k updates + interval host tail, reference
runtime/zenflow/) lives in ``runtime/zero/zenflow.py`` and builds on
this optimizer.

This trades step latency for HBM: the device holds only compute-dtype
params + transient grads — the config that lets a 16G v5e train models
whose Adam state would need 3x more memory (reference claim: 13B on one
V100-32G, docs/_pages/training.md:77).
"""

import concurrent.futures
import ctypes
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.host_adam import HostAdam
from deepspeed_tpu.ops.op_builder import is_native_available, load_host_adam
from deepspeed_tpu.utils.logging import log_dist

Pytree = Any


class FlatLayout:
    """Stable flatten/unflatten between a params pytree and one flat buf."""

    def __init__(self, abstract_params: Pytree):
        leaves, self.treedef = jax.tree_util.tree_flatten(abstract_params)
        self.shapes = [tuple(x.shape) for x in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = np.cumsum([0] + self.sizes)
        self.total = int(self.offsets[-1])
        self.dtypes = [x.dtype for x in leaves]

    # -- device-side (traceable) ------------------------------------------
    def flatten_device(self, tree: Pytree, dtype=jnp.float32) -> jax.Array:
        """Traceable: pytree → flat [total] of ``dtype`` (one array, so the
        engine fetches grads with a single D2H copy)."""
        leaves = self.treedef.flatten_up_to(tree)
        return jnp.concatenate(
            [l.reshape(-1).astype(dtype) for l in leaves])

    def unflatten_device(self, flat: jax.Array, dtypes=None) -> Pytree:
        """Traceable: flat [total] → pytree (leaf dtypes default to the
        layout's)."""
        dtypes = dtypes or self.dtypes
        leaves = []
        for off, size, shape, dt in zip(self.offsets, self.sizes,
                                        self.shapes, dtypes):
            leaves.append(
                jax.lax.dynamic_slice_in_dim(flat, int(off), size)
                .reshape(shape).astype(dt))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # -- host-side ---------------------------------------------------------
    def flatten_np(self, tree: Pytree) -> np.ndarray:
        leaves = self.treedef.flatten_up_to(tree)
        out = np.empty(self.total, np.float32)
        for leaf, off, size, shape in zip(leaves, self.offsets, self.sizes,
                                          self.shapes):
            arr = np.asarray(jax.device_get(leaf), np.float32)
            out[off:off + size] = arr.reshape(-1)
        return out

    def unflatten(self, flat: np.ndarray, dtypes=None) -> Pytree:
        dtypes = dtypes or self.dtypes
        leaves = []
        for off, size, shape, dt in zip(self.offsets, self.sizes,
                                        self.shapes, dtypes):
            leaves.append(flat[off:off + size].reshape(shape).astype(dt))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u16p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


class HostOffloadOptimizer:
    """Engine-facing optimizer whose state lives in host DRAM.

    ``step_flat``/``step_flat_async`` consume the flat transfer-dtype
    gradient array; ``step`` (pytree) remains for the 3-call parity API.
    """

    def __init__(self, abstract_params: Pytree, opt_name: str,
                 opt_params: dict, compute_dtype,
                 allocate_moments: bool = True):
        name = opt_name.lower()
        if name not in ("adam", "adamw", "fusedadam"):
            raise ValueError(
                f"offload_optimizer supports Adam family only (reference "
                f"DeepSpeedCPUAdam); got '{opt_name}'")
        p = dict(opt_params or {})
        p.pop("lr", None)
        betas = p.pop("betas", (0.9, 0.999))
        self.layout = FlatLayout(abstract_params)
        self.adam = HostAdam(self.layout.total,
                             beta1=float(betas[0]), beta2=float(betas[1]),
                             eps=float(p.pop("eps", 1e-8)),
                             weight_decay=float(p.pop("weight_decay", 0.0)),
                             adamw_mode=(name == "adamw"),
                             allocate_state=allocate_moments)
        self.compute_dtype = compute_dtype
        self.master: Optional[np.ndarray] = None
        self.hyperparams = {"name": f"host_{name}", "offload": "cpu",
                            "betas": betas}
        self._lib = load_host_adam() if is_native_available() else None
        # single worker: host steps are strictly ordered
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        # scratch buffers reused across steps (no per-step 100M-element allocs)
        self._g32 = np.empty(self.layout.total, np.float32)
        self._out16 = np.empty(self.layout.total, np.uint16) \
            if compute_dtype == jnp.bfloat16 else None
        log_dist(f"ZeRO-Offload host optimizer: {self.layout.total / 1e6:.1f}M "
                 f"elements in host DRAM "
                 f"({self.layout.total * 12 / 2**30:.2f} GiB opt state)")

    def init_from(self, params: Pytree) -> None:
        """(Re)build the tier from params: fresh master, fresh moments.

        Called both at engine init and on a cross-mode checkpoint restore
        mid-process — the moments/step MUST be reset, or a restore after
        earlier steps in the same process silently resumes with stale
        Adam state."""
        self.master = self.layout.flatten_np(params)
        if self.adam.exp_avg is not None:
            self.adam.exp_avg.fill(0.0)
        if self.adam.exp_avg_sq is not None:
            self.adam.exp_avg_sq.fill(0.0)
        self.adam.step_count = 0

    # ------------------------------------------------------------ flat path
    def _widen_grads(self, flat_g: np.ndarray) -> np.ndarray:
        """transfer-dtype grads → fp32 scratch (C++ widen for bf16)."""
        if flat_g.dtype == np.float32:
            np.copyto(self._g32, flat_g)
        elif flat_g.dtype.name == "bfloat16":     # ml_dtypes; NOT fp16 —
            # fp16 bits through the bf16 widener would be garbage
            u16 = flat_g.view(np.uint16)
            if self._lib is not None and u16.flags.c_contiguous:
                self._lib.ds_bf16_to_f32(_u16p(u16), _f32p(self._g32),
                                         self.layout.total)
            else:
                self._g32[:] = flat_g.astype(np.float32)
        else:
            self._g32[:] = flat_g.astype(np.float32)
        return self._g32

    def _prepare_grads(self, flat_g: np.ndarray, loss_scale: float,
                       grad_clip: float, lr: float, wait_on
                       ) -> Tuple[Optional[np.ndarray], dict]:
        """Shared step preamble: wait for the in-flight H2D upload (overlap
        mode's buffer-reuse hazard), widen/unscale, overflow check, clip.
        Returns (fp32 grads or None on overflow, metrics)."""
        if wait_on is not None:
            import jax as _jax
            _jax.block_until_ready(wait_on)
        g = self._widen_grads(np.asarray(flat_g))
        if loss_scale != 1.0:
            g *= 1.0 / loss_scale
        norm = self.adam.grad_norm(g)
        overflow = not np.isfinite(norm)
        metrics = {"grad_norm": norm, "overflow": int(overflow), "lr": lr}
        if overflow:
            return None, metrics
        if grad_clip > 0 and norm > grad_clip:
            g *= grad_clip / (norm + 1e-6)
        return g, metrics

    def step_flat(self, flat_g: np.ndarray, lr: float,
                  grad_clip: float = 0.0, loss_scale: float = 1.0,
                  wait_on=None) -> Tuple[Optional[np.ndarray], dict]:
        """Host step over the flat gradient → (flat compute-dtype params
        or None on overflow, metrics). Runs on the caller's thread.

        ``wait_on`` — a device array backed by the PREVIOUS step's output
        buffer upload (engine passes the device_put result). Blocking on it
        guarantees the in-flight H2D DMA finished reading
        ``self.master``/``self._out16`` before this step mutates them
        (overlap mode's buffer-reuse hazard)."""
        g, metrics = self._prepare_grads(flat_g, loss_scale, grad_clip, lr,
                                         wait_on)
        if g is None:
            return None, metrics
        self.adam.step(self.master, g, lr=lr)
        return self._narrow_master(), metrics

    def _narrow_range(self, src: np.ndarray, off: int, n: int) -> None:
        """fp32 slice of the master → compute-dtype slice of ``_out16``
        (no-op target when compute dtype is fp32)."""
        if self._out16 is None:
            return
        if self._lib is not None:
            self._lib.ds_f32_to_bf16(_f32p(src[:n]),
                                     _u16p(self._out16[off:off + n]), n)
        else:
            self._out16[off:off + n] = np.asarray(
                jnp.asarray(src[:n]).astype(jnp.bfloat16)).view(np.uint16)

    def _narrow_master(self) -> np.ndarray:
        """fp32 master → flat compute-dtype array for one device_put."""
        if self._out16 is None:
            return self.master
        self._narrow_range(self.master, 0, self.layout.total)
        import ml_dtypes
        return self._out16.view(ml_dtypes.bfloat16)

    def step_flat_async(self, flat_g: np.ndarray, lr: float,
                        grad_clip: float = 0.0, loss_scale: float = 1.0,
                        wait_on=None) -> "concurrent.futures.Future":
        """Submit the host step to the worker thread (ZenFlow overlap)."""
        return self._pool.submit(self.step_flat, flat_g, lr, grad_clip,
                                 loss_scale, wait_on)

    # ---------------------------------------------------------- pytree path
    def step(self, grads: Pytree, lr: float, grad_clip: float = 0.0,
             loss_scale: float = 1.0) -> Tuple[Optional[Pytree], dict]:
        """Pytree-in/pytree-out step (3-call parity API)."""
        flat_g = self.layout.flatten_np(grads)
        new_flat, metrics = self.step_flat(flat_g, lr, grad_clip, loss_scale)
        if new_flat is None:
            return None, metrics
        new_params = self.layout.unflatten(
            self.master, [self.compute_dtype] * len(self.layout.shapes))
        return new_params, metrics

    # -- checkpoint support -------------------------------------------------

    def state_dict(self) -> dict:
        return {"master": self.master, "exp_avg": self.adam.exp_avg,
                "exp_avg_sq": self.adam.exp_avg_sq,
                "step": self.adam.step_count}

    def load_state_dict(self, state: dict) -> None:
        self.master = np.asarray(state["master"], np.float32).copy()
        self.adam.exp_avg = np.asarray(state["exp_avg"], np.float32).copy()
        self.adam.exp_avg_sq = np.asarray(state["exp_avg_sq"],
                                          np.float32).copy()
        self.adam.step_count = int(state["step"])
