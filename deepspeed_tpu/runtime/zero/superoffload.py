"""SuperOffload — bucketed speculative host optimizer step.

Reference: ``runtime/superoffload/superoffload_stage3.py:20``
(SuperOffloadZeroOptimizer: bucketed optimizer-state transfer, CPUAdam
worker pool, "speculative" step with rollback — targets GH200-class hosts
where the CPU↔accelerator link is fast enough that the host step should
START before the full gradient has landed).

TPU translation: the gradient leaves the device as one flat array; instead
of blocking on the whole D2H fetch and then sweeping (HostOffloadOptimizer),
the flat gradient is fetched in BUCKETS on a prefetch thread while the C++
SIMD Adam sweeps the previous bucket — transfer and compute pipeline. The
global grad norm is only known after the last bucket, so the sweep runs
SPECULATIVELY (no pre-pass over the gradient): if the finished norm shows
an overflow or a clip was needed, the step rolls back from per-step backup
buffers and (for clip) re-runs with scaled gradients — the reference's
speculative/rollback design. Cost of the speculation safety net: one extra
master+moments copy (12 B/param host DRAM) and a rare 2× sweep when a clip
triggers; win: the host step starts after ONE bucket instead of the full
transfer.
"""

import concurrent.futures
import math
from typing import Any, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
from deepspeed_tpu.utils.logging import log_dist

Pytree = Any

#: default bucket: 2^22 elements = 16 MiB fp32 per fetch
DEFAULT_BUCKET = 1 << 22


class SuperOffloadOptimizer(HostOffloadOptimizer):
    """Drop-in for HostOffloadOptimizer with the bucketed speculative
    step (``offload_optimizer.device='cpu', superoffload=true``)."""

    def __init__(self, abstract_params: Pytree, opt_name: str,
                 opt_params: dict, compute_dtype,
                 bucket_size: int = DEFAULT_BUCKET):
        super().__init__(abstract_params, opt_name, opt_params,
                         compute_dtype)
        self.bucket = int(min(bucket_size, self.layout.total))
        n = self.layout.total
        # rollback backups (master + both moments) — the speculation net
        self._bk_master = np.empty(n, np.float32)
        self._bk_m = np.empty(n, np.float32)
        self._bk_v = np.empty(n, np.float32)
        self._fetcher = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        self.speculative_rollbacks = 0
        log_dist(f"SuperOffload: bucket {self.bucket / 1e6:.1f}M elements, "
                 f"speculative step with rollback")

    def _nbuckets(self) -> int:
        return (self.layout.total + self.bucket - 1) // self.bucket

    def _fetch(self, flat_g_dev, i: int) -> np.ndarray:
        off = i * self.bucket
        n = min(self.bucket, self.layout.total - off)
        return np.asarray(flat_g_dev[off:off + n])

    def step_flat(self, flat_g, lr: float, grad_clip: float = 0.0,
                  loss_scale: float = 1.0, wait_on=None
                  ) -> Tuple[Optional[np.ndarray], dict]:
        """``flat_g`` may stay a DEVICE array — buckets are fetched on the
        prefetch thread while Adam sweeps (the whole point)."""
        if wait_on is not None:
            import jax as _jax
            _jax.block_until_ready(wait_on)
        a = self.adam
        nb = self._nbuckets()
        inv_scale = 1.0 / loss_scale
        a.step_count += 1

        fut = self._fetcher.submit(self._fetch, flat_g, 0)
        norm_sq = 0.0
        for i in range(nb):
            g_np = fut.result()
            if i + 1 < nb:
                fut = self._fetcher.submit(self._fetch, flat_g, i + 1)
            off = i * self.bucket
            n = g_np.size
            sl = slice(off, off + n)
            g32 = self._g32[sl]
            if g_np.dtype == np.float32:
                np.copyto(g32, g_np)
            else:
                g32[:] = g_np.astype(np.float32)
            if loss_scale != 1.0:
                g32 *= inv_scale
            norm_sq += float(np.dot(g32.astype(np.float64),
                                    g32.astype(np.float64)))
            # speculative: back up THEN update this bucket immediately
            self._bk_master[sl] = self.master[sl]
            self._bk_m[sl] = a.exp_avg[sl]
            self._bk_v[sl] = a.exp_avg_sq[sl]
            a.step_buffers(self.master[sl], g32, a.exp_avg[sl],
                           a.exp_avg_sq[sl], a.step_count, lr)

        norm = math.sqrt(norm_sq)
        overflow = not math.isfinite(norm)
        metrics = {"grad_norm": norm, "overflow": int(overflow), "lr": lr,
                   "speculative_rollbacks": self.speculative_rollbacks}
        if overflow:
            self._rollback()
            a.step_count -= 1
            return None, metrics
        if grad_clip > 0 and norm > grad_clip:
            # rare: redo the sweep with clipped grads (reference rollback)
            self._rollback()
            self.speculative_rollbacks += 1
            metrics["speculative_rollbacks"] = self.speculative_rollbacks
            self._g32 *= grad_clip / (norm + 1e-6)
            a.step_buffers(self.master, self._g32, a.exp_avg,
                           a.exp_avg_sq, a.step_count, lr)
        return self._narrow_master(), metrics

    def _rollback(self) -> None:
        np.copyto(self.master, self._bk_master)
        np.copyto(self.adam.exp_avg, self._bk_m)
        np.copyto(self.adam.exp_avg_sq, self._bk_v)
