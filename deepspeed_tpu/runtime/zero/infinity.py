"""ZeRO-Infinity: optimizer state tier on NVMe.

Reference: ``runtime/zero/stage3.py:703`` → ``runtime/swap_tensor/
partitioned_param_swapper.py`` / ``optimizer_utils.py`` (NVMe swap of fp32
partitions with double-buffered aio). TPU-first translation: at pod scale
the bf16 params comfortably fit HBM sharded over the fsdp axis (70B bf16 /
128 chips ≈ 1.1 GB/chip) — what doesn't fit host DRAM is the fp32
master+moments (12 bytes/param). So the NVMe tier here holds the flat
master/exp_avg/exp_avg_sq files, and the host step becomes a WINDOWED
SWEEP: while window i runs the native SIMD Adam, window i+1's three
buffers stream in and window i-1's stream out through the AsyncIOEngine
(csrc/async_io.cpp) — the reference's double-buffer design
(swap_tensor/optimizer_utils.py) with `drain()` as the pipeline barrier.

Exposes the same protocol as HostOffloadOptimizer, so the engine's flat
grad path, overlap mode, and checkpointing work unchanged with
``offload_optimizer.device: "nvme"``.
"""

import os
from typing import Any, Optional, Tuple

import numpy as np

from deepspeed_tpu.io.async_io import AsyncIOEngine
from deepspeed_tpu.runtime.zero.offload import (FlatLayout,
                                                HostOffloadOptimizer)
from deepspeed_tpu.utils.logging import log_dist

Pytree = Any

#: default window: 2^24 elements = 64 MiB fp32 per tensor per window
DEFAULT_WINDOW = 1 << 24


class NVMeOffloadOptimizer(HostOffloadOptimizer):
    """Adam whose fp32 master/moments live in flat NVMe files."""

    def __init__(self, abstract_params: Pytree, opt_name: str,
                 opt_params: dict, compute_dtype, nvme_path: str,
                 window: int = DEFAULT_WINDOW, aio_threads: int = 4):
        # the full-size moments live on NVMe — never allocate them in DRAM
        super().__init__(abstract_params, opt_name, opt_params,
                         compute_dtype, allocate_moments=False)
        os.makedirs(nvme_path, exist_ok=True)
        self.nvme_path = nvme_path
        self.window = int(min(window, self.layout.total))
        self.files = {name: os.path.join(nvme_path, f"{name}.bin")
                      for name in ("master", "exp_avg", "exp_avg_sq")}
        self.aio = AsyncIOEngine(num_threads=aio_threads)
        # 3-deep rotation per tensor: read-ahead / computing / writing-out
        nw = self.window
        self._bufs = {name: [np.zeros(nw, np.float32) for _ in range(3)]
                      for name in self.files}
        self.bytes_read = 0
        self.bytes_written = 0
        self.hyperparams = dict(self.hyperparams, offload="nvme")
        # pre-size every file SYNCHRONOUSLY before any aio touches it:
        # ftruncate both zero-fills the moments (sparse) and removes the
        # fallback writer's create-vs-write race on fresh files
        for name in self.files:
            self._zero_file(name)
        log_dist(f"ZeRO-Infinity NVMe tier at {nvme_path}: "
                 f"{self.layout.total * 12 / 2**30:.2f} GiB optimizer state "
                 f"on disk, window {self.window / 1e6:.1f}M elements")

    def _zero_file(self, name: str) -> None:
        """(Re)create ``files[name]`` as a zero-filled (sparse) file of the
        full state size."""
        with open(self.files[name], "wb") as fh:
            fh.truncate(self.layout.total * 4)

    # the full master never lives in RAM
    def init_from(self, params: Pytree) -> None:
        flat = self.layout.flatten_np(params)   # one transient full copy
        for off in range(0, self.layout.total, self.window):
            n = min(self.window, self.layout.total - off)
            self.aio.pwrite(self.files["master"],
                            flat[off:off + n].copy(), off * 4)
        self.aio.drain()
        # a mid-process rebuild (cross-mode restore) must also zero the
        # on-disk moments and the step count, or the next sweep resumes
        # with stale Adam state from steps taken before the restore
        for name in ("exp_avg", "exp_avg_sq"):
            self._zero_file(name)
        self.adam.step_count = 0
        self.bytes_written += self.layout.total * 4
        self.master = None

    def _num_windows(self) -> int:
        return (self.layout.total + self.window - 1) // self.window

    def _win(self, i: int) -> Tuple[int, int]:
        off = i * self.window
        return off, min(self.window, self.layout.total - off)

    def _submit_read(self, i: int) -> None:
        off, n = self._win(i)
        for name in self.files:
            buf = self._bufs[name][i % 3]
            self.aio.pread(self.files[name], buf[:n], off * 4)
        self.bytes_read += 3 * n * 4

    def _submit_write(self, i: int) -> None:
        off, n = self._win(i)
        for name in self.files:
            self.aio.pwrite(self.files[name], self._bufs[name][i % 3][:n],
                            off * 4)
        self.bytes_written += 3 * n * 4

    def step_flat(self, flat_g: np.ndarray, lr: float,
                  grad_clip: float = 0.0, loss_scale: float = 1.0,
                  wait_on=None) -> Tuple[Optional[np.ndarray], dict]:
        g, metrics = self._prepare_grads(flat_g, loss_scale, grad_clip, lr,
                                         wait_on)
        if g is None:
            return None, metrics

        self.adam.step_count += 1
        # fp32 compute dtype needs its own output buffer; bf16 narrows
        # straight into the parent's _out16 via _narrow_range
        out = None if self._out16 is not None else \
            np.empty(self.layout.total, np.float32)
        nwin = self._num_windows()
        self._submit_read(0)
        self.aio.drain()
        for i in range(nwin):
            # stream i+1 in and i-1 out WHILE the SIMD Adam sweeps window i
            if i + 1 < nwin:
                self._submit_read(i + 1)
            if i > 0:
                self._submit_write(i - 1)
            off, n = self._win(i)
            self._adam_window(i, g[off:off + n], lr)
            self._narrow_window(i, out, off, n)
            self.aio.drain()
        self._submit_write(nwin - 1)
        self.aio.drain()
        if self._out16 is not None:
            import ml_dtypes
            return self._out16.view(ml_dtypes.bfloat16), metrics
        return out, metrics

    def _adam_window(self, i: int, g: np.ndarray, lr: float) -> None:
        """One fused Adam sweep over window i's buffers; the math lives in
        HostAdam.step_buffers (explicit global step so every window shares
        the same bias correction)."""
        b = {k: self._bufs[k][i % 3] for k in self._bufs}
        n = g.size
        self.adam.step_buffers(b["master"][:n], g, b["exp_avg"][:n],
                               b["exp_avg_sq"][:n], self.adam.step_count,
                               lr)

    def _narrow_window(self, i: int, out: np.ndarray, off: int, n: int
                       ) -> None:
        """window master → compute-dtype slice of the output flat buffer."""
        master = self._bufs["master"][i % 3]
        if self._out16 is not None:
            self._narrow_range(master, off, n)
        else:
            out[off:off + n] = master[:n]

    # -- checkpoint support -------------------------------------------------

    def _read_full(self, name: str) -> np.ndarray:
        out = np.empty(self.layout.total, np.float32)
        for off in range(0, self.layout.total, self.window):
            n = min(self.window, self.layout.total - off)
            self.aio.pread(self.files[name], out[off:off + n], off * 4)
        self.aio.drain()
        return out

    def state_dict(self) -> dict:
        return {"master": self._read_full("master"),
                "exp_avg": self._read_full("exp_avg"),
                "exp_avg_sq": self._read_full("exp_avg_sq"),
                "step": self.adam.step_count}

    def load_state_dict(self, state: dict) -> None:
        for name in ("master", "exp_avg", "exp_avg_sq"):
            flat = np.asarray(state[name], np.float32)
            for off in range(0, self.layout.total, self.window):
                n = min(self.window, self.layout.total - off)
                self.aio.pwrite(self.files[name],
                                flat[off:off + n].copy(), off * 4)
        self.aio.drain()
        self.adam.step_count = int(state["step"])
