"""ZeRO++ — explicit quantized-collective data path.

Reference: ZeRO++ (blogs/zeropp; runtime/zero/stage3.py:1636
``quantize_nontrainable_params`` [qwZ], runtime/comm/
coalesced_collectives.py ``all_to_all_quant_reduce`` [qgZ]; config gates
``zero_quantized_weights`` / ``zero_quantized_gradients``,
engine.py:1108–1117).

The standard engine path lets GSPMD insert exact allgather/reduce-scatter
from sharding annotations; quantized collectives can't be expressed as
annotations, so this mode swaps in one explicit ``shard_map`` step over the
'data' axis:

- **storage**: params live as ONE flat array [padded] sharded over 'data'
  (the reference's flat fp16 partitions); optimizer state (fp32 master +
  moments) is per-chunk — ZeRO-1/2/3 memory in one layout.
- **qwZ**: each step gathers the full flat params from the chunks with an
  int8 block-quantized allgather (comm/quantized.py) — half the bf16
  gather traffic, 4× the fp32.
- **qgZ**: gradients leave the device through a quantized all-to-all +
  local mean (single hop; the hierarchical two-axis variant rides ICI
  before DCN) instead of an exact reduce-scatter.
- the optimizer update runs on the local chunk only.

Restrictions (validated at build): data-parallel only mesh (model = seq =
pipe = expert = 1), bf16/fp32 (fp16 dynamic loss scaling needs the exact
global overflow signal), no offload, fused ``train_batch`` API only — the
same restriction set the reference ties to its quantized paths. The full
flat params are materialized per device during the step (like a ZeRO-3
gather); block-granular gathers can follow.

Accuracy: int8 block-quant error is ≤ absmax/254 per element per hop;
tests assert loss trajectories track the exact path within tolerance.
"""

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from deepspeed_tpu.comm.quantized import (quantized_all_gather,
                                          quantized_reduce_scatter)
from deepspeed_tpu.ops.quantizer import DEFAULT_BLOCK
from deepspeed_tpu.runtime.zero.offload import FlatLayout
from deepspeed_tpu.utils.logging import log_dist

Pytree = Any


def validate_zeropp(engine) -> None:
    mesh = engine.mesh
    for ax in ("model", "seq", "pipe", "expert", "data_inner"):
        if mesh.shape[ax] != 1:
            raise ValueError(
                f"ZeRO++ quantized collectives run over the 'data' axis "
                f"only; mesh axis '{ax}' has size {mesh.shape[ax]}")
    if engine.fp16_enabled:
        raise ValueError("ZeRO++ requires bf16/fp32 (fp16 dynamic loss "
                         "scaling needs the exact overflow signal)")
    if engine.offload_enabled:
        raise ValueError("ZeRO++ and offload_optimizer are mutually "
                         "exclusive (both own the flat layout)")
    if engine.model.pipeline_loss_fn is not None:
        raise ValueError("ZeRO++ does not compose with the pipeline "
                         "schedule yet")


def init_zeropp_state(engine, params, rng) -> None:
    """Install the flat sharded storage: ``engine.params`` becomes ONE
    flat [padded] array sharded over 'data'; optimizer state is the
    matching per-chunk (master/moments) layout."""
    cfg = engine.config
    mesh = engine.mesh
    world = mesh.shape["data"]
    layout = FlatLayout(engine._abstract_params)
    total = layout.total
    quantum = DEFAULT_BLOCK * world
    padded = ((total + quantum - 1) // quantum) * quantum
    engine._zeropp_layout = layout
    engine._zeropp_padded = padded

    compute_dtype = engine.compute_dtype
    flat_sh = NamedSharding(mesh, P("data"))

    def to_flat(p):
        if compute_dtype != jnp.float32:
            p = jax.tree.map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
        flat = layout.flatten_device(p, compute_dtype)
        return jnp.concatenate(
            [flat, jnp.zeros((padded - total,), compute_dtype)])

    if params is None:
        engine.params = jax.jit(
            lambda r: to_flat(engine.model.init_fn(r)),
            out_shardings=flat_sh)(rng)
    else:
        engine.params = jax.jit(to_flat, out_shardings=flat_sh)(params)
    engine._param_shardings = flat_sh
    engine.host_optimizer = None

    abstract_state = jax.eval_shape(engine.optimizer.init, engine.params)
    # flat buffers shard over 'data'; scalar leaves (step counters)
    # replicate
    state_sh = jax.tree.map(
        lambda a: flat_sh if np.ndim(a) else NamedSharding(mesh, P()),
        abstract_state)
    engine.opt_state = jax.jit(engine.optimizer.init,
                               out_shardings=state_sh)(engine.params)
    engine._state_shardings = state_sh
    log_dist(
        f"ZeRO++ path: qwZ={cfg.zero_optimization.zero_quantized_weights} "
        f"qgZ={cfg.zero_optimization.zero_quantized_gradients} dp={world} "
        f"flat={padded / 1e6:.1f}M elements")


def build_zeropp_step(engine) -> None:
    """Install the quantized fused ``train_batch`` step (see module
    docstring for the data path)."""
    cfg = engine.config
    mesh = engine.mesh
    world = mesh.shape["data"]
    qw = cfg.zero_optimization.zero_quantized_weights
    qg = cfg.zero_optimization.zero_quantized_gradients
    layout = engine._zeropp_layout
    total = layout.total
    padded = engine._zeropp_padded
    compute_dtype = engine.compute_dtype

    gas = int(cfg.gradient_accumulation_steps)
    optimizer = engine.optimizer
    lr_schedule = engine.lr_schedule
    grad_clip = float(cfg.gradient_clipping or 0.0)
    loss_fn = engine.model.loss_fn

    def body(flat_chunk, opt_chunk, batch, step, rng):
        """Per-device: gather → fwd/bwd (GAS scan) → quantized reduce →
        chunk update. flat_chunk: [padded/world]; batch leaves
        [gas, local_b, ...]."""
        if qw:
            flat = quantized_all_gather(flat_chunk, "data",
                                        dtype=compute_dtype)
        else:
            flat = lax.all_gather(flat_chunk, "data", tiled=True)
        params = layout.unflatten_device(flat[:total])

        def micro(carry, mb):
            acc, r = carry
            r, sub = jax.random.split(r)

            def lf(p):
                out = loss_fn(p, mb, sub)
                return out[0] if isinstance(out, tuple) else out

            loss, grads = jax.value_and_grad(lf)(params)
            flat_g = layout.flatten_device(grads, jnp.float32)
            return (acc + flat_g, r), loss

        acc0 = jnp.zeros((total,), jnp.float32)
        (acc, _), losses = lax.scan(micro, (acc0, rng), batch)
        acc = acc * (1.0 / gas)
        acc = jnp.concatenate([acc, jnp.zeros((padded - total,),
                                              jnp.float32)])
        if qg:
            g_chunk = quantized_reduce_scatter(acc, "data", mean=True)
        else:
            g_chunk = lax.psum_scatter(acc, "data", tiled=True) / world

        # global grad norm from the chunks (exact — norms are cheap)
        gnorm = jnp.sqrt(lax.psum(jnp.sum(jnp.square(g_chunk)), "data"))
        if grad_clip > 0:
            g_chunk = g_chunk * jnp.minimum(1.0, grad_clip / (gnorm + 1e-6))
        lr = lr_schedule(step)
        new_chunk, new_opt = optimizer.update(g_chunk, opt_chunk,
                                              flat_chunk, lr)
        loss = lax.pmean(jnp.mean(losses), "data")
        return new_chunk, new_opt, loss, gnorm, lr

    opt_specs = jax.tree.map(lambda sh: sh.spec, engine._state_shardings)

    def fused_step(flat_params, opt_state, scaler, batch, step, rng):
        """Engine _fused_step signature; scaler passes through untouched
        (bf16/fp32 only)."""
        batch_specs = jax.tree.map(
            lambda x: P(None, "data", *([None] * (np.ndim(x) - 2))), batch)
        new_flat, new_opt, loss, gnorm, lr = shard_map(
            body, mesh=mesh,
            in_specs=(P("data"), opt_specs, batch_specs, P(), P()),
            out_specs=(P("data"), opt_specs, P(), P(), P()),
            check_vma=False,
        )(flat_params, opt_state, batch, step, rng)
        metrics = {"loss": loss, "lr": lr, "grad_norm": gnorm,
                   "loss_scale": scaler.scale,
                   "overflow": jnp.zeros((), jnp.int32)}
        return new_flat, new_opt, scaler, metrics

    engine._fused_step = jax.jit(fused_step, donate_argnums=(0, 1))
    engine._grad_step = None      # 3-call parity API unsupported here
    engine._acc_add = None
    engine._update_step = None
    engine._rng = jax.random.PRNGKey(cfg.seed + 1)


def unflatten_params(engine) -> Pytree:
    """Flat storage → params pytree (for export / interop; costs one
    gather)."""
    layout = engine._zeropp_layout
    fn = jax.jit(lambda f: layout.unflatten_device(f[:layout.total]))
    return fn(engine.params)
