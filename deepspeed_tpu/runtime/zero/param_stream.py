"""ZeRO-Infinity parameter tier: train models whose params exceed HBM.

Reference: ``runtime/zero/stage3.py:703`` → ``runtime/swap_tensor/
partitioned_param_swapper.py`` — ZeRO-Infinity swaps partitioned *params*
(not just optimizer state) between NVMe/DRAM and device, fetching each
submodule's weights right before use. That is the few-chips-huge-model
training config (reference claim: 13B on one V100-32G,
docs/_pages/training.md:77).

TPU-native redesign — no per-module fetch hooks; the unit of streaming is
the LAYER of the stacked decoder:

* The authoritative parameter copy lives in a file-backed store:
  ``params.bin`` in the compute dtype, next to the NVMe optimizer tier's
  master/moment files (runtime/zero/infinity.py). ``offload_param.device:
  'nvme'`` puts it on disk; ``'cpu'`` uses the same code path on /dev/shm
  (host DRAM).
* Forward: embed runs from the resident tail params; each decoder layer's
  weights are read from the store, put on device, and applied by ONE
  jitted layer step; the layer's input activation is stashed (HBM).
  Peak HBM = one layer + activations + embed/head, independent of L.
* Backward: layers stream again in reverse; a jitted per-layer VJP
  recomputes the layer forward from the stashed input (remat by design)
  and emits (dx, layer grads); grads are written to ``grads.bin`` with a
  running global sum-of-squares for EXACT global-norm clipping.
* Update: the NVMe optimizer's windowed SIMD Adam sweep
  (infinity.py:101 design) runs over (master, m, v, grads) files and
  narrows the new master straight back into ``params.bin``; the resident
  embed/head re-upload, and the next forward streams fresh layer weights.

Composes with gradient accumulation (microbatches past the first
accumulate into ``grads.bin`` by read-modify-write — the reference
swapper's gradient-partition pass, with the global-norm computed from
the final accumulated values) and with a dp>1 mesh (batch sharded over
the data axes, streamed layer weights replicated; GSPMD inserts the
gradient reductions). Remaining scope fences (checked at construction,
loud errors): dense decoders only, bf16/fp32 (no fp16 loss scaling), no
pipeline/SP/MoE composition; the file store itself is one per host —
per-host sharded partition files (the reference swapper's per-rank
files) are a multi-host concern this single-controller runtime does not
exercise.
"""

import math
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import transformer
from deepspeed_tpu.runtime.zero.offload import FlatLayout
from deepspeed_tpu.utils.logging import log_dist

Pytree = Any


class _LayerRanges:
    """Flat-file ranges of one layer's leaves inside the stacked layout.

    FlatLayout orders leaves whole-array; a stacked leaf [L, ...] occupies
    one contiguous block, so layer l of leaf k is the contiguous range
    ``leaf_off[k] + l*per_layer[k] .. +per_layer[k]``."""

    def __init__(self, layout: FlatLayout, abstract_params: Pytree):
        self.layout = layout
        layer_tree = abstract_params["layers"]
        leaves, self.treedef = jax.tree_util.tree_flatten(layer_tree)
        self.num_layers = leaves[0].shape[0]
        flat_all, _ = jax.tree_util.tree_flatten(abstract_params)
        # map each stacked-layer leaf to its offset in the full flat layout
        ids = {id(x): i for i, x in enumerate(flat_all)}
        self.leaf_off = [int(layout.offsets[ids[id(x)]]) for x in leaves]
        self.per_layer = [int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
                          for x in leaves]
        self.shapes = [tuple(x.shape[1:]) for x in leaves]
        self.dtypes = [x.dtype for x in leaves]
        self.layer_elems = sum(self.per_layer)

    def ranges(self, l: int) -> List[Tuple[int, int]]:
        return [(off + l * n, n)
                for off, n in zip(self.leaf_off, self.per_layer)]

    def unflatten_layer(self, chunks: List[np.ndarray]) -> Pytree:
        leaves = [c.reshape(s).astype(d) for c, s, d in
                  zip(chunks, self.shapes, self.dtypes)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class _FileStore:
    """Flat fp-file store through the async-io engine (NVMe or /dev/shm)."""

    def __init__(self, path: str, total: int, itemsize: int, aio):
        self.path = path
        self.itemsize = itemsize
        self.aio = aio
        with open(path, "wb") as fh:
            fh.truncate(total * itemsize)

    def read(self, out_np: np.ndarray, elem_off: int) -> None:
        self.aio.pread(self.path, out_np, elem_off * self.itemsize)

    def write(self, arr_np: np.ndarray, elem_off: int) -> None:
        self.aio.pwrite(self.path, arr_np, elem_off * self.itemsize)

    def drain(self):
        self.aio.drain()


class ParamStreamCoordinator:
    """Layer-streamed train path for ``offload_param.device != none``."""

    def __init__(self, engine):
        from deepspeed_tpu.runtime.zero.infinity import NVMeOffloadOptimizer
        self.engine = engine
        cfg = engine.config
        dec = engine.model.decoder_config
        if dec is None:
            raise ValueError("offload_param requires a DecoderConfig model "
                             "(the layer-streamed path is model-aware)")
        if dec.num_experts:
            raise ValueError("offload_param does not compose with MoE yet")
        if cfg.pipeline.stages > 1 or cfg.sequence_parallel.size > 1:
            raise ValueError(
                "offload_param does not compose with pipeline/sequence "
                "parallelism (one streaming schedule at a time)")
        if engine.fp16_enabled:
            raise ValueError("offload_param requires bf16/fp32")
        if not isinstance(engine.host_optimizer, NVMeOffloadOptimizer):
            raise ValueError(
                "offload_param requires offload_optimizer.device 'nvme' "
                "(or 'cpu', which maps to the same tier on /dev/shm) — "
                "the master weights live in the tiered store")
        self.dec = dec
        self.gas = int(cfg.gradient_accumulation_steps)
        self.opt = engine.host_optimizer
        self.layout: FlatLayout = self.opt.layout
        # dp>1 mesh: the layer step runs SPMD with the batch sharded over
        # the data axes and the streamed layer weights replicated — GSPMD
        # inserts the gradient psum, so the grads written to the store
        # are already the data-parallel mean's numerator
        from deepspeed_tpu.parallel.mesh import get_mesh, has_mesh
        self._mesh = get_mesh() if has_mesh() else None
        self._dp = 1
        if self._mesh is not None:
            for a in ("data", "data_inner", "expert"):
                self._dp *= self._mesh.shape.get(a, 1)
        if self._mesh is not None and self._dp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            axes = tuple(a for a in ("data", "data_inner", "expert")
                         if self._mesh.shape.get(a, 1) > 1)
            spec = axes if len(axes) > 1 else axes[0]
            self._batch_sharding = NamedSharding(self._mesh,
                                                 P(spec))
            self._repl_sharding = NamedSharding(self._mesh, P())
        else:
            self._batch_sharding = self._repl_sharding = None
        self._abstract = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
            engine._abstract_params)
        self.lr_ranges = _LayerRanges(self.layout, self._abstract)
        self.compute_dtype = engine.compute_dtype
        self._p_item = 2 if self.compute_dtype == jnp.bfloat16 else 4
        root = os.path.dirname(self.opt.files["master"])
        self.params_store = _FileStore(
            os.path.join(root, "params.bin"), self.layout.total,
            self._p_item, self.opt.aio)
        self.grads_store = _FileStore(
            os.path.join(root, "grads.bin"), self.layout.total, 4,
            self.opt.aio)
        self._resident_keys = [k for k in self._abstract if k != "layers"]
        self._build_jits()
        self._seed_store(engine.params)
        # device params are now redundant — the store is authoritative;
        # keep only the resident (non-layer) subtree on device
        # (replicated across the mesh when dp > 1)
        self.resident = {k: engine.params[k] for k in self._resident_keys}
        if self._repl_sharding is not None:
            self.resident = jax.device_put(self.resident,
                                           self._repl_sharding)
        engine.params = None
        log_dist(
            f"ZeRO-Infinity param tier: {self.layout.total * self._p_item / 2**30:.2f} "
            f"GiB params + {self.layout.total * 4 / 2**30:.2f} GiB grads in "
            f"{root} ({dec.num_layers} streamed layers, "
            f"{self.lr_ranges.layer_elems / 1e6:.1f}M elems/layer)")

    # ----------------------------------------------------------------- setup
    def _seed_store(self, params: Pytree) -> None:
        """Initial params → store (and master via the optimizer's init)."""
        flat = np.asarray(jax.device_get(
            jax.jit(lambda p: self.layout.flatten_device(
                p, self.compute_dtype))(params)))
        self.params_store.write(flat, 0)
        self.params_store.drain()

    def _build_jits(self):
        dec = self.dec
        attn_fn = transformer.default_attention(dec)

        def embed_fwd(em, tokens):
            b, t = tokens.shape
            positions = jnp.broadcast_to(
                jnp.arange(t, dtype=jnp.int32)[None], (b, t))
            x = transformer.embed_tokens(dec, em["embed"], tokens, positions,
                                         em.get("embed_norm"))
            return x

        def layer_fwd(lp, x, tokens):
            b, t = tokens.shape
            positions = jnp.broadcast_to(
                jnp.arange(t, dtype=jnp.int32)[None], (b, t))
            if dec.pos_emb == "rope":
                sin, cos = transformer.rope_table(dec, positions)
            else:
                sin = cos = jnp.zeros((b, t, 0), jnp.float32)
            out, _aux = transformer.decoder_block(dec, lp, x, sin, cos,
                                                  attn_fn)
            return out

        def head_loss(res, x, labels):
            xn = transformer._norm(dec, res["final_norm"], x)
            return transformer.chunked_cross_entropy(dec, res, xn, labels)

        self._j_embed = jax.jit(embed_fwd)
        self._j_layer = jax.jit(layer_fwd)

        def layer_vjp(lp, x_in, tokens, dy):
            out, vjp = jax.vjp(lambda p, x: layer_fwd(p, x, tokens),
                               lp, x_in)
            dlp, dx = vjp(dy)
            return dx, dlp

        self._j_layer_vjp = jax.jit(layer_vjp)

        def head_vjp(res, x, labels, seed):
            # seed = 1/gas: scales every downstream cotangent so the
            # accumulated grads are the MEAN over microbatches (matching
            # the fused engine path) with zero extra passes
            loss, vjp = jax.vjp(
                lambda r, xx: head_loss(r, xx, labels), res, x)
            dres, dx = vjp(seed)
            return loss, dx, dres

        self._j_head_vjp = jax.jit(head_vjp)
        self._j_head_loss = jax.jit(head_loss)

        def embed_vjp(em, tokens, dx):
            _, vjp = jax.vjp(lambda e: embed_fwd(e, tokens), em)
            (dem,) = vjp(dx)
            return dem

        self._j_embed_vjp = jax.jit(embed_vjp)

    # ------------------------------------------------------------- layer IO
    def _issue_layer(self, l: int) -> Tuple[int, List[np.ndarray]]:
        """Submit layer ``l``'s file reads WITHOUT waiting — the aio
        engine copies in the background while the device computes the
        previous layer (the software-pipelined prefetch the reference
        swapper gets from its side-stream fetch hooks). Pair with
        :meth:`_complete_layer`; the aio drain is a global barrier, so
        never leave an issued layer pending across the optimizer sweep
        (it rewrites params.bin under the reads)."""
        chunks = []
        import ml_dtypes
        np_dt = ml_dtypes.bfloat16 if self._p_item == 2 else np.float32
        for off, n in self.lr_ranges.ranges(l):
            buf = np.empty(n, np_dt)
            self.params_store.read(buf.view(np.uint8).view(np_dt), off)
            chunks.append(buf)
        return l, chunks

    def _complete_layer(self, issued: Tuple[int, List[np.ndarray]]
                        ) -> Pytree:
        _l, chunks = issued
        self.params_store.drain()
        tree = jax.tree.map(jnp.asarray,
                            self.lr_ranges.unflatten_layer(chunks))
        if self._repl_sharding is not None:
            tree = jax.device_put(tree, self._repl_sharding)
        return tree

    def _fetch_layer(self, l: int) -> Pytree:
        return self._complete_layer(self._issue_layer(l))

    def _write_layer_grads(self, l: int, dlp: Pytree,
                           accumulate: bool = False,
                           want_ssq: bool = True) -> float:
        """D2H layer grads → grads.bin (fp32); ``accumulate`` adds to the
        chunk already in the store (microbatches 2..gas — the reference
        swapper's read-modify-write grad partition pass). Returns the sum
        of squares of the WRITTEN values when ``want_ssq`` (only the last
        microbatch's values are the step's true gradient)."""
        leaves = self.lr_ranges.treedef.flatten_up_to(dlp)
        ranges = self.lr_ranges.ranges(l)
        prevs = None
        if accumulate:
            # batch the whole layer's reads behind ONE drain (the
            # per-leaf read+drain pattern stalls the stream)
            prevs = [np.empty(n, np.float32) for _, n in ranges]
            for (off, _n), buf in zip(ranges, prevs):
                self.grads_store.read(buf, off)
            self.grads_store.drain()
        ssq = 0.0
        for i, ((off, n), leaf) in enumerate(zip(ranges, leaves)):
            g = np.asarray(jax.device_get(leaf), np.float32).reshape(-1)
            if prevs is not None:
                g = g + prevs[i]
            if want_ssq:
                ssq += float(g @ g)
            self.grads_store.write(g, off)
        self.grads_store.drain()
        return ssq

    def _write_resident_grads(self, grads: Dict[str, Any]) -> float:
        flat_all, _ = jax.tree_util.tree_flatten(self._abstract)
        abs_flat, _ = jax.tree_util.tree_flatten_with_path(self._abstract)
        ssq = 0.0
        # walk resident subtrees through the full layout
        tmpl = {k: self._abstract[k] for k in self._resident_keys}
        t_leaves, tdef = jax.tree_util.tree_flatten(tmpl)
        g_leaves = tdef.flatten_up_to({k: grads[k]
                                       for k in self._resident_keys})
        ids = {id(x): i for i, x in enumerate(flat_all)}
        for t, g in zip(t_leaves, g_leaves):
            off = int(self.layout.offsets[ids[id(t)]])
            arr = np.asarray(jax.device_get(g), np.float32).reshape(-1)
            ssq += float(arr @ arr)
            self.grads_store.write(arr, off)
        self.grads_store.drain()
        return ssq

    # ------------------------------------------------------------ train step
    def _micro_tokens_labels(self, batch, m: int):
        tokens = jnp.asarray(batch["input_ids"])
        if tokens.ndim == 3:            # engine stacks [gas, B, T]
            tokens = tokens[m]
        labels = batch.get("labels")
        if labels is not None:
            labels = jnp.asarray(labels)
            if labels.ndim == 3:
                labels = labels[m]
        else:
            labels = jnp.concatenate(
                [tokens[:, 1:], jnp.full_like(tokens[:, :1], -100)],
                axis=1)
        if self._batch_sharding is not None:
            tokens = jax.device_put(tokens, self._batch_sharding)
            labels = jax.device_put(labels, self._batch_sharding)
        return tokens, labels

    def train_step(self, batch, rng) -> jax.Array:
        eng = self.engine
        L = self.lr_ranges.num_layers
        gas = self.gas
        seed = jnp.float32(1.0 / gas)
        loss_sum = None
        dres = None
        ssq = 0.0
        for m in range(gas):
            tokens, labels = self._micro_tokens_labels(batch, m)
            last = m == gas - 1
            # forward: stream layers, stash inputs. Layer l+1's reads
            # are ISSUED right after layer l's compute dispatches, so the
            # file IO overlaps device time instead of serializing with it
            # (one layer of lookahead — peak host memory stays at two
            # layers of buffers); the final forward issue targets L-1,
            # prefetching the first backward layer under the head vjp.
            x = self._j_embed(self.resident, tokens)
            stash = [x]
            pending = self._issue_layer(0)
            for l in range(L):
                lp = self._complete_layer(pending)
                x = self._j_layer(lp, x, tokens)
                stash.append(x)
                pending = self._issue_layer(l + 1 if l + 1 < L else L - 1)

            loss, dx, dres_head = self._j_head_vjp(
                self.resident, stash[-1], labels, seed)
            loss_sum = loss if loss_sum is None else loss_sum + loss
            # backward: stream layers in reverse, recompute-from-stash
            # vjp; microbatches past the first ACCUMULATE into grads.bin
            # (read-modify-write — the reference swapper's grad partition
            # pass); the norm is computed from the last micro's final
            # values only. Layer l-1's reads are issued before layer l's
            # grads are written out, overlapping IO with the D2H + write
            # path; nothing stays pending after l=0 (the optimizer sweep
            # rewrites params.bin next).
            for l in reversed(range(L)):
                lp = self._complete_layer(pending)
                dx, dlp = self._j_layer_vjp(lp, stash[l], tokens, dx)
                if l > 0:
                    pending = self._issue_layer(l - 1)
                ssq_l = self._write_layer_grads(l, dlp, accumulate=m > 0,
                                                want_ssq=last)
                if last:
                    ssq += ssq_l
            dres_embed = self._j_embed_vjp(self.resident, tokens, dx)
            dres_m = jax.tree.map(lambda a, b: a + b, dres_head,
                                  dres_embed)
            dres = dres_m if dres is None else jax.tree.map(
                lambda a, b: a + b, dres, dres_m)
        loss = loss_sum / gas
        ssq += self._write_resident_grads(dres)

        gnorm = math.sqrt(ssq)
        lr = float(jax.device_get(
            eng.lr_schedule(jnp.int32(eng.global_steps))))
        clip = float(eng.config.gradient_clipping or 0.0)
        scale = clip / (gnorm + 1e-6) if clip > 0 and gnorm > clip else 1.0

        self._optimizer_sweep(lr, scale)
        self._reload_resident()
        eng._last_metrics = {"grad_norm": gnorm, "overflow": 0, "lr": lr,
                             "loss": loss}
        return loss

    def eval_step(self, batch) -> jax.Array:
        """Forward-only streamed loss (evaluation for models whose params
        don't fit HBM — same layer streaming as training, no stash/vjp)."""
        tokens, labels = self._micro_tokens_labels(batch, 0)
        L = self.lr_ranges.num_layers
        x = self._j_embed(self.resident, tokens)
        pending = self._issue_layer(0)
        for l in range(L):
            lp = self._complete_layer(pending)
            x = self._j_layer(lp, x, tokens)
            if l + 1 < L:
                pending = self._issue_layer(l + 1)
        return self._j_head_loss(self.resident, x, labels)

    # ---------------------------------------------------------------- update
    def _optimizer_sweep(self, lr: float, clip_scale: float) -> None:
        """Windowed Adam over the tiered (master, m, v, grads) files,
        narrowing the new master into params.bin (infinity.py design with
        the gradient source moved from DRAM to the store)."""
        import ml_dtypes
        opt = self.opt
        opt.adam.step_count += 1
        total, W = self.layout.total, opt.window
        np_dt = ml_dtypes.bfloat16 if self._p_item == 2 else np.float32
        gbuf = np.empty(W, np.float32)
        pbuf = np.empty(W, np_dt)
        for off in range(0, total, W):
            n = min(W, total - off)
            b = {k: opt._bufs[k][0] for k in opt.files}
            for name in opt.files:
                opt.aio.pread(opt.files[name], b[name][:n], off * 4)
            self.grads_store.read(gbuf[:n], off)
            opt.aio.drain()
            if clip_scale != 1.0:
                gbuf[:n] *= clip_scale
            opt.adam.step_buffers(b["master"][:n], gbuf[:n],
                                  b["exp_avg"][:n], b["exp_avg_sq"][:n],
                                  opt.adam.step_count, lr)
            for name in opt.files:
                opt.aio.pwrite(opt.files[name], b[name][:n], off * 4)
            pbuf[:n] = b["master"][:n].astype(np_dt)
            self.params_store.write(pbuf[:n].copy(), off)
            opt.aio.drain()

    def _reload_resident(self) -> None:
        """Re-upload the resident (embed/norm/head) subtree from the
        freshly-updated store."""
        import ml_dtypes
        np_dt = ml_dtypes.bfloat16 if self._p_item == 2 else np.float32
        flat_all, _ = jax.tree_util.tree_flatten(self._abstract)
        ids = {id(x): i for i, x in enumerate(flat_all)}
        out = {}
        for key in self._resident_keys:
            t_leaves, tdef = jax.tree_util.tree_flatten(self._abstract[key])
            chunks = []
            for t in t_leaves:
                i = ids[id(t)]
                off = int(self.layout.offsets[i])
                n = int(self.layout.sizes[i])
                buf = np.empty(n, np_dt)
                self.params_store.read(buf, off)
                self.params_store.drain()
                chunks.append(jnp.asarray(
                    buf.reshape(self.layout.shapes[i])).astype(t.dtype))
            out[key] = jax.tree_util.tree_unflatten(tdef, chunks)
        if self._repl_sharding is not None:
            out = jax.device_put(out, self._repl_sharding)
        self.resident = out

    # ------------------------------------------------------------ checkpoint
    def full_params_np(self) -> Pytree:
        """Materialize the full params pytree from the store (host RAM —
        checkpoint-time only)."""
        import ml_dtypes
        np_dt = ml_dtypes.bfloat16 if self._p_item == 2 else np.float32
        flat = np.empty(self.layout.total, np_dt)
        for off in range(0, self.layout.total, self.opt.window):
            n = min(self.opt.window, self.layout.total - off)
            self.params_store.read(flat[off:off + n], off)
        self.params_store.drain()
        return self.layout.unflatten(flat.astype(np.float32))
