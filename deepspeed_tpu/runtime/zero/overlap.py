"""Chunked, overlap-scheduled ZeRO-3 collectives.

The monolithic stage-3 data path relies on GSPMD alone: the fused step's
layer ``lax.scan`` carries the whole stacked parameter tree, so XLA emits
one whole-model param all-gather ahead of the forward and one whole-model
grad reduce-scatter behind the backward — both serialize against compute
(the comm term PR 5's roofline isolates). This module decomposes those
collectives into layer-bucket *chunks* and orders the HLO so XLA's
latency-hiding scheduler can pipeline them against adjacent-chunk compute
(T3, arXiv:2401.16677; "The Big Send-off", arXiv:2504.18658):

* **Bucketing** — layers are grouped into byte-bounded chunks
  (``zero_optimization.overlap_bucket_bytes``; 0 = one layer per chunk).
* **Forward** — chunk *k+d*'s param all-gather (a sharding-constraint
  reshard to the spec with the DP axes removed — GSPMD emits the actual
  all-gather) is issued while chunk *k* computes. An
  ``optimization_barrier`` ties chunk *k+d*'s *sharded* slice to chunk
  *k*'s input activation, so XLA can neither hoist every gather to step
  start (which would materialize the whole gathered model and blow the
  HBM budget) nor sink them behind the compute they must hide under.
  ``d`` is ``zero_optimization.overlap_prefetch``.
* **Backward** — a ``custom_vjp`` around the per-chunk gather constrains
  each chunk's cotangent to the sharded grad spec *inside* the backward,
  so chunk *k*'s grad reduce-scatter is emitted while chunk *k-1*'s
  backward compute runs, instead of one fused whole-model scatter at the
  end.
* **Lifetime** — the gather sits inside a ``jax.checkpoint`` whose policy
  saves everything *except* the gathered chunk
  (``save_anything_except_these_names``), so gathered weights are never
  held as residuals from forward to backward: the backward re-gathers,
  and at most ``prefetch+1`` gathered chunks are live at any instant.
  :meth:`OverlapPlan.transient_bytes` reports that footprint to the
  static HBM budget (telemetry/explain.py) so the budget check stays
  honest. ``zero_optimization.overlap_regather=false`` flips the
  trade: gathered chunks are kept as residuals and reused by the
  backward (reference ``stage3_max_reuse_distance`` semantics) —
  gather traffic halves, but the whole gathered stack is live at the
  forward→backward turnaround, and the budget accounts for it.

Composition fences are checked where the information lives: the model
factory requires stage 3 + a decoder model; :func:`build_overlap_plan`
(mesh in hand) additionally rejects expert parallelism (the 'expert'
mesh axis doubles as an FSDP axis on dense weights but is the EP shard
axis on expert weights — stripping it indiscriminately would replicate
experts).
"""

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import ZERO_AXES
from deepspeed_tpu.utils.logging import logger, warning_once

Pytree = Any

#: residual name for gathered chunks — the checkpoint policy excludes it
#: so backward re-gathers instead of holding gathered weights across the
#: forward→backward gap
GATHERED_NAME = "zero3_gathered_chunk"

@jax.custom_vjp
def _opt_barrier(tup):
    """Differentiable ``lax.optimization_barrier`` (jax 0.4.x defines no
    VJP for the primitive). The backward barriers the cotangents too,
    which is exactly what the overlap schedule wants: tying chunk k+1's
    param cotangent to chunk k's activation cotangent keeps the backward
    chunk order pinned the same way the forward is."""
    return lax.optimization_barrier(tup)


def _opt_barrier_fwd(tup):
    return lax.optimization_barrier(tup), None


def _opt_barrier_bwd(_, ct):
    return (lax.optimization_barrier(ct),)


_opt_barrier.defvjp(_opt_barrier_fwd, _opt_barrier_bwd)


#: XLA scheduler flags that let the compiler interleave the per-chunk
#: collectives with compute (TPU backends; harmless no-ops elsewhere).
#: Probed before use — never assumed (conftest ``_flags_ok`` pattern).
LATENCY_HIDING_FLAGS: Tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
)


# ---------------------------------------------------------------------------
# spec surgery
# ---------------------------------------------------------------------------

def dense_spec(spec: P, dp_axes: Sequence[str] = ZERO_AXES) -> P:
    """The gathered-for-compute layout: ``spec`` with every DP-family
    axis removed (what the leaf would look like under stage < 3 with the
    same TP layout). ``P(None, ('data','data_inner','expert'), 'model')``
    → ``P(None, None, 'model')``."""
    entries = []
    for e in spec:
        if e is None:
            entries.append(None)
            continue
        cur = tuple(e) if isinstance(e, (tuple, list)) else (e,)
        kept = tuple(a for a in cur if a not in dp_axes)
        entries.append(None if not kept else
                       (kept if len(kept) > 1 else kept[0]))
    return P(*entries)


def _spec_axes(spec: P) -> Tuple[str, ...]:
    axes: List[str] = []
    for e in spec:
        if e is None:
            continue
        axes.extend(e if isinstance(e, (tuple, list)) else (e,))
    return tuple(axes)


def _leaf_bytes_per_layer(leaf) -> int:
    """Global bytes of ONE layer of a stacked ``[L, ...]`` leaf."""
    shape = tuple(leaf.shape)[1:]
    return int(np.prod(shape, dtype=np.int64) *
               np.dtype(leaf.dtype).itemsize) if shape else \
        int(np.dtype(leaf.dtype).itemsize)


def chunk_bounds(num_layers: int, per_layer_bytes: int,
                 bucket_bytes: int) -> List[Tuple[int, int]]:
    """Greedy layer bucketing: consecutive layers accumulate into one
    chunk until adding the next would exceed ``bucket_bytes`` (always at
    least one layer per chunk). ``bucket_bytes=0`` → one chunk per layer
    (the default: matches the reference's per-module fetch granularity
    and gives the scheduler the most interleaving freedom)."""
    if num_layers <= 0:
        return []
    if bucket_bytes <= 0 or per_layer_bytes <= 0:
        return [(i, i + 1) for i in range(num_layers)]
    layers_per = max(1, bucket_bytes // per_layer_bytes)
    return [(lo, min(lo + layers_per, num_layers))
            for lo in range(0, num_layers, layers_per)]


# ---------------------------------------------------------------------------
# the plan
# ---------------------------------------------------------------------------

class OverlapPlan:
    """Chunk schedule + shardings for one (model, mesh, knobs) triple.

    ``layer_specs``: PartitionSpec pytree of the stacked ``layers``
    subtree (leading layer dim unsharded). ``abstract_layers``: matching
    ShapeDtypeStructs ``[L, ...]`` in the engine's compute dtype."""

    def __init__(self, mesh: Mesh, layer_specs: Pytree,
                 abstract_layers: Pytree, bucket_bytes: int = 0,
                 prefetch: int = 1, regather: bool = True,
                 dp_axes: Sequence[str] = ZERO_AXES):
        self.mesh = mesh
        self.dp_axes = tuple(dp_axes)
        self.prefetch = max(0, int(prefetch))
        self.regather = bool(regather)
        self.layer_specs = layer_specs
        is_p = lambda x: isinstance(x, P)          # noqa: E731
        self.gather_specs = jax.tree.map(
            lambda s: dense_spec(s, self.dp_axes), layer_specs,
            is_leaf=is_p)
        self._gather_sh = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.gather_specs,
            is_leaf=is_p)
        self._shard_sh = jax.tree.map(
            lambda s: NamedSharding(mesh, s), layer_specs, is_leaf=is_p)
        leaves = jax.tree.leaves(abstract_layers)
        self.num_layers = int(leaves[0].shape[0]) if leaves else 0
        self.per_layer_bytes = sum(_leaf_bytes_per_layer(x) for x in leaves)
        # per-device gathered bytes of one layer: each leaf divided by the
        # mesh extent of the axes its gathered spec STILL uses (TP stays
        # sharded; only the DP shard is materialized by the gather)
        gspecs = jax.tree.leaves(self.gather_specs, is_leaf=is_p)
        per_dev = 0.0
        for leaf, gs in zip(leaves, gspecs):
            denom = 1
            for a in _spec_axes(gs):
                denom *= mesh.shape.get(a, 1)
            per_dev += _leaf_bytes_per_layer(leaf) / max(1, denom)
        self.per_layer_gathered_device_bytes = per_dev
        self.bucket_bytes = int(bucket_bytes)
        self.bounds = chunk_bounds(self.num_layers, self.per_layer_bytes,
                                   self.bucket_bytes)
        self._stream = self._make_stream()

    # ------------------------------------------------------------ accounting

    @property
    def n_chunks(self) -> int:
        return len(self.bounds)

    def chunk_layers(self, k: int) -> int:
        lo, hi = self.bounds[k]
        return hi - lo

    def chunk_global_bytes(self, k: int) -> int:
        return self.chunk_layers(k) * self.per_layer_bytes

    def max_chunk_bytes(self) -> int:
        return max((self.chunk_global_bytes(k)
                    for k in range(self.n_chunks)), default=0)

    def transient_bytes(self) -> float:
        """Per-device HBM transiently held by gathered chunks. With
        ``regather`` (default): the worst sliding window of
        ``prefetch+1`` consecutive chunks (the chunk in use plus the
        ones in flight). Without: every gathered chunk survives as a
        backward residual, so the whole gathered stack is live at the
        forward→backward turnaround. Either way this is what the static
        HBM budget must add on top of the sharded resident params."""
        if not self.bounds:
            return 0.0
        if not self.regather:
            return self.num_layers * self.per_layer_gathered_device_bytes
        w = min(self.prefetch + 1, self.n_chunks)
        worst = 0
        for k in range(self.n_chunks - w + 1):
            worst = max(worst, sum(self.chunk_layers(j)
                                   for j in range(k, k + w)))
        return worst * self.per_layer_gathered_device_bytes

    def describe(self) -> str:
        return (f"zero-3 overlap: {self.n_chunks} chunk(s) over "
                f"{self.num_layers} layers (bucket "
                f"{self.bucket_bytes or 'per-layer'}, prefetch "
                f"{self.prefetch}, "
                f"{'re-gather' if self.regather else 'reuse'} backward), "
                f"~{self.max_chunk_bytes() / 2**20:.1f} "
                f"MiB/chunk global, transient "
                f"{self.transient_bytes() / 2**20:.1f} MiB/device gathered")

    def publish_static_gauges(self) -> None:
        """Static ``overlap/*`` gauges (the measured fraction gauge is
        published per step by the engine)."""
        from deepspeed_tpu.telemetry import registry
        registry.gauge("overlap/chunks",
                       help="ZeRO-3 overlap chunk count").set(self.n_chunks)
        registry.gauge("overlap/prefetch_depth",
                       help="chunks gathered ahead of compute").set(
            self.prefetch)
        registry.gauge("overlap/bucket_bytes",
                       help="largest chunk, global param bytes").set(
            self.max_chunk_bytes())
        registry.gauge(
            "overlap/transient_hbm_bytes",
            help="per-device HBM held by in-flight gathered chunks").set(
            self.transient_bytes())

    # ----------------------------------------------------------- the stream

    def _make_stream(self) -> Callable[[Pytree], Pytree]:
        """Per-chunk gather with an explicit reduce-scatter on the way
        back. Forward: reshard the sharded chunk slice to the DP-free
        spec (GSPMD emits the all-gather). Backward: constrain the
        cotangent to the sharded spec *at this point of the backward* —
        GSPMD fuses the cross-replica sum with the reshard into a
        reduce-scatter, interleaved with the neighbouring chunk's
        backward compute instead of coalesced at the step's end."""
        gather_sh, shard_sh = self._gather_sh, self._shard_sh

        def _constrain(tree: Pytree, sh: Pytree) -> Pytree:
            # shardings were built over full stacked leaves; chunk slices
            # only differ in the (unsharded) leading dim, so they apply
            # to every chunk length unchanged
            return jax.tree.map(
                lax.with_sharding_constraint, tree, sh)

        @jax.custom_vjp
        def stream(chunk):
            return _constrain(chunk, gather_sh)

        def stream_fwd(chunk):
            return _constrain(chunk, gather_sh), None

        def stream_bwd(_, ct):
            return (_constrain(ct, shard_sh),)

        stream.defvjp(stream_fwd, stream_bwd)
        return stream

    # -------------------------------------------------------- the layer loop

    def layer_loop(self, body: Callable, x: jax.Array, xs: Pytree
                   ) -> Tuple[jax.Array, jax.Array]:
        """Drop-in for ``lax.scan(body, x, xs)`` over the stacked layers
        (``xs`` is the layers pytree, or ``(layers, per_layer_extras)``
        when the model scans auxiliary per-layer data alongside — e.g.
        GPT-Neo's attention windows)."""
        layers, extra = (xs if isinstance(xs, tuple) else (xs, None))
        n, d = self.n_chunks, self.prefetch
        if n <= 0:
            return lax.scan(body, x, xs)

        def slice_tree(tree, k):
            lo, hi = self.bounds[k]
            return jax.tree.map(lambda a: a[lo:hi], tree)

        self._record_trace_comms()

        policy = getattr(jax.checkpoint_policies,
                         "save_anything_except_these_names", None)

        def chunk_fn(x, chunk, extra_chunk):
            g = self._stream(chunk)
            g = jax.tree.map(
                lambda a: checkpoint_name(a, GATHERED_NAME), g)
            cxs = (g, extra_chunk) if extra_chunk is not None else g
            return lax.scan(body, x, cxs)

        if self.regather and policy is not None:
            # everything else stays saveable (per-layer remat, if any, is
            # already applied inside ``body``); only the gathered chunk is
            # recomputed — i.e. re-gathered — during backward
            chunk_fn = jax.checkpoint(
                chunk_fn, policy=policy(GATHERED_NAME),
                static_argnums=())
        elif self.regather:                          # pragma: no cover
            warning_once(
                "jax.checkpoint_policies.save_anything_except_these_names "
                "unavailable — gathered ZeRO-3 chunks will be held as "
                "backward residuals (higher transient HBM than reported); "
                "set overlap_regather=False to make the budget match")
        # not self.regather: gathered chunks are KEPT as residuals — the
        # backward reuses them (reference stage3_max_reuse_distance>0
        # semantics): gather traffic halves, transient_bytes() reports
        # the full gathered stack instead of the prefetch window

        window: List[Pytree] = []
        pending: List[int] = []
        for k in range(min(d + 1, n)):
            window.append(slice_tree(layers, k))
            pending.append(k)
        aux_parts: List[Pytree] = []
        for k in range(n):
            chunk = window.pop(0)
            pending.pop(0)
            ek = slice_tree(extra, k) if extra is not None else None
            x, aux = chunk_fn(x, chunk, ek)
            aux_parts.append(jax.tree.map(jnp.atleast_1d, aux))
            nxt = k + d + 1
            if nxt < n:
                # tie the NEXT prefetch slice to the activation just
                # produced: its gather can't issue before chunk k is
                # done, bounding live gathered chunks to prefetch+1
                nchunk, x = _opt_barrier((slice_tree(layers, nxt), x))
                window.append(nchunk)
                pending.append(nxt)
        # aux may be a pytree (health taps' per-layer stats dict), so
        # concatenate leaf-wise along the stacked layer axis
        return x, jax.tree.map(
            lambda *parts: jnp.concatenate(parts), *aux_parts)

    def _record_trace_comms(self) -> None:
        """Trace-time comm accounting for the chunked collectives: the
        per-chunk all-gathers (forward) and reduce-scatters (backward)
        this loop will emit, coalesced by (op, size) so the tracer ring
        sees a handful of markers per traced step instead of 2×chunks
        (comms_logger.append_chunked keeps byte totals exact)."""
        from deepspeed_tpu.comm.comms_logger import comms_logger
        if not comms_logger.enabled:
            return
        sizes: Dict[int, int] = {}
        for k in range(self.n_chunks):
            b = self.chunk_global_bytes(k)
            sizes[b] = sizes.get(b, 0) + 1
        axis = tuple(a for a in self.dp_axes
                     if self.mesh.shape.get(a, 1) > 1) or self.dp_axes
        for size, count in sorted(sizes.items()):
            comms_logger.append_chunked("all_gather", size, axis,
                                        chunks=count)
            comms_logger.append_chunked("reduce_scatter", size, axis,
                                        chunks=count)


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------

def build_overlap_plan(mesh: Mesh, layer_specs: Pytree,
                       abstract_layers: Pytree, zero_config,
                       num_experts: int = 0) -> Optional["OverlapPlan"]:
    """Validated construction from the config knobs; returns ``None``
    (with a loud warning) for meshes the chunked path cannot serve yet.
    Raises only on contradictory explicit configuration."""
    ep = mesh.shape.get("expert", 1)
    if num_experts and ep > 1:
        warning_once(
            "zero_optimization.overlap_comm: expert parallelism "
            f"(expert axis={ep}) is not supported by the chunked overlap "
            "path — the 'expert' axis shards experts, not FSDP, on MoE "
            "weights; falling back to the monolithic ZeRO-3 collectives")
        return None
    if mesh.shape.get("pipe", 1) > 1:
        warning_once(
            "zero_optimization.overlap_comm: pipeline meshes run the "
            "pipe schedule, not the chunked overlap loop; ignoring")
        return None
    prefetch = int(getattr(zero_config, "overlap_prefetch", 1))
    bucket = int(getattr(zero_config, "overlap_bucket_bytes", 0) or 0)
    regather = bool(getattr(zero_config, "overlap_regather", True))
    plan = OverlapPlan(mesh, layer_specs, abstract_layers,
                       bucket_bytes=bucket, prefetch=prefetch,
                       regather=regather)
    if plan.n_chunks <= 1:
        logger.info(
            "zero-3 overlap: bucket covers the whole model (1 chunk) — "
            "schedule degenerates to the monolithic gather; shrink "
            "overlap_bucket_bytes to pipeline collectives")
    return plan


# ---------------------------------------------------------------------------
# overlap fraction + scheduler flags
# ---------------------------------------------------------------------------

def overlap_fraction(compute_s: float, comm_s: float,
                     measured_s: float) -> Optional[float]:
    """Achieved compute/comm overlap from the roofline terms and a
    measured step: a fully serialized step takes ``compute+comm``; a
    fully hidden one takes ``max(compute, comm)``. The fraction is how
    much of the hideable ``min(compute, comm)`` was actually hidden,
    clamped to [0, 1]. ``None`` when any term is missing (CPU without
    modeled peaks) — callers must treat that as "no signal", not 0."""
    if compute_s <= 0 or comm_s <= 0 or measured_s <= 0:
        return None
    hideable = min(compute_s, comm_s)
    return max(0.0, min(1.0, (compute_s + comm_s - measured_s) / hideable))


def _flag_keys(flags: str) -> set:
    """Flag NAMES present in an ``XLA_FLAGS`` string — exact tokens, not
    substrings (``..._async_collective_fusion`` is a prefix of
    ``..._fusion_fuse_all_gather``; substring matching would report the
    former present whenever the latter is)."""
    return {tok.split("=")[0] for tok in flags.split()}


def scheduler_flag_status(env: Optional[Dict[str, str]] = None
                          ) -> Dict[str, bool]:
    """Which latency-hiding flags are present in ``XLA_FLAGS``."""
    flags = (env if env is not None else os.environ).get("XLA_FLAGS", "")
    keys = _flag_keys(flags)
    return {f: f.split("=")[0] in keys for f in LATENCY_HIDING_FLAGS}


def ensure_scheduler_flags(probe: Optional[Callable[[str], bool]] = None,
                           env: Optional[Dict[str, str]] = None) -> str:
    """Append the latency-hiding scheduler flags to ``XLA_FLAGS`` —
    BEFORE backend init only (XLA reads the env once). Each candidate is
    validated through ``probe`` (the conftest ``_flags_ok`` subprocess
    pattern: a flag this jaxlib doesn't know would CHECK-abort the
    process) and silently dropped when rejected. Returns the resulting
    flag string; ``env`` defaults to ``os.environ`` and is mutated."""
    env = os.environ if env is None else env
    flags = env.get("XLA_FLAGS", "")
    for f in LATENCY_HIDING_FLAGS:
        if f.split("=")[0] in _flag_keys(flags):
            continue
        cand = (flags + " " + f).strip()
        if probe is None or probe(cand):
            flags = cand
    env["XLA_FLAGS"] = flags
    return flags


def verify_scheduler_flags() -> None:
    """Engine-side report (no mutation — the backend is already up by
    engine init): on TPU, warn when the latency-hiding scheduler flags
    are absent from the environment; elsewhere this is informational
    (the CPU thunk runtime has no latency-hiding scheduler — the
    dp-mesh CPU tests validate ordering/numerics, not wall clock)."""
    status = scheduler_flag_status()
    missing = [f for f, ok in status.items() if not ok]
    try:
        backend = jax.default_backend()
    except Exception:                                 # pragma: no cover
        backend = "unknown"
    if backend == "tpu" and missing:
        logger.warning(
            "zero-3 overlap: latency-hiding scheduler flags missing from "
            f"XLA_FLAGS ({' '.join(missing)}) — the per-chunk collectives "
            "will be emitted in overlap order but the scheduler may not "
            "interleave them; export them before process start "
            "(overlap.ensure_scheduler_flags)")
    elif missing:
        logger.debug("zero-3 overlap: scheduler flags not set "
                     f"(backend={backend}; only meaningful on TPU)")
