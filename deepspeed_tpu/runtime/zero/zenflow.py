"""ZenFlow: stall-free offload with selective on-device updates.

Reference: ``runtime/zenflow/zenflow_stage_1_and_2.py`` (ZenFlowZeroOptimizer,
:47) + ``ops/adam/zenflow_torch_adam.py:43`` (ZenFlowSelectiveAdamW) +
``runtime/zenflow/zenflow_config.py``. The reference splits gradients by
importance: the top-k "important" gradient columns are updated SYNCHRONOUSLY
on the accelerator every step with a selective AdamW; the unimportant tail
accumulates on the host and a full CPU Adam applies it every
``update_interval`` steps, overlapped with compute (bounded staleness — the
paper's claim is accuracy parity with >60%% of the offload stall removed).

TPU redesign (no per-column torch hooks; everything static-shape SPMD):

* The flat parameter space (runtime/zero/offload.FlatLayout) is cut into
  fixed ``block_size``-element blocks. Importance = per-block gradient
  sum-of-squares, computed inside the jitted step (one reduce, free).
* The top ``K = ceil(topk_ratio * num_blocks)`` blocks carry device-resident
  selective Adam state (m, v, fp32 master — the ZenFlowSelectiveAdamW
  analogue) and are updated INSIDE the train step, every step: important
  gradients are never stale.
* Every step the full flat gradient leaves the device (one D2H, same as
  plain offload) and the host ACCUMULATES it. Every ``update_interval``
  steps the host Adam sweeps the accumulated gradient (mean) — importance
  masking is by overwrite: the device merge keeps its own (fresher)
  values for selected blocks, so the host's writes to them never land.
* Every ``select_interval`` steps the selection refreshes from the latest
  per-block importance: device state for outgoing blocks is written back
  into the host master/moments, and incoming blocks seed their m/v/master
  FROM the host state (the reference re-zeros selective state on
  reselection, zenflow_torch_adam.py:83 clear_selected_mv; seeding from
  host moments is strictly more information).
* ``overlap_step`` (reference zenflow_config.py:31): the host tail sweep
  runs on the worker thread, overlapped with the next ``update_interval``
  device steps; the result merges at the next boundary (staleness bounded
  by one interval, exactly the reference's pipeline).

fp16 is rejected (dynamic loss scaling needs a synchronous overflow signal)
— same restriction as the overlap path and the reference.
"""

import math
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist

Pytree = Any


class ZenFlowDeviceState(NamedTuple):
    """Device-resident selective-optimizer state (ZenFlowSelectiveAdamW
    analogue): K important blocks of the flat parameter space."""
    idx: jax.Array      # [K] int32 — selected block indices (sorted)
    m: jax.Array        # [K, B] fp32 first moment
    v: jax.Array        # [K, B] fp32 second moment
    master: jax.Array   # [K, B] fp32 master copy of the selected params
    t: jax.Array        # [] int32 — selective step count (bias correction)
    imp: jax.Array      # [num_blocks] fp32 EMA of per-block grad sum-sq


class ZenFlowCoordinator:
    """Owns the jitted ZenFlow step + host accumulation/tail pipeline.

    Built by the engine when ``zero_optimization.zenflow`` is enabled with
    ``offload_optimizer.device='cpu'``; the engine delegates its offload
    train path here.
    """

    def __init__(self, engine):
        self.engine = engine
        zf = engine.config.zero_optimization.zenflow
        self.layout = engine.host_optimizer.layout
        total = self.layout.total
        self.block = int(zf.block_size)
        self.num_blocks = -(-total // self.block)
        self.padded = self.num_blocks * self.block
        self.K = max(1, int(math.ceil(self.num_blocks * float(zf.topk_ratio))))
        # dp>1 + shard_selection: selection runs PER-SHARD over dp
        # contiguous ranges of the block space — each data shard picks
        # its own top-k, the sharded analogue of the reference's
        # per-rank selection over its Z1/2 gradient partition
        # (runtime/zenflow/engine_stage3.py). OPT-IN: on this
        # single-controller runtime every shard's blocks live in one
        # host, so global top-K costs the same and selects strictly
        # better; per-shard exists for parity with genuinely
        # partitioned state (and multi-host futures). The total K
        # budget is PRESERVED (floor + remainder distribution), so the
        # knob never inflates device state.
        self.dp_shards = max(1, int(getattr(engine, "dp_world_size", 1)
                                    or 1))
        self._shard_ranges = None
        if self.dp_shards > 1 and bool(getattr(zf, "shard_selection",
                                               False)):
            per = -(-self.num_blocks // self.dp_shards)
            n_shards = -(-self.num_blocks // per)
            base, rem = divmod(self.K, n_shards)
            self._shard_ranges = []
            k_total = 0
            for s in range(n_shards):
                lo = s * per
                hi = min(self.num_blocks, lo + per)
                k = min(base + (1 if s < rem else 0), hi - lo)
                if k > 0:
                    self._shard_ranges.append((lo, hi, k))
                    k_total += k
            self.K = max(1, k_total)
        self.update_interval = 4 if zf.update_interval == "auto" \
            else int(zf.update_interval)
        self.select_interval = 8 * self.update_interval \
            if zf.select_interval == "auto" else int(zf.select_interval)
        self.warmup = int(zf.full_warm_up_rounds)
        self.overlap = bool(zf.overlap_step)
        self.tail_lr_scale = None if zf.tail_lr_scale == "auto" \
            else float(zf.tail_lr_scale)
        host = engine.host_optimizer
        self._b1, self._b2 = host.adam.beta1, host.adam.beta2
        self._eps = host.adam.eps
        self._wd = host.adam.weight_decay
        self._adamw = host.adam.adamw_mode
        # host-side gradient accumulator for the unimportant tail
        self._accum = np.zeros(total, np.float32)
        self._accum_n = 0
        self._tail_future = None
        self._steps_since_select = 0
        self._steps_since_update = 0
        self._last_block_sq: Optional[np.ndarray] = None
        self.state: Optional[ZenFlowDeviceState] = None
        self._build()
        log_dist(f"ZenFlow: {self.K}/{self.num_blocks} blocks "
                 f"({self.K * self.block / 1e6:.1f}M/{total / 1e6:.1f}M "
                 f"elements) on-device selective; tail every "
                 f"{self.update_interval} steps, reselect every "
                 f"{self.select_interval}, overlap={self.overlap}")

    # ------------------------------------------------------------------ jit
    def _build(self):
        eng = self.engine
        layout, B, K = self.layout, self.block, self.K
        total, padded = layout.total, self.padded
        nb = self.num_blocks
        b1, b2, eps, wd = self._b1, self._b2, self._eps, self._wd
        adamw = self._adamw
        gas = int(eng.config.gradient_accumulation_steps)
        transfer_dtype = eng.compute_dtype
        clip = float(eng.config.gradient_clipping or 0.0)

        def to_blocks(flat):
            return jnp.pad(flat, (0, padded - total)).reshape(nb, B)

        def from_blocks(blocks):
            return blocks.reshape(padded)[:total]

        def zf_step(params, state, batch, rng, lr):
            """One ZenFlow train step: grads, importance, selective Adam on
            the K important blocks, flat grad out for host accumulation."""
            acc, losses = eng._accumulate_grads(params, batch,
                                               jnp.float32(1.0), rng)
            acc = jax.tree.map(lambda g: g * (1.0 / gas), acc)
            flat_g32 = layout.flatten_device(acc, jnp.float32)
            gb = to_blocks(flat_g32)
            block_sq = jnp.sum(gb * gb, axis=1)            # [nb]
            # EMA importance (reference avg_critic_sum,
            # zenflow_stage_1_and_2.py:403): single-step magnitudes whip
            # around with the batch; the EMA is what reselection reads
            imp = 0.9 * state.imp + 0.1 * block_sq
            gnorm = jnp.sqrt(jnp.sum(block_sq))
            scale = jnp.where((clip > 0) & (gnorm > clip),
                              clip / (gnorm + 1e-6), 1.0)

            # ----- selective AdamW on the K important blocks (every step)
            g_sel = gb[state.idx] * scale                  # [K, B] gather
            t_sel = state.t + 1
            if wd and not adamw:
                g_sel = g_sel + wd * state.master
            m = b1 * state.m + (1 - b1) * g_sel
            v = b2 * state.v + (1 - b2) * g_sel * g_sel
            mh = m / (1 - b1 ** t_sel.astype(jnp.float32))
            vh = v / (1 - b2 ** t_sel.astype(jnp.float32))
            upd = mh / (jnp.sqrt(vh) + eps)
            if wd and adamw:
                upd = upd + wd * state.master
            master = state.master - lr * upd

            # write the updated important blocks into the live params
            pb = to_blocks(layout.flatten_device(params, transfer_dtype))
            pb = pb.at[state.idx].set(master.astype(transfer_dtype))
            new_params = layout.unflatten_device(from_blocks(pb))
            new_state = ZenFlowDeviceState(state.idx, m, v, master, t_sel,
                                           imp)
            return (new_params, new_state,
                    flat_g32.astype(transfer_dtype), imp,
                    jnp.mean(losses), gnorm)

        self._zf_step = jax.jit(zf_step, donate_argnums=(0, 1))

        def zf_merge(params, idx, uploaded_flat):
            """Fold a finished host tail update in: host values everywhere
            EXCEPT the selected blocks, which keep the (fresher) device
            values — the importance mask by overwrite."""
            pb = to_blocks(layout.flatten_device(params, transfer_dtype))
            ub = to_blocks(uploaded_flat.astype(transfer_dtype))
            ub = ub.at[idx].set(pb[idx])
            return layout.unflatten_device(from_blocks(ub))

        self._zf_merge = jax.jit(zf_merge, donate_argnums=(0,))

        def zf_adopt(params, idx, m, v, imp, t0):
            """Seed a fresh selection: master blocks from the live params
            (they are authoritative after a merge), moments from the host.
            ``t0`` continues the global step count — the imported moments
            are WARM, so restarting bias correction at t=0 would divide by
            (1-b1) and amplify the first post-reselect updates ~10x (the
            reference zeros both moments and step together, which is
            self-consistent; warm import must keep t warm too)."""
            pb = to_blocks(layout.flatten_device(params, jnp.float32))
            return ZenFlowDeviceState(idx, m, v, pb[idx], t0, imp)

        self._zf_adopt = jax.jit(zf_adopt)

    # ----------------------------------------------------------- host side
    def _host_accumulate(self, flat_g: np.ndarray) -> None:
        host = self.engine.host_optimizer
        g32 = host._widen_grads(flat_g)
        self._accum += g32
        self._accum_n += 1

    def _host_tail_step(self, lr: float, wait_on=None) -> np.ndarray:
        """Full host Adam sweep over the MEAN accumulated gradient; returns
        the narrowed compute-dtype master for upload. Selected blocks are
        swept too, but their values never land (merge overwrites) and their
        moments are rewritten at the next reselection.

        tail_lr_scale 'auto' multiplies lr by the accumulated step count:
        ONE Adam update per interval (Adam's √v normalization makes sum vs
        mean gradients near-equivalent) would otherwise move tail weights
        ~1/interval as fast as synchronous training — the reference
        (zenflow_stage_1_and_2.py:605 one cpu step per interval) accepts
        that; 'auto' keeps total tail movement matched to the sync path.

        ``wait_on`` — the device array backed by the PREVIOUS upload of the
        narrowed master: this sweep mutates ``host.master`` (and the shared
        ``_out16`` narrow buffer), so the in-flight H2D DMA must finish
        first (same buffer-reuse hazard as offload.step_flat)."""
        host = self.engine.host_optimizer
        if wait_on is not None:
            jax.block_until_ready(wait_on)
        n = max(1, self._accum_n)
        g = self._accum
        g *= 1.0 / n
        clip = float(self.engine.config.gradient_clipping or 0.0)
        norm = host.adam.grad_norm(g)
        if clip > 0 and np.isfinite(norm) and norm > clip:
            g *= clip / (norm + 1e-6)
        if np.isfinite(norm):
            scale = n if self.tail_lr_scale is None else self.tail_lr_scale
            host.adam.step(host.master, g, lr=lr * scale)
        self._accum[:] = 0.0
        self._accum_n = 0
        return host._narrow_master()

    def _gather_blocks(self, arr: np.ndarray, idx: np.ndarray
                       ) -> np.ndarray:
        """[K, B] copy of the indexed blocks of a flat host array — ONE
        vectorized fancy-index over a reshape view (a Python per-block loop
        here is a multi-second stall at ~1B params); at most one partial
        tail block is handled separately."""
        B, total = self.block, self.layout.total
        nb_full = total // B
        out = np.zeros((len(idx), B), np.float32)
        full = idx < nb_full
        if full.any():
            out[full] = arr[:nb_full * B].reshape(nb_full, B)[idx[full]]
        for j in np.nonzero(~full)[0]:
            off = int(idx[j]) * B
            out[j, :total - off] = arr[off:total]
        return out

    def _scatter_blocks(self, arr: np.ndarray, idx: np.ndarray,
                        vals: np.ndarray) -> None:
        """Inverse of _gather_blocks: write [K, B] block values into the
        flat host array through the reshape view (writes through)."""
        B, total = self.block, self.layout.total
        nb_full = total // B
        full = idx < nb_full
        if full.any():
            arr[:nb_full * B].reshape(nb_full, B)[idx[full]] = vals[full]
        for j in np.nonzero(~full)[0]:
            off = int(idx[j]) * B
            arr[off:total] = vals[j, :total - off]

    def _sync_selection_to_host(self) -> None:
        """Write the device selective state back into the host arrays
        (outgoing blocks must not lose their fresher master/moments)."""
        if self.state is None:
            return
        host = self.engine.host_optimizer
        idx, m, v, master = (np.asarray(jax.device_get(x)) for x in
                             (self.state.idx, self.state.m,
                              self.state.v, self.state.master))
        self._scatter_blocks(host.master, idx, master)
        self._scatter_blocks(host.adam.exp_avg, idx, m)
        self._scatter_blocks(host.adam.exp_avg_sq, idx, v)

    def _topk_indices(self, block_sq: np.ndarray) -> np.ndarray:
        """Global top-K (dp=1) or per-shard top-k over dp contiguous
        block ranges (dp>1 — see __init__)."""
        if self._shard_ranges is None:
            k = min(self.K, self.num_blocks)
            return np.sort(
                np.argpartition(-block_sq, k - 1)[:k]).astype(np.int32)
        parts = []
        for lo, hi, k in self._shard_ranges:
            seg = block_sq[lo:hi]
            parts.append(lo + np.argpartition(-seg, k - 1)[:k])
        return np.sort(np.concatenate(parts)).astype(np.int32)

    def _select(self, block_sq: np.ndarray) -> None:
        """(Re)pick the top-K important blocks and seed device state."""
        self._sync_selection_to_host()
        idx = self._topk_indices(block_sq)
        host = self.engine.host_optimizer
        m = self._gather_blocks(host.adam.exp_avg, idx)
        v = self._gather_blocks(host.adam.exp_avg_sq, idx)
        self.state = self._zf_adopt(self.engine.params, jnp.asarray(idx),
                                    jnp.asarray(m), jnp.asarray(v),
                                    jnp.asarray(block_sq, jnp.float32),
                                    jnp.int32(self.engine.global_steps))
        self._steps_since_select = 0

    # ------------------------------------------------------------ train API
    def train_step(self, batch, rng) -> jax.Array:
        """One engine step under ZenFlow (called from train_batch)."""
        eng = self.engine
        lr = float(jax.device_get(
            eng.lr_schedule(jnp.int32(eng.global_steps))))

        if eng.global_steps < self.warmup or self.state is None:
            # warm-up (reference full_warm_up_rounds): plain synchronous
            # offload steps build reliable moments before selection starts
            flat_g, loss = eng._offload_grad_step(
                eng.params, batch, eng.loss_scale_state.scale, rng)
            g_np = np.asarray(flat_g)
            metrics = eng._apply_host_result(
                eng.host_optimizer.step_flat(
                    g_np, lr, grad_clip=eng.config.gradient_clipping))
            if eng.global_steps + 1 >= self.warmup:
                host = eng.host_optimizer
                g32 = host._widen_grads(g_np)
                gb = np.zeros(self.padded, np.float32)
                gb[:self.layout.total] = g32
                self._select(
                    (gb.reshape(self.num_blocks, self.block) ** 2).sum(1))
            metrics["loss"] = loss
            eng._last_metrics = metrics
            return loss

        (eng.params, self.state, flat_g, block_sq, loss, gnorm) = \
            self._zf_step(eng.params, self.state, batch, rng,
                          jnp.float32(lr))
        # host pipeline: accumulate every step (ordered worker thread)
        g_np = np.asarray(flat_g)        # one D2H
        pool = eng.host_optimizer._pool
        pool.submit(self._host_accumulate, g_np)
        self._steps_since_update += 1
        self._steps_since_select += 1

        # fold in a finished tail update from the PREVIOUS boundary
        if self._tail_future is not None and (
                self._tail_future.done() or
                self._steps_since_update >= self.update_interval):
            self._apply_tail(self._tail_future.result())
            self._tail_future = None

        if self._steps_since_update >= self.update_interval:
            self._steps_since_update = 0
            # ALWAYS submitted to the worker pool: the sweep is ordered
            # after this step's queued _host_accumulate (running it on this
            # thread would race the accumulator — review r4 finding); the
            # non-overlap mode just waits for it immediately
            self._tail_future = pool.submit(
                self._host_tail_step, lr,
                getattr(self, "_last_tail_upload", None))
            if not self.overlap:
                self._apply_tail(self._tail_future.result())
                self._tail_future = None

        self._last_block_sq = block_sq
        if self._steps_since_select >= self.select_interval:
            # selection must see settled host state: drain the tail first
            if self._tail_future is not None:
                self._apply_tail(self._tail_future.result())
                self._tail_future = None
            pool.submit(lambda: None).result()     # drain accumulations
            self._select(np.asarray(jax.device_get(block_sq)))

        eng._last_metrics = {"grad_norm": gnorm, "overflow": 0, "lr": lr,
                             "loss": loss}
        return loss

    def _apply_tail(self, narrowed: np.ndarray) -> None:
        """Upload a finished tail master and merge it (selected blocks keep
        the device values). The upload handle is retained so the NEXT tail
        sweep can wait on it before reusing the shared narrow buffer."""
        eng = self.engine
        uploaded = jnp.asarray(narrowed)           # one async H2D
        self._last_tail_upload = uploaded
        if self.state is not None:
            eng.params = self._zf_merge(eng.params, self.state.idx, uploaded)

    def drain(self) -> None:
        """Settle every in-flight host op and push device state back to the
        host arrays (checkpoint/eval boundary)."""
        eng = self.engine
        pool = eng.host_optimizer._pool
        pool.submit(lambda: None).result()
        if self._tail_future is not None:
            self._apply_tail(self._tail_future.result())
            self._tail_future = None
        self._sync_selection_to_host()
