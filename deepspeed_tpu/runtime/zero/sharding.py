"""ZeRO as sharding layouts.

The TPU-native re-design of the reference's ZeRO optimizers
(runtime/zero/stage_1_and_2.py:125, stage3.py:134,
partition_parameters.py:878). Where the reference maintains flat fp16
partitions, gradient-hook reduce-scatter buckets, and a fetch/release
allgather engine, here each ZeRO stage is a *sharding layout* over the
mesh's data-parallel axes, and XLA's SPMD partitioner emits (and overlaps)
the exact same collectives:

  stage 0 — params/grads/opt replicated over ('data','expert'); grads
            all-reduced (psum from the grad pytree's replicated sharding).
  stage 1 — optimizer state sharded (largest divisible axis over the DP
            axes == the reference's flat fp32 partition per rank,
            stage_1_and_2.py:293-304); updated param shards all-gathered
            back (== step():2058 allgather of updated bit16 partitions).
  stage 2 — + gradients reduce-scattered: the grad pytree carries the
            sharded spec, so XLA lowers grad reduction to reduce-scatter
            (== average_tensor:1184 over the IPG bucket).
  stage 3 — + parameters stored sharded (the model's partition_specs put
            an FSDP axis on each weight == partition_parameters.py
            ds_tensor shards); allgather-on-use is emitted per-layer by
            XLA and overlapped by its latency-hiding scheduler, replacing
            partitioned_param_coordinator.py's prefetch trace machinery.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import ZERO_AXES
from deepspeed_tpu.utils.logging import logger

Pytree = Any


def _axes_size(mesh: Mesh, axes: Tuple[str, ...]) -> int:
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def _spec_axes_used(spec: P):
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


_WARNED: set = set()


def _warn_once(msg: str) -> None:
    """Mis-sized meshes must not degrade silently (VERDICT r1 weak #8)."""
    if msg not in _WARNED:
        _WARNED.add(msg)
        logger.warning(msg)


def shard_over_dp(shape: Tuple[int, ...], spec: Optional[P], mesh: Mesh,
                  dp_axes: Tuple[str, ...] = ZERO_AXES) -> P:
    """Add DP-axis sharding to ``spec`` on the largest eligible dim.

    The analogue of the reference's flat-partition slicing
    (stage_1_and_2.py: each rank owns 1/dp of the flat group): we pick the
    largest dimension not already sharded whose size divides by the DP
    degree and shard it over the (unused) DP axes. Falls back to the
    original spec when nothing divides — the reference pads instead
    (flatten_dense_tensors_aligned:1043); keeping static shapes, we accept
    replication of oddly-shaped (small) leaves.
    """
    spec = spec if spec is not None else P(*([None] * len(shape)))
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = _spec_axes_used(spec)
    free_axes = tuple(a for a in dp_axes if a not in used)
    if not free_axes:
        return P(*entries)
    dp = _axes_size(mesh, free_axes)
    if dp == 1:
        return P(*entries)
    # FIRST: extend a dim already sharded by DP-family axes (hpZ/MiCS
    # param shards over 'data_inner' only). Appending the free axes
    # nests the finer grad/state chunk inside the coarser param shard,
    # so param↔grad↔state reshards stay single-dim slices/allgathers.
    # Sharding a SECOND dim instead (the fallback below) gives the
    # backward matmuls a mixed two-dim target sharding that the SPMD
    # partitioner can only reach by involuntary full rematerialization
    # (replicate-then-slice of every grad scatter — the MULTICHIP_r02
    # dryrun warnings on the mics/multislice paths).
    for i, e in enumerate(entries):
        if e is None:
            continue
        cur = tuple(e) if isinstance(e, (tuple, list)) else (e,)
        if all(a in dp_axes for a in cur) and \
                shape[i] % (_axes_size(mesh, cur) * dp) == 0:
            entries[i] = cur + free_axes
            return P(*entries)
    # candidate dims: unsharded, divisible by dp — largest first
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if entries[i] is None and shape[i] % dp == 0 and shape[i] >= dp:
            entries[i] = free_axes if len(free_axes) > 1 else free_axes[0]
            return P(*entries)
    _warn_once(f"ZeRO sharding: leaf shape {shape} has no dim divisible "
               f"by dp={dp}; replicating (memory cost, no signal loss) — "
               f"resize the dim or the mesh to shard it")
    return P(*entries)


class ZeroShardingPlan:
    """Sharding layout for one (model, mesh, stage) triple.

    Produces NamedSharding pytrees for params, grads, and optimizer state,
    consumed by the engine's jit in/out shardings.
    """

    def __init__(self, mesh: Mesh, stage: int, base_specs: Pytree,
                 abstract_params: Pytree,
                 dp_axes: Tuple[str, ...] = ZERO_AXES):
        self.mesh = mesh
        self.stage = stage
        self.dp_axes = dp_axes
        self.param_specs = base_specs
        # grads: stage>=2 adds DP sharding (reduce-scatter); else follow params
        if stage >= 2:
            self.grad_specs = jax.tree.map(
                lambda p, s: shard_over_dp(p.shape, s, mesh, dp_axes),
                abstract_params, base_specs,
                is_leaf=lambda x: isinstance(x, P))
        else:
            self.grad_specs = base_specs
        # optimizer state mirrors params: stage>=1 adds DP sharding
        if stage >= 1:
            self.state_specs = jax.tree.map(
                lambda p, s: shard_over_dp(p.shape, s, mesh, dp_axes),
                abstract_params, base_specs,
                is_leaf=lambda x: isinstance(x, P))
        else:
            self.state_specs = base_specs

    # -- NamedSharding builders ---------------------------------------------

    def _named(self, spec_tree: Pytree) -> Pytree:
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def param_shardings(self) -> Pytree:
        return self._named(self.param_specs)

    def grad_shardings(self) -> Pytree:
        return self._named(self.grad_specs)

    def opt_state_shardings(self, opt_state: Pytree) -> Pytree:
        """Map optimizer-state leaves to shardings: leaves that mirror a
        param (same shape suffix, e.g. exp_avg/exp_avg_sq/master/momentum)
        get the state spec; scalars/step counters replicate."""
        # opt_state is a dict: {"step": scalar, "exp_avg": params-like, ...}
        def leaf_sharding(x, s: P) -> NamedSharding:
            # placeholder leaves (e.g. muon's scalar stand-ins) may not
            # match the param rank — fall back to the leaf's own shape
            if np.ndim(x) == len(s):
                return NamedSharding(self.mesh, s)
            if self.stage >= 1 and np.ndim(x) > 0:
                return NamedSharding(
                    self.mesh,
                    shard_over_dp(x.shape, None, self.mesh, self.dp_axes))
            return NamedSharding(self.mesh, P())

        out = {}
        for key, sub in opt_state.items():
            leaves = jax.tree.leaves(sub)
            if len(leaves) == 1 and np.ndim(leaves[0]) == 0 and not isinstance(sub, dict):
                out[key] = NamedSharding(self.mesh, P())
            else:
                try:
                    out[key] = jax.tree.map(
                        leaf_sharding, sub, self.state_specs)
                except ValueError:
                    # structure mismatch (optimizer skipped some leaves)
                    out[key] = jax.tree.map(
                        lambda x: leaf_sharding(x, P(*([None] * np.ndim(x)))
                                                if self.stage < 1 else P()),
                        sub)
        return out

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())
