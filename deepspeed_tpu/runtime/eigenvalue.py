"""Power-iteration Hessian eigenvalue estimation.

Reference: ``runtime/eigenvalue.py:13`` (``Eigenvalue.compute_eigenvalue``
— per-block power iteration over autograd with retain_graph, used to
drive the quantization schedule in MoQ). The torch version hand-rolls
Hv products by re-differentiating; on jax an HVP is one ``jax.jvp``
over ``jax.grad`` — forward-over-reverse, one compile, no graph
retention.
"""

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Pytree = Any


def _hvp(loss_fn: Callable[[Pytree], jax.Array], params: Pytree,
         v: Pytree) -> Pytree:
    """Hessian-vector product: H(params) @ v (forward-over-reverse)."""
    return jax.jvp(jax.grad(loss_fn), (params,), (v,))[1]


def _tree_norm(t: Pytree) -> jax.Array:
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in jax.tree.leaves(t)))


def _tree_dot(a: Pytree, b: Pytree) -> jax.Array:
    return sum(jnp.sum(x.astype(jnp.float32) * y.astype(jnp.float32))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def power_iteration(loss_fn: Callable[[Pytree], jax.Array],
                    params: Pytree, rng: jax.Array,
                    max_iter: int = 100, tol: float = 1e-2,
                    stability: float = 1e-6) -> Tuple[jax.Array, Pytree]:
    """Dominant |eigenvalue| of the loss Hessian at ``params`` (reference
    compute_eigenvalue's max_iter/tol/stability semantics). Returns
    (eigenvalue, eigenvector pytree)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(rng, len(leaves))
    v = jax.tree_util.tree_unflatten(
        treedef, [jax.random.normal(k, x.shape, jnp.float32)
                  for k, x in zip(keys, leaves)])
    norm = _tree_norm(v)
    v = jax.tree.map(lambda x: x / (norm + stability), v)

    def body(carry):
        v, prev_ev, i, _ = carry
        hv = _hvp(loss_fn, params, v)
        ev = _tree_dot(v, hv)
        n = _tree_norm(hv)
        v_new = jax.tree.map(lambda x: x / (n + stability), hv)
        converged = jnp.abs(ev - prev_ev) / (jnp.abs(ev) + stability) < tol
        return v_new, ev, i + 1, converged

    def cond(carry):
        _, _, i, converged = carry
        return jnp.logical_and(i < max_iter, jnp.logical_not(converged))

    v, ev, _, _ = jax.lax.while_loop(
        cond, body, (v, jnp.float32(0.0), jnp.int32(0), jnp.bool_(False)))
    return jnp.abs(ev), v


class Eigenvalue:
    """Per-layer eigenvalue sweep (reference Eigenvalue class): computes
    the dominant Hessian eigenvalue restricted to each selected subtree —
    the per-layer sensitivity signal MoQ's quantization scheduler
    consumes."""

    def __init__(self, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability

    def compute_eigenvalue(self, loss_fn: Callable[[Pytree], jax.Array],
                           params: Pytree, rng: jax.Array,
                           layer_keys: Optional[Tuple[str, ...]] = None
                           ) -> Dict[str, float]:
        """layer_keys: top-level keys of ``params`` to analyze (default:
        all). The Hessian block is taken w.r.t. that subtree with the rest
        frozen."""
        keys = layer_keys or tuple(params.keys())
        out: Dict[str, float] = {}
        for i, key in enumerate(keys):
            sub = params[key]

            def block_loss(subtree, key=key):
                merged = dict(params)
                merged[key] = subtree
                return loss_fn(merged)

            ev, _ = power_iteration(block_loss, sub,
                                    jax.random.fold_in(rng, i),
                                    self.max_iter, self.tol,
                                    self.stability)
            out[key] = float(ev)
        return out
