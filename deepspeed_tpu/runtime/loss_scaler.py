"""Dynamic loss scaling for fp16 parity.

Reference: runtime/fp16/loss_scaler.py (LossScalerBase:43,
LossScaler:75 static, DynamicLossScaler:99). TPU-native training is bf16
and needs none of this; the machinery exists for API/numerics parity when
a user config enables fp16. Implemented as a pure state record updated
inside the jitted step (no Python-side branching on traced values).
"""

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jax.Array            # f32 scalar
    good_steps: jax.Array       # i32 consecutive overflow-free steps
    hysteresis: jax.Array       # i32 remaining tolerance


def init_loss_scale(static_scale: float = 0.0,
                    initial_scale_power: int = 16,
                    hysteresis: int = 2) -> LossScaleState:
    scale = static_scale if static_scale > 0 else 2.0 ** initial_scale_power
    return LossScaleState(jnp.float32(scale), jnp.zeros((), jnp.int32),
                          jnp.int32(hysteresis))


def check_overflow(grads) -> jax.Array:
    """Global NaN/Inf check (reference has_overflow_serial /
    check_grad_overflow stage_1_and_2.py:172)."""
    leaves = jax.tree.leaves(grads)
    flags = [jnp.logical_not(jnp.isfinite(g).all()) for g in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def global_check(tree) -> Tuple[jax.Array, Dict]:
    """Per-leaf finite check: returns (any_nonfinite, flags) where
    ``flags`` mirrors ``tree``'s structure with one bool scalar per leaf.
    Unlike :func:`check_overflow` this names WHICH leaf went bad — the
    engine's ``check_nan_inf="scoped"`` mode feeds the flags to
    ``telemetry.anomaly.first_flagged_path`` so the blowup report reads
    "first non-finite leaf: ['decoder']['layers_7']['mlp']['wi']" instead
    of a bare boolean. Jittable; both outputs are tiny (bool scalars)."""
    flags = jax.tree.map(
        lambda g: jnp.logical_not(jnp.isfinite(g).all()), tree)
    leaves = jax.tree.leaves(flags)
    out = leaves[0]
    for f in leaves[1:]:
        out = jnp.logical_or(out, f)
    return out, flags


def update_scale(state: LossScaleState, overflow: jax.Array,
                 dynamic: bool = True,
                 scale_factor: float = 2.0,
                 scale_window: int = 1000,
                 min_scale: float = 1.0,
                 delayed_shift: int = 2,
                 consecutive_hysteresis: bool = False
                 ) -> LossScaleState:
    """Reference DynamicLossScaler.update_scale (loss_scaler.py:150):
    overflow decrements hysteresis and, once exhausted, halves the scale;
    a full overflow-free window doubles the scale and restores hysteresis
    to ``delayed_shift`` (:209); with ``consecutive_hysteresis`` the
    restore happens on every good step instead."""
    if not dynamic:
        return state
    hy = jnp.where(overflow, jnp.maximum(state.hysteresis - 1, 0),
                   state.hysteresis)
    drop = jnp.logical_and(overflow, hy <= 0)
    new_scale = jnp.where(
        drop, jnp.maximum(state.scale / scale_factor, min_scale), state.scale)
    good = jnp.where(overflow, 0, state.good_steps + 1)
    grow = jnp.logical_and(jnp.logical_not(overflow),
                           (good % scale_window) == 0)
    grow = jnp.logical_and(grow, good > 0)
    new_scale = jnp.where(grow, new_scale * scale_factor, new_scale)
    if consecutive_hysteresis:
        hy = jnp.where(jnp.logical_not(overflow), jnp.int32(delayed_shift), hy)
    else:
        hy = jnp.where(grow, jnp.int32(delayed_shift), hy)
    return LossScaleState(new_scale, good, hy)
