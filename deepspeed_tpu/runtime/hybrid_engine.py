"""Hybrid engine — one model flipped between training and fast inference.

Reference: ``runtime/hybrid_engine.py:30`` (``DeepSpeedHybridEngine``:
RLHF actor that trains with ZeRO and generates with the inference
kernels; ``generate``:168, LoRA fuse/unfuse:132–146, Z3 gather before
generation). The torch version must gather ZeRO-3 shards and swap module
implementations; on TPU the flip is cheap by construction:

- params are an immutable pytree — the inference engine REFERENCES the
  training engine's arrays (no copy, no gather: the inference forward's
  own sharding constraints make XLA insert whatever resharding the
  serving layout needs);
- "kernel injection" is just jit of the cached-decode forward;
- after each training step the next ``generate`` picks up the new params
  by version tracking (the reference re-populates its containers the
  same way).

Offloaded/ZeRO++ storages are unflattened on demand. LoRA fuse/unfuse is
exposed for OptimizedLinear-bearing pytrees via
:func:`deepspeed_tpu.linear.merge_lora`.
"""

from typing import Any, Dict, Optional, Union

import jax
import numpy as np

from deepspeed_tpu.inference.engine import (DeepSpeedTPUInferenceConfig,
                                            InferenceEngineTPU)
from deepspeed_tpu.utils.logging import log_dist

Pytree = Any


class DeepSpeedTPUHybridEngine:
    """Wrap a training engine with a `generate()` that always serves the
    CURRENT weights (reference DeepSpeedHybridEngine)."""

    def __init__(self, engine,
                 inference_config: Union[Dict[str, Any],
                                         DeepSpeedTPUInferenceConfig,
                                         None] = None):
        if engine.model.decoder_config is None:
            raise ValueError(
                "hybrid engine needs a ModelSpec built from a "
                "DecoderConfig (model_factory.decoder_model_spec)")
        self.engine = engine
        self.inference_config = inference_config or {"dtype": "bfloat16"
                                                     if engine.bf16_enabled
                                                     else "float32"}
        self._inf: Optional[InferenceEngineTPU] = None
        # staleness tracking by params IDENTITY: every update path
        # (train_batch, the 3-call step(), offload's host apply,
        # load_checkpoint) replaces the immutable params object, so an
        # `is` check catches them all — a manual version counter on
        # train_batch alone would miss the delegated paths
        self._served_params_ref: Any = None
        log_dist("hybrid engine ready: train<->infer flip over shared "
                 "params")

    # -- training passthroughs ---------------------------------------------

    def train_batch(self, *a, **kw):
        return self.engine.train_batch(*a, **kw)

    def __getattr__(self, name):
        # delegate everything else (save_checkpoint, step counters, ...)
        return getattr(self.engine, name)

    # -- the flip -----------------------------------------------------------

    def _current_params(self) -> Pytree:
        eng = self.engine
        if getattr(eng, "_zeropp_enabled", False):
            from deepspeed_tpu.runtime.zero.zeropp import unflatten_params
            return unflatten_params(eng)
        if eng.offload_enabled:
            eng._drain_host_step()      # overlapped update must land
        return eng.params

    def refresh_inference_engine(self) -> None:
        """Rebuild/repoint the serving engine at the latest weights
        (reference: _restore_transformer_layer / populate containers)."""
        params = self._current_params()
        if self._inf is None:
            self._inf = InferenceEngineTPU(
                self.engine.model.decoder_config, self.inference_config,
                params=params, mesh=self.engine.mesh)
        else:
            import jax.numpy as jnp
            cast = jax.tree.map(
                lambda x: x.astype(self._inf.dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
            self._inf.params = jax.device_put(cast, self._inf._param_sh)
        self._served_params_ref = self.engine.params

    def generate(self, input_ids, **kw) -> np.ndarray:
        """Reference hybrid_engine.py:168 — serve the current weights."""
        if self._inf is None or \
                self._served_params_ref is not self.engine.params:
            self.refresh_inference_engine()
        return self._inf.generate(input_ids, **kw)

    def eval(self) -> None:     # parity no-ops (functional engine has no
        pass                    # module train/eval mode)

    def train(self) -> None:
        pass
