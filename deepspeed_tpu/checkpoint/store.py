"""Checkpoint store — universal by construction.

Reference: engine save_checkpoint/load_checkpoint (runtime/engine.py:3621,
3273), the pluggable CheckpointEngine ABC
(runtime/checkpoint_engine/checkpoint_engine.py:21), and Universal
Checkpoint (deepspeed/checkpoint/ds_to_universal.py). The reference writes
per-rank partitioned shards and needs an offline converter to reshape
across (TP,PP,DP) changes; here every leaf is written **once, full-shape**
(gathered from its mesh sharding on save, resharded by ``device_put`` on
load), so *any* later mesh/ZeRO-stage reload works with no conversion —
the UCP property is the default.

Layout::

    <dir>/<tag>/meta.json             # counters + optimizer hyperparams
    <dir>/<tag>/state/<group>/<leaf-path>.npy
    <dir>/latest                      # text file with the newest tag

Multi-host note: round 1 gathers to the host of process 0; a sharded
multi-host writer (per-fragment files + index, Orbax-style) is the
follow-on once multi-process checkpointing is exercised.
"""

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

_SEP = "."


def _leaf_paths(tree) -> Dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = _SEP.join(_path_str(k) for k in path)
        out[key] = leaf
    return out


def _path_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    if hasattr(k, "name"):
        return str(k.name)
    return str(k)


def save_checkpoint(save_dir: str, tag: str, state: Dict[str, Pytree],
                    meta: Dict[str, Any], save_latest: bool = True) -> str:
    """Write ``state`` (dict of named pytrees) + ``meta`` under tag."""
    root = os.path.join(save_dir, tag)
    if os.path.exists(root):
        shutil.rmtree(root)
    os.makedirs(os.path.join(root, "state"), exist_ok=True)
    index: Dict[str, Dict[str, Any]] = {}
    for group, tree in state.items():
        gdir = os.path.join(root, "state", group)
        os.makedirs(gdir, exist_ok=True)
        for key, leaf in _leaf_paths(tree).items():
            arr = np.asarray(jax.device_get(leaf))
            orig_dtype = str(arr.dtype)
            # npy can't round-trip ml_dtypes (bfloat16/fp8): widen to fp32
            # on disk, record the original dtype for exact reload
            if arr.dtype.kind not in "fiub?" or orig_dtype == "bfloat16":
                arr = arr.astype(np.float32)
            fname = key.replace("/", "_") + ".npy"
            np.save(os.path.join(gdir, fname), arr)
            index.setdefault(group, {})[key] = {
                "file": fname, "shape": list(arr.shape),
                "dtype": orig_dtype}
    with open(os.path.join(root, "meta.json"), "w") as fh:
        json.dump({"meta": meta, "index": index}, fh, indent=1)
    if save_latest:
        with open(os.path.join(save_dir, "latest"), "w") as fh:
            fh.write(tag)
    return root


def latest_tag(load_dir: str) -> Optional[str]:
    path = os.path.join(load_dir, "latest")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return fh.read().strip()


def load_checkpoint(load_dir: str, tag: Optional[str],
                    templates: Dict[str, Pytree],
                    shardings: Dict[str, Pytree]
                    ) -> Tuple[Optional[Dict[str, Pytree]],
                               Dict[str, Any], Optional[str]]:
    """Load state matching ``templates`` structure, placing each leaf with
    the corresponding sharding (any mesh — this is the universal reshape)."""
    tag = tag or latest_tag(load_dir)
    if tag is None:
        return None, {}, None
    root = os.path.join(load_dir, tag)
    meta_path = os.path.join(root, "meta.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no checkpoint at {root}")
    with open(meta_path) as fh:
        payload = json.load(fh)
    meta = payload["meta"]

    out: Dict[str, Pytree] = {}
    for group, template in templates.items():
        gdir = os.path.join(root, "state", group)
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        sh_leaves = jax.tree_util.tree_leaves(
            shardings[group], is_leaf=lambda x: hasattr(x, "mesh"))
        if len(sh_leaves) != len(flat):
            # sharding tree may mirror template exactly; flatten generally
            sh_flat, _ = jax.tree_util.tree_flatten_with_path(
                shardings[group], is_leaf=lambda x: hasattr(x, "mesh"))
            sh_leaves = [leaf for _, leaf in sh_flat]
        leaves = []
        for (path, tmpl), sh in zip(flat, sh_leaves):
            key = _SEP.join(_path_str(k) for k in path)
            fname = os.path.join(gdir, key.replace("/", "_") + ".npy")
            arr = jnp.asarray(np.load(fname))
            tdtype = jnp.asarray(tmpl).dtype
            if arr.dtype != tdtype:
                arr = arr.astype(tdtype)
            leaves.append(jax.device_put(arr, sh))
        out[group] = jax.tree_util.tree_unflatten(treedef, leaves)
    return out, meta, tag


def consolidate_to_fp32(load_dir: str, tag: Optional[str] = None
                        ) -> Dict[str, np.ndarray]:
    """Offline merge to fp32 state dict (reference
    utils/zero_to_fp32.py:188) — trivially: read the master (or params)
    leaves back as fp32 numpy arrays without any runtime."""
    tag = tag or latest_tag(load_dir)
    root = os.path.join(load_dir, tag)
    with open(os.path.join(root, "meta.json")) as fh:
        payload = json.load(fh)
    index = payload["index"]
    src = "params"
    master_keys = {k: v for k, v in index.get("opt_state", {}).items()
                   if k.startswith("master" + _SEP)}
    out = {}
    if master_keys:
        for key, entry in master_keys.items():
            arr = np.load(os.path.join(root, "state", "opt_state",
                                       entry["file"]))
            out[key[len("master" + _SEP):]] = arr.astype(np.float32)
    else:
        for key, entry in index.get(src, {}).items():
            arr = np.load(os.path.join(root, "state", src, entry["file"]))
            out[key] = arr.astype(np.float32)
    return out
