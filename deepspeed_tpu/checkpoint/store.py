"""Checkpoint store — sharded fragments, universal by construction.

Reference: engine save_checkpoint/load_checkpoint (runtime/engine.py:3621,
3273; per-rank shard naming :3197–3261), the pluggable CheckpointEngine ABC
(runtime/checkpoint_engine/checkpoint_engine.py:21, Fast/Decoupled async
engines), and Universal Checkpoint (deepspeed/checkpoint/ds_to_universal.py).

Design:

- **Sharded writing.** Every process writes ONLY its addressable shards
  (one raw-bytes fragment file per distinct shard, ``replica_id == 0``
  filter deduplicates replicas) — no full-model gather ever lands on one
  host, the property the reference gets from per-rank
  ``zero_pp_rank_X_mp_rank_XX`` files.
- **Universal reload.** Fragments carry (start, stop) index metadata in
  FULL-array coordinates, so load assembles any leaf under any later mesh,
  ZeRO stage, or offload mode — the UCP reshape with no offline converter.
- **Async commit.** The device→host snapshot is taken synchronously (jax
  arrays are immutable but donation invalidates buffers, so the copy must
  happen before training continues); file writes + the meta.json commit
  + the ``latest`` marker run on a background thread through the
  AsyncIOEngine (reference: DecoupledCheckpointEngine, deepspeed/io/
  fast_file_writer.py). A checkpoint is complete only when EVERY process's
  ``meta.p<idx>.json`` is present (the loader enforces this via the
  recorded ``process_count``); the ``latest`` marker is published only
  after a collective all-processes-committed agreement.

Layout (v2, multi-host)::

    <dir>/<tag>/meta.p<idx>.json   # per-process fragment index; p0's file
                                   # carries meta + process_count; a save
                                   # is complete only when ALL process
                                   # files are present (loader enforces)
    <dir>/<tag>/meta.json          # p0 alias (back-compat / single-file)
    <dir>/<tag>/state/<group>/<leaf>.p<idx>f<k>.bin  # raw C-order bytes
    <dir>/latest                   # newest committed tag (written by p0)
"""

import functools
import json
import os
import shutil
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from deepspeed_tpu.io.async_io import atomic_write, pread_retry
from deepspeed_tpu.resilience.faults import fault_injector
from deepspeed_tpu.utils.logging import logger

Pytree = Any

_SEP = "."

#: bounded exponential-backoff retry for transient fragment-write IO
#: errors (NFS blips, injected faults); env-overridable for tests
IO_RETRIES = int(os.environ.get("DSTPU_CKPT_RETRIES", "3"))
IO_BACKOFF_S = float(os.environ.get("DSTPU_CKPT_BACKOFF_S", "0.05"))


class CheckpointCorrupt(RuntimeError):
    """A tag failed integrity verification: torn/short/CRC-mismatched
    fragment, missing fragment file, or incomplete per-process index.
    ``load_checkpoint`` quarantines the tag and falls back to the newest
    valid one."""


def _write_fragment(path: str, data: bytes, retries: int = None,
                    backoff_s: float = None) -> None:
    """One fragment write with bounded exponential-backoff retry on
    ``OSError`` (the transient class: full/flaky network filesystems).
    The chaos hook sits INSIDE the loop so an injected
    ``io_error:checkpoint`` exercises exactly this retry path."""
    retries = IO_RETRIES if retries is None else retries
    backoff_s = IO_BACKOFF_S if backoff_s is None else backoff_s
    last: Optional[OSError] = None
    for attempt in range(retries + 1):
        try:
            # advisory=False: torn_fragment stays pending for commit(),
            # which owns the file-tearing mechanics
            fault_injector.fire("checkpoint", advisory=False)
            with open(path, "wb") as fh:
                fh.write(data)
            if last is not None:
                from deepspeed_tpu.resilience.faults import record_recovery
                record_recovery("ckpt_io_retry", path=os.path.basename(path),
                                attempts=attempt + 1)
            return
        except OSError as e:
            last = e
            try:
                from deepspeed_tpu import telemetry
                telemetry.registry.counter(
                    "resilience/ckpt_retries",
                    help="checkpoint fragment writes retried after "
                         "transient IO errors").inc()
            except Exception:                        # noqa: BLE001
                pass
            if attempt >= retries:
                raise
            delay = backoff_s * (2 ** attempt)
            logger.warning(f"checkpoint write {os.path.basename(path)} "
                           f"failed ({e}); retry {attempt + 1}/{retries} "
                           f"in {delay:.3f}s")
            time.sleep(delay)


def _np_dtype(name: str):
    return {"bfloat16": ml_dtypes.bfloat16,
            "float8_e4m3fn": ml_dtypes.float8_e4m3fn,
            "float8_e5m2": ml_dtypes.float8_e5m2}.get(name) or np.dtype(name)


def _leaf_paths(tree) -> Dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = _SEP.join(_path_str(k) for k in path)
        out[key] = leaf
    return out


def _path_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    if hasattr(k, "name"):
        return str(k.name)
    return str(k)


def _index_bounds(index, shape) -> Tuple[List[int], List[int]]:
    """jax shard index (tuple of slices) → (start, stop) per dim."""
    starts, stops = [], []
    for sl, dim in zip(index, shape):
        starts.append(0 if sl.start is None else int(sl.start))
        stops.append(dim if sl.stop is None else int(sl.stop))
    return starts, stops


def _snapshot_shards(leaf) -> List[Tuple[List[int], List[int], np.ndarray]]:
    """Host copies of this process's distinct shards of one jax array."""
    if not isinstance(leaf, jax.Array):
        arr = np.asarray(leaf)
        return [([0] * arr.ndim, list(arr.shape), arr)]
    out = []
    for shard in leaf.addressable_shards:
        if shard.replica_id != 0:
            continue
        starts, stops = _index_bounds(shard.index, leaf.shape)
        out.append((starts, stops, np.asarray(shard.data)))
    return out


def _agree_ok(ok: bool) -> bool:
    """All-process AND of a local success flag. Every process calls this at
    the same point (it doubles as a barrier), so one host's failure raises
    a collective error everywhere instead of deadlocking the others at a
    barrier they'll never leave."""
    if jax.process_count() <= 1:
        return ok
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(
        np.asarray([1 if ok else 0], np.int32))
    return bool(np.all(flags))


def _traced(name: str):
    """Wrap a store entry point in a retroactive tracer span (``ph="X"``
    via :meth:`Tracer.complete`) so the goodput ledger can attribute
    checkpoint wall time to its ``ckpt`` category. No-op overhead when
    the tracer is disabled; for async saves only the synchronous
    device→host snapshot portion lands in the span — the background
    commit is overlapped with training and is not badput."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            from deepspeed_tpu.telemetry.tracer import tracer
            t0 = tracer.now()
            try:
                return fn(*args, **kwargs)
            finally:
                tracer.complete(name, t0, tracer.now())
        return wrapper
    return deco


@_traced("checkpoint/save")
def save_checkpoint(save_dir: str, tag: str, state: Dict[str, Pytree],
                    meta: Dict[str, Any], save_latest: bool = True,
                    async_save: bool = False):
    """Write ``state`` (dict of named pytrees) + ``meta`` under tag.

    Multi-host protocol: process 0 clears/creates the tag directory (behind
    a cross-host barrier), every process writes only its own fragment files
    plus a per-process ``meta.p<idx>.json`` carrying its fragment index;
    the loader merges all per-process indexes. Process 0's meta file also
    records ``process_count`` so an incomplete save is detectable.

    The ``latest`` marker is published only after ALL processes' commits
    succeed (collective agreement via :func:`_agree_ok`), so auto-resume
    can never land on a half-written multi-host checkpoint. For async
    saves that publication happens in :func:`wait_pending` / the next
    save — both are collective calls every process must reach.

    Returns the checkpoint root; with ``async_save`` also returns after the
    device→host snapshot — call :func:`wait_pending` before relying on the
    files (a failed async commit re-raises there and on the next save)."""
    # drain previous async commits WITHOUT raising yet: every process must
    # reach the agreement point or a failure on one host would strand the
    # others at the barrier
    first, pubs = _drain_pending()
    if not _agree_ok(first is None):
        raise RuntimeError("async checkpoint commit failed (this or a peer "
                           "process)") from first
    for ent in pubs:
        _publish_latest(ent)
    root = os.path.join(save_dir, tag)
    pidx = jax.process_index()
    clear_err: Optional[BaseException] = None
    if pidx == 0:
        try:
            if os.path.exists(root):
                shutil.rmtree(root)
            os.makedirs(os.path.join(root, "state"), exist_ok=True)
        except BaseException as e:
            clear_err = e
    # doubles as the "nobody writes before p0 cleared the dir" barrier
    if not _agree_ok(clear_err is None):
        raise RuntimeError(
            f"could not clear checkpoint dir {root}") from clear_err

    # ---- synchronous snapshot (before donation can invalidate buffers)
    # (path, host array, index fragment record — CRC stamped at commit)
    work: List[Tuple[str, np.ndarray, Dict[str, Any]]] = []
    index: Dict[str, Dict[str, Any]] = {}
    for group, tree in state.items():
        gdir = os.path.join(root, "state", group)
        os.makedirs(gdir, exist_ok=True)
        for key, leaf in _leaf_paths(tree).items():
            shards = _snapshot_shards(leaf)
            full_shape = list(np.shape(leaf))
            dtype = str(np.asarray(shards[0][2]).dtype) if shards else "float32"
            frags = []
            for k, (starts, stops, arr) in enumerate(shards):
                fname = f"{key.replace('/', '_')}.p{pidx}f{k}.bin"
                frag = {"file": fname, "start": starts, "stop": stops}
                work.append((os.path.join(gdir, fname),
                             np.ascontiguousarray(arr), frag))
                frags.append(frag)
            if frags:       # processes owning no shard of this leaf skip it
                index.setdefault(group, {})[key] = {
                    "shape": full_shape, "dtype": dtype, "fragments": frags}

    def commit():
        for path, arr, frag in work:
            data = arr.tobytes()
            # integrity stamp: the loader verifies bytes+CRC per fragment
            # and falls back to the previous valid tag on a torn read
            frag["bytes"] = len(data)
            frag["crc32"] = zlib.crc32(data) & 0xFFFFFFFF
            _write_fragment(path, data)
        # chaos: a scheduled torn_fragment truncates one just-written
        # fragment AFTER its (correct) CRC was stamped — exactly the
        # torn-write the loader's verification must catch
        if "torn_fragment" in fault_injector.fire("checkpoint") and work:
            victim = work[-1][0]
            size = os.path.getsize(victim)
            with open(victim, "r+b") as fh:
                fh.truncate(max(0, size // 2))
            logger.warning(f"CHAOS: tore checkpoint fragment "
                           f"{os.path.basename(victim)} "
                           f"({size} -> {max(0, size // 2)} bytes)")
        # per-process meta LAST — its presence commits this process's part
        payload = {"meta": meta, "index": index, "version": 2,
                   "process_count": jax.process_count()}
        with open(os.path.join(root, f"meta.p{pidx}.json"), "w") as fh:
            json.dump(payload, fh, indent=1)
        if pidx == 0:
            # back-compat alias (the real commit point is the full set of
            # per-process meta files; `latest` waits for agreement)
            with open(os.path.join(root, "meta.json"), "w") as fh:
                json.dump(payload, fh, indent=1)

    pub = {"save_dir": save_dir, "tag": tag, "save_latest": save_latest}
    if async_save:
        err: List[BaseException] = []

        def run():
            try:
                commit()
            except BaseException as e:     # surfaced by wait_pending
                err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        _PENDING.append({"thread": t, "err": err, **pub})
        return root

    commit_err: Optional[BaseException] = None
    try:
        commit()
    except BaseException as e:
        commit_err = e
    if not _agree_ok(commit_err is None):
        raise RuntimeError("checkpoint commit failed (this or a peer "
                           "process)") from commit_err
    _publish_latest(pub)
    return root


#: in-flight async commits (reference: DecoupledCheckpointEngine queue)
_PENDING: List[Dict[str, Any]] = []


def _publish_latest(ent: Dict[str, Any]) -> None:
    """Write the ``latest`` marker (p0 only). Callers must have already
    agreed all processes committed."""
    if ent["save_latest"] and jax.process_index() == 0:
        _write_latest(ent["save_dir"], ent["tag"])


def _write_latest(save_dir: str, tag: str) -> None:
    """Atomic+durable ``latest`` publish: temp file, fsync, ``os.replace``
    (atomic on POSIX), then directory fsync — a crash mid-publish leaves
    either the old marker or the new one, never a torn read, and the
    marker survives power loss once this returns."""
    atomic_write(os.path.join(save_dir, "latest"), tag.encode(),
                 durable=True)


def _drain_pending() -> Tuple[Optional[BaseException], List[Dict[str, Any]]]:
    """Join in-flight async commits. Returns (first local failure or None,
    successfully-committed entries awaiting `latest` publication). Never
    raises — callers run the collective agreement first."""
    first: Optional[BaseException] = None
    pubs: List[Dict[str, Any]] = []
    while _PENDING:
        ent = _PENDING.pop(0)
        ent["thread"].join()
        if ent["err"]:
            first = first or ent["err"][0]
        else:
            pubs.append(ent)
    return first, pubs


def wait_pending() -> None:
    """Join in-flight async commits; collective across processes. Re-raises
    the first failure anywhere (the reference surfaces write errors — a
    checkpoint that silently never committed is worse than a crash); on
    success publishes the deferred ``latest`` markers."""
    first, pubs = _drain_pending()
    if not _agree_ok(first is None):
        raise RuntimeError("async checkpoint commit failed (this or a peer "
                           "process)") from first
    for ent in pubs:
        _publish_latest(ent)


def _read_merged_index(root: str) -> Tuple[Dict[str, Any],
                                           Dict[str, Dict[str, Any]]]:
    """Read meta + fragment index, merging every process's
    ``meta.p<idx>.json`` (v2 multi-host) and falling back to plain
    ``meta.json`` (v1 / single-file saves)."""
    pfiles = sorted(f for f in os.listdir(root)
                    if f.startswith("meta.p") and f.endswith(".json")) \
        if os.path.isdir(root) else []
    if not pfiles:
        meta_path = os.path.join(root, "meta.json")
        if not os.path.exists(meta_path):
            raise FileNotFoundError(f"no checkpoint at {root}")
        with open(meta_path) as fh:
            payload = json.load(fh)
        return payload["meta"], payload["index"]

    # meta + process_count come from process 0's file per the save
    # protocol; if p0's file is the missing one, fall back to any present
    # file (all carry process_count) so the completeness check below can
    # produce its diagnostic instead of a raw FileNotFoundError
    meta_src = "meta.p0.json" if "meta.p0.json" in pfiles else pfiles[0]
    with open(os.path.join(root, meta_src)) as fh:
        p0 = json.load(fh)
    meta: Dict[str, Any] = p0["meta"]
    expected = p0.get("process_count")
    index: Dict[str, Dict[str, Any]] = {}
    for fname in pfiles:
        with open(os.path.join(root, fname)) as fh:
            payload = json.load(fh)
        for group, entries in payload["index"].items():
            gindex = index.setdefault(group, {})
            for key, entry in entries.items():
                if key in gindex:
                    gindex[key]["fragments"].extend(entry["fragments"])
                else:
                    gindex[key] = {"shape": entry["shape"],
                                   "dtype": entry["dtype"],
                                   "fragments": list(entry["fragments"])}
    if expected is not None and len(pfiles) != expected:
        raise CheckpointCorrupt(
            f"incomplete checkpoint at {root}: {len(pfiles)} of "
            f"{expected} per-process index files present")
    return meta, index


def latest_tag(load_dir: str) -> Optional[str]:
    path = os.path.join(load_dir, "latest")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return fh.read().strip()


def _read_fragment(gdir: str, f: Dict[str, Any], dtype) -> np.ndarray:
    """Read one fragment, verifying byte length and CRC32 when the index
    carries them (every v2 save since the integrity stamp; older
    checkpoints load unverified). A short read or checksum mismatch is a
    TORN fragment — raise :class:`CheckpointCorrupt` so the loader falls
    back instead of resuming from garbage bytes."""
    path = os.path.join(gdir, f["file"])
    try:
        raw = pread_retry(path, retries=IO_RETRIES, backoff_s=IO_BACKOFF_S)
    except FileNotFoundError as e:
        raise CheckpointCorrupt(
            f"missing checkpoint fragment {f['file']}") from e
    except OSError as e:
        raise CheckpointCorrupt(
            f"unreadable checkpoint fragment {f['file']} after "
            f"{IO_RETRIES} retries: {e}") from e
    if "bytes" in f and len(raw) != int(f["bytes"]):
        raise CheckpointCorrupt(
            f"torn checkpoint fragment {f['file']}: {len(raw)} bytes on "
            f"disk, {f['bytes']} at commit")
    if "crc32" in f:
        crc = zlib.crc32(raw) & 0xFFFFFFFF
        if crc != int(f["crc32"]):
            raise CheckpointCorrupt(
                f"checkpoint fragment {f['file']} failed CRC32 "
                f"verification ({crc:#010x} != {int(f['crc32']):#010x})")
    return np.frombuffer(raw, dtype=dtype)


def _assemble(gdir: str, entry: Dict[str, Any]) -> np.ndarray:
    """Fragments → full np array (any-mesh reshape happens at device_put),
    CRC-verified per fragment."""
    dtype = _np_dtype(entry["dtype"])
    shape = tuple(entry["shape"])
    if "fragments" not in entry:
        # version-1 format: one full-shape .npy per leaf
        if "file" in entry:
            return np.load(os.path.join(gdir, entry["file"]))
        raise ValueError(f"unrecognized checkpoint index entry: "
                         f"{sorted(entry)} (expected 'fragments' [v2] or "
                         f"'file' [v1])")
    frags = entry["fragments"]
    if len(frags) == 1 and tuple(frags[0]["start"]) == (0,) * len(shape) \
            and tuple(frags[0]["stop"]) == shape:
        return _read_fragment(gdir, frags[0], dtype).reshape(shape)
    out = np.empty(shape, dtype)
    for f in frags:
        sl = tuple(slice(a, b) for a, b in zip(f["start"], f["stop"]))
        piece = _read_fragment(gdir, f, dtype)
        out[sl] = piece.reshape(tuple(b - a for a, b in
                                      zip(f["start"], f["stop"])))
    return out


#: optimizer-state leaves added to the runtime AFTER older checkpoints were
#: written (0/1 Adam accumulator + adaptive-interval policy scalars and comm
#: telemetry) — the only leaves that may silently fall back to their
#: freshly-initialized template value under a strict load
_FORWARD_COMPAT_LEAVES = frozenset({
    "u", "lrs", "var_interval", "var_counter",
    "local_interval", "local_counter", "exact_comms", "onebit_comms",
})


def _missing_leaf_is_critical(group: str, key: str) -> bool:
    """A missing 'params' leaf or any real optimizer-state leaf (fp32
    'master' copies, Adam moments, step counter, error-feedback buffers)
    means the checkpoint is incomplete or structurally mismatched (renamed
    layer, truncated save) — resuming from the freshly-initialized template
    would silently continue from partly-random state. Only the allowlisted
    forward-compat telemetry above may fall back to the template."""
    if group == "params":
        return True
    if group != "opt_state":
        return False          # loss_scale etc.: safe to re-init
    return key.split(_SEP, 1)[0] not in _FORWARD_COMPAT_LEAVES


def _quarantine_tag(load_dir: str, tag: str, why: BaseException) -> None:
    """Move a corrupt tag dir aside (``<tag>.quarantined``) so auto-resume
    never lands on it again; p0 only, best effort (a rename failure just
    leaves the dir to be skipped by the excluded-tags set)."""
    if jax.process_index() != 0:
        return
    src = os.path.join(load_dir, tag)
    dst = f"{src}.quarantined"
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{src}.quarantined.{n}"
    try:
        os.replace(src, dst)
        logger.error(f"checkpoint tag '{tag}' QUARANTINED -> "
                     f"{os.path.basename(dst)}: {why}")
    except OSError as e:
        logger.error(f"checkpoint tag '{tag}' corrupt ({why}) and could "
                     f"not be quarantined: {e}")


def _candidate_tags(load_dir: str, exclude=()) -> List[str]:
    """Committed tags newest-first (by index mtime), skipping quarantined
    dirs and ``exclude`` — the fallback search order."""
    out = []
    try:
        names = os.listdir(load_dir)
    except OSError:
        return out
    for name in names:
        if name in exclude or ".quarantined" in name:
            continue
        root = os.path.join(load_dir, name)
        if not os.path.isdir(root):
            continue
        metas = [os.path.join(root, f) for f in os.listdir(root)
                 if f.startswith("meta") and f.endswith(".json")]
        if metas:
            out.append((max(os.path.getmtime(m) for m in metas), name))
    return [name for _, name in sorted(out, reverse=True)]


@_traced("checkpoint/restore")
def load_checkpoint(load_dir: str, tag: Optional[str],
                    templates: Dict[str, Pytree],
                    shardings: Dict[str, Pytree],
                    strict=True, fallback: bool = True
                    ) -> Tuple[Optional[Dict[str, Pytree]],
                               Dict[str, Any], Optional[str]]:
    """Load state matching ``templates`` structure, placing each leaf with
    the corresponding sharding (any mesh — the universal reshape).

    ``strict`` may be ``True`` (all groups), ``False`` (none), or a
    collection of group names: within a strict group, a missing
    model-critical leaf ('params' leaves, fp32 masters, optimizer moments)
    raises ``KeyError`` instead of loading partly-initialized state. A group
    entirely absent from the checkpoint is NOT an error — that is a
    cross-mode checkpoint (e.g. host-offload runs keep optimizer state in
    ``host_optimizer.npz``, params-only exports); the group is omitted from
    the returned dict so the caller can rebuild it.

    With ``fallback`` (the default), a tag that fails integrity
    verification (torn/CRC-mismatched fragment, incomplete index) is
    QUARANTINED and the newest remaining valid tag is loaded instead —
    auto-resume survives a checkpoint torn by the very preemption it is
    resuming from. Each hop bumps ``resilience/ckpt_fallbacks``; the
    original error re-raises when no valid tag remains."""
    wait_pending()
    tag = tag or latest_tag(load_dir)
    if tag is None:
        return None, {}, None
    first_err: Optional[BaseException] = None
    tried: set = set()
    while True:
        try:
            out = _load_tag(load_dir, tag, templates, shardings, strict)
            if tried:
                # recovered onto a fallback tag: repoint auto-resume and
                # close the faults_injected == recoveries ledger
                if jax.process_index() == 0:
                    try:
                        _write_latest(load_dir, tag)
                    except OSError:
                        pass
                from deepspeed_tpu.resilience.faults import record_recovery
                record_recovery("ckpt_fallback", to_tag=tag,
                                bad_tags=sorted(tried))
            return out
        except (CheckpointCorrupt, FileNotFoundError) as e:
            first_err = first_err or e
            if not fallback:
                raise
            tried.add(tag)
            logger.error(f"checkpoint '{tag}' failed verification: {e}")
            _quarantine_tag(load_dir, tag, e)
            try:
                from deepspeed_tpu import telemetry
                telemetry.registry.counter(
                    "resilience/ckpt_fallbacks",
                    help="corrupt-tag fallbacks during checkpoint "
                         "load").inc()
                telemetry.flight_recorder.record_event(
                    "ckpt_fallback", bad_tag=tag, error=str(e)[:200])
            except Exception:                        # noqa: BLE001
                pass
            candidates = _candidate_tags(load_dir, exclude=tried)
            if not candidates:
                logger.error(f"no valid checkpoint tag left in {load_dir} "
                             f"(tried {sorted(tried)})")
                raise first_err
            tag = candidates[0]
            logger.warning(f"falling back to newest valid checkpoint "
                           f"tag '{tag}'")


def _load_tag(load_dir: str, tag: str, templates: Dict[str, Pytree],
              shardings: Dict[str, Pytree], strict
              ) -> Tuple[Optional[Dict[str, Pytree]],
                         Dict[str, Any], Optional[str]]:
    root = os.path.join(load_dir, tag)
    meta, index = _read_merged_index(root)
    if strict is True:
        strict = frozenset(templates)
    elif strict is False:
        strict = frozenset()

    out: Dict[str, Pytree] = {}
    for group, template in templates.items():
        if group not in index:
            logger.warning(f"checkpoint {tag}: no '{group}' state group "
                           f"(cross-mode or partial checkpoint) — caller "
                           f"keeps/rebuilds its own state")
            continue
        gdir = os.path.join(root, "state", group)
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        sh_flat, _ = jax.tree_util.tree_flatten_with_path(
            shardings[group], is_leaf=lambda x: hasattr(x, "mesh"))
        sh_leaves = [leaf for _, leaf in sh_flat]
        leaves = []
        for (path, tmpl), sh in zip(flat, sh_leaves):
            key = _SEP.join(_path_str(k) for k in path)
            if key not in index[group]:
                if group in strict and _missing_leaf_is_critical(group, key):
                    raise KeyError(
                        f"checkpoint {tag}: required state leaf "
                        f"'{group}/{key}' is missing — the checkpoint is "
                        f"incomplete or structurally mismatched (renamed "
                        f"layer / truncated save). Pass strict=False to "
                        f"keep the freshly-initialized value anyway.")
                # forward compatibility: a non-critical leaf added to the
                # runtime state after the checkpoint was written (e.g. new
                # optimizer telemetry scalars) keeps its freshly-initialized
                # template value instead of failing the whole restore
                logger.warning(f"checkpoint {tag}: state leaf '{group}/{key}' "
                         f"absent — keeping initialized value")
                leaves.append(jax.device_put(jnp.asarray(tmpl), sh))
                continue
            arr = jnp.asarray(_assemble(gdir, index[group][key]))
            tdtype = jnp.asarray(tmpl).dtype
            if arr.dtype != tdtype:
                arr = arr.astype(tdtype)
            leaves.append(jax.device_put(arr, sh))
        out[group] = jax.tree_util.tree_unflatten(treedef, leaves)
    return out, meta, tag


def consolidate_to_fp32(load_dir: str, tag: Optional[str] = None
                        ) -> Dict[str, np.ndarray]:
    """Offline merge to fp32 state dict (reference
    utils/zero_to_fp32.py:188): assemble fragment files back into full
    fp32 arrays without any runtime — prefers the fp32 master leaves."""
    wait_pending()
    tag = tag or latest_tag(load_dir)
    root = os.path.join(load_dir, tag)
    _, index = _read_merged_index(root)
    master_keys = {k: v for k, v in index.get("opt_state", {}).items()
                   if k.startswith("master" + _SEP)}
    out = {}
    if master_keys:
        gdir = os.path.join(root, "state", "opt_state")
        for key, entry in master_keys.items():
            out[key[len("master" + _SEP):]] = \
                _assemble(gdir, entry).astype(np.float32)
    else:
        gdir = os.path.join(root, "state", "params")
        for key, entry in index.get("params", {}).items():
            out[key] = _assemble(gdir, entry).astype(np.float32)
    return out
