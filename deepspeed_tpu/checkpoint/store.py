"""Checkpoint store — sharded fragments, universal by construction.

Reference: engine save_checkpoint/load_checkpoint (runtime/engine.py:3621,
3273; per-rank shard naming :3197–3261), the pluggable CheckpointEngine ABC
(runtime/checkpoint_engine/checkpoint_engine.py:21, Fast/Decoupled async
engines), and Universal Checkpoint (deepspeed/checkpoint/ds_to_universal.py).

Design:

- **Sharded writing.** Every process writes ONLY its addressable shards
  (one raw-bytes fragment file per distinct shard, ``replica_id == 0``
  filter deduplicates replicas) — no full-model gather ever lands on one
  host, the property the reference gets from per-rank
  ``zero_pp_rank_X_mp_rank_XX`` files.
- **Universal reload.** Fragments carry (start, stop) index metadata in
  FULL-array coordinates, so load assembles any leaf under any later mesh,
  ZeRO stage, or offload mode — the UCP reshape with no offline converter.
- **Async commit.** The device→host snapshot is taken synchronously (jax
  arrays are immutable but donation invalidates buffers, so the copy must
  happen before training continues); file writes + the meta.json commit
  + the ``latest`` marker run on a background thread through the
  AsyncIOEngine (reference: DecoupledCheckpointEngine, deepspeed/io/
  fast_file_writer.py). A checkpoint is visible only after its meta.json
  is fully written — the commit point.

Layout::

    <dir>/<tag>/meta.json                     # meta + fragment index
    <dir>/<tag>/state/<group>/<leaf>.f<k>.bin # raw C-order fragment bytes
    <dir>/latest                              # newest committed tag
"""

import json
import os
import shutil
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from deepspeed_tpu.utils.logging import logger

Pytree = Any

_SEP = "."


def _np_dtype(name: str):
    return {"bfloat16": ml_dtypes.bfloat16,
            "float8_e4m3fn": ml_dtypes.float8_e4m3fn,
            "float8_e5m2": ml_dtypes.float8_e5m2}.get(name) or np.dtype(name)


def _leaf_paths(tree) -> Dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = _SEP.join(_path_str(k) for k in path)
        out[key] = leaf
    return out


def _path_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    if hasattr(k, "name"):
        return str(k.name)
    return str(k)


def _index_bounds(index, shape) -> Tuple[List[int], List[int]]:
    """jax shard index (tuple of slices) → (start, stop) per dim."""
    starts, stops = [], []
    for sl, dim in zip(index, shape):
        starts.append(0 if sl.start is None else int(sl.start))
        stops.append(dim if sl.stop is None else int(sl.stop))
    return starts, stops


def _snapshot_shards(leaf) -> List[Tuple[List[int], List[int], np.ndarray]]:
    """Host copies of this process's distinct shards of one jax array."""
    if not isinstance(leaf, jax.Array):
        arr = np.asarray(leaf)
        return [([0] * arr.ndim, list(arr.shape), arr)]
    out = []
    for shard in leaf.addressable_shards:
        if shard.replica_id != 0:
            continue
        starts, stops = _index_bounds(shard.index, leaf.shape)
        out.append((starts, stops, np.asarray(shard.data)))
    return out


def save_checkpoint(save_dir: str, tag: str, state: Dict[str, Pytree],
                    meta: Dict[str, Any], save_latest: bool = True,
                    async_save: bool = False):
    """Write ``state`` (dict of named pytrees) + ``meta`` under tag.

    Returns the checkpoint root; with ``async_save`` also returns after the
    device→host snapshot — call :func:`wait_pending` (or save again) before
    relying on the files."""
    root = os.path.join(save_dir, tag)
    if os.path.exists(root):
        shutil.rmtree(root)
    os.makedirs(os.path.join(root, "state"), exist_ok=True)

    # ---- synchronous snapshot (before donation can invalidate buffers)
    work: List[Tuple[str, np.ndarray]] = []     # (path, host array)
    index: Dict[str, Dict[str, Any]] = {}
    pidx = jax.process_index()
    for group, tree in state.items():
        gdir = os.path.join(root, "state", group)
        os.makedirs(gdir, exist_ok=True)
        for key, leaf in _leaf_paths(tree).items():
            shards = _snapshot_shards(leaf)
            full_shape = list(np.shape(leaf))
            dtype = str(np.asarray(shards[0][2]).dtype) if shards else "float32"
            frags = []
            for k, (starts, stops, arr) in enumerate(shards):
                fname = f"{key.replace('/', '_')}.p{pidx}f{k}.bin"
                work.append((os.path.join(gdir, fname),
                             np.ascontiguousarray(arr)))
                frags.append({"file": fname, "start": starts, "stop": stops})
            index.setdefault(group, {})[key] = {
                "shape": full_shape, "dtype": dtype, "fragments": frags}

    def commit():
        for path, arr in work:
            with open(path, "wb") as fh:
                fh.write(arr.tobytes())
        # meta.json last — its presence IS the commit point
        with open(os.path.join(root, "meta.json"), "w") as fh:
            json.dump({"meta": meta, "index": index, "version": 2}, fh,
                      indent=1)
        if save_latest:
            with open(os.path.join(save_dir, "latest"), "w") as fh:
                fh.write(tag)

    if async_save:
        t = threading.Thread(target=commit, daemon=True)
        t.start()
        _PENDING.append(t)
        return root
    commit()
    return root


#: in-flight async commits (reference: DecoupledCheckpointEngine queue)
_PENDING: List[threading.Thread] = []


def wait_pending() -> None:
    while _PENDING:
        _PENDING.pop().join()


def latest_tag(load_dir: str) -> Optional[str]:
    path = os.path.join(load_dir, "latest")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return fh.read().strip()


def _assemble(gdir: str, entry: Dict[str, Any]) -> np.ndarray:
    """Fragments → full np array (any-mesh reshape happens at device_put)."""
    dtype = _np_dtype(entry["dtype"])
    shape = tuple(entry["shape"])
    frags = entry["fragments"]
    if len(frags) == 1 and tuple(frags[0]["start"]) == (0,) * len(shape) \
            and tuple(frags[0]["stop"]) == shape:
        raw = np.fromfile(os.path.join(gdir, frags[0]["file"]), dtype=dtype)
        return raw.reshape(shape)
    out = np.empty(shape, dtype)
    for f in frags:
        sl = tuple(slice(a, b) for a, b in zip(f["start"], f["stop"]))
        piece = np.fromfile(os.path.join(gdir, f["file"]), dtype=dtype)
        out[sl] = piece.reshape(tuple(b - a for a, b in
                                      zip(f["start"], f["stop"])))
    return out


def load_checkpoint(load_dir: str, tag: Optional[str],
                    templates: Dict[str, Pytree],
                    shardings: Dict[str, Pytree]
                    ) -> Tuple[Optional[Dict[str, Pytree]],
                               Dict[str, Any], Optional[str]]:
    """Load state matching ``templates`` structure, placing each leaf with
    the corresponding sharding (any mesh — the universal reshape)."""
    wait_pending()
    tag = tag or latest_tag(load_dir)
    if tag is None:
        return None, {}, None
    root = os.path.join(load_dir, tag)
    meta_path = os.path.join(root, "meta.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no checkpoint at {root}")
    with open(meta_path) as fh:
        payload = json.load(fh)
    meta = payload["meta"]
    index = payload["index"]

    out: Dict[str, Pytree] = {}
    for group, template in templates.items():
        gdir = os.path.join(root, "state", group)
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        sh_flat, _ = jax.tree_util.tree_flatten_with_path(
            shardings[group], is_leaf=lambda x: hasattr(x, "mesh"))
        sh_leaves = [leaf for _, leaf in sh_flat]
        leaves = []
        for (path, tmpl), sh in zip(flat, sh_leaves):
            key = _SEP.join(_path_str(k) for k in path)
            arr = jnp.asarray(_assemble(gdir, index[group][key]))
            tdtype = jnp.asarray(tmpl).dtype
            if arr.dtype != tdtype:
                arr = arr.astype(tdtype)
            leaves.append(jax.device_put(arr, sh))
        out[group] = jax.tree_util.tree_unflatten(treedef, leaves)
    return out, meta, tag


def consolidate_to_fp32(load_dir: str, tag: Optional[str] = None
                        ) -> Dict[str, np.ndarray]:
    """Offline merge to fp32 state dict (reference
    utils/zero_to_fp32.py:188): assemble fragment files back into full
    fp32 arrays without any runtime — prefers the fp32 master leaves."""
    wait_pending()
    tag = tag or latest_tag(load_dir)
    root = os.path.join(load_dir, tag)
    with open(os.path.join(root, "meta.json")) as fh:
        payload = json.load(fh)
    index = payload["index"]
    master_keys = {k: v for k, v in index.get("opt_state", {}).items()
                   if k.startswith("master" + _SEP)}
    out = {}
    if master_keys:
        gdir = os.path.join(root, "state", "opt_state")
        for key, entry in master_keys.items():
            out[key[len("master" + _SEP):]] = \
                _assemble(gdir, entry).astype(np.float32)
    else:
        gdir = os.path.join(root, "state", "params")
        for key, entry in index.get("params", {}).items():
            out[key] = _assemble(gdir, entry).astype(np.float32)
    return out
