"""Import checkpoints saved by the reference (DeepSpeed) into this framework.

Migration path for users switching from the reference: their training runs
left behind DeepSpeed checkpoint directories, and those weights should load
here without a detour through torch.

Two on-disk formats are supported (both documented in SURVEY.md §5
"Checkpoint / resume"; format details verified against the reference's
writer, runtime/engine.py:3197–3261 and checkpoint/ds_to_universal.py:469):

1. **Engine checkpoints** — ``<dir>/<tag>/mp_rank_00_model_states.pt``
   written by ``engine.save_checkpoint``. The ``module`` entry is the
   wrapped model's own ``state_dict()``; for HF models that means HF tensor
   names, so the mapping into our pytree is exactly the HF-interop mapping
   (`models/hf_loader.params_from_state`). The optional ``latest`` file at
   the directory root names the tag.
2. **Universal checkpoints (UCP)** — ``<dir>/<tag>/zero/<param_name>/fp32.pt``
   per-parameter fp32 fragments produced by ``ds_to_universal.py``. Param
   names are again module state-dict names, so the same mapping applies.

Also supported (r4, VERDICT r3 #5):

3. **MoE expert shards** — ``layer_<L>_expert_<E>_mp_rank_00_model_states.pt``
   (and the legacy ``expert_<E>_mp_rank_*`` form) written by the reference's
   MoE save path (runtime/engine.py:3111 ``_get_expert_ckpt_name``:3249).
   Expert keys carry the DeepSpeed-MoE wrapper infix
   ``.deepspeed_moe.experts.deepspeed_experts.<gid>.``; stripping it back to
   ``.experts.<gid>.`` recovers the wrapped module's own naming (HF naming
   for HF MoE models), so the same HF-interop mapping applies.
4. **Direct ZeRO optimizer shards** —
   ``(bf16_)zero_pp_rank_<d>_mp_rank_00_optim_states.pt``: the fp32 master
   partitions ARE the authoritative weights of a ZeRO run; they are
   reconstructed here exactly as the reference's offline
   ``utils/zero_to_fp32.py`` does (Z1/2: per-group concat across dp ranks,
   :252 ``_zero2_merge_trainable_params``; Z3: per-param zip of per-rank
   slices, :303 ``_zero3_merge_trainable_params``) — no prior
   ``ds_to_universal`` pass needed. Adam moments ride the same flat layout
   and are reconstructed alongside when present.

Scope, by design:
- Model-parallel (``mp_rank_01+``) shards are rejected with instructions to
  consolidate first (the reference's own migration guidance); TP resharding
  happens on OUR side via `module_inject/auto_tp.py` partition specs after
  the full-shape weights are loaded — the AutoTP analogue shards pytrees,
  not files.

Requires torch (CPU) to deserialize ``.pt`` files; gated at call time.
"""

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import DecoderConfig
from deepspeed_tpu.models.hf_loader import config_from_hf, params_from_state
from deepspeed_tpu.utils.logging import logger

Params = Any


def _torch():
    try:
        import torch
    except ImportError as exc:                       # pragma: no cover
        raise RuntimeError(
            "importing DeepSpeed .pt checkpoints requires torch "
            "(CPU build is enough)") from exc
    return torch


def resolve_tag(ckpt_dir: str, tag: Optional[str] = None) -> str:
    """Tag resolution mirroring the reference's ``latest`` convention."""
    if tag is not None:
        return tag
    from deepspeed_tpu.checkpoint.store import latest_tag
    latest = latest_tag(ckpt_dir)
    if latest is not None:
        return latest
    # single-subdir checkpoint dirs are unambiguous
    subs = [d for d in sorted(os.listdir(ckpt_dir))
            if os.path.isdir(os.path.join(ckpt_dir, d))]
    if len(subs) == 1:
        return subs[0]
    raise ValueError(
        f"cannot resolve checkpoint tag in {ckpt_dir}: no 'latest' file "
        f"and {len(subs)} candidate subdirectories {subs}")


def _strip_prefixes(sd: Dict[str, Any]) -> Dict[str, Any]:
    """Strip wrapper prefixes ('module.', DDP-style) off state-dict keys."""
    for prefix in ("module.", "model.module."):
        if all(k.startswith(prefix) for k in sd):
            sd = {k[len(prefix):]: v for k, v in sd.items()}
    return sd


def _state_reader(sd: Dict[str, Any]):
    """(get, names) view over a torch state dict, matching _reader()."""
    def get(name: str) -> np.ndarray:
        t = sd[name]
        if hasattr(t, "detach"):
            t = t.detach().to("cpu").float().numpy()
        return np.asarray(t)
    return get, set(sd.keys())


def load_ds_checkpoint(ckpt_dir: str, hf_config: Dict[str, Any],
                       tag: Optional[str] = None, dtype=np.float32
                       ) -> Tuple[DecoderConfig, Params]:
    """Load a reference engine checkpoint into (DecoderConfig, params).

    ``hf_config`` is the HF ``config.json`` dict of the wrapped model (the
    reference does not checkpoint the model config — users keep it next to
    the weights; same requirement here).
    """
    torch = _torch()
    tag = resolve_tag(ckpt_dir, tag)
    path = os.path.join(ckpt_dir, tag, "mp_rank_00_model_states.pt")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no model states at {path}")
    other = os.path.join(ckpt_dir, tag, "mp_rank_01_model_states.pt")
    if os.path.exists(other):
        raise ValueError(
            f"{ckpt_dir} is a model-parallel checkpoint ({other} "
            "exists). Consolidate it first (reference: "
            "ds_to_universal.py merges TP slices), then import the "
            "universal checkpoint via load_universal_checkpoint().")
    blob = torch.load(path, map_location="cpu", weights_only=False)
    sd = blob.get("module", blob)
    if not isinstance(sd, dict):                     # pragma: no cover
        raise ValueError(f"unexpected model-states payload in {path}")
    # MoE runs save expert weights in separate per-expert shard files
    # (reference engine.py:3111); fold them back in before mapping
    merge_expert_shards(ckpt_dir, tag, sd)
    sd = _strip_prefixes(sd)
    # ZeRO-3 model states saved without gather_16bit_weights hold 0-size
    # placeholders (params live in the zero_pp_rank_* optimizer shards) —
    # fail fast instead of stacking empty arrays into a garbage pytree
    if any(getattr(t, "numel", lambda: 1)() == 0 for t in sd.values()):
        raise ValueError(
            f"{path} holds ZeRO-3 placeholder (0-size) tensors — the "
            "weights live in the zero_pp_rank_* shards. Re-save with "
            "stage3_gather_16bit_weights_on_model_save, or convert with "
            "the reference's ds_to_universal.py / zero_to_fp32.py and "
            "import via load_universal_checkpoint().")
    cfg = config_from_hf(hf_config)
    get, names = _state_reader(sd)
    params = params_from_state(cfg, hf_config, get, names, dtype)
    logger.info(f"imported DeepSpeed checkpoint {ckpt_dir}@{tag}: "
                f"{cfg.num_params() / 1e6:.1f}M params")
    return cfg, params


_MOE_INFIX = ".deepspeed_moe.experts.deepspeed_experts."


def _natural_key(path: str):
    import re
    return [int(t) if t.isdigit() else t
            for t in re.split(r"(\d+)", os.path.basename(path))]


def merge_expert_shards(ckpt_dir: str, tag: str,
                        sd: Dict[str, Any]) -> int:
    """Fold the reference's per-expert shard files into ``sd`` (reference
    load_moe_state_dict engine.py:3111; file naming _get_expert_ckpt_name
    :3249). The DeepSpeed-MoE wrapper infix is stripped so keys return to
    the wrapped module's own naming: ``<p>.deepspeed_moe.experts.
    deepspeed_experts.<gid>.<w>`` → ``<p>.experts.<gid>.<w>``. Returns the
    number of expert files merged."""
    import glob as _glob
    torch = _torch()
    root = os.path.join(ckpt_dir, tag)
    files = sorted(
        _glob.glob(os.path.join(root, "layer_*_expert_*_model_states.pt"))
        + _glob.glob(os.path.join(root, "expert_*_model_states.pt")),
        key=_natural_key)
    for path in files:
        if "_mp_rank_00_" not in os.path.basename(path) and \
                "_mp_rank_" in os.path.basename(path):
            raise ValueError(
                f"{path} is a model-parallel expert shard; consolidate TP "
                f"first (same restriction as mp_rank_01 model states)")
        esd = torch.load(path, map_location="cpu", weights_only=False)
        esd = esd.get("model", esd)
        for key, val in esd.items():
            if _MOE_INFIX in key:
                prefix, rest = key.split(_MOE_INFIX, 1)
                key = f"{prefix}.experts.{rest}"
            sd[key] = val
    if files:
        logger.info(f"merged {len(files)} reference MoE expert shards")
    return len(files)


def _reconstruct_flat_z2(shapes_groups, per_rank_groups) -> Dict[str, np.ndarray]:
    """Z1/2: per-group partitions concatenated across dp ranks, then sliced
    by param shape in declaration order (reference zero_to_fp32.py:252;
    trailing alignment padding 0..2*world_size is simply left unread)."""
    out: Dict[str, np.ndarray] = {}
    for gi, shapes in enumerate(shapes_groups):
        merged = np.concatenate(
            [np.asarray(rank[gi], np.float32).ravel()
             for rank in per_rank_groups])
        off = 0
        for name, shape in shapes.items():
            shape = tuple(shape)
            n = int(np.prod(shape)) if shape else 1
            out[name] = merged[off:off + n].reshape(shape)
            off += n
    return out


def _reconstruct_flat_z3(shapes_groups, per_rank_flats, world_size
                         ) -> Dict[str, np.ndarray]:
    """Z3: every param is partitioned per-param (padded to world_size);
    rank r holds [offset, offset+ceil(n/ws)) of each param — zip the
    per-rank slices back (reference zero_to_fp32.py:303)."""
    shapes = {k: v for d in shapes_groups for k, v in d.items()}
    ranks = [np.concatenate([np.asarray(t, np.float32).ravel()
                             for t in flats]) for flats in per_rank_flats]
    out: Dict[str, np.ndarray] = {}
    off = 0
    for name, shape in shapes.items():
        shape = tuple(shape)
        n = int(np.prod(shape)) if shape else 1
        pn = -(-n // world_size)
        full = np.concatenate([r[off:off + pn] for r in ranks])
        out[name] = full[:n].reshape(shape)
        off += pn
    return out


def load_zero_checkpoint(ckpt_dir: str, hf_config: Dict[str, Any],
                         tag: Optional[str] = None, dtype=np.float32,
                         load_optimizer_states: bool = False):
    """Import a reference ZeRO checkpoint DIRECTLY from its
    ``zero_pp_rank_*_optim_states.pt`` shards — no ds_to_universal pass.

    The fp32 master partitions in the optimizer shards are the
    authoritative weights of a ZeRO run; reconstruction follows the
    reference's own offline merge (utils/zero_to_fp32.py:188). With
    ``load_optimizer_states`` (stage ≤ 2), the Adam moments — which ride
    the identical flat layout — are reconstructed too and mapped through
    the same HF-interop transform as the weights (layout transforms are
    elementwise-aligned, so moments stay aligned with their weights).

    Returns ``(cfg, params)`` or ``(cfg, params, moments)`` where moments
    is ``{"exp_avg": pytree, "exp_avg_sq": pytree, "step": int}``.
    """
    import glob as _glob
    torch = _torch()
    tag = resolve_tag(ckpt_dir, tag)
    root = os.path.join(ckpt_dir, tag)
    files = sorted(_glob.glob(os.path.join(root, "*_optim_states.pt")),
                   key=_natural_key)
    if not files:
        raise FileNotFoundError(f"no *_optim_states.pt under {root}")
    blobs = [torch.load(f, map_location="cpu", weights_only=False)
             for f in files]
    osds = [b["optimizer_state_dict"] for b in blobs]
    stage = int(osds[0]["zero_stage"])
    world = osds[0]["partition_count"]
    if isinstance(world, (list, tuple)):
        world = max(world)
    world = int(world)
    if world != len(files):
        raise ValueError(
            f"expected {world} optim shards under {root}, found "
            f"{len(files)} — incomplete checkpoint")

    # param_shapes live in the model-states file (reference
    # zero_to_fp32.get_model_state_file:68)
    ms_name = "zero_pp_rank_0_mp_rank_00_model_states.pt" if stage == 3 \
        else "mp_rank_00_model_states.pt"
    ms_path = os.path.join(root, ms_name)
    if not os.path.exists(ms_path):
        raise FileNotFoundError(f"no model states at {ms_path}")
    ms = torch.load(ms_path, map_location="cpu", weights_only=False)
    shapes_groups = ms["param_shapes"]
    if isinstance(shapes_groups, dict):
        shapes_groups = [shapes_groups]

    if stage <= 2:
        per_rank = [osd["single_partition_of_fp32_groups"] for osd in osds]
        fp32 = _reconstruct_flat_z2(shapes_groups, per_rank)
    else:
        per_rank = [osd["fp32_flat_groups"] for osd in osds]
        fp32 = _reconstruct_flat_z3(shapes_groups, per_rank, world)

    names = set(fp32.keys())
    strip = names and all(n.startswith("module.") for n in names)

    def reader(table):
        def get(name):
            return table["module." + name if strip else name]
        return get

    cfg = config_from_hf(hf_config)
    vis_names = {n[len("module."):] for n in names} if strip else names
    params = params_from_state(cfg, hf_config, reader(fp32), vis_names,
                               dtype)
    logger.info(f"imported reference ZeRO-{stage} checkpoint "
                f"{ckpt_dir}@{tag}: dp={world}, "
                f"{cfg.num_params() / 1e6:.1f}M params (direct from "
                f"optim shards, no ds_to_universal)")
    if not load_optimizer_states:
        return cfg, params

    if stage == 3:
        raise ValueError(
            "load_optimizer_states for reference stage-3 checkpoints is "
            "not supported (sub-group moment layout); load weights only "
            "and let the engine rebuild moments")
    def _group_states(osd):
        """Per-group inner Adam state. Reference key is
        'base_optimizer_state' (checkpoint/constants.py:16) holding either
        the torch optimizer state_dict (non-elastic, stage_1_and_2.py:2389)
        or a per-group list (elastic, :2384 _get_base_optimizer_state);
        'optimizer_state_dict' accepted as a fallback variant."""
        base = osd.get("base_optimizer_state")
        if base is None:
            base = osd.get("optimizer_state_dict") or {}
        if isinstance(base, list):
            return base
        state = base.get("state", {})
        return [state[i] for i in sorted(state)]

    moments = {}
    for key in ("exp_avg", "exp_avg_sq"):
        per_rank_m = []
        for osd in osds:
            gs = _group_states(osd)
            if len(gs) < len(shapes_groups):
                raise ValueError(
                    f"optimizer shard holds {len(gs)} group states, "
                    f"expected {len(shapes_groups)}")
            per_rank_m.append([np.asarray(gs[i][key], np.float32)
                               for i in range(len(shapes_groups))])
        table = _reconstruct_flat_z2(shapes_groups, per_rank_m)
        moments[key] = params_from_state(cfg, hf_config, reader(table),
                                         vis_names, np.float32)
    step = osds[0].get("base_optimizer_state_step")
    if step is None:
        gs = _group_states(osds[0])
        step = gs[0].get("step", 0) if gs else 0
    moments["step"] = int(step.item() if hasattr(step, "item") else step)
    return cfg, params, moments


def load_universal_checkpoint(ckpt_dir: str, hf_config: Dict[str, Any],
                              tag: Optional[str] = None, dtype=np.float32
                              ) -> Tuple[DecoderConfig, Params]:
    """Load a reference *universal* checkpoint (ds_to_universal output).

    Layout: ``<dir>/<tag>/zero/<param_name>/fp32.pt`` holds the merged
    full-shape fp32 weight per parameter (reference
    checkpoint/ds_to_universal.py: `merge_tp_slices`:232 writes one file
    per param). Optimizer-state fragments (``exp_avg.pt`` …) are ignored —
    moments are rebuilt in this framework's sharding-aware layout.
    """
    torch = _torch()
    tag = resolve_tag(ckpt_dir, tag)
    zero_dir = os.path.join(ckpt_dir, tag, "zero")
    if not os.path.isdir(zero_dir):
        raise FileNotFoundError(f"no universal-checkpoint dir at {zero_dir}")

    def get(name: str) -> np.ndarray:
        # no caching: each param is read exactly once by params_from_state,
        # and holding fp32 copies would double peak host RAM at 70B scale
        t = torch.load(os.path.join(zero_dir, name, "fp32.pt"),
                       map_location="cpu", weights_only=False)
        if isinstance(t, dict):                      # {'param': tensor} form
            t = t.get("param", t)
        return t.detach().float().numpy()

    names = {d for d in os.listdir(zero_dir)
             if os.path.exists(os.path.join(zero_dir, d, "fp32.pt"))}
    # param dirs may carry the 'module.' prefix; normalize both views
    if names and all(n.startswith("module.") for n in names):
        raw_get = get

        def get(name):                               # noqa: F811
            return raw_get("module." + name)
        names = {n[len("module."):] for n in names}
    cfg = config_from_hf(hf_config)
    params = params_from_state(cfg, hf_config, get, names, dtype)
    logger.info(f"imported universal checkpoint {ckpt_dir}@{tag}: "
                f"{cfg.num_params() / 1e6:.1f}M params")
    return cfg, params
